"""Protocol state-machine model checker (the engine behind EDL009).

EDL007 proved the coordinator protocol's *shape* agrees across the C++
server, the wire client, and the in-process twin. This module checks its
*behavior*: the per-op ``state_effects`` block of ``protocol_schema.json``
declares how each op touches coordinator state (epoch bumps, lease
acquire/release, dedup keys, fd-parking), a small abstract interpreter
(`ProtocolModel`) executes those declarations, and a bounded explicit-state
explorer enumerates every interleaving of N scripted workers — including
crash/restart and duplicate-delivery faults — checking four invariants on
every trace:

- **epoch monotonicity**: the epoch observed on any worker's reply stream
  never decreases;
- **exactly-once**: a replayed ``req_id``/``op_id`` must return the original
  effect (same task, same counter value), never apply a second one;
- **lease exclusivity**: at most one live lease per task, transfers only
  through an explicit requeue event (complete/fail/takeover/drop);
- **progress**: every parked op (barrier/sync) is eventually released and
  every script drains — a schedule where all runnable workers are parked is
  a deadlock, reported without replay.

Every completed trace is then replayed op-for-op against a fresh
``InProcessCoordinator`` (the executable oracle): each model-predicted reply
must be a subset of the oracle's reply, with the epoch matching exactly. A
model/oracle divergence means either the schema's behavioral annotations or
the twin drifted — both are findings.

Exploration is exhaustive by default (DFS over all interleavings) and can
run as a seeded random walk (``fuzz_samples``/``fuzz_seed``), whose explored
trace set — and therefore violation set — is provably a subset of the
exhaustive run at equal depth: both draw schedules from the same runnable
sets, the walk just samples one branch per node.

**Durability lane (EDL010).** Schedules flagged ``durable`` additionally
split the model into volatile vs durable halves: every op's ``durability``
tag in ``state_effects`` declares which journal records it emits
(``journal:kv``, ``journal:meta,lease``, ``volatile``, ``none``), handlers
emit those records into a per-turn frame, and each frame group-commits
(one fsync per event-loop turn, a trailing commit-marker record closing
the frame — mirroring the native journal byte-for-byte in structure). A
``crash`` pseudo-op (modes ``clean`` / ``pre_ack`` / ``torn`` /
``during_compaction``) is a first-class schedule step: the DFS interleaves
it like any other op, so its position enumerates every crash point; its
semantics discard volatile state, replay the committed journal exactly the
way ``load_state`` does (epoch+1, leases restored under holders, req_id
dedup cache rebuilt, torn tail frames dropped whole), and its oracle
realization kills and restarts a REAL coordinator — the file-backed
``InProcessCoordinator`` persistence twin in the default lane, the native
binary with env-gated crash injection (``EDL_COORD_CRASH_AFTER_APPENDS``)
in ``edl_tpu.analysis.native_oracle``. Invariants added on top of the
four above: acked-implies-durable, exactly-once across crash,
snapshot⊕journal-suffix equivalence at every compaction, epoch
monotonicity across restart, and ladder honesty for the deliberately
unjournaled shard store (loss may cost a recovery rung, never contradict
a durable ack). A sleep-set partial-order reduction over commuting ops
(disjoint static footprints; any epoch-writing op conflicts with
everything) keeps crash-point exploration inside EDL009's budget.

``python -m edl_tpu.analysis.modelcheck`` runs the default bounded
configuration — the 2-worker faulty base (13 ops including ``batch``, one
crash+restart, two duplicate deliveries), the checkpoint-plane ops, a
watch/notify schedule, a redirect-during-watch schedule against a sharded
root, and the durability schedules (post-fsync survival, pre-fsync loss,
torn tail, crash-during-compaction, shard-store-across-crash) — and exits
1 on any violation: the ``make modelcheck`` gate. ``--schedules`` filters
by name, ``--dump-trace`` writes the first violating interleaving as a
JSON spec, ``--replay-trace`` re-executes such a spec in isolation.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import threading
import time
import weakref
from dataclasses import dataclass, field, replace as _dc_replace
from typing import Any, Callable, Dict, List, Optional, Sequence, Set, Tuple

from edl_tpu.coordinator.sharding import shard_of

#: ops a ``call_batch`` frame refuses (they park, nest framing, or bind an
#: out-of-band push stream to the connection — ``watch``); mirrored from the
#: wire protocol, used by the composite handler.
_NON_BATCHABLE = ("batch", "barrier", "sync", "watch")

#: sentinel request-field value: resolved at issue time to the task named in
#: the issuing worker's most recent acquire reply (each side — model and
#: oracle — resolves from its OWN reply stream, so a grant divergence is
#: reported once at the acquire, not echoed by every downstream op).
LAST_TASK = "__edl_modelcheck_last_task__"

#: the crash pseudo-op (not a wire op): kills the coordinator at this point
#: in the interleaving and replays recovery. Scheduled like any other
#: ScriptOp, so DFS position = crash point.
CRASH_OP = "crash"

#: crash modes. ``clean``: nothing in flight, recover to the committed
#: journal. ``pre_ack``: the inflight op's frame is appended AND fsynced but
#: the reply never flushes — its effects must survive (post-fsync survival).
#: ``torn``: the inflight frame is appended but torn mid-write (no commit
#: marker reaches disk) — the WHOLE frame must be absent after recovery
#: (pre-fsync loss; all-or-nothing is the frame contract). #:
#: ``during_compaction``: the inflight op triggers a snapshot that dies
#: after the tmp write, before the rename — the journal is untouched and
#: the inflight effects are lost, unacked.
CRASH_MODES = ("clean", "pre_ack", "torn", "during_compaction")

#: journal record kinds, mirroring the native journal line vocabulary.
_JOURNAL_KINDS = ("meta", "todo", "done", "lease", "kv", "kvdel")


class ModelCheckError(Exception):
    """The schema's state_effects block cannot drive the model (missing op,
    unknown effect tag): a behavioral-spec error, not a trace violation."""


class _SnapshotDivergence(Exception):
    """The model's own snapshot⊕journal-suffix self-check failed: replaying
    the compacted journal did not reconstruct the live durable state. The
    explorer converts this into a ``snapshot-divergence`` violation on the
    trace that triggered the compaction."""


#: sentinel distinguishing "no durability tag at all" from an empty kind set.
_MISSING_TAG = object()


def _durability_kinds(effects: Dict[str, Dict[str, Any]], op: str):
    """Parse an op's ``durability`` tag into its declared journal-record
    kind set. ``none``/``volatile`` -> empty set (the op must emit no
    journal records), ``journal:<k1,k2>`` -> {k1, k2}, ``composite`` ->
    None (batch: checked against the union of its sub-ops), missing ->
    ``_MISSING_TAG``."""
    tag = effects.get(op, {}).get("durability")
    if tag is None:
        return _MISSING_TAG
    if tag in ("none", "volatile"):
        return set()
    if tag == "composite":
        return None
    if isinstance(tag, str) and tag.startswith("journal:"):
        kinds = {k.strip() for k in tag[len("journal:"):].split(",") if k.strip()}
        bad = kinds - set(_JOURNAL_KINDS)
        if bad:
            raise ModelCheckError(
                f"state_effects[{op!r}] durability tag names unknown "
                f"journal kind(s) {sorted(bad)} — known: {_JOURNAL_KINDS}"
            )
        return kinds
    raise ModelCheckError(
        f"state_effects[{op!r}] durability tag {tag!r} is malformed — "
        "expected journal:<kinds>, volatile, none, or composite"
    )


@dataclass(frozen=True)
class ScriptOp:
    """One scripted client op. ``note`` tags fault injections ("dup",
    "restart") for trace rendering; semantics live entirely in op+fields."""

    op: str
    fields: Tuple[Tuple[str, Any], ...] = ()
    note: str = ""

    @staticmethod
    def make(op: str, note: str = "", **fields: Any) -> "ScriptOp":
        frozen = []
        for k in sorted(fields):
            v = fields[k]
            if isinstance(v, list):
                v = tuple(
                    tuple(sorted(d.items())) if isinstance(d, dict) else d
                    for v_ in [v] for d in v_
                )
            frozen.append((k, v))
        return ScriptOp(op=op, fields=tuple(frozen), note=note)

    def field_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for k, v in self.fields:
            if isinstance(v, tuple) and v and isinstance(v[0], tuple):
                # list-of-dicts (batch sub-ops) round-trips through tuples
                out[k] = [dict(item) for item in v]
            elif isinstance(v, tuple):
                out[k] = list(v)
            else:
                out[k] = v
        return out

    def render(self) -> str:
        parts = ", ".join(
            f"{k}={v!r}" for k, v in self.fields if k != "ops"
        )
        tag = f" [{self.note}]" if self.note else ""
        return f"{self.op}({parts}){tag}"


@dataclass
class Violation:
    kind: str  # epoch-monotonicity | exactly-once | lease-exclusivity |
    #            progress | oracle-divergence | conservation |
    #            acked-durability | snapshot-divergence
    message: str
    trace: str  # stable rendering of the schedule that produced it
    schedule: str = ""  # named schedule that produced it ("" for ad-hoc)
    order: Tuple[str, ...] = ()  # worker step order, for --dump-trace

    def key(self) -> Tuple[str, str]:
        return (self.kind, self.trace)


@dataclass
class ModelCheckResult:
    traces: int = 0
    replays: int = 0
    violations: List[Violation] = field(default_factory=list)
    #: per-schedule (name, traces, seconds) rows — the --timings split.
    timings: List[Tuple[str, int, float]] = field(default_factory=list)

    def ok(self) -> bool:
        return not self.violations

    def violation_keys(self) -> set:
        return {v.key() for v in self.violations}


# -- the abstract model --------------------------------------------------------


class ProtocolModel:
    """Explicit-state interpreter for the coordinator protocol, driven by the
    ``state_effects`` declarations. Predicts, for every (worker, op, fields)
    event, the reply the real coordinator must produce; the oracle replay
    checks the prediction. Time never passes: leases and heartbeats cannot
    expire, which matches the replay coordinator's near-infinite TTLs."""

    _KNOWN_TAGS = {
        "epoch", "lease", "dedup", "kv", "queue", "membership", "parks",
        "composite", "shard", "watch", "routing", "durability", "preempt",
    }

    def __init__(self, effects: Dict[str, Dict[str, Any]],
                 shard_endpoints: Optional[Sequence[str]] = None,
                 durable: bool = False,
                 compact_every: Optional[int] = None):
        for op, tags in effects.items():
            unknown = set(tags) - self._KNOWN_TAGS
            if unknown:
                raise ModelCheckError(
                    f"state_effects[{op!r}] has unknown tag(s): "
                    f"{sorted(unknown)}"
                )
        self.effects = effects
        # Durable half (EDL010): the journal as committed frames. Volatile
        # state is everything below; the journal is what a crash preserves.
        self.durable = durable
        self.compact_every = compact_every
        if durable:
            for op in effects:
                if _durability_kinds(effects, op) is _MISSING_TAG:
                    raise ModelCheckError(
                        f"state_effects[{op!r}] has no durability tag — "
                        "every op needs one before the durability model "
                        "can run (journal:<kinds>, volatile, none, or "
                        "composite)"
                    )
        #: committed frames, each a tuple of journal records. The first
        #: frame at boot is the meta record load_state queues on a missing
        #: state file. A snapshot replaces the whole list with one frame.
        self.journal: List[Tuple[Tuple[Any, ...], ...]] = []
        self.frames = 0  # append batches (group commits), incl. boot frame
        self.records_since = 0  # journal lines since last snapshot
        self.snapshots = 0
        self._pending: List[Tuple[Any, ...]] = []  # current turn's records
        self._apply_depth = 0
        self.last_crash_info: Optional[Dict[str, Any]] = None
        if durable:
            self._append_frame((("meta", 0),))  # boot: record_epoch()
        # Sharded-ROOT mode (native --shards): with endpoints configured,
        # every keyspace op answers a redirect instead of being served.
        self.shard_endpoints: List[str] = list(shard_endpoints or [])
        self.epoch = 0
        self.members: Dict[str, int] = {}  # name -> rank
        self.next_rank = 0
        self.todo: List[str] = []
        self.leased: Dict[str, str] = {}  # task -> worker (insertion-ordered)
        self.done: set = set()
        self.acquire_cache: Dict[str, Tuple[str, str]] = {}
        self.kv: Dict[str, str] = {}
        self.barriers: Dict[str, Dict[str, Any]] = {}
        self.sync_arrived: set = set()
        self.sync_generation = 0
        # Checkpoint plane: owner -> {step, chunks, nbytes, group, data}.
        self.shards: Dict[str, Dict[str, Any]] = {}
        self.shard_put_seen: set = set()
        # Watch subscriptions: worker -> pending notification frames.
        self.watch_queues: Dict[str, List[Dict[str, Any]]] = {}
        # Pending advance-notice revocations: worker -> {notice_s, reason,
        # seq}. Volatile (native preempts_ is never journaled).
        self.preempts: Dict[str, Dict[str, Any]] = {}
        self.preempt_seq = 0

    def copy(self) -> "ProtocolModel":
        m = ProtocolModel.__new__(ProtocolModel)
        m.effects = self.effects
        m.durable = self.durable
        m.compact_every = self.compact_every
        m.journal = list(self.journal)  # frames are immutable tuples
        m.frames = self.frames
        m.records_since = self.records_since
        m.snapshots = self.snapshots
        m._pending = list(self._pending)
        m._apply_depth = self._apply_depth
        m.last_crash_info = self.last_crash_info
        m.shard_endpoints = list(self.shard_endpoints)
        m.epoch = self.epoch
        m.members = dict(self.members)
        m.next_rank = self.next_rank
        m.todo = list(self.todo)
        m.leased = dict(self.leased)
        m.done = set(self.done)
        m.acquire_cache = dict(self.acquire_cache)
        m.kv = dict(self.kv)
        m.barriers = {
            k: {"arrived": set(v["arrived"]), "generation": v["generation"],
                "want": v["want"]}
            for k, v in self.barriers.items()
        }
        m.sync_arrived = set(self.sync_arrived)
        m.sync_generation = self.sync_generation
        m.shards = {
            owner: {"step": b["step"], "chunks": b["chunks"],
                    "nbytes": b["nbytes"], "group": list(b["group"]),
                    "data": dict(b["data"])}
            for owner, b in self.shards.items()
        }
        m.shard_put_seen = set(self.shard_put_seen)
        m.watch_queues = {
            w: [dict(f) for f in q] for w, q in self.watch_queues.items()
        }
        m.preempts = {w: dict(p) for w, p in self.preempts.items()}
        m.preempt_seq = self.preempt_seq
        return m

    # Every handler returns (reply_prediction | None-if-parked, released)
    # where released is [(worker, reply_prediction), ...] for parked ops
    # this event unblocked.

    def apply(self, worker: str, op: str, fields: Dict[str, Any]):
        if op == CRASH_OP:
            if self._apply_depth:
                raise ModelCheckError("crash cannot nest inside batch")
            return self._op_crash(worker, fields)
        if op not in self.effects:
            raise ModelCheckError(
                f"op {op!r} has no state_effects entry in the schema"
            )
        handler = getattr(self, f"_op_{op}", None)
        if handler is None:
            raise ModelCheckError(f"model has no handler for op {op!r}")
        self._apply_depth += 1
        try:
            out = handler(worker, fields)
        finally:
            self._apply_depth -= 1
        if self.durable and self._apply_depth == 0:
            self._check_durability_tag(op, fields)
            self._commit_frame()
        return out

    # -- durable plane: journal frames, commit, snapshot, recovery replay ------

    def _rec(self, *record: Any) -> None:
        """Emit one journal record into the current turn's frame. Handlers
        call this exactly where the native server calls its ``record_*``
        helpers; a no-op outside durable mode."""
        if self.durable:
            self._pending.append(tuple(record))

    def _check_durability_tag(self, op: str, fields: Dict[str, Any]) -> None:
        """Dynamic half of the durability ratchet: the records an op's
        handler actually emitted must be covered by its declared tag (batch
        checks against the union of its sub-ops' tags)."""
        emitted = {r[0] for r in self._pending}
        if not emitted:
            return
        allowed = _durability_kinds(self.effects, op)
        if allowed is None:  # composite: union over sub-ops
            allowed = set()
            for sub in fields.get("ops", []) or []:
                sub_kinds = _durability_kinds(self.effects,
                                              sub.get("op", ""))
                if isinstance(sub_kinds, set):
                    allowed |= sub_kinds
        if allowed is _MISSING_TAG or emitted - allowed:
            raise ModelCheckError(
                f"durability tag drift: op {op!r} emitted journal "
                f"record kind(s) {sorted(emitted)} but its durability tag "
                f"declares {sorted(allowed) if isinstance(allowed, set) else 'nothing'}"
            )

    def _append_frame(self, frame: Tuple[Tuple[Any, ...], ...]) -> None:
        """Group-commit one frame: append + trailing commit marker + fsync
        (the marker is implicit here — `len(frame) + 1` records — and a
        literal ``{"k":"c"}`` line on disk in both real journals)."""
        self.journal.append(frame)
        self.frames += 1
        self.records_since += len(frame) + 1

    def _commit_frame(self) -> None:
        """End of an event-loop turn: group-commit the pending records —
        or, past the compaction threshold, fold everything into a snapshot
        (the native ``maybe_save_state`` shape: the threshold is checked
        BEFORE appending, and the snapshot covers the pending effects
        because in-memory state already has them)."""
        if not self._pending:
            return
        frame = tuple(self._pending)
        self._pending = []
        if (self.compact_every is not None
                and self.records_since >= self.compact_every):
            self._compact()
        else:
            self._append_frame(frame)

    def _compact(self) -> None:
        """Snapshot the durable projection of current state into a single
        frame, replacing the journal — with the snapshot⊕journal-suffix
        self-check: replaying the new journal must reconstruct exactly the
        state replaying the old one did (plus anything the pending frame
        just folded in, i.e. current state)."""
        snap = self._snapshot_frame()
        self.journal = [snap]
        self.snapshots += 1
        self.records_since = 0
        replayed = self._replay_journal(self.journal)
        now = self._durable_projection()
        if replayed != now:
            raise _SnapshotDivergence(
                f"snapshot replay diverges from live durable state: "
                f"{replayed!r} != {now!r}"
            )

    def _snapshot_frame(self) -> Tuple[Tuple[Any, ...], ...]:
        """The native ``save_snapshot`` layout: meta, todo (live queue
        order), one lease line per held lease (sorted by task, carrying the
        holder's cached req_id when it names this task), done, kv lines
        (sorted)."""
        records: List[Tuple[Any, ...]] = [("meta", self.epoch)]
        if self.todo:
            records.append(("todo", tuple(self.todo)))
        req_of = {}
        for w, (req, task) in self.acquire_cache.items():
            req_of[(task, w)] = req
        for task in sorted(self.leased):
            w = self.leased[task]
            records.append(("lease", task, w, req_of.get((task, w), "")))
        for task in sorted(self.done):
            records.append(("done", task))
        for key in sorted(self.kv):
            records.append(("kv", key, self.kv[key]))
        return tuple(records)

    def _durable_projection(self) -> Tuple[Any, ...]:
        """The journaled slice of live state, in recovery-normal form —
        what a crash right now must reconstruct. Note the todo ORDER is the
        live queue order only until records replay through first-mention
        order; the projection therefore compares sets for todo except
        after a snapshot, where the snapshot pins live order."""
        return (
            self.epoch,
            tuple(sorted(self.todo)),
            tuple(sorted(self.leased.items())),
            tuple(sorted(self.done)),
            tuple(sorted(self.kv.items())),
        )

    def _replay_journal(self, frames: Sequence[Tuple[Tuple[Any, ...], ...]]):
        """Mirror of the native ``load_state`` two-phase replay, in
        recovery-normal form (matching ``_durable_projection``)."""
        epoch = 0
        todo_order: List[str] = []
        seen: Set[str] = set()
        lease_of: Dict[str, str] = {}
        done: Set[str] = set()
        kv: Dict[str, Any] = {}
        for frame in frames:
            for rec in frame:
                kind = rec[0]
                if kind == "meta":
                    epoch = int(rec[1])
                elif kind == "todo":
                    for t in rec[1]:
                        if t not in seen:
                            seen.add(t)
                            todo_order.append(t)
                elif kind == "lease":
                    t = rec[1]
                    if t not in seen:  # lease implies the task exists
                        seen.add(t)
                        todo_order.append(t)
                    lease_of[t] = rec[2]
                elif kind == "done":
                    done.add(rec[1])
                elif kind == "kv":
                    kv[rec[1]] = rec[2]
                elif kind == "kvdel":
                    kv.pop(rec[1], None)
        todo = [t for t in todo_order
                if t not in done and not lease_of.get(t)]
        leased = {t: w for t, w in lease_of.items()
                  if w and t not in done}
        return (
            epoch,
            tuple(sorted(todo)),
            tuple(sorted(leased.items())),
            tuple(sorted(done)),
            tuple(sorted(kv.items())),
        )

    def _recover(self) -> None:
        """``load_state`` semantics on the committed journal: durable state
        replayed, epoch bumped (a restart IS a membership event), every
        volatile table wiped — members, barriers, sync parks, the shard
        store and its put_id dedup (ladder honesty: gone, not lied about),
        watch subscriptions — and the acquire req_id cache REBUILT from the
        journaled lease records (dedup tables are durable state)."""
        epoch = 0
        todo_order: List[str] = []
        seen: Set[str] = set()
        lease_of: Dict[str, str] = {}
        done: Set[str] = set()
        kv: Dict[str, Any] = {}
        cache: Dict[str, Tuple[str, str]] = {}
        for frame in self.journal:
            for rec in frame:
                kind = rec[0]
                if kind == "meta":
                    epoch = int(rec[1])
                elif kind == "todo":
                    for t in rec[1]:
                        if t not in seen:
                            seen.add(t)
                            todo_order.append(t)
                elif kind == "lease":
                    t, w = rec[1], rec[2]
                    req = rec[3] if len(rec) > 3 else ""
                    if t not in seen:
                        seen.add(t)
                        todo_order.append(t)
                    lease_of[t] = w
                    if w and req:
                        cache[w] = (req, t)
                elif kind == "done":
                    done.add(rec[1])
                elif kind == "kv":
                    kv[rec[1]] = rec[2]
                elif kind == "kvdel":
                    kv.pop(rec[1], None)
        self.epoch = epoch + 1
        self.todo = [t for t in todo_order
                     if t not in done and not lease_of.get(t)]
        self.leased = {t: w for t, w in lease_of.items()
                       if w and t not in done}
        self.done = done
        self.kv = kv
        self.acquire_cache = cache
        self.members = {}
        self.next_rank = 0
        self.barriers = {}
        self.sync_arrived = set()
        self.sync_generation = 0
        self.shards = {}
        self.shard_put_seen = set()
        self.watch_queues = {}
        # preempt notices are volatile: a restarted coordinator forgets
        # them and the scheduler re-issues (ladder honesty, like shards).
        self.preempts = {}
        self.preempt_seq = 0
        # boot of the new incarnation: load_state queues record_epoch();
        # crash-injection env does not survive the restart, so compaction
        # reverts to the (never-reached) native default threshold.
        self.compact_every = None
        self.records_since = sum(len(f) + 1 for f in self.journal)
        self._append_frame((("meta", self.epoch),))

    def durability_counters(self) -> Dict[str, int]:
        return {"frames": self.frames, "records": self.records_since,
                "snapshots": self.snapshots}

    def _op_crash(self, worker: str, fields: Dict[str, Any]):
        if not self.durable:
            raise ModelCheckError(
                "crash op scheduled outside a durable schedule"
            )
        mode = fields.get("mode", "clean")
        if mode not in CRASH_MODES:
            raise ModelCheckError(
                f"crash mode {mode!r} — expected one of {CRASH_MODES}"
            )
        inflight = fields.get("inflight") or []
        if mode == "clean" and inflight:
            raise ModelCheckError("crash(clean) takes no inflight ops")
        if mode != "clean" and len(inflight) != 1:
            raise ModelCheckError(
                f"crash({mode}) needs exactly one inflight op (one frame)"
            )
        if mode != "clean" and self.compact_every is not None:
            raise ModelCheckError(
                f"crash({mode}) cannot combine with a compact_every "
                "schedule — the inflight frame's append/snapshot fate "
                "would depend on the interleaving"
            )
        info: Dict[str, Any] = {
            "mode": mode,
            "inflight": [dict(s) for s in inflight],
            "frames_before": self.frames,
            "records_before": self.records_since,
            "snapshots_before": self.snapshots,
        }
        # Hold the apply depth up while applying the inflight op, so the
        # nested apply() does NOT auto-commit its frame — the whole point
        # is that this frame's fate (append / discard) is the crash mode's
        # to decide.
        self._apply_depth += 1
        try:
            for spec in inflight:
                sub = dict(spec)
                sub_op = sub.pop("op", "")
                sub_worker = sub.pop("worker", worker)
                if any(v == LAST_TASK for v in sub.values()):
                    raise ModelCheckError(
                        "inflight crash ops cannot use LAST_TASK"
                    )
                reply, released = self.apply(sub_worker, sub_op, sub)
                if reply is None or released:
                    raise ModelCheckError(
                        f"inflight crash op {sub_op!r} parked or released — "
                        "only plain request/reply ops can ride a crash frame"
                    )
        finally:
            self._apply_depth -= 1
        frame = tuple(self._pending)
        self._pending = []
        info["inflight_records"] = len(frame)
        if mode == "pre_ack" and frame:
            # appended + fsynced, reply never flushed: effects are durable.
            # (Schedules never combine pre_ack with compact_every, so this
            # is always an append, never a snapshot.)
            self._append_frame(frame)
        # torn / during_compaction: the frame never commits — recovery
        # must show NONE of its effects. An empty frame (the inflight op
        # deduplicated, journaling nothing) degrades every mode to clean.
        self._recover()
        info["epoch_after"] = self.epoch
        self.last_crash_info = info
        return {"ok": True, "crash": mode, "epoch": self.epoch}, []

    def _membership_reply(self, worker: str) -> Dict[str, Any]:
        rank = self.members.get(worker, -1)
        return {"ok": True, "rank": rank, "epoch": self.epoch,
                "world": len(self.members)}

    def _redirect(self, key: Any) -> Optional[Dict[str, Any]]:
        """Redirect prediction for a keyspace op on a sharded ROOT; None on
        a plain coordinator. Mirrors the twin's ``redirect_for`` (which
        mirrors the native ``redirect_reply``), including the epoch stamp
        and the answer-before-validation placement."""
        if not self.shard_endpoints:
            return None
        s = shard_of(str(key), len(self.shard_endpoints))
        return {"ok": False, "error": "wrong shard",
                "redirect": self.shard_endpoints[s], "shard": s,
                "epoch": self.epoch}

    def _notify_frame(self, e: int) -> Dict[str, Any]:
        return {"ok": True, "notify": "epoch", "epoch": int(e),
                "cursor": int(e), "world": len(self.members)}

    def _notify_watchers(self) -> None:
        """Epoch moved: one notification frame per live subscription."""
        for q in self.watch_queues.values():
            q.append(self._notify_frame(self.epoch))

    def _preempt_frame(self, worker: str) -> Dict[str, Any]:
        p = self.preempts[worker]
        return {"ok": True, "notify": "preempt", "worker": worker,
                "notice_s": p["notice_s"], "reason": p["reason"],
                "seq": p["seq"], "epoch": self.epoch,
                "cursor": self.epoch, "world": len(self.members)}

    def _requeue_worker_leases(self, worker: str) -> None:
        stale = [t for t, w in self.leased.items() if w == worker]
        for t in stale:
            del self.leased[t]
            self.todo.append(t)
            self._rec("lease", t, "", "")  # native: record_lease(task, "")

    def _release_sync_on_epoch_change(self) -> List[Tuple[str, Dict]]:
        """Membership moved (epoch already bumped): every parked sync wakes
        and observes the epoch mismatch — resync replies."""
        released = [
            (w, {"ok": False, "resync": True, "epoch": self.epoch,
                 "world": len(self.members)})
            for w in sorted(self.sync_arrived)
        ]
        self.sync_arrived = set()
        return released

    def _op_register(self, worker: str, fields: Dict[str, Any]):
        released: List[Tuple[str, Dict]] = []
        tags = self.effects["register"]
        if fields.get("takeover") and tags.get("lease") == "requeue_on_takeover":
            self._requeue_worker_leases(worker)
        if worker not in self.members:
            self.members[worker] = self.next_rank
            self.next_rank += 1
            if tags.get("epoch") == "bump_on_join":
                self.epoch += 1
                self._rec("meta", self.epoch)  # native: bump_epoch() records
                self._notify_watchers()
                released = self._release_sync_on_epoch_change()
        return self._membership_reply(worker), released

    def _op_heartbeat(self, worker: str, fields: Dict[str, Any]):
        if worker not in self.members:
            return ({"ok": False, "error": "unknown worker",
                     "epoch": self.epoch}, [])
        return self._membership_reply(worker), []

    def _op_leave(self, worker: str, fields: Dict[str, Any]):
        # The shim binds leave to the calling client's own worker name; the
        # "worker" request field is envelope, not a target selector.
        target = worker
        released: List[Tuple[str, Dict]] = []
        if target in self.members:
            del self.members[target]
            ranked = sorted(self.members.items(), key=lambda kv: kv[1])
            for r, (name, _) in enumerate(ranked):
                self.members[name] = r
            self.next_rank = len(self.members)
            if self.effects["leave"].get("epoch") == "bump_on_drop":
                self.epoch += 1
                self._rec("meta", self.epoch)
                self._notify_watchers()
            self._requeue_worker_leases(target)
            self.acquire_cache.pop(target, None)
            self.preempts.pop(target, None)  # departure consumes the notice
            released = self._release_sync_on_epoch_change()
        return {"ok": True, "epoch": self.epoch}, released

    def _op_members(self, worker: str, fields: Dict[str, Any]):
        names = [n for n, _ in sorted(self.members.items(),
                                      key=lambda kv: kv[1])]
        return {"ok": True, "members": names, "epoch": self.epoch}, []

    def _op_ping(self, worker: str, fields: Dict[str, Any]):
        return {"ok": True, "pong": True, "epoch": self.epoch}, []

    def _op_add_tasks(self, worker: str, fields: Dict[str, Any]):
        tasks = fields.get("tasks") or []
        r = self._redirect(str(tasks[0]) if tasks else "")
        if r:
            return r, []
        fresh = []
        for t in fields.get("tasks", []):
            if t in self.done or t in self.leased or t in self.todo:
                continue
            self.todo.append(t)
            fresh.append(t)
        if fresh:  # native record_todo skips the empty list
            self._rec("todo", tuple(fresh))
        added = len(fresh)
        return ({"ok": True, "added": added, "queued": len(self.todo),
                 "epoch": self.epoch}, [])

    def _op_acquire_task(self, worker: str, fields: Dict[str, Any]):
        r = self._redirect(worker)
        if r:
            return r, []
        req_id = fields.get("req_id")
        if req_id and self.effects["acquire_task"].get("dedup") == "req_id":
            cached = self.acquire_cache.get(worker)
            if cached and cached[0] == req_id:
                task = cached[1]
                if self.leased.get(task) == worker:
                    return ({"ok": True, "task": task, "duplicate": True,
                             "epoch": self.epoch}, [])
        if not self.todo:
            return ({"ok": True, "task": None,
                     "exhausted": not self.leased, "epoch": self.epoch}, [])
        task = self.todo.pop(0)
        self.leased[task] = worker
        # journaling the req_id with the lease is THE durability fix for
        # exactly-once across crash: the cache rebuilds from this record.
        self._rec("lease", task, worker, req_id or "")
        if req_id:
            self.acquire_cache[worker] = (req_id, task)
        return {"ok": True, "task": task, "epoch": self.epoch}, []

    def _op_complete_task(self, worker: str, fields: Dict[str, Any]):
        task = fields.get("task")
        r = self._redirect(task)
        if r:
            return r, []
        if task in self.done:
            return ({"ok": True, "duplicate": True, "done": len(self.done),
                     "queued": len(self.todo), "epoch": self.epoch}, [])
        if task not in self.leased:
            if task in self.todo:
                self.todo.remove(task)
                self.done.add(task)
                self._rec("done", task)
                return ({"ok": True, "requeued": True,
                         "done": len(self.done), "queued": len(self.todo),
                         "epoch": self.epoch}, [])
            return ({"ok": False, "error": "not leased",
                     "epoch": self.epoch}, [])
        if self.leased[task] != worker:
            return ({"ok": False, "error": "lease not owned",
                     "epoch": self.epoch}, [])
        del self.leased[task]
        self.done.add(task)
        self._rec("done", task)
        return ({"ok": True, "done": len(self.done),
                 "queued": len(self.todo), "epoch": self.epoch}, [])

    def _op_fail_task(self, worker: str, fields: Dict[str, Any]):
        task = fields.get("task")
        r = self._redirect(task)
        if r:
            return r, []
        if task not in self.leased:
            return ({"ok": False, "error": "not leased",
                     "epoch": self.epoch}, [])
        if self.leased[task] != worker:
            return ({"ok": False, "error": "lease not owned",
                     "epoch": self.epoch}, [])
        del self.leased[task]
        self.todo.append(task)
        self._rec("lease", task, "", "")
        return {"ok": True, "epoch": self.epoch}, []

    def _op_kv_put(self, worker: str, fields: Dict[str, Any]):
        key = fields.get("key")
        r = self._redirect(key or "")
        if r:
            return r, []
        if not key:
            return ({"ok": False, "error": "key required",
                     "epoch": self.epoch}, [])
        self.kv[key] = fields.get("value")
        self._rec("kv", key, self.kv[key])
        return {"ok": True, "epoch": self.epoch}, []

    def _op_kv_get(self, worker: str, fields: Dict[str, Any]):
        r = self._redirect(fields.get("key") or "")
        if r:
            return r, []
        return ({"ok": True, "value": self.kv.get(fields.get("key")),
                 "epoch": self.epoch}, [])

    def _op_kv_del(self, worker: str, fields: Dict[str, Any]):
        r = self._redirect(fields.get("key") or "")
        if r:
            return r, []
        key = fields.get("key")
        if key in self.kv:  # native records only when the erase took
            del self.kv[key]
            self._rec("kvdel", key)
        return {"ok": True, "epoch": self.epoch}, []

    def _op_kv_incr(self, worker: str, fields: Dict[str, Any]):
        key = fields.get("key", "")
        r = self._redirect(key)
        if r:
            return r, []
        if not key:
            return ({"ok": False, "error": "key required",
                     "epoch": self.epoch}, [])
        op_id = fields.get("op_id")
        marker = f"__edl_op/{op_id}" if op_id else None
        if (marker and marker in self.kv
                and self.effects["kv_incr"].get("dedup") == "op_id"):
            return ({"ok": True, "value": int(self.kv[marker]),
                     "duplicate": True, "epoch": self.epoch}, [])
        cur = int(self.kv.get(key, "0") or "0") + int(fields.get("delta", 1))
        self.kv[key] = str(cur)
        # value record + marker record ride ONE frame: the torn-tail
        # schedule exists to prove they live or die together.
        self._rec("kv", key, str(cur))
        if marker:
            self.kv[marker] = str(cur)
            self._rec("kv", marker, str(cur))
        return {"ok": True, "value": cur, "epoch": self.epoch}, []

    # Checkpoint-plane ops (memory-resident shard replication). Mirror the
    # twin's shard_* methods exactly: step supersedes, put_id dedups
    # exactly-once, drop with a step only removes that exact step. None of
    # them touch the epoch or park.

    def _op_shard_put(self, worker: str, fields: Dict[str, Any]):
        owner = fields.get("owner", "")
        r = self._redirect(owner)
        if r:
            return r, []
        step = int(fields.get("step", -1))
        chunk = int(fields.get("chunk", -1))
        chunks = int(fields.get("chunks", 0))
        if not owner or step < 0 or chunks < 1 or not 0 <= chunk < chunks:
            return ({"ok": False,
                     "error": "shard_put requires owner, step>=0, "
                              "0<=chunk<chunks",
                     "epoch": self.epoch}, [])
        put_id = fields.get("put_id")
        if (put_id and put_id in self.shard_put_seen
                and self.effects["shard_put"].get("dedup") == "put_id"):
            return ({"ok": True, "duplicate": True, "stored": True,
                     "epoch": self.epoch}, [])
        blob = self.shards.setdefault(
            owner, {"step": -1, "chunks": 0, "nbytes": 0,
                    "group": [], "data": {}})
        if step < blob["step"]:
            return ({"ok": True, "duplicate": False, "stored": False,
                     "epoch": self.epoch}, [])
        if step > blob["step"]:
            blob["step"] = step
            blob["data"] = {}
            blob["group"] = []
        blob["chunks"] = chunks
        blob["nbytes"] = int(fields.get("nbytes", 0))
        group = fields.get("group")
        if isinstance(group, list):
            blob["group"] = [str(g) for g in group]
        blob["data"][chunk] = fields.get("data", "")
        if put_id:
            self.shard_put_seen.add(put_id)
        return ({"ok": True, "duplicate": False, "stored": True,
                 "epoch": self.epoch}, [])

    def _op_shard_get(self, worker: str, fields: Dict[str, Any]):
        owner = fields.get("owner", "")
        r = self._redirect(owner)
        if r:
            return r, []
        step = int(fields.get("step", -1))
        chunk = int(fields.get("chunk", 0))
        blob = self.shards.get(owner)
        if blob is None or (step >= 0 and blob["step"] != step):
            return ({"ok": True, "found": False, "data": "", "chunks": 0,
                     "epoch": self.epoch}, [])
        payload = blob["data"].get(chunk)
        if payload is None:
            return ({"ok": True, "found": False, "data": "",
                     "chunks": blob["chunks"], "epoch": self.epoch}, [])
        return ({"ok": True, "found": True, "data": payload,
                 "chunks": blob["chunks"], "epoch": self.epoch}, [])

    def _op_shard_meta(self, worker: str, fields: Dict[str, Any]):
        r = self._redirect(fields.get("owner", ""))
        if r:
            return r, []
        blob = self.shards.get(fields.get("owner", ""))
        if blob is None or blob["step"] < 0:
            return ({"ok": True, "found": False, "step": -1, "chunks": 0,
                     "nbytes": 0, "complete": False, "group": [],
                     "epoch": self.epoch}, [])
        complete = blob["chunks"] > 0 and len(blob["data"]) == blob["chunks"]
        return ({"ok": True, "found": True, "step": blob["step"],
                 "chunks": blob["chunks"], "nbytes": blob["nbytes"],
                 "complete": complete, "group": list(blob["group"]),
                 "epoch": self.epoch}, [])

    def _op_shard_drop(self, worker: str, fields: Dict[str, Any]):
        owner = fields.get("owner", "")
        r = self._redirect(owner)
        if r:
            return r, []
        step = int(fields.get("step", -1))
        blob = self.shards.get(owner)
        dropped = False
        if blob is not None and (step < 0 or blob["step"] == step):
            del self.shards[owner]
            dropped = True
        return {"ok": True, "dropped": dropped, "epoch": self.epoch}, []

    def _op_bump_epoch(self, worker: str, fields: Dict[str, Any]):
        self.epoch += 1
        self._rec("meta", self.epoch)
        self._notify_watchers()
        released = self._release_sync_on_epoch_change()
        return {"ok": True, "epoch": self.epoch}, released

    def _op_status(self, worker: str, fields: Dict[str, Any]):
        return ({"ok": True, "epoch": self.epoch,
                 "world": len(self.members), "queued": len(self.todo),
                 "leased": len(self.leased), "done": len(self.done),
                 "preempts": sorted(
                     f"{w}={int(p['notice_s'])}"
                     for w, p in self.preempts.items())}, [])

    def _op_preempt_notice(self, worker: str, fields: Dict[str, Any]):
        targets = fields.get("targets")
        if not isinstance(targets, list) or not targets:
            return ({"ok": False, "error": "targets array required",
                     "epoch": self.epoch}, [])
        notice_s = float(fields.get("notice_s", 0) or 0)
        reason = fields.get("reason") or "preempt"
        revoked: List[str] = []
        for t in targets:
            t = str(t)
            self.preempt_seq += 1
            self.preempts[t] = {"notice_s": notice_s, "reason": reason,
                                "seq": self.preempt_seq}
            q = self.watch_queues.get(t)
            if q is not None:
                q.append(self._preempt_frame(t))
            revoked.append(t)
        return {"ok": True, "revoked": revoked, "epoch": self.epoch}, []

    # Watch/notify ops (push-based epoch discovery). The twin has no socket
    # to push to, so delivery is modeled the way the shim serves it: a
    # subscribe queues replayed frames for every epoch in (cursor, current],
    # epoch bumps append live frames, and ``watch`` with take=True drains
    # one frame (the in-process stand-in for the wire server's unsolicited
    # push). Frames carry the epoch being ANNOUNCED, which may be historical.

    def _op_watch(self, worker: str, fields: Dict[str, Any]):
        if fields.get("take"):
            q = self.watch_queues.get(worker)
            if not q:
                return ({"ok": True, "notify": None, "cursor": self.epoch,
                         "world": len(self.members),
                         "epoch": self.epoch}, [])
            return dict(q.pop(0)), []
        q = self.watch_queues.setdefault(worker, [])
        cursor = int(fields.get("cursor", -1))
        if cursor >= 0:
            for e in range(cursor + 1, self.epoch + 1):
                q.append(self._notify_frame(e))
        if worker in self.preempts:  # late subscriber: replay the notice
            q.append(self._preempt_frame(worker))
        return ({"ok": True, "watch": True, "cursor": self.epoch,
                 "epoch": self.epoch}, [])

    def _op_watch_cancel(self, worker: str, fields: Dict[str, Any]):
        cancelled = worker in self.watch_queues
        self.watch_queues.pop(worker, None)
        return {"ok": True, "cancelled": cancelled, "epoch": self.epoch}, []

    def _op_shard_map(self, worker: str, fields: Dict[str, Any]):
        return ({"ok": True, "root": bool(self.shard_endpoints),
                 "nshards": len(self.shard_endpoints),
                 "shards": list(self.shard_endpoints), "shard_index": -1,
                 "epoch": self.epoch}, [])

    def _op_batch(self, worker: str, fields: Dict[str, Any]):
        if not self.effects["batch"].get("composite"):
            raise ModelCheckError(
                "state_effects['batch'] lost its composite tag"
            )
        replies = []
        released: List[Tuple[str, Dict]] = []
        for sub in fields.get("ops", []):
            sub = dict(sub)
            sub_op = sub.pop("op", "")
            if sub_op in _NON_BATCHABLE:
                replies.append(
                    {"ok": False, "error": f"op not batchable: {sub_op}"})
                continue
            reply, rel = self.apply(worker, sub_op, sub)
            replies.append(reply)
            released.extend(rel)
        return ({"ok": True, "replies": replies, "epoch": self.epoch},
                released)

    # Parked ops return (None, released): the caller must park the worker.

    def _op_barrier(self, worker: str, fields: Dict[str, Any]):
        name = fields["name"]
        count = int(fields["count"])
        b = self.barriers.setdefault(
            name, {"arrived": set(), "generation": 0, "want": 0})
        if not b["arrived"]:
            b["want"] = count
        elif count != b["want"]:
            return ({"ok": False, "error": "barrier count mismatch",
                     "want": b["want"], "epoch": self.epoch}, [])
        gen = b["generation"]
        b["arrived"].add(worker)
        if len(b["arrived"]) >= b["want"]:
            b["generation"] += 1
            parked = sorted(b["arrived"] - {worker})
            b["arrived"] = set()
            released = [
                (w, {"ok": True, "barrier": name, "generation": gen,
                     "epoch": self.epoch})
                for w in parked
            ]
            return ({"ok": True, "barrier": name, "generation": gen,
                     "epoch": self.epoch}, released)
        return None, []  # parked

    def _op_sync(self, worker: str, fields: Dict[str, Any]):
        if worker not in self.members:
            return ({"ok": False, "error": "unknown worker",
                     "epoch": self.epoch, "world": len(self.members)}, [])
        if int(fields["epoch"]) != self.epoch:
            return ({"ok": False, "resync": True, "epoch": self.epoch,
                     "world": len(self.members)}, [])
        self.sync_arrived.add(worker)
        if self.sync_arrived >= set(self.members):
            parked = sorted(self.sync_arrived - {worker})
            self.sync_arrived = set()
            self.sync_generation += 1
            reply = {"ok": True, "epoch": self.epoch,
                     "world": len(self.members)}
            return reply, [(w, dict(reply)) for w in parked]
        return None, []  # parked


# -- explorer ------------------------------------------------------------------


@dataclass
class _Event:
    """One scheduled op in a concrete trace, with the model's prediction."""

    worker: str
    op: ScriptOp
    fields: Dict[str, Any]  # LAST_TASK already resolved (model view)
    predicted: Optional[Dict[str, Any]]  # None while parked
    parked: bool = False
    released_at: Optional[int] = None  # index of the releasing event
    #: for CRASH_OP events: the model's crash bookkeeping (mode, inflight
    #: specs, pre-crash frame/record counters) — the oracle adapter arms
    #: its crash injection from this.
    crash_info: Optional[Dict[str, Any]] = None


def _resolve_last_task(fields: Dict[str, Any], last_task: Any):
    out = {}
    for k, v in fields.items():
        if v == LAST_TASK:
            out[k] = last_task
        elif k == "ops" and isinstance(v, list):
            out[k] = [_resolve_last_task(dict(sub), last_task) for sub in v]
        else:
            out[k] = v
    return out


def _grants_from_reply(op: str, fields: Dict[str, Any], reply: Any):
    """(task, duplicate) grant observations in a reply (incl. batch subs)."""
    if not isinstance(reply, dict):
        return
    if op == "acquire_task" and reply.get("ok") and reply.get("task"):
        yield reply["task"], bool(reply.get("duplicate")), fields.get("req_id")
    if op == "batch":
        subs = fields.get("ops", [])
        for sub, sub_reply in zip(subs, reply.get("replies", []) or []):
            sub_op = sub.get("op", "")
            yield from _grants_from_reply(sub_op, sub, sub_reply)


class _TraceState:
    """One DFS node: per-worker program counters + parked set + model."""

    def __init__(self, scripts: Dict[str, Sequence[ScriptOp]],
                 model: ProtocolModel):
        self.scripts = scripts
        self.pcs = {w: 0 for w in scripts}
        self.parked: Dict[str, int] = {}  # worker -> event index in trace
        self.last_task: Dict[str, Any] = {w: None for w in scripts}
        self.model = model
        self.trace: List[_Event] = []

    def runnable(self) -> List[str]:
        return sorted(
            w for w, pc in self.pcs.items()
            if pc < len(self.scripts[w]) and w not in self.parked
        )

    def done(self) -> bool:
        return not self.parked and all(
            pc >= len(self.scripts[w]) for w, pc in self.pcs.items()
        )

    def copy(self) -> "_TraceState":
        st = _TraceState.__new__(_TraceState)
        st.scripts = self.scripts
        st.pcs = dict(self.pcs)
        st.parked = dict(self.parked)
        st.last_task = dict(self.last_task)
        st.model = self.model.copy()
        st.trace = [
            _Event(e.worker, e.op, e.fields, e.predicted, e.parked,
                   e.released_at, e.crash_info)
            for e in self.trace
        ]
        return st

    def step(self, worker: str) -> None:
        """Advance ``worker`` one op through the model."""
        sop = self.scripts[worker][self.pcs[worker]]
        self.pcs[worker] += 1
        if sop.op == CRASH_OP and self.parked:
            raise ModelCheckError(
                "crash scheduled while a worker is parked — durable "
                "schedules must not mix crash with barrier/sync ops"
            )
        fields = _resolve_last_task(sop.field_dict(), self.last_task[worker])
        predicted, released = self.model.apply(worker, sop.op, fields)
        ev = _Event(worker=worker, op=sop, fields=fields,
                    predicted=predicted, parked=predicted is None)
        if sop.op == CRASH_OP:
            ev.crash_info = self.model.last_crash_info
        self.trace.append(ev)
        idx = len(self.trace) - 1
        if ev.parked:
            self.parked[worker] = idx
        else:
            self._note_grants(worker, sop.op, fields, predicted)
        for released_worker, reply in released:
            parked_idx = self.parked.pop(released_worker, None)
            if parked_idx is not None:
                parked_ev = self.trace[parked_idx]
                parked_ev.predicted = reply
                parked_ev.parked = False
                parked_ev.released_at = idx
                self._note_grants(released_worker, parked_ev.op.op,
                                  parked_ev.fields, reply)

    def _note_grants(self, worker, op, fields, reply):
        for task, _dup, _req in _grants_from_reply(op, fields, reply):
            self.last_task[worker] = task

    def render(self) -> str:
        return " ; ".join(f"{e.worker}:{e.op.render()}" for e in self.trace)


CoordinatorFactory = Callable[[], Any]


def _default_coordinator_factory():
    from edl_tpu.coordinator.inprocess import InProcessCoordinator

    # Time must not pass for the model: near-infinite lease/TTL windows.
    return InProcessCoordinator(task_lease_sec=1e9, heartbeat_ttl_sec=1e9)


def _replay_trace(trace: List[_Event], factory: CoordinatorFactory,
                  rendered: str, violations: List[Violation],
                  join_timeout: float = 30.0) -> None:
    """Execute the scheduled trace against a fresh coordinator and check
    model predictions + runtime invariants on the oracle's replies."""
    coord = factory()
    # Oracles that must know the crash point BEFORE the first op (the
    # native coordinator reads its crash-injection env at boot) get the
    # whole trace up front; the in-process twin has no such hook.
    begin = getattr(coord, "begin_trace", None)
    if begin is not None:
        begin(trace)
    clients = {}
    last_task: Dict[str, Any] = {}
    last_epoch: Dict[str, int] = {}
    live_grants: Dict[str, str] = {}  # task -> worker (oracle view)
    grants_by_req: Dict[Tuple[str, str], set] = {}
    pending: Dict[int, Tuple[threading.Thread, List]] = {}
    added_total = 0
    crashed = [False]  # flips at the first crash event in the trace

    def client(worker: str):
        if worker not in clients:
            clients[worker] = coord.client(worker)
        return clients[worker]

    def div_kind() -> str:
        """Model/oracle reply divergences BEFORE any crash are plain
        spec/twin drift; AFTER a crash they mean recovery reconstructed
        different durable state than a correct journal replay would — the
        acked-durability invariant."""
        return "acked-durability" if crashed[0] else "oracle-divergence"

    def requeue_events(worker: str, op: str, fields: Dict[str, Any]):
        """Lease-release points: a grant after one is a transfer, not a
        violation. Mirrors the coordinator's requeue semantics."""
        if op == "register" and fields.get("takeover"):
            for t, w in list(live_grants.items()):
                if w == worker:
                    del live_grants[t]
        if op == "leave":
            for t, w in list(live_grants.items()):
                if w == worker:
                    del live_grants[t]
        if op in ("fail_task", "complete_task"):
            live_grants.pop(fields.get("task"), None)

    def check_reply(idx: int, ev: _Event, fields: Dict[str, Any],
                    reply: Any) -> None:
        """``fields`` is the ORACLE-side resolution of the scripted op
        (LAST_TASK bound from the oracle's own reply stream)."""
        nonlocal added_total
        where = f"step {idx} ({ev.worker}:{ev.op.render()})"
        if not isinstance(reply, dict):
            violations.append(Violation(
                div_kind(),
                f"{where}: oracle returned non-dict reply {reply!r}",
                rendered))
            return
        # model prediction must be a subset of the oracle reply, epoch exact
        for key, want in (ev.predicted or {}).items():
            have = reply.get(key, "<absent>")
            if key == "replies":
                continue  # batch sub-replies compared below
            if have != want:
                violations.append(Violation(
                    div_kind(),
                    f"{where}: model predicts {key}={want!r}, oracle "
                    f"replied {key}={have!r}",
                    rendered))
        if ev.op.op == "batch":
            want_subs = (ev.predicted or {}).get("replies", [])
            have_subs = reply.get("replies", [])
            if len(want_subs) != len(have_subs):
                violations.append(Violation(
                    div_kind(),
                    f"{where}: batch sub-reply count mismatch "
                    f"(model {len(want_subs)}, oracle {len(have_subs)})",
                    rendered))
            for j, (ws, hs) in enumerate(zip(want_subs, have_subs)):
                for key, want in ws.items():
                    if not isinstance(hs, dict) or hs.get(key, "<absent>") != want:
                        violations.append(Violation(
                            div_kind(),
                            f"{where} sub-op {j}: model predicts "
                            f"{key}={want!r}, oracle replied "
                            f"{(hs or {}).get(key, '<absent>')!r}",
                            rendered))
        # invariant: per-stream epoch monotonicity. Notification frames are
        # exempt: their "epoch" names the (possibly historical) epoch being
        # announced — on the wire they ride a dedicated watch connection,
        # not the request/reply stream the invariant is defined over.
        if "epoch" in reply and not reply.get("notify"):
            ep = int(reply["epoch"])
            if ep < last_epoch.get(ev.worker, 0):
                violations.append(Violation(
                    "epoch-monotonicity",
                    f"{where}: epoch went backwards "
                    f"({last_epoch[ev.worker]} -> {ep}) on "
                    f"{ev.worker}'s reply stream",
                    rendered))
            last_epoch[ev.worker] = max(last_epoch.get(ev.worker, 0), ep)
        # invariants: exactly-once + lease exclusivity on oracle grants
        requeue_events(ev.worker, ev.op.op, fields)
        if ev.op.op == "batch":
            for sub in fields.get("ops", []):
                requeue_events(ev.worker, sub.get("op", ""), sub)
        for task, dup, req_id in _grants_from_reply(
                ev.op.op, fields, reply):
            last_task[ev.worker] = task
            if req_id:
                seen = grants_by_req.setdefault((ev.worker, req_id), set())
                seen.add(task)
                if len(seen) > 1:
                    violations.append(Violation(
                        "exactly-once",
                        f"{where}: req_id {req_id!r} was granted "
                        f"{sorted(seen)} — a replayed acquire popped a "
                        "second task instead of returning the original "
                        "lease",
                        rendered))
            if not dup:
                holder = live_grants.get(task)
                if holder is not None and holder != ev.worker:
                    violations.append(Violation(
                        "lease-exclusivity",
                        f"{where}: task {task!r} granted to {ev.worker} "
                        f"while {holder} still holds the lease",
                        rendered))
                live_grants[task] = ev.worker
        if ev.op.op == "add_tasks" and reply.get("ok"):
            added_total += int(reply.get("added", 0))
        if ev.op.op == "batch":
            for sub, sub_reply in zip(fields.get("ops", []),
                                      reply.get("replies", []) or []):
                if (sub.get("op") == "add_tasks"
                        and isinstance(sub_reply, dict)
                        and sub_reply.get("ok")):
                    added_total += int(sub_reply.get("added", 0))
        if ev.op.op == "status" and reply.get("ok"):
            # invariant: task conservation — at this point in the schedule
            # every task added so far is queued, leased, or done.
            total = (int(reply.get("queued", 0))
                     + int(reply.get("leased", 0))
                     + int(reply.get("done", 0)))
            if total != added_total:
                violations.append(Violation(
                    "conservation",
                    f"{where}: status queued+leased+done={total} != "
                    f"tasks added so far={added_total}",
                    rendered))

    oracle_fields: Dict[int, Dict[str, Any]] = {}
    for idx, ev in enumerate(trace):
        # Resolve LAST_TASK from the ORACLE's own reply stream (ev.fields is
        # the model-side resolution; the two views stay independent so a
        # grant divergence is reported once, at the acquire).
        fields = _resolve_last_task(ev.op.field_dict(),
                                    last_task.get(ev.worker))
        oracle_fields[idx] = fields
        if ev.op.op == CRASH_OP:
            if not hasattr(coord, "model_crash"):
                raise ModelCheckError(
                    "schedule contains a crash op but the oracle factory "
                    "built a coordinator without model_crash() — durable "
                    "schedules need a crash-capable oracle adapter"
                )
            reply = coord.model_crash(ev.crash_info or {})
            crashed[0] = True
            clients.clear()  # old incarnation's clients are dead
            check_reply(idx, ev, fields, reply)
            continue
        if ev.parked or ev.released_at is not None:
            holder: List = []

            def run(c=client(ev.worker), op=ev.op.op, f=fields, h=holder):
                try:
                    h.append(c.call(op, timeout=join_timeout, **f))
                except Exception as e:  # edl: noqa[EDL005] stashed in holder; join() turns it into a violation
                    h.append(e)

            th = threading.Thread(target=run, daemon=True)
            th.start()
            pending[idx] = (th, holder)
        else:
            reply = client(ev.worker).call(ev.op.op, **fields)
            check_reply(idx, ev, fields, reply)
        # join any parked ops this event released
        for pidx in [p for p in list(pending)
                     if trace[p].released_at == idx]:
            th, holder = pending.pop(pidx)
            th.join(join_timeout)
            if th.is_alive() or not holder:
                violations.append(Violation(
                    "progress",
                    f"step {pidx} ({trace[pidx].worker}:"
                    f"{trace[pidx].op.render()}): oracle did not release "
                    "the parked op the model says this event releases",
                    rendered))
                continue
            reply = holder[0]
            if isinstance(reply, Exception):
                violations.append(Violation(
                    "oracle-divergence",
                    f"step {pidx}: parked op raised {reply!r}", rendered))
                continue
            check_reply(pidx, trace[pidx], oracle_fields[pidx], reply)

    if pending:
        violations.append(Violation(
            "progress",
            f"{len(pending)} parked op(s) never released by trace end",
            rendered))

    close = getattr(coord, "close", None)
    if close is not None:
        close()  # durable oracles hold a temp state dir per replay


def _footprint(sop: ScriptOp):
    """Static footprint of a scripted op for the sleep-set POR. ``None``
    means global (conflicts with every other op): epoch writers, crash,
    batch, parked ops, the watch plane. Non-global ops commute iff their
    footprints are disjoint — replies (epoch included: nobody here bumps
    it) and the reached state are then identical in either order, so the
    pruned interleaving is trace-equivalent to an explored one."""
    op = sop.op
    f = dict(sop.fields)
    if op in ("ping", "members", "shard_map"):
        return frozenset()
    if op in ("kv_put", "kv_get", "kv_del"):
        return frozenset({("kv", f.get("key"))})
    if op == "kv_incr":
        keys = {("kv", f.get("key"))}
        if f.get("op_id"):
            keys.add(("kv", f"__edl_op/{f.get('op_id')}"))
        return frozenset(keys)
    if op in ("shard_put", "shard_get", "shard_meta", "shard_drop"):
        return frozenset({("shard", f.get("owner"))})
    if op in ("acquire_task", "add_tasks", "complete_task", "fail_task",
              "status"):
        return frozenset({("queue",)})
    return None


def _independent(a: ScriptOp, b: ScriptOp) -> bool:
    fa, fb = _footprint(a), _footprint(b)
    return fa is not None and fb is not None and not (fa & fb)


def explore(
    scripts: Dict[str, Sequence[ScriptOp]],
    effects: Dict[str, Dict[str, Any]],
    coordinator_factory: Optional[CoordinatorFactory] = None,
    max_traces: int = 20000,
    max_violations: int = 25,
    fuzz_samples: int = 0,
    fuzz_seed: int = 0,
    replay: bool = True,
    shard_endpoints: Optional[Sequence[str]] = None,
    durable: bool = False,
    compact_every: Optional[int] = None,
    por: bool = False,
    name: str = "",
) -> ModelCheckResult:
    """Enumerate interleavings of ``scripts`` (exhaustive DFS, or a seeded
    random walk when ``fuzz_samples > 0``), model-check each, and replay
    completed traces against the oracle coordinator. ``shard_endpoints``
    puts the MODEL in sharded-root mode — pair it with a factory that
    builds the oracle with the same endpoints. ``durable`` runs the
    journaled model (crash ops allowed; factory must build a crash-capable
    oracle adapter). ``por`` turns on the sleep-set partial-order
    reduction (exhaustive mode only; off under fuzz and compaction, where
    frame counting makes commutation journal-visible)."""
    factory = coordinator_factory or _default_coordinator_factory
    result = ModelCheckResult()

    def model() -> ProtocolModel:
        return ProtocolModel(effects, shard_endpoints,
                             durable=durable, compact_every=compact_every)

    def annotate(start: int, state: _TraceState) -> None:
        order = tuple(e.worker for e in state.trace)
        for v in result.violations[start:]:
            v.schedule = name
            v.order = order

    def finish(state: _TraceState) -> None:
        result.traces += 1
        rendered = state.render()
        start = len(result.violations)
        if not state.done():
            # all runnable workers parked / drained with parked remainder
            stuck = sorted(state.parked)
            result.violations.append(Violation(
                "progress",
                f"deadlock: worker(s) {stuck} parked with no releasing op "
                "left in any script",
                rendered))
            annotate(start, state)
            return  # replay would hang on the parked ops
        if replay:
            result.replays += 1
            _replay_trace(state.trace, factory, rendered, result.violations)
            annotate(start, state)

    def budget_left() -> bool:
        return (result.traces < max_traces
                and len(result.violations) < max_violations)

    def snapshot_diverged(state: _TraceState, exc: _SnapshotDivergence):
        result.traces += 1
        start = len(result.violations)
        result.violations.append(Violation(
            "snapshot-divergence", str(exc), state.render()))
        annotate(start, state)

    if fuzz_samples > 0:
        import random

        rng = random.Random(fuzz_seed)
        seen = set()
        for _ in range(fuzz_samples):
            if not budget_left():
                break
            state = _TraceState(scripts, model())
            diverged = False
            while True:
                workers = state.runnable()
                if not workers:
                    break
                try:
                    state.step(rng.choice(workers))
                except _SnapshotDivergence as exc:
                    snapshot_diverged(state, exc)
                    diverged = True
                    break
            if diverged:
                continue
            key = state.render()
            if key in seen:
                continue
            seen.add(key)
            finish(state)
        return result

    use_por = por and compact_every is None

    def next_op(state: _TraceState, worker: str) -> ScriptOp:
        return state.scripts[worker][state.pcs[worker]]

    def dfs(state: _TraceState, sleep: frozenset) -> None:
        if not budget_left():
            return
        workers = state.runnable()
        if not workers:
            finish(state)
            return
        active = [w for w in workers if w not in sleep]
        if not active:
            return  # every continuation is covered by an explored sibling
        explored: List[str] = []
        for i, worker in enumerate(active):
            branch = state if i == len(active) - 1 else state.copy()
            if use_por:
                here = next_op(state, worker)
                child_sleep = frozenset(
                    v for v in (set(sleep) | set(explored))
                    if v != worker and v in workers
                    and _independent(next_op(state, v), here)
                )
            else:
                child_sleep = frozenset()
            try:
                branch.step(worker)
            except _SnapshotDivergence as exc:
                snapshot_diverged(branch, exc)
            else:
                dfs(branch, child_sleep)
            explored.append(worker)
            if not budget_left():
                return

    dfs(_TraceState(scripts, model()), frozenset())
    return result


# -- default bounded configuration ---------------------------------------------


def default_scripts() -> Dict[str, List[ScriptOp]]:
    """The acceptance configuration: 2 workers, 13 ops including ``batch``,
    one crash+restart (register takeover), and two duplicate deliveries
    (an acquire req_id replay and a kv_incr op_id replay)."""
    mk = ScriptOp.make
    w0 = [
        mk("register", worker="w0"),
        mk("add_tasks", tasks=["t0", "t1", "t2", "t3"]),
        mk("acquire_task", req_id="w0-a1", worker="w0"),
        mk("acquire_task", note="dup", req_id="w0-a1", worker="w0"),
        mk("register", note="restart", takeover=True, worker="w0"),
        mk("batch", ops=[
            {"op": "acquire_task", "req_id": "w0-a2"},
            {"op": "kv_incr", "key": "steps", "delta": 1,
             "op_id": "w0-i1"},
        ]),
        mk("complete_task", task=LAST_TASK, worker="w0"),
    ]
    w1 = [
        mk("register", worker="w1"),
        mk("acquire_task", req_id="w1-a1", worker="w1"),
        mk("kv_incr", key="steps", delta=1, op_id="w1-i1"),
        mk("kv_incr", note="dup", key="steps", delta=1, op_id="w1-i1"),
        mk("complete_task", task=LAST_TASK, worker="w1"),
        mk("status"),
    ]
    return {"w0": w0, "w1": w1}


def ckpt_plane_scripts() -> Dict[str, List[ScriptOp]]:
    """Checkpoint-plane schedule: 2 workers exercising the shard_* ops —
    a batched two-chunk replication pass, a duplicate shard_put replay
    (exactly-once under put_id dedup), a stale put racing a newer pass, a
    chunk fetch, and a step-conditional drop. Kept separate from
    ``default_scripts`` so the combined interleaving count stays inside the
    exploration budget (adding 5 ops to the default schedule would blow it)."""
    mk = ScriptOp.make
    w0 = [
        mk("register", worker="w0"),
        mk("batch", ops=[
            {"op": "shard_put", "owner": "w0", "step": 1, "chunk": 0,
             "chunks": 2, "nbytes": 8, "data": "AAAA", "put_id": "w0-p1",
             "group": ["w1"]},
            {"op": "shard_put", "owner": "w0", "step": 1, "chunk": 1,
             "chunks": 2, "nbytes": 8, "data": "BBBB", "put_id": "w0-p2",
             "group": ["w1"]},
        ]),
        mk("shard_put", note="dup", owner="w0", step=1, chunk=0, chunks=2,
           nbytes=8, data="AAAA", put_id="w0-p1", group=["w1"]),
        mk("shard_meta", owner="w0"),
    ]
    w1 = [
        mk("register", worker="w1"),
        mk("shard_put", note="stale", owner="w0", step=0, chunk=0, chunks=1,
           nbytes=4, data="OLD", put_id="w1-p1"),
        mk("shard_get", owner="w0", step=-1, chunk=0),
        mk("shard_drop", owner="w0", step=1),
    ]
    return {"w0": w0, "w1": w1}


#: fake shard endpoints driving the redirect schedules — never dialed; the
#: sharded root (model AND twin) only hashes keys against them (FNV-1a).
SHARD_ENDPOINTS = ["10.0.0.1:7164", "10.0.0.2:7164"]


def watch_scripts() -> Dict[str, List[ScriptOp]]:
    """Watch/notify schedule: subscribe with a resume cursor, epoch bumps
    from joins and an explicit bump, frame drains interleaved with the
    bumps, a duplicate re-subscribe at a stale cursor (at-least-once
    delivery replays already-announced epochs — the model must predict the
    duplicates exactly), and a cancel. Runs against the plain twin."""
    mk = ScriptOp.make
    w0 = [
        mk("register", worker="w0"),
        mk("watch", cursor=0, worker="w0"),
        mk("watch", take=True, worker="w0"),
        mk("bump_epoch"),
        mk("watch", take=True, worker="w0"),
        mk("watch", note="dup", cursor=0, worker="w0"),
        mk("watch", take=True, worker="w0"),
        mk("watch_cancel", worker="w0"),
    ]
    w1 = [
        mk("register", worker="w1"),
        mk("shard_map"),
        mk("status"),
    ]
    return {"w0": w0, "w1": w1}


def preempt_scripts() -> Dict[str, List[ScriptOp]]:
    """Advance-notice revocation schedule: w1 issues a ``preempt_notice``
    targeting w0 while w0 subscribes/drains its watch stream — the
    interleavings cover both the live-push order (subscribe first) and the
    late-subscriber replay order (notice first), plus the malformed
    empty-targets reply, status rendering of pending revocations, and the
    departure-consumes-notice rule on leave. Runs against the plain twin
    (``take`` is the in-process drain verb, absent from the wire)."""
    mk = ScriptOp.make
    w0 = [
        mk("register", worker="w0"),
        mk("watch", cursor=0, worker="w0"),
        mk("watch", take=True, worker="w0"),
        mk("watch", take=True, worker="w0"),
        mk("status"),
        mk("leave", worker="w0"),
    ]
    w1 = [
        mk("register", worker="w1"),
        mk("preempt_notice", targets=["w0"], notice_s=30, reason="spot"),
        mk("preempt_notice", note="empty", targets=[]),
        mk("status"),
    ]
    return {"w0": w0, "w1": w1}


def watch_redirect_scripts() -> Dict[str, List[ScriptOp]]:
    """Redirect-during-watch schedule against a sharded ROOT
    (``SHARD_ENDPOINTS``): every keyspace op answers a redirect computed by
    key hash (never served), while membership, epoch bumps, and the watch
    stream stay root-local — notifications keep flowing to a subscriber
    whose data ops are being bounced to shard servers."""
    mk = ScriptOp.make
    w0 = [
        mk("register", worker="w0"),
        mk("watch", cursor=0, worker="w0"),
        mk("kv_put", key="alpha", value="1"),
        mk("bump_epoch"),
        mk("watch", take=True, worker="w0"),
        mk("shard_map"),
    ]
    w1 = [
        mk("register", worker="w1"),
        mk("acquire_task", req_id="w1-a1", worker="w1"),
        mk("add_tasks", tasks=["t0"]),
        mk("kv_get", key="beta"),
    ]
    return {"w0": w0, "w1": w1}


def _sharded_root_factory():
    from edl_tpu.coordinator.inprocess import InProcessCoordinator

    return InProcessCoordinator(task_lease_sec=1e9, heartbeat_ttl_sec=1e9,
                                shard_endpoints=list(SHARD_ENDPOINTS))


# -- durable oracle adapter ------------------------------------------------------


def _truncate_torn_tail(path: str) -> None:
    """Tear the journal's final frame: drop its commit-marker line (it
    never reached disk) and cut the last data record in half, leaving an
    unparseable tail — the on-disk shape of a crash mid-``fwrite``."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            lines = f.read().splitlines()
    except OSError:
        return
    while lines and not lines[-1].strip():
        lines.pop()
    if not lines:
        return
    last = lines.pop()
    try:
        is_marker = json.loads(last).get("k") == "c"
    except ValueError:
        is_marker = False
    if not is_marker:
        lines.append(last)  # already torn; just halve the data record
    if lines:
        lines[-1] = lines[-1][: max(1, len(lines[-1]) // 2)]
    with open(path, "w", encoding="utf-8") as f:
        for ln in lines:
            f.write(ln + "\n")


class DurableTwinOracle:
    """Crash-capable oracle adapter for durable schedules: an
    ``InProcessCoordinator`` with its state-file persistence twin enabled,
    plus ``model_crash()`` — the oracle realization of the model's crash
    pseudo-op. ``clean`` reboots from the state file; ``pre_ack`` applies
    the inflight op (its frame commits) and discards the reply; ``torn``
    applies the inflight op then tears the journal tail; and
    ``during_compaction`` arms the crash-before-commit hook so the inflight
    frame never reaches disk. ``skip_tail_scan`` is the EDL010 mutant
    knob: recovery skips torn-tail detection, replaying partial frames —
    which the acked-durability invariant must catch."""

    def __init__(self, compact_every: Optional[int] = None,
                 skip_tail_scan: bool = False,
                 disable_dedup: bool = False):
        self._dir = tempfile.mkdtemp(prefix="edl-modelcheck-")
        self._finalizer = weakref.finalize(
            self, shutil.rmtree, self._dir, True)
        self._path = os.path.join(self._dir, "coordinator_state.jsonl")
        self._skip_tail_scan = skip_tail_scan
        self._disable_dedup = disable_dedup
        self._coord = self._boot(compact_every)

    def _boot(self, compact_every: Optional[int]):
        from edl_tpu.coordinator.inprocess import InProcessCoordinator

        c = InProcessCoordinator(
            task_lease_sec=1e9, heartbeat_ttl_sec=1e9,
            state_file=self._path, run_id="modelcheck",
            compact_every=compact_every,
            skip_tail_commit_scan=self._skip_tail_scan,
        )
        if self._disable_dedup:
            c._test_disable_dedup = True
        return c

    def client(self, worker: str):
        return self._coord.client(worker)

    def model_crash(self, info: Dict[str, Any]) -> Dict[str, Any]:
        mode = info.get("mode", "clean")
        for spec in info.get("inflight", []):
            sub = dict(spec)
            sub_op = sub.pop("op", "")
            sub_worker = sub.pop("worker", "__crash__")
            if mode == "during_compaction":
                self._coord._test_crash_before_commit = True
            self._coord.client(sub_worker).call(sub_op, **sub)  # reply lost
        if mode == "torn" and info.get("inflight_records", 0) > 0:
            _truncate_torn_tail(self._path)
        # reboot: a fresh incarnation recovering from the state file. The
        # crash-injection env never survives a restart, so neither does a
        # compaction override.
        self._coord = self._boot(compact_every=None)
        status = self._coord.client("__crash__").call("status")
        return {"ok": True, "crash": mode, "epoch": status.get("epoch")}

    def close(self) -> None:
        self._finalizer()


def _durable_twin_factory():
    return DurableTwinOracle()


#: compaction threshold (journal lines incl. commit markers) for the
#: durability-compact schedule — low enough that most interleavings snapshot
#: mid-trace. The model and the twin count records identically.
_COMPACT_EVERY = 6


def _durable_compact_twin_factory():
    return DurableTwinOracle(compact_every=_COMPACT_EVERY)


def _native_oracle_factory():
    from edl_tpu.analysis.native_oracle import NativeCrashOracle

    return NativeCrashOracle()


def _native_compact_oracle_factory():
    from edl_tpu.analysis.native_oracle import NativeCrashOracle

    return NativeCrashOracle(compact_every=_COMPACT_EVERY)


def durability_base_scripts() -> Dict[str, List[ScriptOp]]:
    """Durability base schedule: a clean crash interleaved through the
    journaled op set — the DFS position of the crash op enumerates every
    crash point. Post-crash: the duplicate acquire must return the
    original lease (req_id dedup cache rebuilt from journaled lease
    records) and the completed task must conserve."""
    mk = ScriptOp.make
    w0 = [
        mk("register", worker="w0"),
        mk("add_tasks", tasks=["d0", "d1"]),
        mk("acquire_task", req_id="w0-a1", worker="w0"),
        mk("crash", mode="clean", worker="w0"),
        mk("acquire_task", note="dup", req_id="w0-a1", worker="w0"),
        mk("complete_task", task=LAST_TASK, worker="w0"),
    ]
    w1 = [
        mk("register", worker="w1"),
        mk("kv_put", key="alpha", value="1"),
        mk("kv_incr", key="steps", delta=1, op_id="w1-i1"),
        mk("kv_get", key="alpha"),
        mk("kv_del", key="alpha"),
        mk("status"),
    ]
    return {"w0": w0, "w1": w1}


def durability_dedup_scripts() -> Dict[str, List[ScriptOp]]:
    """Post-fsync survival (``pre_ack``): the inflight kv_put's frame is
    fsynced but its reply never flushes — recovery must show the value.
    The duplicate acquire and duplicate kv_incr straddle the crash point
    in some interleavings: exactly-once across crash."""
    mk = ScriptOp.make
    w0 = [
        mk("register", worker="w0"),
        mk("add_tasks", tasks=["d0", "d1"]),
        mk("acquire_task", req_id="w0-a1", worker="w0"),
        mk("crash", mode="pre_ack", worker="w0",
           inflight=[{"op": "kv_put", "key": "ck", "value": "committed"}]),
        mk("acquire_task", note="dup", req_id="w0-a1", worker="w0"),
        mk("kv_get", key="ck"),
    ]
    w1 = [
        mk("register", worker="w1"),
        mk("kv_incr", key="steps", delta=1, op_id="w1-i1"),
        mk("kv_incr", note="dup", key="steps", delta=1, op_id="w1-i1"),
        mk("status"),
    ]
    return {"w0": w0, "w1": w1}


def durability_torn_scripts() -> Dict[str, List[ScriptOp]]:
    """Pre-fsync loss (``torn``): the inflight kv_incr writes its value
    record and its op_id marker record into ONE frame, and the tail is
    torn mid-write — recovery must drop the whole frame (all-or-nothing),
    so the post-crash retry applies exactly once. A twin that skips
    torn-tail detection replays the value without the marker and
    double-applies: the mutant-teeth scenario."""
    mk = ScriptOp.make
    w0 = [
        mk("register", worker="w0"),
        mk("kv_incr", key="steps", delta=1, op_id="w0-i1"),
        mk("crash", mode="torn", worker="w0",
           inflight=[{"op": "kv_incr", "key": "steps", "delta": 1,
                      "op_id": "w0-i2"}]),
        mk("kv_incr", note="retry", key="steps", delta=1, op_id="w0-i2"),
        mk("kv_get", key="steps"),
    ]
    w1 = [
        mk("register", worker="w1"),
        mk("kv_put", key="alpha", value="1"),
        mk("kv_get", key="alpha"),
        mk("kv_del", key="alpha"),
        mk("status"),
    ]
    return {"w0": w0, "w1": w1}


def durability_compact_scripts() -> Dict[str, List[ScriptOp]]:
    """Snapshot/compaction schedule (``compact_every=_COMPACT_EVERY``):
    most interleavings cross the threshold mid-trace, so the model's
    snapshot⊕journal-suffix self-check runs at a different point per
    interleaving, and the clean crash recovers from snapshot + suffix.
    POR is off here: frame counting makes commutation journal-visible."""
    mk = ScriptOp.make
    w0 = [
        mk("register", worker="w0"),
        mk("add_tasks", tasks=["c0", "c1"]),
        mk("acquire_task", req_id="w0-a1", worker="w0"),
        mk("complete_task", task=LAST_TASK, worker="w0"),
        mk("crash", mode="clean", worker="w0"),
        mk("kv_get", key="a"),
        mk("kv_incr", key="steps", delta=1, op_id="w0-i1"),
    ]
    w1 = [
        mk("register", worker="w1"),
        mk("kv_put", key="a", value="1"),
        mk("kv_incr", key="steps", delta=1, op_id="w1-i1"),
        mk("kv_put", key="b", value="2"),
        mk("kv_del", key="b"),
        mk("status"),
    ]
    return {"w0": w0, "w1": w1}


def durability_crash_compact_scripts() -> Dict[str, List[ScriptOp]]:
    """Crash during compaction: the inflight kv_put triggers a snapshot
    that dies after the tmp write, before the rename — the journal is
    untouched and the inflight effects are lost, unacked. Recovery must
    show the pre-crash journal state exactly."""
    mk = ScriptOp.make
    w0 = [
        mk("register", worker="w0"),
        mk("kv_put", key="s1", value="v1"),
        mk("crash", mode="during_compaction", worker="w0",
           inflight=[{"op": "kv_put", "key": "s2", "value": "v2"}]),
        mk("kv_get", key="s2"),
        mk("kv_get", key="s1"),
    ]
    w1 = [
        mk("register", worker="w1"),
        mk("add_tasks", tasks=["x0"]),
        mk("acquire_task", req_id="w1-a1", worker="w1"),
        mk("status"),
    ]
    return {"w0": w0, "w1": w1}


def durability_shard_scripts() -> Dict[str, List[ScriptOp]]:
    """Ladder honesty for the deliberately-unjournaled shard store: a
    crash loses the blobs AND the put_id dedup table, so a replayed
    shard_put re-stores (duplicate=False) instead of lying about
    durability — its loss costs a recovery rung, never contradicts an
    ack."""
    mk = ScriptOp.make
    w0 = [
        mk("register", worker="w0"),
        mk("shard_put", owner="w0", step=1, chunk=0, chunks=1, nbytes=4,
           data="AAAA", put_id="w0-p1", group=["w1"]),
        mk("crash", mode="clean", worker="w0"),
        mk("shard_put", note="dup", owner="w0", step=1, chunk=0, chunks=1,
           nbytes=4, data="AAAA", put_id="w0-p1", group=["w1"]),
        mk("shard_meta", owner="w0"),
    ]
    w1 = [
        mk("register", worker="w1"),
        mk("shard_get", owner="w0", step=-1, chunk=0),
        mk("kv_put", key="k", value="v1"),
        mk("shard_meta", owner="w0"),
        mk("shard_get", owner="w0", step=-1, chunk=0),
        mk("status"),
    ]
    return {"w0": w0, "w1": w1}


def durability_preempt_scripts() -> Dict[str, List[ScriptOp]]:
    """Ladder honesty for the deliberately-unjournaled preempt table: a
    pending revocation notice is scheduler state, so a crashed coordinator
    forgets it (the scheduler re-issues) — ``status`` must show the
    pending notice before the crash and an empty table after, never a
    journal-resurrected ghost. No ``take`` frames here: this row replays
    against the native crash oracle, whose wire has no drain verb."""
    mk = ScriptOp.make
    w0 = [
        mk("register", worker="w0"),
        mk("kv_put", key="pk", value="v1"),
        mk("crash", mode="clean", worker="w0"),
        mk("status"),
        mk("kv_get", key="pk"),
    ]
    w1 = [
        mk("register", worker="w1"),
        mk("preempt_notice", targets=["w1"], notice_s=45, reason="maint"),
        mk("status"),
    ]
    return {"w0": w0, "w1": w1}


@dataclass
class Schedule:
    """One named row of the acceptance configuration: scripts + the oracle
    factory + the model knobs. ``default_schedules`` returns these;
    ``run_default`` explores each and merges results."""

    name: str
    scripts: Dict[str, List[ScriptOp]]
    factory: Optional[CoordinatorFactory] = None
    shard_endpoints: Optional[List[str]] = None
    durable: bool = False
    compact_every: Optional[int] = None
    por: bool = False


def durability_schedules() -> List[Schedule]:
    """The EDL010 rows: every journaled op crossed with enumerated crash
    points, plus the shard-store (unjournaled) schedule — all replayed
    against the file-backed persistence twin."""
    return [
        Schedule("durability-base", durability_base_scripts(),
                 _durable_twin_factory, durable=True, por=True),
        Schedule("durability-dedup", durability_dedup_scripts(),
                 _durable_twin_factory, durable=True, por=True),
        Schedule("durability-torn", durability_torn_scripts(),
                 _durable_twin_factory, durable=True, por=True),
        Schedule("durability-compact", durability_compact_scripts(),
                 _durable_compact_twin_factory, durable=True,
                 compact_every=_COMPACT_EVERY, por=False),
        Schedule("durability-crash-compact",
                 durability_crash_compact_scripts(),
                 _durable_twin_factory, durable=True, por=True),
        Schedule("durability-shard", durability_shard_scripts(),
                 _durable_twin_factory, durable=True, por=True),
        Schedule("durability-preempt", durability_preempt_scripts(),
                 _durable_twin_factory, durable=True, por=True),
    ]


def default_schedules(
    coordinator_factory: Optional[CoordinatorFactory] = None,
) -> List[Schedule]:
    """The acceptance schedules — explored separately so each stays inside
    the interleaving budget; results merge. With a caller-supplied
    ``coordinator_factory`` (the broken-twin tests) the redirect schedule
    runs UNSHARDED against that factory, and the durability rows are
    dropped entirely: a caller's factory has neither the persistence twin
    nor ``model_crash`` (durable mutants use ``explore`` directly with a
    ``DurableTwinOracle`` variant)."""
    rows = [
        Schedule("default", default_scripts(), coordinator_factory),
        Schedule("ckpt-plane", ckpt_plane_scripts(), coordinator_factory),
        Schedule("watch", watch_scripts(), coordinator_factory),
        Schedule("preempt", preempt_scripts(), coordinator_factory),
    ]
    if coordinator_factory is None:
        rows.append(Schedule("watch-redirect", watch_redirect_scripts(),
                             _sharded_root_factory,
                             shard_endpoints=list(SHARD_ENDPOINTS)))
        rows.extend(durability_schedules())
    else:
        rows.append(Schedule("watch-redirect", watch_redirect_scripts(),
                             coordinator_factory))
    return rows


def load_state_effects(root: str, schema_rel: str = "protocol_schema.json"):
    """(state_effects dict or None, declared op set or None, error string)."""
    path = os.path.join(root, schema_rel)
    try:
        with open(path, "r", encoding="utf-8") as f:
            schema = json.load(f)
    except OSError:
        return None, None, f"{schema_rel} is missing"
    except json.JSONDecodeError as e:
        return None, None, f"{schema_rel} is not valid JSON: {e}"
    effects = schema.get("state_effects")
    ops = set(schema.get("ops", {}))
    if effects is None:
        return None, ops, (
            f"{schema_rel} has no state_effects block — the behavioral "
            "spec EDL009 model-checks against"
        )
    return effects, ops, None


def run_default(
    coordinator_factory: Optional[CoordinatorFactory] = None,
    effects: Optional[Dict[str, Dict[str, Any]]] = None,
    fuzz_samples: int = 0,
    fuzz_seed: int = 0,
    max_traces: int = 20000,
    max_violations: int = 25,
    schedules: Optional[Sequence[str]] = None,
    native: bool = False,
) -> ModelCheckResult:
    """Explore the default schedule set (optionally filtered to the named
    ``schedules``) and merge results. ``result.timings`` carries one
    (name, traces, seconds) row per schedule.

    ``native=True`` swaps the durability rows' oracle for the crash-armed
    ``edl-coordinator`` subprocess (``NativeCrashOracle``) and drops the
    non-durable rows: each trace then boots/kills/restarts a real server,
    so only the crash-recovery lanes are worth the wall-clock."""
    if effects is None:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        effects, _ops, err = load_state_effects(root)
        if err:
            raise ModelCheckError(err)
    rows = default_schedules(coordinator_factory)
    if native:
        rows = [
            _dc_replace(
                s, factory=(_native_compact_oracle_factory
                            if s.compact_every else _native_oracle_factory))
            for s in rows if s.durable
        ]
    if schedules is not None:
        known = {s.name for s in rows}
        unknown = set(schedules) - known
        if unknown:
            raise ModelCheckError(
                f"unknown schedule(s) {sorted(unknown)} — "
                f"known: {sorted(known)}"
            )
        rows = [s for s in rows if s.name in set(schedules)]
    result = ModelCheckResult()
    for sched in rows:
        t0 = time.monotonic()
        extra = explore(
            sched.scripts, effects,
            coordinator_factory=sched.factory,
            fuzz_samples=fuzz_samples, fuzz_seed=fuzz_seed,
            max_traces=max_traces, max_violations=max_violations,
            shard_endpoints=sched.shard_endpoints,
            durable=sched.durable, compact_every=sched.compact_every,
            por=sched.por, name=sched.name,
        )
        result.traces += extra.traces
        result.replays += extra.replays
        result.violations.extend(extra.violations)
        result.timings.append(
            (sched.name, extra.traces, time.monotonic() - t0))
    return result


# -- trace spec round-trip (--dump-trace / --replay-trace) -----------------------


def dump_trace_spec(v: Violation,
                    schedules: Optional[List[Schedule]] = None
                    ) -> Dict[str, Any]:
    """Serialize a violating interleaving as a self-contained JSON spec
    (same round-trip discipline as ChaosScenario): the schedule's scripts,
    the exact worker step order, and the model knobs needed to re-create
    the run in isolation."""
    rows = schedules if schedules is not None else default_schedules()
    sched = next((s for s in rows if s.name == v.schedule), None)
    if sched is None:
        raise ModelCheckError(
            f"violation carries no known schedule name ({v.schedule!r}) — "
            "only violations from named schedules can be dumped"
        )
    return {
        "schedule": sched.name,
        "kind": v.kind,
        "message": v.message,
        "order": list(v.order),
        "scripts": {
            w: [{"op": s.op, "note": s.note, "fields": s.field_dict()}
                for s in ops]
            for w, ops in sched.scripts.items()
        },
        "durable": sched.durable,
        "compact_every": sched.compact_every,
        "shard_endpoints": sched.shard_endpoints,
    }


def _factory_for_spec(spec: Dict[str, Any]) -> CoordinatorFactory:
    if spec.get("durable"):
        compact = spec.get("compact_every")
        return lambda: DurableTwinOracle(compact_every=compact)
    endpoints = spec.get("shard_endpoints")
    if endpoints:
        from edl_tpu.coordinator.inprocess import InProcessCoordinator

        return lambda: InProcessCoordinator(
            task_lease_sec=1e9, heartbeat_ttl_sec=1e9,
            shard_endpoints=list(endpoints))
    return _default_coordinator_factory


def replay_trace_spec(
    spec: Dict[str, Any],
    effects: Dict[str, Dict[str, Any]],
    coordinator_factory: Optional[CoordinatorFactory] = None,
) -> List[Violation]:
    """Re-execute one dumped interleaving — the exact step order, no
    exploration — through the model and against the oracle; returns the
    violations it reproduces."""
    scripts = {
        w: [ScriptOp.make(e["op"], e.get("note", ""),
                          **(e.get("fields") or {}))
            for e in ops]
        for w, ops in spec.get("scripts", {}).items()
    }
    model = ProtocolModel(
        effects, spec.get("shard_endpoints"),
        durable=bool(spec.get("durable")),
        compact_every=spec.get("compact_every"))
    state = _TraceState(scripts, model)
    violations: List[Violation] = []
    for w in spec.get("order", []):
        try:
            state.step(w)
        except _SnapshotDivergence as exc:
            violations.append(Violation(
                "snapshot-divergence", str(exc), state.render(),
                schedule=spec.get("schedule", ""),
                order=tuple(spec.get("order", []))))
            return violations
    rendered = state.render()
    if not state.done():
        violations.append(Violation(
            "progress",
            f"deadlock: worker(s) {sorted(state.parked)} parked at spec "
            "end",
            rendered, schedule=spec.get("schedule", ""),
            order=tuple(spec.get("order", []))))
        return violations
    factory = coordinator_factory or _factory_for_spec(spec)
    _replay_trace(state.trace, factory, rendered, violations)
    for v in violations:
        v.schedule = spec.get("schedule", "")
        v.order = tuple(spec.get("order", []))
    return violations


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m edl_tpu.analysis.modelcheck",
        description=(
            "Bounded explicit-state model check of the coordinator "
            "protocol's behavioral spec (protocol_schema.json "
            "state_effects) against the in-process oracle."
        ),
    )
    parser.add_argument(
        "--fuzz", type=int, default=0, metavar="N",
        help="seeded random-walk mode: sample N schedules instead of "
             "exhaustive DFS (findings are a subset of the exhaustive run)")
    parser.add_argument(
        "--seed", type=int, default=0, help="fuzz-mode RNG seed")
    parser.add_argument(
        "--max-traces", type=int, default=20000,
        help="exploration budget (default: 20000)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable result")
    parser.add_argument(
        "--schedules", default=None, metavar="NAME,...",
        help="comma-separated schedule filter (e.g. "
             "durability-base,durability-torn); default: all")
    parser.add_argument(
        "--dump-trace", default=None, metavar="PATH",
        help="on the first violation, write the interleaving as a JSON "
             "spec replayable with --replay-trace")
    parser.add_argument(
        "--replay-trace", default=None, metavar="PATH",
        help="re-execute one dumped trace spec in isolation instead of "
             "exploring")
    parser.add_argument(
        "--timings", action="store_true",
        help="print a per-schedule (traces, seconds) split")
    parser.add_argument(
        "--native", action="store_true",
        help="replay the durability schedules against the crash-armed "
             "native edl-coordinator binary instead of the in-process "
             "persistence twin (drops the non-durable schedules; exits 0 "
             "with a notice when no C++ toolchain is on PATH)")
    args = parser.parse_args(argv)

    if args.replay_trace:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        effects, _ops, err = load_state_effects(root)
        if err:
            print(f"modelcheck: {err}")
            return 2
        with open(args.replay_trace, "r", encoding="utf-8") as f:
            spec = json.load(f)
        violations = replay_trace_spec(spec, effects)
        print(
            f"modelcheck [replay {spec.get('schedule', '?')}]: 1 trace, "
            f"{len(violations)} violation(s)"
        )
        for v in violations:
            print(f"  [{v.kind}] {v.message}")
            print(f"    trace: {v.trace}")
        return 0 if not violations else 1

    schedules = None
    if args.schedules:
        schedules = [s.strip() for s in args.schedules.split(",")
                     if s.strip()]
    if args.native:
        from edl_tpu.analysis.native_oracle import native_toolchain_available

        if not native_toolchain_available():
            print("modelcheck [native]: no C++ toolchain on PATH — "
                  "native-oracle lane skipped")
            return 0
        from edl_tpu.coordinator.server import CoordinatorError, ensure_built

        try:
            ensure_built()
        except CoordinatorError as e:
            print(f"modelcheck [native]: coordinator build failed: {e}")
            return 2
    result = run_default(
        fuzz_samples=args.fuzz, fuzz_seed=args.seed,
        max_traces=args.max_traces,
        schedules=schedules,
        native=args.native,
    )
    if args.dump_trace and result.violations:
        spec = dump_trace_spec(result.violations[0])
        with open(args.dump_trace, "w", encoding="utf-8") as f:
            json.dump(spec, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"modelcheck: first violating trace dumped to "
              f"{args.dump_trace}")
    if args.json:
        print(json.dumps({
            "traces": result.traces,
            "replays": result.replays,
            "timings": [
                {"schedule": n, "traces": t, "seconds": round(s, 3)}
                for n, t, s in result.timings
            ],
            "violations": [
                {"kind": v.kind, "message": v.message, "trace": v.trace,
                 "schedule": v.schedule}
                for v in result.violations
            ],
        }, indent=2))
    else:
        mode = f"fuzz({args.fuzz}, seed={args.seed})" if args.fuzz else "exhaustive"
        if args.native:
            mode += ", native"
        oracle = ("crash-armed edl-coordinator" if args.native
                  else "InProcessCoordinator")
        print(
            f"modelcheck [{mode}]: {result.traces} trace(s) explored, "
            f"{result.replays} replayed against {oracle}, "
            f"{len(result.violations)} violation(s)"
        )
        if args.timings:
            for n, t, s in result.timings:
                print(f"  {n}: {t} trace(s) in {s:.2f}s")
        for v in result.violations:
            print(f"  [{v.kind}] {v.message}")
            print(f"    trace: {v.trace}")
    return 0 if result.ok() else 1


if __name__ == "__main__":
    import sys

    sys.exit(main())
