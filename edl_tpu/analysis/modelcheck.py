"""Protocol state-machine model checker (the engine behind EDL009).

EDL007 proved the coordinator protocol's *shape* agrees across the C++
server, the wire client, and the in-process twin. This module checks its
*behavior*: the per-op ``state_effects`` block of ``protocol_schema.json``
declares how each op touches coordinator state (epoch bumps, lease
acquire/release, dedup keys, fd-parking), a small abstract interpreter
(`ProtocolModel`) executes those declarations, and a bounded explicit-state
explorer enumerates every interleaving of N scripted workers — including
crash/restart and duplicate-delivery faults — checking four invariants on
every trace:

- **epoch monotonicity**: the epoch observed on any worker's reply stream
  never decreases;
- **exactly-once**: a replayed ``req_id``/``op_id`` must return the original
  effect (same task, same counter value), never apply a second one;
- **lease exclusivity**: at most one live lease per task, transfers only
  through an explicit requeue event (complete/fail/takeover/drop);
- **progress**: every parked op (barrier/sync) is eventually released and
  every script drains — a schedule where all runnable workers are parked is
  a deadlock, reported without replay.

Every completed trace is then replayed op-for-op against a fresh
``InProcessCoordinator`` (the executable oracle): each model-predicted reply
must be a subset of the oracle's reply, with the epoch matching exactly. A
model/oracle divergence means either the schema's behavioral annotations or
the twin drifted — both are findings.

Exploration is exhaustive by default (DFS over all interleavings) and can
run as a seeded random walk (``fuzz_samples``/``fuzz_seed``), whose explored
trace set — and therefore violation set — is provably a subset of the
exhaustive run at equal depth: both draw schedules from the same runnable
sets, the walk just samples one branch per node.

``python -m edl_tpu.analysis.modelcheck`` runs the default bounded
configuration — four merged schedules: the 2-worker faulty base (13 ops
including ``batch``, one crash+restart, two duplicate deliveries), the
checkpoint-plane ops, a watch/notify schedule (resume-cursor replay,
duplicate notification delivery via a stale re-subscribe), and a
redirect-during-watch schedule against a sharded root — and exits 1 on any
violation: the ``make modelcheck`` gate.
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from edl_tpu.coordinator.sharding import shard_of

#: ops a ``call_batch`` frame refuses (they park, nest framing, or bind an
#: out-of-band push stream to the connection — ``watch``); mirrored from the
#: wire protocol, used by the composite handler.
_NON_BATCHABLE = ("batch", "barrier", "sync", "watch")

#: sentinel request-field value: resolved at issue time to the task named in
#: the issuing worker's most recent acquire reply (each side — model and
#: oracle — resolves from its OWN reply stream, so a grant divergence is
#: reported once at the acquire, not echoed by every downstream op).
LAST_TASK = "__edl_modelcheck_last_task__"


class ModelCheckError(Exception):
    """The schema's state_effects block cannot drive the model (missing op,
    unknown effect tag): a behavioral-spec error, not a trace violation."""


@dataclass(frozen=True)
class ScriptOp:
    """One scripted client op. ``note`` tags fault injections ("dup",
    "restart") for trace rendering; semantics live entirely in op+fields."""

    op: str
    fields: Tuple[Tuple[str, Any], ...] = ()
    note: str = ""

    @staticmethod
    def make(op: str, note: str = "", **fields: Any) -> "ScriptOp":
        frozen = []
        for k in sorted(fields):
            v = fields[k]
            if isinstance(v, list):
                v = tuple(
                    tuple(sorted(d.items())) if isinstance(d, dict) else d
                    for v_ in [v] for d in v_
                )
            frozen.append((k, v))
        return ScriptOp(op=op, fields=tuple(frozen), note=note)

    def field_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for k, v in self.fields:
            if isinstance(v, tuple) and v and isinstance(v[0], tuple):
                # list-of-dicts (batch sub-ops) round-trips through tuples
                out[k] = [dict(item) for item in v]
            elif isinstance(v, tuple):
                out[k] = list(v)
            else:
                out[k] = v
        return out

    def render(self) -> str:
        parts = ", ".join(
            f"{k}={v!r}" for k, v in self.fields if k != "ops"
        )
        tag = f" [{self.note}]" if self.note else ""
        return f"{self.op}({parts}){tag}"


@dataclass
class Violation:
    kind: str  # epoch-monotonicity | exactly-once | lease-exclusivity |
    #            progress | oracle-divergence | conservation
    message: str
    trace: str  # stable rendering of the schedule that produced it

    def key(self) -> Tuple[str, str]:
        return (self.kind, self.trace)


@dataclass
class ModelCheckResult:
    traces: int = 0
    replays: int = 0
    violations: List[Violation] = field(default_factory=list)

    def ok(self) -> bool:
        return not self.violations

    def violation_keys(self) -> set:
        return {v.key() for v in self.violations}


# -- the abstract model --------------------------------------------------------


class ProtocolModel:
    """Explicit-state interpreter for the coordinator protocol, driven by the
    ``state_effects`` declarations. Predicts, for every (worker, op, fields)
    event, the reply the real coordinator must produce; the oracle replay
    checks the prediction. Time never passes: leases and heartbeats cannot
    expire, which matches the replay coordinator's near-infinite TTLs."""

    _KNOWN_TAGS = {
        "epoch", "lease", "dedup", "kv", "queue", "membership", "parks",
        "composite", "shard", "watch", "routing",
    }

    def __init__(self, effects: Dict[str, Dict[str, Any]],
                 shard_endpoints: Optional[Sequence[str]] = None):
        for op, tags in effects.items():
            unknown = set(tags) - self._KNOWN_TAGS
            if unknown:
                raise ModelCheckError(
                    f"state_effects[{op!r}] has unknown tag(s): "
                    f"{sorted(unknown)}"
                )
        self.effects = effects
        # Sharded-ROOT mode (native --shards): with endpoints configured,
        # every keyspace op answers a redirect instead of being served.
        self.shard_endpoints: List[str] = list(shard_endpoints or [])
        self.epoch = 0
        self.members: Dict[str, int] = {}  # name -> rank
        self.next_rank = 0
        self.todo: List[str] = []
        self.leased: Dict[str, str] = {}  # task -> worker (insertion-ordered)
        self.done: set = set()
        self.acquire_cache: Dict[str, Tuple[str, str]] = {}
        self.kv: Dict[str, str] = {}
        self.barriers: Dict[str, Dict[str, Any]] = {}
        self.sync_arrived: set = set()
        self.sync_generation = 0
        # Checkpoint plane: owner -> {step, chunks, nbytes, group, data}.
        self.shards: Dict[str, Dict[str, Any]] = {}
        self.shard_put_seen: set = set()
        # Watch subscriptions: worker -> pending notification frames.
        self.watch_queues: Dict[str, List[Dict[str, Any]]] = {}

    def copy(self) -> "ProtocolModel":
        m = ProtocolModel.__new__(ProtocolModel)
        m.effects = self.effects
        m.shard_endpoints = list(self.shard_endpoints)
        m.epoch = self.epoch
        m.members = dict(self.members)
        m.next_rank = self.next_rank
        m.todo = list(self.todo)
        m.leased = dict(self.leased)
        m.done = set(self.done)
        m.acquire_cache = dict(self.acquire_cache)
        m.kv = dict(self.kv)
        m.barriers = {
            k: {"arrived": set(v["arrived"]), "generation": v["generation"],
                "want": v["want"]}
            for k, v in self.barriers.items()
        }
        m.sync_arrived = set(self.sync_arrived)
        m.sync_generation = self.sync_generation
        m.shards = {
            owner: {"step": b["step"], "chunks": b["chunks"],
                    "nbytes": b["nbytes"], "group": list(b["group"]),
                    "data": dict(b["data"])}
            for owner, b in self.shards.items()
        }
        m.shard_put_seen = set(self.shard_put_seen)
        m.watch_queues = {
            w: [dict(f) for f in q] for w, q in self.watch_queues.items()
        }
        return m

    # Every handler returns (reply_prediction | None-if-parked, released)
    # where released is [(worker, reply_prediction), ...] for parked ops
    # this event unblocked.

    def apply(self, worker: str, op: str, fields: Dict[str, Any]):
        if op not in self.effects:
            raise ModelCheckError(
                f"op {op!r} has no state_effects entry in the schema"
            )
        handler = getattr(self, f"_op_{op}", None)
        if handler is None:
            raise ModelCheckError(f"model has no handler for op {op!r}")
        return handler(worker, fields)

    def _membership_reply(self, worker: str) -> Dict[str, Any]:
        rank = self.members.get(worker, -1)
        return {"ok": True, "rank": rank, "epoch": self.epoch,
                "world": len(self.members)}

    def _redirect(self, key: Any) -> Optional[Dict[str, Any]]:
        """Redirect prediction for a keyspace op on a sharded ROOT; None on
        a plain coordinator. Mirrors the twin's ``redirect_for`` (which
        mirrors the native ``redirect_reply``), including the epoch stamp
        and the answer-before-validation placement."""
        if not self.shard_endpoints:
            return None
        s = shard_of(str(key), len(self.shard_endpoints))
        return {"ok": False, "error": "wrong shard",
                "redirect": self.shard_endpoints[s], "shard": s,
                "epoch": self.epoch}

    def _notify_frame(self, e: int) -> Dict[str, Any]:
        return {"ok": True, "notify": "epoch", "epoch": int(e),
                "cursor": int(e), "world": len(self.members)}

    def _notify_watchers(self) -> None:
        """Epoch moved: one notification frame per live subscription."""
        for q in self.watch_queues.values():
            q.append(self._notify_frame(self.epoch))

    def _requeue_worker_leases(self, worker: str) -> None:
        stale = [t for t, w in self.leased.items() if w == worker]
        for t in stale:
            del self.leased[t]
            self.todo.append(t)

    def _release_sync_on_epoch_change(self) -> List[Tuple[str, Dict]]:
        """Membership moved (epoch already bumped): every parked sync wakes
        and observes the epoch mismatch — resync replies."""
        released = [
            (w, {"ok": False, "resync": True, "epoch": self.epoch,
                 "world": len(self.members)})
            for w in sorted(self.sync_arrived)
        ]
        self.sync_arrived = set()
        return released

    def _op_register(self, worker: str, fields: Dict[str, Any]):
        released: List[Tuple[str, Dict]] = []
        tags = self.effects["register"]
        if fields.get("takeover") and tags.get("lease") == "requeue_on_takeover":
            self._requeue_worker_leases(worker)
        if worker not in self.members:
            self.members[worker] = self.next_rank
            self.next_rank += 1
            if tags.get("epoch") == "bump_on_join":
                self.epoch += 1
                self._notify_watchers()
                released = self._release_sync_on_epoch_change()
        return self._membership_reply(worker), released

    def _op_heartbeat(self, worker: str, fields: Dict[str, Any]):
        if worker not in self.members:
            return ({"ok": False, "error": "unknown worker",
                     "epoch": self.epoch}, [])
        return self._membership_reply(worker), []

    def _op_leave(self, worker: str, fields: Dict[str, Any]):
        # The shim binds leave to the calling client's own worker name; the
        # "worker" request field is envelope, not a target selector.
        target = worker
        released: List[Tuple[str, Dict]] = []
        if target in self.members:
            del self.members[target]
            ranked = sorted(self.members.items(), key=lambda kv: kv[1])
            for r, (name, _) in enumerate(ranked):
                self.members[name] = r
            self.next_rank = len(self.members)
            if self.effects["leave"].get("epoch") == "bump_on_drop":
                self.epoch += 1
                self._notify_watchers()
            self._requeue_worker_leases(target)
            self.acquire_cache.pop(target, None)
            released = self._release_sync_on_epoch_change()
        return {"ok": True, "epoch": self.epoch}, released

    def _op_members(self, worker: str, fields: Dict[str, Any]):
        names = [n for n, _ in sorted(self.members.items(),
                                      key=lambda kv: kv[1])]
        return {"ok": True, "members": names, "epoch": self.epoch}, []

    def _op_ping(self, worker: str, fields: Dict[str, Any]):
        return {"ok": True, "pong": True, "epoch": self.epoch}, []

    def _op_add_tasks(self, worker: str, fields: Dict[str, Any]):
        tasks = fields.get("tasks") or []
        r = self._redirect(str(tasks[0]) if tasks else "")
        if r:
            return r, []
        added = 0
        for t in fields.get("tasks", []):
            if t in self.done or t in self.leased or t in self.todo:
                continue
            self.todo.append(t)
            added += 1
        return ({"ok": True, "added": added, "queued": len(self.todo),
                 "epoch": self.epoch}, [])

    def _op_acquire_task(self, worker: str, fields: Dict[str, Any]):
        r = self._redirect(worker)
        if r:
            return r, []
        req_id = fields.get("req_id")
        if req_id and self.effects["acquire_task"].get("dedup") == "req_id":
            cached = self.acquire_cache.get(worker)
            if cached and cached[0] == req_id:
                task = cached[1]
                if self.leased.get(task) == worker:
                    return ({"ok": True, "task": task, "duplicate": True,
                             "epoch": self.epoch}, [])
        if not self.todo:
            return ({"ok": True, "task": None,
                     "exhausted": not self.leased, "epoch": self.epoch}, [])
        task = self.todo.pop(0)
        self.leased[task] = worker
        if req_id:
            self.acquire_cache[worker] = (req_id, task)
        return {"ok": True, "task": task, "epoch": self.epoch}, []

    def _op_complete_task(self, worker: str, fields: Dict[str, Any]):
        task = fields.get("task")
        r = self._redirect(task)
        if r:
            return r, []
        if task in self.done:
            return ({"ok": True, "duplicate": True, "done": len(self.done),
                     "queued": len(self.todo), "epoch": self.epoch}, [])
        if task not in self.leased:
            if task in self.todo:
                self.todo.remove(task)
                self.done.add(task)
                return ({"ok": True, "requeued": True,
                         "done": len(self.done), "queued": len(self.todo),
                         "epoch": self.epoch}, [])
            return ({"ok": False, "error": "not leased",
                     "epoch": self.epoch}, [])
        if self.leased[task] != worker:
            return ({"ok": False, "error": "lease not owned",
                     "epoch": self.epoch}, [])
        del self.leased[task]
        self.done.add(task)
        return ({"ok": True, "done": len(self.done),
                 "queued": len(self.todo), "epoch": self.epoch}, [])

    def _op_fail_task(self, worker: str, fields: Dict[str, Any]):
        task = fields.get("task")
        r = self._redirect(task)
        if r:
            return r, []
        if task not in self.leased:
            return ({"ok": False, "error": "not leased",
                     "epoch": self.epoch}, [])
        if self.leased[task] != worker:
            return ({"ok": False, "error": "lease not owned",
                     "epoch": self.epoch}, [])
        del self.leased[task]
        self.todo.append(task)
        return {"ok": True, "epoch": self.epoch}, []

    def _op_kv_put(self, worker: str, fields: Dict[str, Any]):
        key = fields.get("key")
        r = self._redirect(key or "")
        if r:
            return r, []
        if not key:
            return ({"ok": False, "error": "key required",
                     "epoch": self.epoch}, [])
        self.kv[key] = fields.get("value")
        return {"ok": True, "epoch": self.epoch}, []

    def _op_kv_get(self, worker: str, fields: Dict[str, Any]):
        r = self._redirect(fields.get("key") or "")
        if r:
            return r, []
        return ({"ok": True, "value": self.kv.get(fields.get("key")),
                 "epoch": self.epoch}, [])

    def _op_kv_del(self, worker: str, fields: Dict[str, Any]):
        r = self._redirect(fields.get("key") or "")
        if r:
            return r, []
        self.kv.pop(fields.get("key"), None)
        return {"ok": True, "epoch": self.epoch}, []

    def _op_kv_incr(self, worker: str, fields: Dict[str, Any]):
        key = fields.get("key", "")
        r = self._redirect(key)
        if r:
            return r, []
        if not key:
            return ({"ok": False, "error": "key required",
                     "epoch": self.epoch}, [])
        op_id = fields.get("op_id")
        marker = f"__edl_op/{op_id}" if op_id else None
        if (marker and marker in self.kv
                and self.effects["kv_incr"].get("dedup") == "op_id"):
            return ({"ok": True, "value": int(self.kv[marker]),
                     "duplicate": True, "epoch": self.epoch}, [])
        cur = int(self.kv.get(key, "0") or "0") + int(fields.get("delta", 1))
        self.kv[key] = str(cur)
        if marker:
            self.kv[marker] = str(cur)
        return {"ok": True, "value": cur, "epoch": self.epoch}, []

    # Checkpoint-plane ops (memory-resident shard replication). Mirror the
    # twin's shard_* methods exactly: step supersedes, put_id dedups
    # exactly-once, drop with a step only removes that exact step. None of
    # them touch the epoch or park.

    def _op_shard_put(self, worker: str, fields: Dict[str, Any]):
        owner = fields.get("owner", "")
        r = self._redirect(owner)
        if r:
            return r, []
        step = int(fields.get("step", -1))
        chunk = int(fields.get("chunk", -1))
        chunks = int(fields.get("chunks", 0))
        if not owner or step < 0 or chunks < 1 or not 0 <= chunk < chunks:
            return ({"ok": False,
                     "error": "shard_put requires owner, step>=0, "
                              "0<=chunk<chunks",
                     "epoch": self.epoch}, [])
        put_id = fields.get("put_id")
        if (put_id and put_id in self.shard_put_seen
                and self.effects["shard_put"].get("dedup") == "put_id"):
            return ({"ok": True, "duplicate": True, "stored": True,
                     "epoch": self.epoch}, [])
        blob = self.shards.setdefault(
            owner, {"step": -1, "chunks": 0, "nbytes": 0,
                    "group": [], "data": {}})
        if step < blob["step"]:
            return ({"ok": True, "duplicate": False, "stored": False,
                     "epoch": self.epoch}, [])
        if step > blob["step"]:
            blob["step"] = step
            blob["data"] = {}
            blob["group"] = []
        blob["chunks"] = chunks
        blob["nbytes"] = int(fields.get("nbytes", 0))
        group = fields.get("group")
        if isinstance(group, list):
            blob["group"] = [str(g) for g in group]
        blob["data"][chunk] = fields.get("data", "")
        if put_id:
            self.shard_put_seen.add(put_id)
        return ({"ok": True, "duplicate": False, "stored": True,
                 "epoch": self.epoch}, [])

    def _op_shard_get(self, worker: str, fields: Dict[str, Any]):
        owner = fields.get("owner", "")
        r = self._redirect(owner)
        if r:
            return r, []
        step = int(fields.get("step", -1))
        chunk = int(fields.get("chunk", 0))
        blob = self.shards.get(owner)
        if blob is None or (step >= 0 and blob["step"] != step):
            return ({"ok": True, "found": False, "data": "", "chunks": 0,
                     "epoch": self.epoch}, [])
        payload = blob["data"].get(chunk)
        if payload is None:
            return ({"ok": True, "found": False, "data": "",
                     "chunks": blob["chunks"], "epoch": self.epoch}, [])
        return ({"ok": True, "found": True, "data": payload,
                 "chunks": blob["chunks"], "epoch": self.epoch}, [])

    def _op_shard_meta(self, worker: str, fields: Dict[str, Any]):
        r = self._redirect(fields.get("owner", ""))
        if r:
            return r, []
        blob = self.shards.get(fields.get("owner", ""))
        if blob is None or blob["step"] < 0:
            return ({"ok": True, "found": False, "step": -1, "chunks": 0,
                     "nbytes": 0, "complete": False, "group": [],
                     "epoch": self.epoch}, [])
        complete = blob["chunks"] > 0 and len(blob["data"]) == blob["chunks"]
        return ({"ok": True, "found": True, "step": blob["step"],
                 "chunks": blob["chunks"], "nbytes": blob["nbytes"],
                 "complete": complete, "group": list(blob["group"]),
                 "epoch": self.epoch}, [])

    def _op_shard_drop(self, worker: str, fields: Dict[str, Any]):
        owner = fields.get("owner", "")
        r = self._redirect(owner)
        if r:
            return r, []
        step = int(fields.get("step", -1))
        blob = self.shards.get(owner)
        dropped = False
        if blob is not None and (step < 0 or blob["step"] == step):
            del self.shards[owner]
            dropped = True
        return {"ok": True, "dropped": dropped, "epoch": self.epoch}, []

    def _op_bump_epoch(self, worker: str, fields: Dict[str, Any]):
        self.epoch += 1
        self._notify_watchers()
        released = self._release_sync_on_epoch_change()
        return {"ok": True, "epoch": self.epoch}, released

    def _op_status(self, worker: str, fields: Dict[str, Any]):
        return ({"ok": True, "epoch": self.epoch,
                 "world": len(self.members), "queued": len(self.todo),
                 "leased": len(self.leased), "done": len(self.done)}, [])

    # Watch/notify ops (push-based epoch discovery). The twin has no socket
    # to push to, so delivery is modeled the way the shim serves it: a
    # subscribe queues replayed frames for every epoch in (cursor, current],
    # epoch bumps append live frames, and ``watch`` with take=True drains
    # one frame (the in-process stand-in for the wire server's unsolicited
    # push). Frames carry the epoch being ANNOUNCED, which may be historical.

    def _op_watch(self, worker: str, fields: Dict[str, Any]):
        if fields.get("take"):
            q = self.watch_queues.get(worker)
            if not q:
                return ({"ok": True, "notify": None, "cursor": self.epoch,
                         "world": len(self.members),
                         "epoch": self.epoch}, [])
            return dict(q.pop(0)), []
        q = self.watch_queues.setdefault(worker, [])
        cursor = int(fields.get("cursor", -1))
        if cursor >= 0:
            for e in range(cursor + 1, self.epoch + 1):
                q.append(self._notify_frame(e))
        return ({"ok": True, "watch": True, "cursor": self.epoch,
                 "epoch": self.epoch}, [])

    def _op_watch_cancel(self, worker: str, fields: Dict[str, Any]):
        cancelled = worker in self.watch_queues
        self.watch_queues.pop(worker, None)
        return {"ok": True, "cancelled": cancelled, "epoch": self.epoch}, []

    def _op_shard_map(self, worker: str, fields: Dict[str, Any]):
        return ({"ok": True, "root": bool(self.shard_endpoints),
                 "nshards": len(self.shard_endpoints),
                 "shards": list(self.shard_endpoints), "shard_index": -1,
                 "epoch": self.epoch}, [])

    def _op_batch(self, worker: str, fields: Dict[str, Any]):
        if not self.effects["batch"].get("composite"):
            raise ModelCheckError(
                "state_effects['batch'] lost its composite tag"
            )
        replies = []
        released: List[Tuple[str, Dict]] = []
        for sub in fields.get("ops", []):
            sub = dict(sub)
            sub_op = sub.pop("op", "")
            if sub_op in _NON_BATCHABLE:
                replies.append(
                    {"ok": False, "error": f"op not batchable: {sub_op}"})
                continue
            reply, rel = self.apply(worker, sub_op, sub)
            replies.append(reply)
            released.extend(rel)
        return ({"ok": True, "replies": replies, "epoch": self.epoch},
                released)

    # Parked ops return (None, released): the caller must park the worker.

    def _op_barrier(self, worker: str, fields: Dict[str, Any]):
        name = fields["name"]
        count = int(fields["count"])
        b = self.barriers.setdefault(
            name, {"arrived": set(), "generation": 0, "want": 0})
        if not b["arrived"]:
            b["want"] = count
        elif count != b["want"]:
            return ({"ok": False, "error": "barrier count mismatch",
                     "want": b["want"], "epoch": self.epoch}, [])
        gen = b["generation"]
        b["arrived"].add(worker)
        if len(b["arrived"]) >= b["want"]:
            b["generation"] += 1
            parked = sorted(b["arrived"] - {worker})
            b["arrived"] = set()
            released = [
                (w, {"ok": True, "barrier": name, "generation": gen,
                     "epoch": self.epoch})
                for w in parked
            ]
            return ({"ok": True, "barrier": name, "generation": gen,
                     "epoch": self.epoch}, released)
        return None, []  # parked

    def _op_sync(self, worker: str, fields: Dict[str, Any]):
        if worker not in self.members:
            return ({"ok": False, "error": "unknown worker",
                     "epoch": self.epoch, "world": len(self.members)}, [])
        if int(fields["epoch"]) != self.epoch:
            return ({"ok": False, "resync": True, "epoch": self.epoch,
                     "world": len(self.members)}, [])
        self.sync_arrived.add(worker)
        if self.sync_arrived >= set(self.members):
            parked = sorted(self.sync_arrived - {worker})
            self.sync_arrived = set()
            self.sync_generation += 1
            reply = {"ok": True, "epoch": self.epoch,
                     "world": len(self.members)}
            return reply, [(w, dict(reply)) for w in parked]
        return None, []  # parked


# -- explorer ------------------------------------------------------------------


@dataclass
class _Event:
    """One scheduled op in a concrete trace, with the model's prediction."""

    worker: str
    op: ScriptOp
    fields: Dict[str, Any]  # LAST_TASK already resolved (model view)
    predicted: Optional[Dict[str, Any]]  # None while parked
    parked: bool = False
    released_at: Optional[int] = None  # index of the releasing event


def _resolve_last_task(fields: Dict[str, Any], last_task: Any):
    out = {}
    for k, v in fields.items():
        if v == LAST_TASK:
            out[k] = last_task
        elif k == "ops" and isinstance(v, list):
            out[k] = [_resolve_last_task(dict(sub), last_task) for sub in v]
        else:
            out[k] = v
    return out


def _grants_from_reply(op: str, fields: Dict[str, Any], reply: Any):
    """(task, duplicate) grant observations in a reply (incl. batch subs)."""
    if not isinstance(reply, dict):
        return
    if op == "acquire_task" and reply.get("ok") and reply.get("task"):
        yield reply["task"], bool(reply.get("duplicate")), fields.get("req_id")
    if op == "batch":
        subs = fields.get("ops", [])
        for sub, sub_reply in zip(subs, reply.get("replies", []) or []):
            sub_op = sub.get("op", "")
            yield from _grants_from_reply(sub_op, sub, sub_reply)


class _TraceState:
    """One DFS node: per-worker program counters + parked set + model."""

    def __init__(self, scripts: Dict[str, Sequence[ScriptOp]],
                 model: ProtocolModel):
        self.scripts = scripts
        self.pcs = {w: 0 for w in scripts}
        self.parked: Dict[str, int] = {}  # worker -> event index in trace
        self.last_task: Dict[str, Any] = {w: None for w in scripts}
        self.model = model
        self.trace: List[_Event] = []

    def runnable(self) -> List[str]:
        return sorted(
            w for w, pc in self.pcs.items()
            if pc < len(self.scripts[w]) and w not in self.parked
        )

    def done(self) -> bool:
        return not self.parked and all(
            pc >= len(self.scripts[w]) for w, pc in self.pcs.items()
        )

    def copy(self) -> "_TraceState":
        st = _TraceState.__new__(_TraceState)
        st.scripts = self.scripts
        st.pcs = dict(self.pcs)
        st.parked = dict(self.parked)
        st.last_task = dict(self.last_task)
        st.model = self.model.copy()
        st.trace = [
            _Event(e.worker, e.op, e.fields, e.predicted, e.parked,
                   e.released_at)
            for e in self.trace
        ]
        return st

    def step(self, worker: str) -> None:
        """Advance ``worker`` one op through the model."""
        sop = self.scripts[worker][self.pcs[worker]]
        self.pcs[worker] += 1
        fields = _resolve_last_task(sop.field_dict(), self.last_task[worker])
        predicted, released = self.model.apply(worker, sop.op, fields)
        ev = _Event(worker=worker, op=sop, fields=fields,
                    predicted=predicted, parked=predicted is None)
        self.trace.append(ev)
        idx = len(self.trace) - 1
        if ev.parked:
            self.parked[worker] = idx
        else:
            self._note_grants(worker, sop.op, fields, predicted)
        for released_worker, reply in released:
            parked_idx = self.parked.pop(released_worker, None)
            if parked_idx is not None:
                parked_ev = self.trace[parked_idx]
                parked_ev.predicted = reply
                parked_ev.parked = False
                parked_ev.released_at = idx
                self._note_grants(released_worker, parked_ev.op.op,
                                  parked_ev.fields, reply)

    def _note_grants(self, worker, op, fields, reply):
        for task, _dup, _req in _grants_from_reply(op, fields, reply):
            self.last_task[worker] = task

    def render(self) -> str:
        return " ; ".join(f"{e.worker}:{e.op.render()}" for e in self.trace)


CoordinatorFactory = Callable[[], Any]


def _default_coordinator_factory():
    from edl_tpu.coordinator.inprocess import InProcessCoordinator

    # Time must not pass for the model: near-infinite lease/TTL windows.
    return InProcessCoordinator(task_lease_sec=1e9, heartbeat_ttl_sec=1e9)


def _replay_trace(trace: List[_Event], factory: CoordinatorFactory,
                  rendered: str, violations: List[Violation],
                  join_timeout: float = 30.0) -> None:
    """Execute the scheduled trace against a fresh coordinator and check
    model predictions + runtime invariants on the oracle's replies."""
    coord = factory()
    clients = {}
    last_task: Dict[str, Any] = {}
    last_epoch: Dict[str, int] = {}
    live_grants: Dict[str, str] = {}  # task -> worker (oracle view)
    grants_by_req: Dict[Tuple[str, str], set] = {}
    pending: Dict[int, Tuple[threading.Thread, List]] = {}
    added_total = 0

    def client(worker: str):
        if worker not in clients:
            clients[worker] = coord.client(worker)
        return clients[worker]

    def requeue_events(worker: str, op: str, fields: Dict[str, Any]):
        """Lease-release points: a grant after one is a transfer, not a
        violation. Mirrors the coordinator's requeue semantics."""
        if op == "register" and fields.get("takeover"):
            for t, w in list(live_grants.items()):
                if w == worker:
                    del live_grants[t]
        if op == "leave":
            for t, w in list(live_grants.items()):
                if w == worker:
                    del live_grants[t]
        if op in ("fail_task", "complete_task"):
            live_grants.pop(fields.get("task"), None)

    def check_reply(idx: int, ev: _Event, fields: Dict[str, Any],
                    reply: Any) -> None:
        """``fields`` is the ORACLE-side resolution of the scripted op
        (LAST_TASK bound from the oracle's own reply stream)."""
        nonlocal added_total
        where = f"step {idx} ({ev.worker}:{ev.op.render()})"
        if not isinstance(reply, dict):
            violations.append(Violation(
                "oracle-divergence",
                f"{where}: oracle returned non-dict reply {reply!r}",
                rendered))
            return
        # model prediction must be a subset of the oracle reply, epoch exact
        for key, want in (ev.predicted or {}).items():
            have = reply.get(key, "<absent>")
            if key == "replies":
                continue  # batch sub-replies compared below
            if have != want:
                violations.append(Violation(
                    "oracle-divergence",
                    f"{where}: model predicts {key}={want!r}, oracle "
                    f"replied {key}={have!r}",
                    rendered))
        if ev.op.op == "batch":
            want_subs = (ev.predicted or {}).get("replies", [])
            have_subs = reply.get("replies", [])
            if len(want_subs) != len(have_subs):
                violations.append(Violation(
                    "oracle-divergence",
                    f"{where}: batch sub-reply count mismatch "
                    f"(model {len(want_subs)}, oracle {len(have_subs)})",
                    rendered))
            for j, (ws, hs) in enumerate(zip(want_subs, have_subs)):
                for key, want in ws.items():
                    if not isinstance(hs, dict) or hs.get(key, "<absent>") != want:
                        violations.append(Violation(
                            "oracle-divergence",
                            f"{where} sub-op {j}: model predicts "
                            f"{key}={want!r}, oracle replied "
                            f"{(hs or {}).get(key, '<absent>')!r}",
                            rendered))
        # invariant: per-stream epoch monotonicity. Notification frames are
        # exempt: their "epoch" names the (possibly historical) epoch being
        # announced — on the wire they ride a dedicated watch connection,
        # not the request/reply stream the invariant is defined over.
        if "epoch" in reply and not reply.get("notify"):
            ep = int(reply["epoch"])
            if ep < last_epoch.get(ev.worker, 0):
                violations.append(Violation(
                    "epoch-monotonicity",
                    f"{where}: epoch went backwards "
                    f"({last_epoch[ev.worker]} -> {ep}) on "
                    f"{ev.worker}'s reply stream",
                    rendered))
            last_epoch[ev.worker] = max(last_epoch.get(ev.worker, 0), ep)
        # invariants: exactly-once + lease exclusivity on oracle grants
        requeue_events(ev.worker, ev.op.op, fields)
        if ev.op.op == "batch":
            for sub in fields.get("ops", []):
                requeue_events(ev.worker, sub.get("op", ""), sub)
        for task, dup, req_id in _grants_from_reply(
                ev.op.op, fields, reply):
            last_task[ev.worker] = task
            if req_id:
                seen = grants_by_req.setdefault((ev.worker, req_id), set())
                seen.add(task)
                if len(seen) > 1:
                    violations.append(Violation(
                        "exactly-once",
                        f"{where}: req_id {req_id!r} was granted "
                        f"{sorted(seen)} — a replayed acquire popped a "
                        "second task instead of returning the original "
                        "lease",
                        rendered))
            if not dup:
                holder = live_grants.get(task)
                if holder is not None and holder != ev.worker:
                    violations.append(Violation(
                        "lease-exclusivity",
                        f"{where}: task {task!r} granted to {ev.worker} "
                        f"while {holder} still holds the lease",
                        rendered))
                live_grants[task] = ev.worker
        if ev.op.op == "add_tasks" and reply.get("ok"):
            added_total += int(reply.get("added", 0))
        if ev.op.op == "batch":
            for sub, sub_reply in zip(fields.get("ops", []),
                                      reply.get("replies", []) or []):
                if (sub.get("op") == "add_tasks"
                        and isinstance(sub_reply, dict)
                        and sub_reply.get("ok")):
                    added_total += int(sub_reply.get("added", 0))
        if ev.op.op == "status" and reply.get("ok"):
            # invariant: task conservation — at this point in the schedule
            # every task added so far is queued, leased, or done.
            total = (int(reply.get("queued", 0))
                     + int(reply.get("leased", 0))
                     + int(reply.get("done", 0)))
            if total != added_total:
                violations.append(Violation(
                    "conservation",
                    f"{where}: status queued+leased+done={total} != "
                    f"tasks added so far={added_total}",
                    rendered))

    oracle_fields: Dict[int, Dict[str, Any]] = {}
    for idx, ev in enumerate(trace):
        # Resolve LAST_TASK from the ORACLE's own reply stream (ev.fields is
        # the model-side resolution; the two views stay independent so a
        # grant divergence is reported once, at the acquire).
        fields = _resolve_last_task(ev.op.field_dict(),
                                    last_task.get(ev.worker))
        oracle_fields[idx] = fields
        if ev.parked or ev.released_at is not None:
            holder: List = []

            def run(c=client(ev.worker), op=ev.op.op, f=fields, h=holder):
                try:
                    h.append(c.call(op, timeout=join_timeout, **f))
                except Exception as e:  # edl: noqa[EDL005] stashed in holder; join() turns it into a violation
                    h.append(e)

            th = threading.Thread(target=run, daemon=True)
            th.start()
            pending[idx] = (th, holder)
        else:
            reply = client(ev.worker).call(ev.op.op, **fields)
            check_reply(idx, ev, fields, reply)
        # join any parked ops this event released
        for pidx in [p for p in list(pending)
                     if trace[p].released_at == idx]:
            th, holder = pending.pop(pidx)
            th.join(join_timeout)
            if th.is_alive() or not holder:
                violations.append(Violation(
                    "progress",
                    f"step {pidx} ({trace[pidx].worker}:"
                    f"{trace[pidx].op.render()}): oracle did not release "
                    "the parked op the model says this event releases",
                    rendered))
                continue
            reply = holder[0]
            if isinstance(reply, Exception):
                violations.append(Violation(
                    "oracle-divergence",
                    f"step {pidx}: parked op raised {reply!r}", rendered))
                continue
            check_reply(pidx, trace[pidx], oracle_fields[pidx], reply)

    if pending:
        violations.append(Violation(
            "progress",
            f"{len(pending)} parked op(s) never released by trace end",
            rendered))


def explore(
    scripts: Dict[str, Sequence[ScriptOp]],
    effects: Dict[str, Dict[str, Any]],
    coordinator_factory: Optional[CoordinatorFactory] = None,
    max_traces: int = 20000,
    max_violations: int = 25,
    fuzz_samples: int = 0,
    fuzz_seed: int = 0,
    replay: bool = True,
    shard_endpoints: Optional[Sequence[str]] = None,
) -> ModelCheckResult:
    """Enumerate interleavings of ``scripts`` (exhaustive DFS, or a seeded
    random walk when ``fuzz_samples > 0``), model-check each, and replay
    completed traces against the oracle coordinator. ``shard_endpoints``
    puts the MODEL in sharded-root mode — pair it with a factory that
    builds the oracle with the same endpoints."""
    factory = coordinator_factory or _default_coordinator_factory
    result = ModelCheckResult()

    def finish(state: _TraceState) -> None:
        result.traces += 1
        rendered = state.render()
        if not state.done():
            # all runnable workers parked / drained with parked remainder
            stuck = sorted(state.parked)
            result.violations.append(Violation(
                "progress",
                f"deadlock: worker(s) {stuck} parked with no releasing op "
                "left in any script",
                rendered))
            return  # replay would hang on the parked ops
        if replay:
            result.replays += 1
            _replay_trace(state.trace, factory, rendered, result.violations)

    def budget_left() -> bool:
        return (result.traces < max_traces
                and len(result.violations) < max_violations)

    if fuzz_samples > 0:
        import random

        rng = random.Random(fuzz_seed)
        seen = set()
        for _ in range(fuzz_samples):
            if not budget_left():
                break
            state = _TraceState(
                scripts, ProtocolModel(effects, shard_endpoints))
            while True:
                workers = state.runnable()
                if not workers:
                    break
                state.step(rng.choice(workers))
            key = state.render()
            if key in seen:
                continue
            seen.add(key)
            finish(state)
        return result

    def dfs(state: _TraceState) -> None:
        if not budget_left():
            return
        workers = state.runnable()
        if not workers:
            finish(state)
            return
        for i, worker in enumerate(workers):
            branch = state if i == len(workers) - 1 else state.copy()
            branch.step(worker)
            dfs(branch)
            if not budget_left():
                return

    dfs(_TraceState(scripts, ProtocolModel(effects, shard_endpoints)))
    return result


# -- default bounded configuration ---------------------------------------------


def default_scripts() -> Dict[str, List[ScriptOp]]:
    """The acceptance configuration: 2 workers, 13 ops including ``batch``,
    one crash+restart (register takeover), and two duplicate deliveries
    (an acquire req_id replay and a kv_incr op_id replay)."""
    mk = ScriptOp.make
    w0 = [
        mk("register", worker="w0"),
        mk("add_tasks", tasks=["t0", "t1", "t2", "t3"]),
        mk("acquire_task", req_id="w0-a1", worker="w0"),
        mk("acquire_task", note="dup", req_id="w0-a1", worker="w0"),
        mk("register", note="restart", takeover=True, worker="w0"),
        mk("batch", ops=[
            {"op": "acquire_task", "req_id": "w0-a2"},
            {"op": "kv_incr", "key": "steps", "delta": 1,
             "op_id": "w0-i1"},
        ]),
        mk("complete_task", task=LAST_TASK, worker="w0"),
    ]
    w1 = [
        mk("register", worker="w1"),
        mk("acquire_task", req_id="w1-a1", worker="w1"),
        mk("kv_incr", key="steps", delta=1, op_id="w1-i1"),
        mk("kv_incr", note="dup", key="steps", delta=1, op_id="w1-i1"),
        mk("complete_task", task=LAST_TASK, worker="w1"),
        mk("status"),
    ]
    return {"w0": w0, "w1": w1}


def ckpt_plane_scripts() -> Dict[str, List[ScriptOp]]:
    """Checkpoint-plane schedule: 2 workers exercising the shard_* ops —
    a batched two-chunk replication pass, a duplicate shard_put replay
    (exactly-once under put_id dedup), a stale put racing a newer pass, a
    chunk fetch, and a step-conditional drop. Kept separate from
    ``default_scripts`` so the combined interleaving count stays inside the
    exploration budget (adding 5 ops to the default schedule would blow it)."""
    mk = ScriptOp.make
    w0 = [
        mk("register", worker="w0"),
        mk("batch", ops=[
            {"op": "shard_put", "owner": "w0", "step": 1, "chunk": 0,
             "chunks": 2, "nbytes": 8, "data": "AAAA", "put_id": "w0-p1",
             "group": ["w1"]},
            {"op": "shard_put", "owner": "w0", "step": 1, "chunk": 1,
             "chunks": 2, "nbytes": 8, "data": "BBBB", "put_id": "w0-p2",
             "group": ["w1"]},
        ]),
        mk("shard_put", note="dup", owner="w0", step=1, chunk=0, chunks=2,
           nbytes=8, data="AAAA", put_id="w0-p1", group=["w1"]),
        mk("shard_meta", owner="w0"),
    ]
    w1 = [
        mk("register", worker="w1"),
        mk("shard_put", note="stale", owner="w0", step=0, chunk=0, chunks=1,
           nbytes=4, data="OLD", put_id="w1-p1"),
        mk("shard_get", owner="w0", step=-1, chunk=0),
        mk("shard_drop", owner="w0", step=1),
    ]
    return {"w0": w0, "w1": w1}


#: fake shard endpoints driving the redirect schedules — never dialed; the
#: sharded root (model AND twin) only hashes keys against them (FNV-1a).
SHARD_ENDPOINTS = ["10.0.0.1:7164", "10.0.0.2:7164"]


def watch_scripts() -> Dict[str, List[ScriptOp]]:
    """Watch/notify schedule: subscribe with a resume cursor, epoch bumps
    from joins and an explicit bump, frame drains interleaved with the
    bumps, a duplicate re-subscribe at a stale cursor (at-least-once
    delivery replays already-announced epochs — the model must predict the
    duplicates exactly), and a cancel. Runs against the plain twin."""
    mk = ScriptOp.make
    w0 = [
        mk("register", worker="w0"),
        mk("watch", cursor=0, worker="w0"),
        mk("watch", take=True, worker="w0"),
        mk("bump_epoch"),
        mk("watch", take=True, worker="w0"),
        mk("watch", note="dup", cursor=0, worker="w0"),
        mk("watch", take=True, worker="w0"),
        mk("watch_cancel", worker="w0"),
    ]
    w1 = [
        mk("register", worker="w1"),
        mk("shard_map"),
        mk("status"),
    ]
    return {"w0": w0, "w1": w1}


def watch_redirect_scripts() -> Dict[str, List[ScriptOp]]:
    """Redirect-during-watch schedule against a sharded ROOT
    (``SHARD_ENDPOINTS``): every keyspace op answers a redirect computed by
    key hash (never served), while membership, epoch bumps, and the watch
    stream stay root-local — notifications keep flowing to a subscriber
    whose data ops are being bounced to shard servers."""
    mk = ScriptOp.make
    w0 = [
        mk("register", worker="w0"),
        mk("watch", cursor=0, worker="w0"),
        mk("kv_put", key="alpha", value="1"),
        mk("bump_epoch"),
        mk("watch", take=True, worker="w0"),
        mk("shard_map"),
    ]
    w1 = [
        mk("register", worker="w1"),
        mk("acquire_task", req_id="w1-a1", worker="w1"),
        mk("add_tasks", tasks=["t0"]),
        mk("kv_get", key="beta"),
    ]
    return {"w0": w0, "w1": w1}


def _sharded_root_factory():
    from edl_tpu.coordinator.inprocess import InProcessCoordinator

    return InProcessCoordinator(task_lease_sec=1e9, heartbeat_ttl_sec=1e9,
                                shard_endpoints=list(SHARD_ENDPOINTS))


def default_schedules(
    coordinator_factory: Optional[CoordinatorFactory] = None,
) -> List[Tuple[Dict[str, List[ScriptOp]],
                Optional[CoordinatorFactory],
                Optional[List[str]]]]:
    """The acceptance schedules as (scripts, factory, shard_endpoints)
    rows — explored separately so each stays inside the interleaving
    budget; results merge. With a caller-supplied ``coordinator_factory``
    (the broken-twin tests) the redirect schedule runs UNSHARDED against
    that factory: routing is only modeled when we also control the oracle's
    shard configuration."""
    rows: List[Tuple[Dict[str, List[ScriptOp]],
                     Optional[CoordinatorFactory],
                     Optional[List[str]]]] = [
        (default_scripts(), coordinator_factory, None),
        (ckpt_plane_scripts(), coordinator_factory, None),
        (watch_scripts(), coordinator_factory, None),
    ]
    if coordinator_factory is None:
        rows.append((watch_redirect_scripts(), _sharded_root_factory,
                     list(SHARD_ENDPOINTS)))
    else:
        rows.append((watch_redirect_scripts(), coordinator_factory, None))
    return rows


def load_state_effects(root: str, schema_rel: str = "protocol_schema.json"):
    """(state_effects dict or None, declared op set or None, error string)."""
    path = os.path.join(root, schema_rel)
    try:
        with open(path, "r", encoding="utf-8") as f:
            schema = json.load(f)
    except OSError:
        return None, None, f"{schema_rel} is missing"
    except json.JSONDecodeError as e:
        return None, None, f"{schema_rel} is not valid JSON: {e}"
    effects = schema.get("state_effects")
    ops = set(schema.get("ops", {}))
    if effects is None:
        return None, ops, (
            f"{schema_rel} has no state_effects block — the behavioral "
            "spec EDL009 model-checks against"
        )
    return effects, ops, None


def run_default(
    coordinator_factory: Optional[CoordinatorFactory] = None,
    effects: Optional[Dict[str, Dict[str, Any]]] = None,
    fuzz_samples: int = 0,
    fuzz_seed: int = 0,
    max_traces: int = 20000,
    max_violations: int = 25,
) -> ModelCheckResult:
    if effects is None:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        effects, _ops, err = load_state_effects(root)
        if err:
            raise ModelCheckError(err)
    result = ModelCheckResult()
    for scripts, factory, endpoints in default_schedules(coordinator_factory):
        extra = explore(
            scripts, effects,
            coordinator_factory=factory,
            fuzz_samples=fuzz_samples, fuzz_seed=fuzz_seed,
            max_traces=max_traces, max_violations=max_violations,
            shard_endpoints=endpoints,
        )
        result.traces += extra.traces
        result.replays += extra.replays
        result.violations.extend(extra.violations)
    return result


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m edl_tpu.analysis.modelcheck",
        description=(
            "Bounded explicit-state model check of the coordinator "
            "protocol's behavioral spec (protocol_schema.json "
            "state_effects) against the in-process oracle."
        ),
    )
    parser.add_argument(
        "--fuzz", type=int, default=0, metavar="N",
        help="seeded random-walk mode: sample N schedules instead of "
             "exhaustive DFS (findings are a subset of the exhaustive run)")
    parser.add_argument(
        "--seed", type=int, default=0, help="fuzz-mode RNG seed")
    parser.add_argument(
        "--max-traces", type=int, default=20000,
        help="exploration budget (default: 20000)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable result")
    args = parser.parse_args(argv)

    result = run_default(
        fuzz_samples=args.fuzz, fuzz_seed=args.seed,
        max_traces=args.max_traces,
    )
    if args.json:
        print(json.dumps({
            "traces": result.traces,
            "replays": result.replays,
            "violations": [
                {"kind": v.kind, "message": v.message, "trace": v.trace}
                for v in result.violations
            ],
        }, indent=2))
    else:
        mode = f"fuzz({args.fuzz}, seed={args.seed})" if args.fuzz else "exhaustive"
        print(
            f"modelcheck [{mode}]: {result.traces} trace(s) explored, "
            f"{result.replays} replayed against InProcessCoordinator, "
            f"{len(result.violations)} violation(s)"
        )
        for v in result.violations:
            print(f"  [{v.kind}] {v.message}")
            print(f"    trace: {v.trace}")
    return 0 if result.ok() else 1


if __name__ == "__main__":
    import sys

    sys.exit(main())
