"""CLI: ``python -m edl_tpu.analysis [paths...]``.

Exit codes: 0 = clean (every finding baselined or suppressed), 1 = new
findings (or stale baseline entries — the ratchet cuts both ways), 2 =
usage / parse errors.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from edl_tpu.analysis.baseline import (
    DEFAULT_BASELINE_NAME,
    apply_baseline,
    load_baseline,
    write_baseline,
)
from edl_tpu.analysis.engine import analyze, detect_root


def _build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m edl_tpu.analysis",
        description=(
            "Domain-specific static analysis for the elastic-training "
            "codebase (lock-discipline, trace-hygiene, sharding-"
            "consistency, blocking-in-lock, exception-hygiene, "
            "thread-races, wire-protocol, elastic-determinism, "
            "protocol-model, durability-model)."
        ),
    )
    parser.add_argument(
        "paths",
        nargs="*",
        default=["edl_tpu"],
        help="files or directories to analyze (default: edl_tpu)",
    )
    parser.add_argument(
        "--format",
        choices=("text", "json", "sarif"),
        default="text",
        help=(
            "output format (default: text; sarif emits a SARIF 2.1.0 "
            "document for CI code annotations)"
        ),
    )
    parser.add_argument(
        "--rules",
        default=None,
        metavar="EDL001,EDL002,...",
        help="comma-separated rule subset (default: all)",
    )
    parser.add_argument(
        "--baseline",
        default=None,
        metavar="PATH",
        help=(
            f"baseline file (default: <root>/{DEFAULT_BASELINE_NAME} when "
            "present; 'none' disables)"
        ),
    )
    parser.add_argument(
        "--write-baseline",
        action="store_true",
        help="rewrite the baseline from current findings and exit 0",
    )
    parser.add_argument(
        "--show-baselined",
        action="store_true",
        help="also print findings accepted by the baseline",
    )
    parser.add_argument(
        "--list-rules",
        action="store_true",
        help="print the rule catalog and exit",
    )
    parser.add_argument(
        "--jobs",
        type=int,
        default=None,
        metavar="N",
        help=(
            "worker processes for the per-file phase (default: auto — "
            "EDL_ANALYZE_JOBS, else one per core, serial for small trees)"
        ),
    )
    parser.add_argument(
        "--timings",
        action="store_true",
        help="print per-rule wall-clock seconds after the summary",
    )
    parser.add_argument(
        "--write-protocol",
        action="store_true",
        help=(
            "re-extract the native wire schema into protocol_schema.json "
            "(the EDL007 ratchet artifact; the hand-authored state_effects "
            "block is preserved) and exit 0"
        ),
    )
    return parser


def _write_protocol(root: str) -> int:
    from edl_tpu.analysis.checkers.wire_protocol import (
        DEFAULT_SCHEMA_NAME,
        load_native_schema,
    )

    schema, native_rel = load_native_schema(root, {})
    if schema is None:
        print(f"error: {native_rel} not found under {root}", file=sys.stderr)
        return 2
    target = os.path.join(root, DEFAULT_SCHEMA_NAME)
    # state_effects is hand-authored behavioral annotation (the EDL009
    # model-check spec), not extractable from the .cc — carry it through
    # regeneration so --write-protocol never silently drops it.
    if os.path.isfile(target):
        try:
            with open(target, "r", encoding="utf-8") as f:
                previous = json.load(f)
        except (json.JSONDecodeError, OSError):
            previous = {}
        if isinstance(previous, dict) and "state_effects" in previous:
            schema["state_effects"] = previous["state_effects"]
    with open(target, "w", encoding="utf-8") as f:
        json.dump(schema, f, indent=2, sort_keys=True)
        f.write("\n")
    print(
        f"wrote {target}: {len(schema['ops'])} op(s) extracted from "
        f"{native_rel}"
    )
    return 0


def _list_rules() -> int:
    from edl_tpu.analysis.checkers import ALL_CHECKERS

    for cls in ALL_CHECKERS:
        print(f"{cls.rule}  {cls.info.name}: {cls.info.description}")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    args = _build_parser().parse_args(argv)
    if args.list_rules:
        return _list_rules()

    rules = (
        [r.strip() for r in args.rules.split(",") if r.strip()]
        if args.rules
        else None
    )
    root = detect_root(args.paths)
    if args.write_protocol:
        return _write_protocol(root)
    report = analyze(args.paths, root=root, rules=rules, jobs=args.jobs)

    baseline_path = args.baseline
    if baseline_path is None:
        candidate = os.path.join(root, DEFAULT_BASELINE_NAME)
        baseline_path = candidate if os.path.isfile(candidate) else "none"

    if args.write_baseline:
        target = (
            baseline_path
            if baseline_path != "none"
            else os.path.join(root, DEFAULT_BASELINE_NAME)
        )
        baseline = write_baseline(target, report.findings)
        print(
            f"wrote {target}: {baseline.total()} accepted finding(s) "
            f"across {len(baseline.entries)} entries"
        )
        return 0

    if baseline_path != "none":
        try:
            baseline = load_baseline(baseline_path)
        except (ValueError, json.JSONDecodeError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        new, accepted, stale = apply_baseline(report.findings, baseline)
    else:
        new, accepted, stale = report.findings, [], []

    if args.format == "sarif":
        from edl_tpu.analysis.sarif import to_sarif

        print(json.dumps(to_sarif(new, accepted), indent=2))
    elif args.format == "json":
        payload = {
            "version": 1,
            "findings": [
                {**f.to_dict(), "baselined": False} for f in new
            ] + [{**f.to_dict(), "baselined": True} for f in accepted],
            "stale_baseline": stale,
            "summary": {
                "new": len(new),
                "baselined": len(accepted),
                "suppressed": len(report.suppressed),
                "files": report.files_checked,
                "jobs": report.jobs,
                "timings": {
                    r: round(s, 4) for r, s in sorted(report.timings.items())
                },
                "reduce_timings": {
                    r: round(s, 4)
                    for r, s in sorted(report.reduce_timings.items())
                },
                "parse_errors": [
                    {"path": p, "error": e} for p, e in report.parse_errors
                ],
            },
        }
        print(json.dumps(payload, indent=2))
    else:
        for f in new:
            print(f"{f.location()}: {f.rule} {f.message}")
        if args.show_baselined:
            for f in accepted:
                print(f"{f.location()}: {f.rule} [baselined] {f.message}")
        for entry in stale:
            print(
                f"stale baseline entry ({entry['rule']} {entry['path']} "
                f"'{entry['symbol']}'): finding no longer occurs — run "
                "--write-baseline to ratchet it out"
            )
        for path, err in report.parse_errors:
            print(f"{path}: parse error: {err}", file=sys.stderr)
        print(
            f"{len(new)} new, {len(accepted)} baselined, "
            f"{len(report.suppressed)} suppressed finding(s) across "
            f"{report.files_checked} file(s)"
        )
        if args.timings:
            for rule, sec in sorted(report.timings.items()):
                print(f"  {rule}: {sec:.3f}s (map)")
            for rule, sec in sorted(report.reduce_timings.items()):
                print(f"  {rule}: {sec:.3f}s (reduce)")
            print(f"  jobs: {report.jobs}")

    if report.parse_errors:
        return 2
    return 1 if new or stale else 0


if __name__ == "__main__":
    sys.exit(main())
