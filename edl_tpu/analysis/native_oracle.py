"""Crash-injected native oracle for the EDL010 durability lane.

Replays each modeled trace against the REAL ``edl-coordinator`` binary:
ops go over the wire, and a modeled crash point is realized by the
binary's env-gated hooks (``native/coordinator/coordinator.cc``):

- ``EDL_COORD_CRASH_AFTER_APPENDS=<n>`` — ``_exit(2)`` after the n-th
  committed append frame (the frame IS durable; the reply never flushes,
  which is exactly the ``pre_ack`` crash mode);
- ``EDL_COORD_CRASH_TORN=1`` — before dying, rewind the journal to
  mid-frame (commit marker gone, final data record halved): the on-disk
  shape of power dying inside the write instead of after it;
- ``EDL_COORD_COMPACT_EVERY=<n>`` + ``EDL_COORD_CRASH_IN_SNAPSHOT=<k>`` —
  force the compaction threshold down and die inside the k-th snapshot
  write before its rename (``during_compaction``: journal untouched, the
  triggering frame lost, unacked).

Arming needs the crash point at BOOT time (the env is read once, in the
coordinator's constructor), so the oracle reads it from the trace before
replay begins: ``begin_trace`` scans for the crash event and uses the
``crash_info`` the MODEL computed during exploration (``frames_before`` /
``records_before`` / ``snapshots_before``). Frame counts line up because
both sides group-commit one frame per op turn and both write a boot meta
frame first — the server's readiness ping flushes the native one before
any scripted op runs. A count mismatch is NOT masked: the binary then
dies at a different op than the model crashed at, and the replay reports
the divergence as a finding.

The post-crash restart boots with every hook cleared (the model's
recovery also drops ``compact_every`` — env does not survive a crash) and
must reconstruct exactly the committed journal prefix; any drift surfaces
as an acked-durability violation in ``_replay_trace``.
"""

from __future__ import annotations

import os
import shutil
import tempfile
import weakref
from typing import Any, Dict, List, Optional

CRASH_OP = "crash"


class NativeCrashOracle:
    """Oracle adapter over a crash-armed ``edl-coordinator`` subprocess.

    Implements the durable-oracle protocol ``_replay_trace`` drives:
    ``begin_trace(trace)`` (boot, armed from the trace's crash event),
    ``client(worker)``, ``model_crash(crash_info) -> reply``, ``close()``.
    """

    RUN_ID = "modelcheck"

    def __init__(self, compact_every: Optional[int] = None):
        self._dir = tempfile.mkdtemp(prefix="edl-modelcheck-native-")
        self._state = os.path.join(self._dir, "state.jsonl")
        self._compact_every = compact_every
        self._server = None
        self._crash: Optional[Dict[str, Any]] = None
        self._finalizer = weakref.finalize(
            self, shutil.rmtree, self._dir, True)

    # -- trace lifecycle -------------------------------------------------------

    def begin_trace(self, trace: List[Any]) -> None:
        for ev in trace:
            if ev.op.op == CRASH_OP:
                self._crash = dict(ev.crash_info or {})
                break
        env: Dict[str, str] = {}
        if self._compact_every is not None:
            env["EDL_COORD_COMPACT_EVERY"] = str(self._compact_every)
        if self._crash and self._crash.get("mode") != "clean" \
                and int(self._crash.get("inflight_records", 0)) > 0:
            mode = self._crash["mode"]
            if mode == "during_compaction":
                env["EDL_COORD_COMPACT_EVERY"] = str(
                    int(self._crash["records_before"]))
                env["EDL_COORD_CRASH_IN_SNAPSHOT"] = str(
                    int(self._crash["snapshots_before"]) + 1)
            else:  # pre_ack / torn: die after the inflight op's append
                env["EDL_COORD_CRASH_AFTER_APPENDS"] = str(
                    int(self._crash["frames_before"]) + 1)
                if mode == "torn":
                    env["EDL_COORD_CRASH_TORN"] = "1"
        self._boot(env)

    def _boot(self, env: Dict[str, str]) -> None:
        from edl_tpu.coordinator.server import CoordinatorServer

        # Near-infinite lease/TTL windows: wall time must not pass for the
        # model. auth_token="" disables auth regardless of the parent env.
        self._server = CoordinatorServer(
            task_lease_sec=1e9, heartbeat_ttl_sec=1e9,
            state_file=self._state, run_id=self.RUN_ID,
            auth_token="", extra_env=env)
        # start()'s readiness ping runs one event-loop turn, flushing the
        # boot meta frame as its own append — frame #1 on both sides.
        self._server.start(wait=30.0)

    def client(self, worker: str):
        return self._server.client(worker)

    # -- the crash step --------------------------------------------------------

    def model_crash(self, info: Dict[str, Any]) -> Dict[str, Any]:
        from edl_tpu.coordinator.client import (
            CoordinatorClient,
            CoordinatorError,
        )

        mode = info.get("mode", "clean")
        armed = mode != "clean" and int(info.get("inflight_records", 0)) > 0
        if mode != "clean":
            # Deliver the inflight op. When armed, the server _exit(2)s
            # inside this call — ack-after-durability means the journal
            # write happens BEFORE the reply flushes, so the client sees a
            # dead connection, never the ack. An unarmed delivery (the op
            # deduplicated: zero records, every mode degrades to clean)
            # returns normally and the reply is discarded, matching the
            # model's lost-reply semantics.
            for sub in info.get("inflight", []):
                fields = dict(sub)
                op = fields.pop("op", "")
                w = fields.pop("worker", "")
                cl = CoordinatorClient(port=self._server.port, worker=w,
                                       token="", retry=None,
                                       connect_timeout=5.0)
                try:
                    cl.call(op, timeout=15.0, **fields)
                except (CoordinatorError, OSError):
                    pass
                finally:
                    cl.close()
        if armed:
            rc = self._server.wait()
            if rc != 2:
                # Surfaced as a reply divergence: the hook did not fire
                # where the model crashed (a frame-count mismatch) — the
                # epoch below will disagree too, but say why.
                self._server.stop()
                return {"ok": False,
                        "error": f"armed crash hook exited rc={rc}, "
                                 "expected _exit(2) at the modeled frame"}
            self._server.stop()  # reap bookkeeping; process already dead
        else:
            # Clean crash between turns: kill -9. Every acked frame is
            # already group-committed, so nothing is in flight.
            self._server.kill()
        # Restart with every crash hook cleared — the model's recovery
        # also drops compact_every (env does not survive the crash).
        self._server.extra_env = {}
        self._server.start(wait=30.0)
        cl = self._server.client("")
        try:
            st = cl.call("status")
        finally:
            cl.close()
        return {"ok": True, "crash": mode,
                "epoch": int(st.get("epoch", -1))}

    def close(self) -> None:
        if self._server is not None:
            self._server.stop()
            self._server = None
        self._finalizer()


def native_toolchain_available() -> bool:
    """True when the native coordinator can be built (a C++ toolchain is
    on PATH) — the modelcheck-native lane's clean-skip condition."""
    cxx = os.environ.get("CXX", "g++")
    return shutil.which(cxx) is not None
