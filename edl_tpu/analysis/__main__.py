"""Entry point for ``python -m edl_tpu.analysis``."""

import sys

from edl_tpu.analysis.cli import main

sys.exit(main())
