"""EDL007 — wire-protocol conformance across the three coordinator
implementations.

The control-plane protocol (newline-delimited JSON over TCP) exists three
times: the C++ server's dispatch table (`native/coordinator/coordinator.cc`),
the wire client (`edl_tpu/coordinator/client.py`), and the hermetic twin
(`edl_tpu/coordinator/inprocess.py`). Nothing at runtime checks they agree —
a field added to one and not the others only surfaces as a recovery-path
hang weeks later. This pass makes the protocol a *checked artifact*:

1. **Native extraction** (regex over the .cc, no compiler needed): every
   ``if (op == "...")`` arm of the dispatch table, each handler's request
   fields (``get_str(req, ...)`` / ``get_num(req, ...)`` / ``req.find``) and
   reply fields (``.field(...)`` / ``.field_null(...)``), expanding helpers
   reached via ``return helper(...)`` (``membership_reply``) and — for
   fd-taking handlers — helpers that write parked/deferred replies
   (``release_sync``). ``handle()``'s ``stamp_epoch`` adds the implicit
   ``epoch`` to every non-deferred reply; deferred replies must carry it
   explicitly or that is a finding.
2. **Schema ratchet:** the extracted schema is diffed against the committed
   ``protocol_schema.json``. Any drift (op added/removed, field change,
   stamping change) is a finding until the artifact is regenerated with
   ``--write-protocol`` — so the schema diff shows up in review, like the
   baseline.
3. **Python conformance:** every literal ``client.call("op", field=...)``
   site must name a dispatch-table op and send only fields the server
   reads (plus the ``worker``/``token`` envelope); ``InProcessClient.call``
   must cover exactly the native op set and each branch's reply-dict keys
   must equal the native reply fields (resolving ``self._c.method()``
   delegation, ``_note_reply`` pass-through, and ``_stamp`` epoch
   injection).

Config overrides (all relative to the analysis root) exist so fixtures can
exercise the rule on a toy .cc/.py pair: ``edl007_native_source``,
``edl007_schema``, ``edl007_prefixes``.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, Iterator, List, Optional, Sequence, Set, Tuple

import ast

from edl_tpu.analysis.core import Finding, RuleInfo, SourceFile

DEFAULT_NATIVE_SOURCE = "native/coordinator/coordinator.cc"
DEFAULT_SCHEMA_NAME = "protocol_schema.json"
#: python files whose .call(...) sites / shim classes speak the protocol
DEFAULT_PREFIXES = ("edl_tpu/coordinator/", "edl_tpu/cli.py")

#: fields every request may carry regardless of op: the client's envelope
ENVELOPE_REQUEST = ("op", "token", "worker")

#: ops the server refuses inside a batch frame (they park the connection,
#: nest framing, or — watch — bind an out-of-band push stream to the fd)
NON_BATCHABLE = ("batch", "barrier", "sync", "watch")

SCHEMA_VERSION = 1

_OP_ARM_RE = re.compile(r'if \(op == "(\w+)"\)')
_MEMBER_RE = re.compile(
    r"^[A-Za-z_][\w:<>,&* ]*\bCoordinator::(\w+)\s*\(([^)]*)\)", re.M
)
_HANDLER_CALL_RE = re.compile(r"\b(op_\w+)\s*\(")
_REQ_FIELD_RE = re.compile(
    r'(?:get_str|get_num)\(req,\s*"(\w+)"|req\.find\("(\w+)"\)'
)
_REPLY_FIELD_RE = re.compile(r'\.field(?:_null)?\("(\w+)"')
_RETURN_HELPER_RE = re.compile(r"return (\w+)\(")
_CALLED_MEMBER_RE = re.compile(r"\b(\w+)\s*\(")


def _strip_comments(cc_text: str) -> str:
    """Drop // and /* */ comments (quote-aware for //): a comment that
    mentions ``deferred_`` or ``.field("x")`` must not count as code."""
    cc_text = re.sub(r"/\*.*?\*/", " ", cc_text, flags=re.S)
    out_lines = []
    for line in cc_text.split("\n"):
        in_str = False
        i = 0
        while i < len(line) - 1:
            ch = line[i]
            if ch == '"' and (i == 0 or line[i - 1] != "\\"):
                in_str = not in_str
            elif not in_str and ch == "/" and line[i + 1] == "/":
                line = line[:i]
                break
            i += 1
        out_lines.append(line)
    return "\n".join(out_lines)


def extract_native_schema(cc_text: str, source_relpath: str) -> Dict[str, Any]:
    """Parse the dispatch table + handlers out of coordinator.cc text into
    the ``protocol_schema.json`` shape. Pure function of the source text, so
    both the checker and ``--write-protocol`` produce identical artifacts."""
    cc_text = _strip_comments(cc_text)
    # Member-function spans: text between successive `... Coordinator::name(`.
    matches = list(_MEMBER_RE.finditer(cc_text))
    spans: Dict[str, str] = {}
    params: Dict[str, str] = {}
    for i, m in enumerate(matches):
        end = matches[i + 1].start() if i + 1 < len(matches) else len(cc_text)
        # First definition wins (declarations inside the class body are not
        # matched — they lack the Coordinator:: prefix).
        spans.setdefault(m.group(1), cc_text[m.start():end])
        params.setdefault(m.group(1), m.group(2))

    stamped = "stamp_epoch(dispatch" in cc_text

    def helper_reply(name: str, seen: Set[str]) -> Set[str]:
        if name in seen or name not in spans:
            return set()
        seen.add(name)
        body = spans[name]
        out = set(_REPLY_FIELD_RE.findall(body))
        for ret in _RETURN_HELPER_RE.findall(body):
            out |= helper_reply(ret, seen)
        return out

    ops: Dict[str, Dict[str, Any]] = {}
    arms = list(_OP_ARM_RE.finditer(cc_text))
    for i, arm in enumerate(arms):
        op = arm.group(1)
        if op in ops:
            continue  # batch appears in handle() AND as a sub-op guard
        nxt = arms[i + 1].start() if i + 1 < len(arms) else len(cc_text)
        chunk = cc_text[arm.end():min(nxt, arm.end() + 600)]
        handler = _HANDLER_CALL_RE.search(chunk)
        request: Set[str] = set()
        reply: Set[str] = set()
        deferred = False
        if handler and handler.group(1) in spans:
            hname = handler.group(1)
            body = spans[hname]
            for a, b in _REQ_FIELD_RE.findall(body):
                request.add(a or b)
            reply |= set(_REPLY_FIELD_RE.findall(body))
            for ret in _RETURN_HELPER_RE.findall(body):
                reply |= helper_reply(ret, {hname})
            takes_fd = "int fd" in params.get(hname, "")
            if takes_fd:
                # A parked connection's eventual reply may be written by a
                # helper into the deferred queue (sync -> release_sync).
                for callee in set(_CALLED_MEMBER_RE.findall(body)):
                    if callee != hname and "deferred_" in spans.get(callee, ""):
                        deferred = True
                        reply |= helper_reply(callee, {hname})
                if "deferred_" in body:
                    deferred = True
        else:
            # Inline arm (ping): fields from the single return statement.
            stmt = chunk.split(";", 1)[0]
            reply |= set(_REPLY_FIELD_RE.findall(stmt))
        ops[op] = {
            "request": sorted(request),
            "reply": sorted(reply),  # effective epoch added below
            "deferred": deferred,
            "batchable": op not in NON_BATCHABLE,
        }

    # Deferred replies bypass handle()'s stamp — they must carry epoch in
    # their own fields. Record the raw miss before normalizing.
    unstamped_deferred = sorted(
        op for op, spec in ops.items()
        if spec["deferred"] and "epoch" not in spec["reply"]
    )
    if stamped:
        for spec in ops.values():
            if "epoch" not in spec["reply"]:
                spec["reply"] = sorted(spec["reply"] + ["epoch"])

    return {
        "version": SCHEMA_VERSION,
        "source": source_relpath,
        "epoch_stamped": stamped,
        "unstamped_deferred_ops": unstamped_deferred,
        "envelope": {"request": sorted(ENVELOPE_REQUEST)},
        "ops": {op: ops[op] for op in sorted(ops)},
    }


def load_native_schema(
    root: str, config: Dict[str, Any]
) -> Tuple[Optional[Dict[str, Any]], str]:
    """(extracted schema or None, native source relpath)."""
    rel = config.get("edl007_native_source", DEFAULT_NATIVE_SOURCE)
    path = os.path.join(root, rel)
    try:
        with open(path, "r", encoding="utf-8") as f:
            text = f.read()
    except OSError:
        return None, rel
    return extract_native_schema(text, rel), rel


class WireProtocolChecker:
    rule = "EDL007"
    name = "wire-protocol"
    scope = "program"
    info = RuleInfo(
        rule="EDL007",
        name="wire-protocol",
        description=(
            "the C++ dispatch table, the wire client's call() sites, the "
            "in-process twin, and the committed protocol_schema.json must "
            "agree on ops, request/reply fields, and epoch stamping"
        ),
    )

    # -- map phase -------------------------------------------------------------

    def summarize(self, sf: SourceFile, ctx) -> Optional[Dict[str, Any]]:
        prefixes = tuple(ctx.config.get("edl007_prefixes", DEFAULT_PREFIXES))
        if not any(
            sf.relpath == p or sf.relpath.startswith(p) for p in prefixes
        ):
            return None
        out: Dict[str, Any] = {"call_sites": [], "shim": None}
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call):
                site = self._call_site(node)
                if site is not None:
                    out["call_sites"].append(site)
            elif (
                isinstance(node, ast.ClassDef)
                and node.name == "InProcessClient"
            ):
                out["shim"] = self._scan_shim(sf.tree, node)
        if not out["call_sites"] and out["shim"] is None:
            return None
        return out

    @staticmethod
    def _call_site(node: ast.Call):
        """('op', sorted field kwargs, line, col) for ``<x>.call("op", ...)``
        with a literal op name; None otherwise."""
        func = node.func
        if not (isinstance(func, ast.Attribute) and func.attr == "call"):
            return None
        if not (
            node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            return None
        fields = sorted(
            kw.arg for kw in node.keywords
            if kw.arg is not None and kw.arg != "timeout"
        )
        return (node.args[0].value, fields, node.lineno, node.col_offset)

    def _scan_shim(
        self, tree: ast.Module, shim_cls: ast.ClassDef
    ) -> Dict[str, Any]:
        """Per-op reply-key sets for ``InProcessClient.call``, resolving
        delegation into the coordinator class in the same module."""
        coord_keys = self._coordinator_reply_keys(tree)

        call_fn = next(
            (
                n for n in shim_cls.body
                if isinstance(n, ast.FunctionDef) and n.name == "call"
            ),
            None,
        )
        shim: Dict[str, Any] = {
            "line": shim_cls.lineno,
            "call_line": call_fn.lineno if call_fn else shim_cls.lineno,
            "ops": {},
        }
        if call_fn is None:
            return shim

        def branch_ops(test: ast.AST) -> List[str]:
            # `op == "x"` or `op in ("x", "y")`
            if not (
                isinstance(test, ast.Compare)
                and isinstance(test.left, ast.Name)
                and test.left.id == "op"
                and len(test.comparators) == 1
            ):
                return []
            cmp = test.comparators[0]
            if isinstance(test.ops[0], ast.Eq) and isinstance(cmp, ast.Constant):
                return [cmp.value]
            if isinstance(test.ops[0], ast.In) and isinstance(
                cmp, (ast.Tuple, ast.List)
            ):
                return [
                    e.value for e in cmp.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                ]
            return []

        for node in ast.walk(call_fn):
            if not isinstance(node, ast.If):
                continue
            ops = branch_ops(node.test)
            if not ops:
                continue
            keys: Set[str] = set()
            for sub in node.body:
                for ret in ast.walk(sub):
                    if isinstance(ret, ast.Return) and ret.value is not None:
                        keys |= self._reply_keys(ret.value, coord_keys)
            for op in ops:
                spec = shim["ops"].setdefault(
                    op, {"keys": set(), "line": node.lineno}
                )
                spec["keys"] |= keys
        for spec in shim["ops"].values():
            spec["keys"] = sorted(spec["keys"])
        return shim

    def _coordinator_reply_keys(self, tree: ast.Module) -> Dict[str, Set[str]]:
        """InProcessCoordinator method -> union of returned dict keys, with
        intra-class ``return self.helper(...)`` expansion to a fixpoint."""
        coord = next(
            (
                n for n in tree.body
                if isinstance(n, ast.ClassDef)
                and n.name == "InProcessCoordinator"
            ),
            None,
        )
        if coord is None:
            return {}
        raw: Dict[str, Tuple[Set[str], Set[str]]] = {}
        for fn in coord.body:
            if not isinstance(fn, ast.FunctionDef):
                continue
            keys: Set[str] = set()
            helpers: Set[str] = set()
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Return) and node.value):
                    continue
                keys |= self._literal_keys(node.value)
                for call in ast.walk(node.value):
                    if (
                        isinstance(call, ast.Call)
                        and isinstance(call.func, ast.Attribute)
                        and isinstance(call.func.value, ast.Name)
                        and call.func.value.id == "self"
                    ):
                        helpers.add(call.func.attr)
            raw[fn.name] = (keys, helpers)
        out = {name: set(keys) for name, (keys, _) in raw.items()}
        changed = True
        while changed:
            changed = False
            for name, (_, helpers) in raw.items():
                for h in helpers:
                    if h in out and not out[h] <= out[name]:
                        out[name] |= out[h]
                        changed = True
        return out

    def _reply_keys(
        self, expr: ast.AST, coord_keys: Dict[str, Set[str]]
    ) -> Set[str]:
        """Keys of the reply a shim branch returns: dict literals, plus
        delegation through ``self._c.method(...)``; ``self._note_reply(x)``
        is transparent and ``self._stamp(x)`` injects ``epoch``."""
        keys = self._literal_keys(expr)
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not isinstance(func, ast.Attribute):
                continue
            recv = func.value
            if isinstance(recv, ast.Name) and recv.id == "self":
                if func.attr == "_stamp":
                    keys.add("epoch")
                # _note_reply/_stamp arguments are walked anyway (ast.walk
                # descends into call args), so nothing else to do here.
            elif (
                isinstance(recv, ast.Attribute)
                and isinstance(recv.value, ast.Name)
                and recv.value.id == "self"
                and recv.attr == "_c"
                and func.attr in coord_keys
            ):
                keys |= coord_keys[func.attr]
        return keys

    @staticmethod
    def _literal_keys(expr: ast.AST) -> Set[str]:
        keys: Set[str] = set()
        for node in ast.walk(expr):
            if isinstance(node, ast.Dict):
                for k in node.keys:
                    if isinstance(k, ast.Constant) and isinstance(k.value, str):
                        keys.add(k.value)
        return keys

    # -- reduce phase ----------------------------------------------------------

    def reduce(
        self, summaries: List[Tuple[str, Optional[Dict[str, Any]]]], ctx
    ) -> Iterator[Finding]:
        schema, native_rel = load_native_schema(ctx.root, ctx.config)
        if schema is None:
            # No native source in this tree (pure-python fixture dirs):
            # nothing to conform to.
            return

        def cc_finding(message: str, symbol: str = "") -> Finding:
            return Finding(
                rule=self.rule, path=native_rel, line=1, col=0,
                message=message, symbol=symbol,
            )

        ops = schema["ops"]

        for op in schema["unstamped_deferred_ops"]:
            yield cc_finding(
                f"deferred reply for '{op}' bypasses stamp_epoch but does "
                "not carry an explicit 'epoch' field",
                symbol=op,
            )
        if not schema["epoch_stamped"]:
            yield cc_finding(
                "handle() does not stamp_epoch replies — clients cannot "
                "coalesce epoch observation"
            )

        # Ratchet: extracted schema vs the committed artifact.
        schema_rel = ctx.config.get("edl007_schema", DEFAULT_SCHEMA_NAME)
        yield from self._diff_committed(schema, schema_rel, ctx, cc_finding)

        request_ok = {
            op: set(spec["request"]) | set(ENVELOPE_REQUEST)
            for op, spec in ops.items()
        }
        for relpath, summary in sorted(summaries):
            if not summary:
                continue
            for op, fields, line, col in summary["call_sites"]:
                if op not in ops:
                    yield Finding(
                        rule=self.rule, path=relpath, line=line, col=col,
                        message=(
                            f"call('{op}') is not in the native dispatch "
                            "table"
                        ),
                    )
                    continue
                extra = sorted(set(fields) - request_ok[op])
                if extra:
                    yield Finding(
                        rule=self.rule, path=relpath, line=line, col=col,
                        message=(
                            f"call('{op}') sends field(s) the server never "
                            f"reads: {', '.join(extra)}"
                        ),
                    )
            if summary["shim"] is not None:
                yield from self._check_shim(relpath, summary["shim"], schema)

    def _diff_committed(
        self, schema: Dict[str, Any], schema_rel: str, ctx, cc_finding
    ) -> Iterator[Finding]:
        path = os.path.join(ctx.root, schema_rel)
        try:
            with open(path, "r", encoding="utf-8") as f:
                committed = json.load(f)
        except OSError:
            yield cc_finding(
                f"{schema_rel} is missing — run --write-protocol to commit "
                "the extracted schema"
            )
            return
        except json.JSONDecodeError as e:
            yield cc_finding(f"{schema_rel} is not valid JSON: {e}")
            return
        if committed == schema:
            return
        cops = committed.get("ops", {})
        for op in sorted(set(schema["ops"]) - set(cops)):
            yield cc_finding(
                f"op '{op}' is in the dispatch table but not in "
                f"{schema_rel} — run --write-protocol and review the diff",
                symbol=op,
            )
        for op in sorted(set(cops) - set(schema["ops"])):
            yield cc_finding(
                f"op '{op}' is in {schema_rel} but no longer in the "
                "dispatch table — run --write-protocol and review the diff",
                symbol=op,
            )
        for op in sorted(set(cops) & set(schema["ops"])):
            if cops[op] != schema["ops"][op]:
                yield cc_finding(
                    f"op '{op}' drifted from {schema_rel} (request/reply/"
                    "deferred changed) — run --write-protocol and review "
                    "the diff",
                    symbol=op,
                )
        if committed.get("epoch_stamped") != schema["epoch_stamped"]:
            yield cc_finding(
                f"epoch stamping changed vs {schema_rel} — run "
                "--write-protocol and review the diff"
            )

    def _check_shim(
        self, relpath: str, shim: Dict[str, Any], schema: Dict[str, Any]
    ) -> Iterator[Finding]:
        ops = schema["ops"]
        for op in sorted(set(ops) - set(shim["ops"])):
            yield Finding(
                rule=self.rule, path=relpath,
                line=shim["call_line"], col=0,
                message=(
                    f"InProcessClient.call() does not handle op '{op}' "
                    "(native dispatch does)"
                ),
            )
        for op in sorted(set(shim["ops"]) - set(ops)):
            yield Finding(
                rule=self.rule, path=relpath,
                line=shim["ops"][op]["line"], col=0,
                message=(
                    f"InProcessClient.call() handles op '{op}' which is "
                    "not in the native dispatch table"
                ),
            )
        for op in sorted(set(shim["ops"]) & set(ops)):
            have = set(shim["ops"][op]["keys"])
            want = set(ops[op]["reply"])
            if have == want:
                continue
            missing = sorted(want - have)
            extra = sorted(have - want)
            parts = []
            if missing:
                parts.append(f"missing: {', '.join(missing)}")
            if extra:
                parts.append(f"extra: {', '.join(extra)}")
            yield Finding(
                rule=self.rule, path=relpath,
                line=shim["ops"][op]["line"], col=0,
                message=(
                    f"in-process reply for '{op}' diverges from the native "
                    f"reply fields ({'; '.join(parts)})"
                ),
            )
