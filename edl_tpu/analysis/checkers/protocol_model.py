"""EDL009 — protocol state-machine model checking (whole-program).

EDL007 ratchets the protocol's *shape*; this rule checks its *behavior*.
``protocol_schema.json`` carries a hand-authored ``state_effects`` block —
per-op declarations of how each op touches coordinator state (epoch bumps,
lease acquire/release, ``req_id``/``op_id`` dedup, fd-parking). The reduce
phase:

1. validates that ``state_effects`` covers exactly the extracted op set
   (an op added to the dispatch table without a behavioral annotation is a
   finding, as is a stale annotation);
2. runs the bounded explicit-state exploration from
   ``edl_tpu.analysis.modelcheck``: every interleaving of the default
   2-worker scripted config (crash+restart, duplicate delivery, a ``batch``
   frame) is executed through the abstract model AND replayed against
   ``InProcessCoordinator``, checking epoch monotonicity, exactly-once
   replay, lease exclusivity, task conservation, and barrier/sync progress.

A model/oracle divergence or invariant violation is a finding anchored on
the in-process twin — the executable spec drifted from the declared
behavior. Fixture trees are exempt automatically: the reduce phase is
skipped entirely unless the target file (default
``edl_tpu/coordinator/inprocess.py``) was among the analyzed files, so
per-rule fixture runs never pay the exploration cost.

Config overrides: ``edl009_target`` (relpath of the oracle module),
``edl009_schema`` (schema artifact relpath), ``edl009_max_traces`` /
``edl009_fuzz`` / ``edl009_fuzz_seed`` (exploration budget; fuzz > 0
switches the checker to the seeded random-walk mode).
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Tuple

from edl_tpu.analysis.core import Finding, RuleInfo, SourceFile

DEFAULT_TARGET = "edl_tpu/coordinator/inprocess.py"
DEFAULT_SCHEMA_NAME = "protocol_schema.json"

#: findings beyond this are summarized into one overflow finding — a broken
#: twin fails on hundreds of interleavings but the first few name the bug.
MAX_VIOLATION_FINDINGS = 8


class ProtocolModelChecker:
    rule = "EDL009"
    name = "protocol-model"
    scope = "program"
    info = RuleInfo(
        rule="EDL009",
        name="protocol-model",
        description=(
            "bounded model check of protocol_schema.json state_effects "
            "against the in-process coordinator: every interleaving of a "
            "faulty 2-worker schedule (crash+restart, duplicate delivery) "
            "must satisfy epoch monotonicity, exactly-once replay, lease "
            "exclusivity, and progress"
        ),
    )

    # -- map phase -------------------------------------------------------------

    def summarize(self, sf: SourceFile, ctx) -> Optional[Dict[str, Any]]:
        target = ctx.config.get("edl009_target", DEFAULT_TARGET)
        if sf.relpath != target:
            return None
        return {"target": True, "line": 1}

    # -- reduce phase ----------------------------------------------------------

    def reduce(
        self, summaries: List[Tuple[str, Optional[Dict[str, Any]]]], ctx
    ) -> Iterator[Finding]:
        from edl_tpu.analysis.modelcheck import (
            ModelCheckError,
            default_schedules,
            explore,
            load_state_effects,
        )

        target_rel = None
        for relpath, summary in summaries:
            if summary and summary.get("target"):
                target_rel = relpath
                break
        if target_rel is None:
            # The oracle module is not in this analysis scope (fixture
            # trees, partial runs): nothing to model-check.
            return

        schema_rel = ctx.config.get("edl009_schema", DEFAULT_SCHEMA_NAME)
        effects, ops, err = load_state_effects(ctx.root, schema_rel)

        def schema_finding(message: str, symbol: str = "") -> Finding:
            return Finding(
                rule=self.rule, path=schema_rel, line=1, col=0,
                message=message, symbol=symbol,
            )

        if err is not None:
            yield schema_finding(err)
            return

        # Coverage ratchet: the behavioral spec must track the op set.
        drift = False
        for op in sorted((ops or set()) - set(effects)):
            drift = True
            yield schema_finding(
                f"op '{op}' is in the dispatch table but has no "
                "state_effects entry — annotate its behavior before the "
                "model check can cover it",
                symbol=op,
            )
        for op in sorted(set(effects) - (ops or set())):
            drift = True
            yield schema_finding(
                f"state_effects entry '{op}' names no dispatch-table op — "
                "stale behavioral annotation",
                symbol=op,
            )
        if drift:
            return  # exploration over a drifted spec only repeats the news

        fuzz = int(ctx.config.get("edl009_fuzz", 0))
        violations = []
        try:
            # The acceptance schedules (faulty base, checkpoint plane,
            # watch/notify, redirect-during-watch) — each explored
            # separately so every schedule stays inside the interleaving
            # budget; findings merge. Durability rows belong to EDL010 and
            # are filtered out here.
            for sched in default_schedules():
                if sched.durable:
                    continue
                result = explore(
                    sched.scripts,
                    effects,
                    coordinator_factory=sched.factory,
                    max_traces=int(
                        ctx.config.get("edl009_max_traces", 20000)),
                    max_violations=MAX_VIOLATION_FINDINGS * 4,
                    fuzz_samples=fuzz,
                    fuzz_seed=int(ctx.config.get("edl009_fuzz_seed", 0)),
                    shard_endpoints=sched.shard_endpoints,
                    name=sched.name,
                )
                violations.extend(result.violations)
        except ModelCheckError as e:
            yield schema_finding(f"state_effects cannot drive the model: {e}")
            return

        for v in violations[:MAX_VIOLATION_FINDINGS]:
            yield Finding(
                rule=self.rule, path=target_rel, line=1, col=0,
                message=(
                    f"model check [{v.kind}]: {v.message} | schedule: "
                    f"{v.trace}"
                ),
                symbol=v.kind,
            )
        overflow = len(violations) - MAX_VIOLATION_FINDINGS
        if overflow > 0:
            yield Finding(
                rule=self.rule, path=target_rel, line=1, col=0,
                message=(
                    f"model check: {overflow} further violation(s) "
                    "suppressed — run python -m edl_tpu.analysis.modelcheck "
                    "for the full list"
                ),
                symbol="overflow",
            )
