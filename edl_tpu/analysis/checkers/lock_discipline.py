"""EDL001 — lock-discipline for classes that own a threading lock.

The elastic control plane (coordinator, job store, controller) is a set of
classes that own a ``threading.Lock``/``RLock``/``Condition`` and are hit
concurrently by handler threads, informer threads, and the autoscaler loop.
The invariant: every write to shared ``self`` state must happen while the
class's lock is held. A write that races a rescale corrupts membership or
job state silently — exactly the bug class generic linters cannot see.

Analysis (class-local, flow-insensitive but call-graph-aware):

1. A class "owns a lock" if any method assigns ``self.X = threading.Lock()``
   (or RLock/Condition). A ``Condition`` wraps the lock, so holding either
   counts as holding the guard.
2. Per method, record every write to ``self.<attr>`` (plain, augmented,
   subscript — mutating ``self._cache[k]`` is a write to ``_cache``) along
   with whether it is lexically inside ``with self.<lock>``.
3. Compute which methods can run WITHOUT the lock: public methods are entry
   points; a private method joins the set when a lock-free-reachable method
   calls it outside a ``with self.<lock>`` block, or when it escapes as a
   callback (``threading.Thread(target=self._run)``).
4. Unguarded writes in lock-free-reachable methods are violations.
   ``__init__`` is exempt (construction happens-before publication).

Known limits (by design, to stay precise): aliasing the lock through a
local, releasing via ``acquire``/``release`` pairs, and cross-class locking
are not modeled — use ``# edl: noqa[EDL001]`` with a justification there.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Set, Tuple

from edl_tpu.analysis.core import (
    Finding,
    RuleInfo,
    SourceFile,
    is_self_attr,
    self_attr_root,
)

LOCK_FACTORIES = {"Lock", "RLock", "Condition"}

#: Dunders that run before the object is shared (or are init-adjacent).
_CONSTRUCTION = {"__init__", "__new__", "__post_init__", "__init_subclass__"}


@dataclass
class _MethodScan:
    #: (attr, node, locked) for writes to self state
    writes: List[Tuple[str, ast.AST, bool]] = field(default_factory=list)
    #: (callee, locked) for self.method(...) calls
    calls: List[Tuple[str, bool]] = field(default_factory=list)
    #: method names referenced without being called (escaping callbacks)
    escapes: Set[str] = field(default_factory=set)


class LockDisciplineChecker:
    rule = "EDL001"
    name = "lock-discipline"
    info = RuleInfo(
        rule="EDL001",
        name="lock-discipline",
        description=(
            "attributes of a class that owns a threading.Lock/RLock/"
            "Condition must only be written under `with self.<lock>`"
        ),
    )

    def check(self, sf: SourceFile, ctx) -> Iterator[Finding]:
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(sf, node)

    # -- per class -------------------------------------------------------------

    def _check_class(self, sf: SourceFile, cls: ast.ClassDef) -> Iterator[Finding]:
        methods: Dict[str, ast.FunctionDef] = {
            n.name: n
            for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        lock_attrs = self._lock_attrs(methods.values())
        if not lock_attrs:
            return

        scans = {
            name: self._scan_method(fn, lock_attrs)
            for name, fn in methods.items()
        }

        unlocked = self._reachable_unlocked(methods, scans)
        guard = "/".join(sorted(lock_attrs))
        for name in sorted(unlocked):
            if name in _CONSTRUCTION:
                continue
            for attr, node, locked in scans[name].writes:
                if locked or attr in lock_attrs:
                    continue
                yield Finding(
                    rule=self.rule,
                    path=sf.relpath,
                    line=node.lineno,
                    col=node.col_offset,
                    message=(
                        f"'{cls.name}.{name}' writes 'self.{attr}' without "
                        f"holding 'self.{guard}' ({cls.name} owns a "
                        "threading lock)"
                    ),
                )

    @staticmethod
    def _lock_attrs(methods) -> Set[str]:
        attrs: Set[str] = set()
        for fn in methods:
            for node in ast.walk(fn):
                if not (
                    isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                ):
                    continue
                func = node.value.func
                fname = (
                    func.attr
                    if isinstance(func, ast.Attribute)
                    else func.id if isinstance(func, ast.Name) else None
                )
                if fname in LOCK_FACTORIES:
                    for target in node.targets:
                        attr = is_self_attr(target)
                        if attr:
                            attrs.add(attr)
        return attrs

    # -- per method ------------------------------------------------------------

    def _scan_method(self, fn: ast.AST, lock_attrs: Set[str]) -> _MethodScan:
        scan = _MethodScan()
        #: Attribute nodes that are the func of a Call (so not escapes)
        call_funcs: Set[int] = set()

        def is_lock_item(expr: ast.AST) -> bool:
            attr = is_self_attr(expr)
            return attr is not None and attr in lock_attrs

        def visit(node: ast.AST, locked: bool) -> None:
            if isinstance(node, (ast.With, ast.AsyncWith)):
                now_locked = locked or any(
                    is_lock_item(item.context_expr) for item in node.items
                )
                for item in node.items:
                    visit(item.context_expr, locked)
                for stmt in node.body:
                    visit(stmt, now_locked)
                return
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for target in targets:
                    for t in self._flatten_targets(target):
                        attr = self_attr_root(t)
                        if attr:
                            scan.writes.append((attr, node, locked))
                value = getattr(node, "value", None)
                if value is not None:
                    visit(value, locked)
                return
            if isinstance(node, ast.Delete):
                for t in node.targets:
                    attr = self_attr_root(t)
                    if attr:
                        scan.writes.append((attr, node, locked))
                return
            if isinstance(node, ast.Call):
                attr = is_self_attr(node.func)
                if attr is not None:
                    call_funcs.add(id(node.func))
                    scan.calls.append((attr, locked))
                for child in ast.iter_child_nodes(node):
                    visit(child, locked)
                return
            if isinstance(node, ast.Attribute):
                attr = is_self_attr(node)
                if attr is not None and id(node) not in call_funcs:
                    scan.escapes.add(attr)
                for child in ast.iter_child_nodes(node):
                    visit(child, locked)
                return
            for child in ast.iter_child_nodes(node):
                visit(child, locked)

        for stmt in fn.body:
            visit(stmt, False)
        return scan

    @staticmethod
    def _flatten_targets(target: ast.AST) -> Iterator[ast.AST]:
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                yield from LockDisciplineChecker._flatten_targets(elt)
        else:
            yield target

    # -- reachability ----------------------------------------------------------

    @staticmethod
    def _reachable_unlocked(
        methods: Dict[str, ast.FunctionDef], scans: Dict[str, _MethodScan]
    ) -> Set[str]:
        def is_entry(name: str) -> bool:
            if name in _CONSTRUCTION:
                return False
            if not name.startswith("_"):
                return True
            # Public dunders (__enter__, __call__, ...) are entry points too.
            return name.startswith("__") and name.endswith("__")

        unlocked = {n for n in methods if is_entry(n)}
        # Methods that escape as callbacks run on foreign threads, lock-free.
        for scan in scans.values():
            unlocked |= {m for m in scan.escapes if m in methods}
        changed = True
        while changed:
            changed = False
            for name in list(unlocked):
                for callee, locked in scans[name].calls:
                    if not locked and callee in methods and callee not in unlocked:
                        unlocked.add(callee)
                        changed = True
        return unlocked
