"""Checker registry: one module per EDL rule."""

from edl_tpu.analysis.checkers.lock_discipline import LockDisciplineChecker
from edl_tpu.analysis.checkers.trace_hygiene import TraceHygieneChecker
from edl_tpu.analysis.checkers.sharding_consistency import (
    ShardingConsistencyChecker,
)
from edl_tpu.analysis.checkers.blocking import BlockingInLockChecker
from edl_tpu.analysis.checkers.exception_hygiene import ExceptionHygieneChecker
from edl_tpu.analysis.checkers.thread_races import ThreadRaceChecker
from edl_tpu.analysis.checkers.wire_protocol import WireProtocolChecker
from edl_tpu.analysis.checkers.elastic_determinism import (
    ElasticDeterminismChecker,
)
from edl_tpu.analysis.checkers.protocol_model import ProtocolModelChecker
from edl_tpu.analysis.checkers.durability import DurabilityModelChecker

ALL_CHECKERS = (
    LockDisciplineChecker,
    TraceHygieneChecker,
    ShardingConsistencyChecker,
    BlockingInLockChecker,
    ExceptionHygieneChecker,
    ThreadRaceChecker,
    WireProtocolChecker,
    ElasticDeterminismChecker,
    ProtocolModelChecker,
    DurabilityModelChecker,
)

RULES = {c.rule: c for c in ALL_CHECKERS}

__all__ = ["ALL_CHECKERS", "RULES"]
