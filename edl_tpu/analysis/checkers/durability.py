"""EDL010 — crash-recovery model checking of the durability plane.

EDL009 checks the protocol's live behavior; this rule checks what
survives death. ``protocol_schema.json``'s ``state_effects`` entries
carry a ``durability`` tag — ``none`` (read-only), ``volatile`` (mutates
only state a restart legitimately wipes), ``journal:<kinds>`` (the op
group-commits the named record kinds: ``meta``/``todo``/``done``/
``lease``/``kv``/``kvdel``), or ``composite`` (``batch``: the union of
its sub-ops, one frame). The reduce phase:

1. ratchets tag coverage — every dispatch-table op must carry a valid
   ``durability`` tag (an untagged op is durability the model cannot see,
   and a typo'd record kind is a spec that cannot drive replay);
2. runs the durability schedules from ``edl_tpu.analysis.modelcheck``:
   crash points enumerated between persistence effects (clean / pre-ack /
   torn-tail / during-compaction), recovery replay as a first-class
   schedule step, every trace replayed against the file-backed
   ``InProcessCoordinator`` persistence twin. Invariants: acked implies
   durable, exactly-once across crash (journaled dedup), snapshot ⊕
   journal-suffix ≡ pre-crash durable state, epoch monotonicity across
   restart, and ladder honesty for the unjournaled shard store.

Findings anchor on the persistence twin (the executable durability
spec). Fixture trees never pay the exploration cost: the reduce phase is
skipped unless the target file was among the analyzed files.

Config overrides: ``edl010_target`` (relpath of the twin module),
``edl010_schema`` (schema artifact relpath), ``edl010_max_traces`` /
``edl010_fuzz`` / ``edl010_fuzz_seed`` (exploration budget; fuzz > 0
switches to the seeded random-walk mode).

The same schedules replay against the crash-armed native binary via
``make modelcheck-native`` (env-gated ``_exit(2)`` hooks in
``native/coordinator/coordinator.cc``) — that lane needs a subprocess
per trace, so it runs in CI/verify rather than inside this checker.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Tuple

from edl_tpu.analysis.core import Finding, RuleInfo, SourceFile

DEFAULT_TARGET = "edl_tpu/coordinator/inprocess.py"
DEFAULT_SCHEMA_NAME = "protocol_schema.json"

#: the journal's record vocabulary — a ``journal:`` tag naming anything
#: else is a spec typo, not a new record kind.
JOURNAL_KINDS = frozenset({"meta", "todo", "done", "lease", "kv", "kvdel"})

#: non-journal tag values.
SIMPLE_TAGS = frozenset({"none", "volatile", "composite"})

MAX_VIOLATION_FINDINGS = 8


def validate_durability_tag(tag: Any) -> Optional[str]:
    """None when ``tag`` is a well-formed durability tag, else the
    problem as a string."""
    if not isinstance(tag, str) or not tag:
        return "missing or non-string durability tag"
    if tag in SIMPLE_TAGS:
        return None
    if tag.startswith("journal:"):
        kinds = [k for k in tag[len("journal:"):].split(",") if k]
        if not kinds:
            return "journal: tag names no record kinds"
        bad = sorted(set(kinds) - JOURNAL_KINDS)
        if bad:
            return (f"journal: tag names unknown record kind(s) {bad} — "
                    f"known: {sorted(JOURNAL_KINDS)}")
        return None
    return (f"unknown durability tag {tag!r} — expected one of "
            f"{sorted(SIMPLE_TAGS)} or journal:<kinds>")


class DurabilityModelChecker:
    rule = "EDL010"
    name = "durability-model"
    scope = "program"
    info = RuleInfo(
        rule="EDL010",
        name="durability-model",
        description=(
            "crash-recovery model check of the journal/snapshot durability "
            "plane: per-op durability tags ratcheted over the protocol "
            "schema, then every crash point (clean, pre-ack, torn tail, "
            "during compaction) explored with recovery replay and checked "
            "against the file-backed persistence twin — acked implies "
            "durable, exactly-once across crash, snapshot+suffix "
            "equivalence, epoch monotonicity across restart"
        ),
    )

    # -- map phase -------------------------------------------------------------

    def summarize(self, sf: SourceFile, ctx) -> Optional[Dict[str, Any]]:
        target = ctx.config.get("edl010_target", DEFAULT_TARGET)
        if sf.relpath != target:
            return None
        return {"target": True, "line": 1}

    # -- reduce phase ----------------------------------------------------------

    def reduce(
        self, summaries: List[Tuple[str, Optional[Dict[str, Any]]]], ctx
    ) -> Iterator[Finding]:
        from edl_tpu.analysis.modelcheck import (
            ModelCheckError,
            durability_schedules,
            explore,
            load_state_effects,
        )

        target_rel = None
        for relpath, summary in summaries:
            if summary and summary.get("target"):
                target_rel = relpath
                break
        if target_rel is None:
            # The persistence twin is not in this analysis scope (fixture
            # trees, partial runs): nothing to check.
            return

        schema_rel = ctx.config.get("edl010_schema", DEFAULT_SCHEMA_NAME)
        effects, ops, err = load_state_effects(ctx.root, schema_rel)

        def schema_finding(message: str, symbol: str = "") -> Finding:
            return Finding(
                rule=self.rule, path=schema_rel, line=1, col=0,
                message=message, symbol=symbol,
            )

        if err is not None:
            yield schema_finding(err)
            return

        # Durability-tag coverage ratchet: every op the dispatch table
        # knows must declare what it persists. Op-set drift itself is
        # EDL009's finding; this rule only judges the tags of ops that
        # have entries.
        drift = False
        for op in sorted(set(effects) & (ops or set(effects))):
            problem = validate_durability_tag(
                (effects.get(op) or {}).get("durability"))
            if problem is not None:
                drift = True
                yield schema_finding(
                    f"op '{op}': {problem} — the durability model cannot "
                    "place its crash points until the tag is fixed",
                    symbol=op,
                )
        if drift:
            return  # exploration over an untagged spec proves nothing

        fuzz = int(ctx.config.get("edl010_fuzz", 0))
        violations = []
        try:
            for sched in durability_schedules():
                result = explore(
                    sched.scripts,
                    effects,
                    coordinator_factory=sched.factory,
                    max_traces=int(
                        ctx.config.get("edl010_max_traces", 20000)),
                    max_violations=MAX_VIOLATION_FINDINGS * 4,
                    fuzz_samples=fuzz,
                    fuzz_seed=int(ctx.config.get("edl010_fuzz_seed", 0)),
                    durable=sched.durable,
                    compact_every=sched.compact_every,
                    por=sched.por,
                    name=sched.name,
                )
                violations.extend(result.violations)
        except ModelCheckError as e:
            yield schema_finding(
                f"durability tags cannot drive the model: {e}")
            return

        for v in violations[:MAX_VIOLATION_FINDINGS]:
            yield Finding(
                rule=self.rule, path=target_rel, line=1, col=0,
                message=(
                    f"durability check [{v.kind}]: {v.message} | schedule: "
                    f"{v.trace}"
                ),
                symbol=v.kind,
            )
        overflow = len(violations) - MAX_VIOLATION_FINDINGS
        if overflow > 0:
            yield Finding(
                rule=self.rule, path=target_rel, line=1, col=0,
                message=(
                    f"durability check: {overflow} further violation(s) "
                    "suppressed — run python -m edl_tpu.analysis.modelcheck "
                    "--schedules durability-base,durability-dedup,"
                    "durability-torn,durability-compact,"
                    "durability-crash-compact,durability-shard for the "
                    "full list"
                ),
                symbol="overflow",
            )
