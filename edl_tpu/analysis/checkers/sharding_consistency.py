"""EDL003 — PartitionSpec axis names must exist on the meshes we build.

A ``PartitionSpec("modle")`` typo or an axis name no mesh constructor ever
declares does not fail loudly — on a mesh without that axis JAX raises at
placement time deep inside a rescale, or (worse, for optional axes resolved
via ``present_axes``) silently falls back to replication and throws away
the parallelism the spec asked for. ElasWave-style elastic correctness
("consistent sharding across rescale") starts with a single source of truth
for axis names: ``AXIS_ORDER`` in ``edl_tpu/parallel/mesh.py``.

The checker collects axis-name candidates in ``parallel/`` and ``models/``
files from:

- string literals (and tuples of them) passed to ``P(...)`` /
  ``PartitionSpec(...)``;
- string defaults of parameters named ``axis``/``*_axis`` (tuple defaults
  for ``*_axes``), including dataclass fields and module constants named
  ``*_AXIS``;
- ``axis_name=``/``axis=`` keywords and positional axis strings of the
  named collectives (``psum``, ``all_gather``, ``ppermute``, ...).

Every candidate must appear in the declared-axis universe parsed from
``AXIS_ORDER``. Fixture trees can override the universe and scope via
``config={"sharding_axes": [...], "sharding_all_files": True}``.
"""

from __future__ import annotations

import ast
import os
from typing import Iterator, List, Optional, Set, Tuple

from edl_tpu.analysis.core import Finding, RuleInfo, SourceFile, dotted_name

_SPEC_FUNCS = {"P", "PartitionSpec"}

_COLLECTIVES = {
    "psum",
    "pmean",
    "pmax",
    "pmin",
    "psum_scatter",
    "all_gather",
    "all_to_all",
    "ppermute",
    "pshuffle",
    "axis_index",
    "axis_size",
}

_AXIS_KEYWORDS = {"axis_name", "axis_names"}

_MESH_RELPATH = os.path.join("edl_tpu", "parallel", "mesh.py")


class ShardingConsistencyChecker:
    rule = "EDL003"
    name = "sharding-consistency"
    info = RuleInfo(
        rule="EDL003",
        name="sharding-consistency",
        description=(
            "PartitionSpec / collective axis names used in parallel/, "
            "models/, and runtime/ must be declared by AXIS_ORDER in "
            "parallel/mesh.py"
        ),
    )

    def check(self, sf: SourceFile, ctx) -> Iterator[Finding]:
        if not self._applies(sf, ctx):
            return
        declared = self._declared_axes(ctx)
        if declared is None:
            return  # no mesh module (fixture tree without an override)
        for name, node, where in self._candidates(sf.tree):
            if name in declared:
                continue
            yield Finding(
                rule=self.rule,
                path=sf.relpath,
                line=node.lineno,
                col=node.col_offset,
                message=(
                    f"mesh axis '{name}' ({where}) is not declared in "
                    "parallel/mesh.py AXIS_ORDER — a mesh built by this "
                    "codebase never has it"
                ),
            )

    # -- scope / config --------------------------------------------------------

    @staticmethod
    def _applies(sf: SourceFile, ctx) -> bool:
        if ctx.config.get("sharding_all_files"):
            return True
        rel = sf.relpath
        if rel.endswith("parallel/mesh.py"):
            return False  # the declaration site itself
        # runtime/ joined the scope when PR 6's ZeRO specs put P(...)
        # literals there (_zero_specs / zero_shard_spec).
        return "parallel/" in rel or "models/" in rel or "runtime/" in rel

    def _declared_axes(self, ctx) -> Optional[Set[str]]:
        override = ctx.config.get("sharding_axes")
        if override is not None:
            return set(override)
        cached = ctx.cache.get("edl003_axes")
        if cached is not None:
            return cached
        mesh_path = os.path.join(ctx.root, _MESH_RELPATH)
        if not os.path.isfile(mesh_path):
            return None
        axes: Set[str] = set()
        try:
            with open(mesh_path, "r", encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=mesh_path)
        except SyntaxError:
            return None
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "AXIS_ORDER"
                for t in node.targets
            ):
                if isinstance(node.value, (ast.Tuple, ast.List)):
                    for elt in node.value.elts:
                        if isinstance(elt, ast.Constant) and isinstance(
                            elt.value, str
                        ):
                            axes.add(elt.value)
        axes = axes or None
        ctx.cache["edl003_axes"] = axes
        return axes

    # -- candidate collection --------------------------------------------------

    def _candidates(
        self, tree: ast.AST
    ) -> Iterator[Tuple[str, ast.AST, str]]:
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                yield from self._call_candidates(node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._default_candidates(node)
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                if isinstance(node.target, ast.Name) and self._axis_named(
                    node.target.id
                ):
                    yield from self._string_values(
                        node.value, f"field '{node.target.id}'"
                    )
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and self._axis_named(t.id):
                        yield from self._string_values(
                            node.value, f"constant '{t.id}'"
                        )

    @staticmethod
    def _axis_named(name: str) -> bool:
        low = name.lower()
        return low == "axis" or low.endswith(("_axis", "_axes"))

    def _call_candidates(self, node: ast.Call):
        name = dotted_name(node.func)
        base = name.split(".")[-1] if name else ""
        if base in _SPEC_FUNCS:
            for arg in node.args:
                yield from self._string_values(arg, f"{base}(...) entry")
        elif base in _COLLECTIVES:
            # axis is the conventional second positional of lax collectives
            for arg in node.args[1:]:
                yield from self._string_values(arg, f"{base}(...) axis")
        for kw in node.keywords:
            if kw.arg in _AXIS_KEYWORDS:
                yield from self._string_values(
                    kw.value, f"{kw.arg}= of {base or 'call'}(...)"
                )

    def _default_candidates(self, fn: ast.AST):
        args = fn.args
        pos = args.posonlyargs + args.args
        for arg, default in zip(pos[len(pos) - len(args.defaults):], args.defaults):
            if self._axis_named(arg.arg):
                yield from self._string_values(default, f"default of '{arg.arg}'")
        for arg, default in zip(args.kwonlyargs, args.kw_defaults):
            if default is not None and self._axis_named(arg.arg):
                yield from self._string_values(default, f"default of '{arg.arg}'")

    @staticmethod
    def _string_values(node: ast.AST, where: str):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            yield node.value, node, where
        elif isinstance(node, (ast.Tuple, ast.List)):
            for elt in node.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                    yield elt.value, elt, where
