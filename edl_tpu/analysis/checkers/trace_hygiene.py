"""EDL002 — trace-hygiene inside jit/pjit/shard_map'd functions.

The hot loop is one jitted step function; anything host-side that sneaks
into its body either bakes a stale value into the compiled program
(``time.time()``, ``np.random``), forces a device sync, or triggers silent
retracing — the exact perf bugs the retrace canary in
``runtime/train_loop.py`` catches at runtime. This checker catches them at
review time.

Traced functions are found per file:

- ``@jax.jit`` / ``@pjit`` / ``@partial(jax.jit, ...)`` decorators;
- local functions or lambdas passed to ``jax.jit(...)`` / ``pjit(...)`` /
  ``shard_map(...)`` call sites anywhere in the file.

Inside a traced body (nested defs included) it flags:

- host clocks: ``time.time/perf_counter/monotonic/process_time/sleep``;
- host RNG: ``np.random.*`` / ``numpy.random.*`` / stdlib ``random.*``
  (``jax.random`` is fine — it is traced);
- host callbacks: ``jax.pure_callback``, ``io_callback``,
  ``host_callback.*``, ``jax.debug.callback``, plus ``print``/``input``/
  ``breakpoint``;
- value-dependent Python control flow: ``if``/``while`` tests that use a
  traced parameter directly (``.shape``/``.ndim``/``.dtype`` accesses and
  ``len``/``isinstance`` are static and allowed), and ``float()/int()/
  bool()`` on a parameter (forces a blocking device sync).

The parameter check is name-based and local — values laundered through
assignments are not chased. That keeps every report actionable.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set

from edl_tpu.analysis.core import Finding, RuleInfo, SourceFile, dotted_name

_TRACERS = {"jit", "pjit", "shard_map"}

_HOST_CLOCKS = {
    "time.time",
    "time.perf_counter",
    "time.monotonic",
    "time.process_time",
    "time.sleep",
}

_HOST_CALLBACKS = {
    "jax.pure_callback",
    "pure_callback",
    "jax.experimental.io_callback",
    "io_callback",
    "jax.debug.callback",
}

_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}
_STATIC_FUNCS = {"len", "isinstance", "getattr", "hasattr", "type"}


def _is_tracer(func: ast.AST) -> bool:
    """True for ``jit``/``jax.jit``/``pjit``/``shard_map`` references."""
    name = dotted_name(func)
    if name is None:
        return False
    return name.split(".")[-1] in _TRACERS


def _partial_of_tracer(call: ast.Call) -> bool:
    name = dotted_name(call.func)
    if name is None or name.split(".")[-1] != "partial":
        return False
    return bool(call.args) and _is_tracer(call.args[0])


class TraceHygieneChecker:
    rule = "EDL002"
    name = "trace-hygiene"
    info = RuleInfo(
        rule="EDL002",
        name="trace-hygiene",
        description=(
            "no host clocks, host RNG, host callbacks, or value-dependent "
            "Python branching inside jit/pjit/shard_map traced functions"
        ),
    )

    def check(self, sf: SourceFile, ctx) -> Iterator[Finding]:
        for fn, how in self._traced_functions(sf.tree):
            yield from self._check_traced(sf, fn, how)

    # -- discovery -------------------------------------------------------------

    def _traced_functions(self, tree: ast.AST):
        defs = {}  # name -> innermost def seen (good enough per file)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs[node.name] = node

        seen: Set[int] = set()

        def mark(fn: ast.AST, how: str):
            if id(fn) not in seen:
                seen.add(id(fn))
                yield fn, how

        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if _is_tracer(dec) or (
                        isinstance(dec, ast.Call)
                        and (_is_tracer(dec.func) or _partial_of_tracer(dec))
                    ):
                        yield from mark(node, f"@{dotted_name(dec) or 'jit'}")
            elif isinstance(node, ast.Call) and _is_tracer(node.func):
                if not node.args:
                    continue
                target = node.args[0]
                tracer = dotted_name(node.func) or "jit"
                if isinstance(target, ast.Lambda):
                    yield from mark(target, f"{tracer}(<lambda>)")
                elif isinstance(target, ast.Name) and target.id in defs:
                    yield from mark(defs[target.id], f"{tracer}({target.id})")

    # -- body checks -----------------------------------------------------------

    def _check_traced(
        self, sf: SourceFile, fn: ast.AST, how: str
    ) -> Iterator[Finding]:
        fn_name = getattr(fn, "name", "<lambda>")
        params = self._param_names(fn)

        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for node in body:
            yield from self._walk(sf, node, fn_name, how, params)

    @staticmethod
    def _param_names(fn: ast.AST) -> Set[str]:
        args = fn.args
        names = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
        if args.vararg:
            names.append(args.vararg.arg)
        if args.kwarg:
            names.append(args.kwarg.arg)
        return {n for n in names if n != "self"}

    def _walk(
        self,
        sf: SourceFile,
        node: ast.AST,
        fn_name: str,
        how: str,
        params: Set[str],
    ) -> Iterator[Finding]:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # Nested helpers are traced too; their params join the traced set.
            inner = params | self._param_names(node)
            for child in node.body:
                yield from self._walk(sf, child, fn_name, how, inner)
            return

        if isinstance(node, ast.Call):
            finding = self._check_call(sf, node, fn_name, how, params)
            if finding is not None:
                yield finding

        if isinstance(node, (ast.If, ast.While)):
            traced = self._traced_names_in(node.test, params)
            if traced:
                names = ", ".join(sorted(traced))
                kind = "if" if isinstance(node, ast.If) else "while"
                yield Finding(
                    rule=self.rule,
                    path=sf.relpath,
                    line=node.test.lineno,
                    col=node.test.col_offset,
                    message=(
                        f"Python `{kind}` on traced value(s) {names} inside "
                        f"{how}-traced '{fn_name}' — use jax.lax.cond/while "
                        "or hoist the branch out of the traced function"
                    ),
                )

        for child in ast.iter_child_nodes(node):
            yield from self._walk(sf, child, fn_name, how, params)

    def _check_call(
        self,
        sf: SourceFile,
        node: ast.Call,
        fn_name: str,
        how: str,
        params: Set[str],
    ) -> Optional[Finding]:
        name = dotted_name(node.func)

        def finding(msg: str) -> Finding:
            return Finding(
                rule=self.rule,
                path=sf.relpath,
                line=node.lineno,
                col=node.col_offset,
                message=msg + f" inside {how}-traced '{fn_name}'",
            )

        if name in _HOST_CLOCKS:
            return finding(
                f"host clock `{name}()` — its value is baked in at trace "
                "time (and never updates across steps)"
            )
        if name is not None:
            root = name.split(".")[0]
            if (
                name.startswith(("np.random.", "numpy.random.", "random."))
                and root != "jax"
            ):
                return finding(
                    f"host RNG `{name}()` — draws once at trace time; use "
                    "jax.random with a threaded key"
                )
            if name in _HOST_CALLBACKS or root == "host_callback":
                return finding(f"host callback `{name}(...)`")
        if isinstance(node.func, ast.Name):
            if node.func.id in {"print", "input", "breakpoint"}:
                return finding(
                    f"host call `{node.func.id}(...)` — use jax.debug.print "
                    "for traced values"
                )
            if node.func.id in {"float", "int", "bool"} and any(
                isinstance(a, ast.Name) and a.id in params for a in node.args
            ):
                return finding(
                    f"`{node.func.id}()` on a traced parameter forces a "
                    "blocking device sync"
                )
        return None

    def _traced_names_in(self, test: ast.AST, params: Set[str]) -> Set[str]:
        """Param names used by value (not just statically) in a test expr."""
        traced: Set[str] = set()

        def visit(node: ast.AST) -> None:
            if isinstance(node, ast.Attribute):
                if node.attr in _STATIC_ATTRS:
                    return  # x.shape / x.ndim / x.dtype are static
                visit(node.value)
                return
            if isinstance(node, ast.Call):
                name = dotted_name(node.func)
                base = name.split(".")[-1] if name else ""
                if base in _STATIC_FUNCS:
                    return  # len(x), isinstance(x, T) are static
                for child in ast.iter_child_nodes(node):
                    visit(child)
                return
            if isinstance(node, ast.Name) and node.id in params:
                traced.add(node.id)
                return
            for child in ast.iter_child_nodes(node):
                visit(child)

        visit(test)
        return traced
