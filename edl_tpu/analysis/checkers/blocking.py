"""EDL004 — no blocking calls while holding a service lock.

Coordinator handlers (and every informer/store callback) serialize on the
class lock. A ``time.sleep`` or subprocess/socket round-trip executed
inside ``with self._lock`` parks every other handler — heartbeats miss,
leases expire, and a 50 ms backoff becomes a cluster-wide stall. The fix is
always the same: sleep outside the lock, or use ``Condition.wait`` (which
releases the lock while parked, and is therefore allowed).

Detection: lexically inside a ``with`` on a lock-like guard — an attribute
the class assigned from ``threading.Lock/RLock/Condition`` (same discovery
as EDL001), or any name matching ``*lock*``/``*cv*``/``*cond*``/``*mutex*``
— flag calls to ``time.sleep``, ``subprocess.run/call/check_call/
check_output/Popen``, ``os.system``, ``select.select``, and socket
``accept/recv/recvfrom/connect/sendall`` methods.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Optional, Set

from edl_tpu.analysis.core import (
    Finding,
    RuleInfo,
    SourceFile,
    dotted_name,
    is_self_attr,
)
from edl_tpu.analysis.checkers.lock_discipline import LOCK_FACTORIES

_BLOCKING_DOTTED = {
    "time.sleep",
    "subprocess.run",
    "subprocess.call",
    "subprocess.check_call",
    "subprocess.check_output",
    "subprocess.Popen",
    "os.system",
    "select.select",
}

_BLOCKING_SOCKET_METHODS = {"accept", "recv", "recvfrom", "connect", "sendall"}

_LOCKISH_NAME = re.compile(r"(?:^|_)(?:lock|cv|cond|mutex)", re.IGNORECASE)


class BlockingInLockChecker:
    rule = "EDL004"
    name = "blocking-in-event-loop"
    info = RuleInfo(
        rule="EDL004",
        name="blocking-in-event-loop",
        description=(
            "no time.sleep / subprocess / blocking socket calls while "
            "holding a lock — coordinator handler paths serialize on it"
        ),
    )

    def check(self, sf: SourceFile, ctx) -> Iterator[Finding]:
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                lock_attrs = self._class_lock_attrs(node)
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        yield from self._scan(sf, item, lock_attrs, None)
        # Module-level functions can hold module-level locks too.
        for node in sf.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._scan(sf, node, set(), None)

    @staticmethod
    def _class_lock_attrs(cls: ast.ClassDef) -> Set[str]:
        attrs: Set[str] = set()
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                func = node.value.func
                fname = (
                    func.attr
                    if isinstance(func, ast.Attribute)
                    else func.id if isinstance(func, ast.Name) else None
                )
                if fname in LOCK_FACTORIES:
                    for target in node.targets:
                        attr = is_self_attr(target)
                        if attr:
                            attrs.add(attr)
        return attrs

    def _guard_name(self, expr: ast.AST, lock_attrs: Set[str]) -> Optional[str]:
        attr = is_self_attr(expr)
        if attr is not None:
            if attr in lock_attrs or _LOCKISH_NAME.search(attr):
                return f"self.{attr}"
            return None
        if isinstance(expr, ast.Name) and _LOCKISH_NAME.search(expr.id):
            return expr.id
        return None

    def _scan(
        self,
        sf: SourceFile,
        node: ast.AST,
        lock_attrs: Set[str],
        held: Optional[str],
    ) -> Iterator[Finding]:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            guard = held
            for item in node.items:
                g = self._guard_name(item.context_expr, lock_attrs)
                if g is not None:
                    guard = g
            for stmt in node.body:
                yield from self._scan(sf, stmt, lock_attrs, guard)
            return

        if isinstance(node, ast.Call) and held is not None:
            finding = self._blocking_call(sf, node, held)
            if finding is not None:
                yield finding

        for child in ast.iter_child_nodes(node):
            yield from self._scan(sf, child, lock_attrs, held)

    def _blocking_call(
        self, sf: SourceFile, node: ast.Call, held: str
    ) -> Optional[Finding]:
        name = dotted_name(node.func)

        def finding(what: str) -> Finding:
            return Finding(
                rule=self.rule,
                path=sf.relpath,
                line=node.lineno,
                col=node.col_offset,
                message=(
                    f"blocking call {what} while holding '{held}' — every "
                    "other handler serialized on that lock stalls; move it "
                    "outside the lock or use Condition.wait"
                ),
            )

        if name in _BLOCKING_DOTTED:
            return finding(f"`{name}(...)`")
        if isinstance(node.func, ast.Attribute):
            method = node.func.attr
            base = dotted_name(node.func.value) or ""
            if method in _BLOCKING_SOCKET_METHODS and re.search(
                r"(?:^|[._])(sock|socket|conn|client)", base, re.IGNORECASE
            ):
                return finding(f"`{base}.{method}(...)`")
        return None
