"""EDL005 — broad exception handlers must re-raise, log, or justify.

An elastic system's whole job is surviving failures — which makes silent
``except Exception: pass`` the most dangerous line in the codebase: a
swallowed checkpoint error or coordinator transport failure turns a clean
rescale into silent data loss. Broad handlers are allowed, but only when
the failure leaves a trace:

- the handler re-raises (``raise`` / raise-from), or
- the handler calls a logging method (``log.exception``, ``log.warning``,
  ``warnings.warn``, ...), or
- the ``except`` line carries ``# edl: noqa[EDL005] <why swallowing is
  correct here>``.

Flagged: ``except:``, ``except Exception:``, ``except BaseException:``
(bare or inside a tuple) whose body does none of the above.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from edl_tpu.analysis.core import Finding, RuleInfo, SourceFile, dotted_name

_BROAD = {"Exception", "BaseException"}

_LOGGING_METHODS = {
    "exception",
    "warning",
    "warn",
    "error",
    "critical",
    "info",
    "debug",
    "log",
}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True  # bare except
    types = (
        handler.type.elts
        if isinstance(handler.type, ast.Tuple)
        else [handler.type]
    )
    for t in types:
        name = dotted_name(t)
        if name and name.split(".")[-1] in _BROAD:
            return True
    return False


#: Helper functions that report by convention (``self._warn_unreachable``,
#: ``_log_failure``) count as leaving a trace — the handler delegates the
#: reporting, it does not swallow.
_REPORTING_NAME = re.compile(r"warn|log|report|print_exc", re.IGNORECASE)


def _leaves_a_trace(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Attribute) and func.attr in _LOGGING_METHODS:
                return True
            name = dotted_name(func)
            base = name.split(".")[-1] if name else ""
            if _REPORTING_NAME.search(base):
                return True
    return False


class ExceptionHygieneChecker:
    rule = "EDL005"
    name = "exception-hygiene"
    info = RuleInfo(
        rule="EDL005",
        name="exception-hygiene",
        description=(
            "bare/broad `except` must re-raise, log the failure, or carry "
            "an explicit `# edl: noqa[EDL005]` justification"
        ),
    )

    def check(self, sf: SourceFile, ctx) -> Iterator[Finding]:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node) or _leaves_a_trace(node):
                continue
            caught = (
                "bare except"
                if node.type is None
                else f"except {ast.unparse(node.type)}"
            )
            yield Finding(
                rule=self.rule,
                path=sf.relpath,
                line=node.lineno,
                col=node.col_offset,
                message=(
                    f"`{caught}` swallows the failure silently in "
                    f"'{sf.symbol_at(node.lineno) or '<module>'}' — "
                    "re-raise, log it, or justify with "
                    "`# edl: noqa[EDL005] <reason>`"
                ),
            )
