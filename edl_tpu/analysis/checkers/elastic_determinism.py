"""EDL008 — elastic determinism: training state must not depend on who is
running it.

Accuracy-consistent elasticity (the EasyScale deliverable in ROADMAP.md)
requires that the loss curve be a function of the *logical* schedule —
global step, logical batch index, shard index — never of the physical
membership that happens to execute it. Two bug classes break that and
survive every unit test, because single-host test runs have a stable
identity and a stable iteration order:

- **A. host-identity RNG** (``rng-host-identity``): an RNG constructed or
  seeded from ``jax.process_index()``, the hostname, the PID, a wall clock,
  or a worker-name string. Rescale the job and every worker re-derives
  different randomness for the *same* logical batch — dropout masks,
  shuffles, and augmentations silently change with membership history.
- **B. unordered accumulation** (``unordered-accumulation``): a numeric
  reduction driven by iteration over a ``set`` (or the views of a
  membership dict). Set iteration order is hash-seed and insertion-history
  dependent, so float accumulation order — and therefore the rounded
  result — varies across hosts and across rescales.

The rule is scoped to the training-state surface (``runtime/``,
``parallel/``, ``models/`` by default). Control-plane timing code — e.g.
heartbeat jitter that *should* decorrelate per worker — is exactly what
line-level ``# edl: noqa[EDL008] <why>`` is for.

Detection is a per-function (plus module-level) forward taint pass:
identity/clock *sources* propagate through assignments, f-strings, and
arithmetic to RNG-constructor/seeder *sinks*. It runs as a program-scope
rule purely so the per-file pass rides the map phase's process pool; the
reduce phase only re-emits the per-file candidates (no cross-file state).

Config overrides: ``edl008_prefixes`` (iterable of relpath prefixes),
``edl008_all_files`` (bool: lint every analyzed file — fixtures use this).
"""

from __future__ import annotations

import ast
from typing import Any, Dict, Iterator, List, Optional, Tuple

from edl_tpu.analysis.core import Finding, RuleInfo, SourceFile, dotted_name

DEFAULT_PREFIXES = (
    "edl_tpu/runtime/",
    "edl_tpu/parallel/",
    "edl_tpu/models/",
)

#: dotted-name *tails* that read host identity / process identity / entropy.
#: Matched against the last component of the called name, so they survive
#: ``import socket`` vs ``from socket import gethostname`` equally.
_SOURCE_CALL_TAILS = {
    "process_index",
    "process_count",
    "gethostname",
    "getfqdn",
    "getpid",
    "getppid",
    "urandom",
    "uuid1",
    "uuid4",
    "time_ns",
    "monotonic",
    "monotonic_ns",
    "perf_counter",
    "perf_counter_ns",
}

#: full dotted names whose tail alone is too generic to match ("time",
#: "node" would fire on every ast.walk visitor).
_SOURCE_CALL_EXACT = {
    "time.time",
    "platform.node",
    "datetime.now",
    "datetime.datetime.now",
    "datetime.utcnow",
    "datetime.datetime.utcnow",
}

#: bare names / attribute tails that carry a worker's identity by
#: convention in this codebase (coordinator clients expose ``.worker``,
#: configs expose ``host_id``).
_IDENTITY_NAME_TAILS = {
    "worker",
    "worker_name",
    "worker_id",
    "hostname",
    "host_name",
    "host_id",
    "process_index",
    "nodename",
    "pod_name",
}

#: call tails that construct or (re)seed an RNG — the sinks.
_RNG_SINK_TAILS = {
    "PRNGKey",
    "key",          # jax.random.key — new-style typed keys
    "fold_in",
    "default_rng",
    "Random",
    "RandomState",
    "SeedSequence",
    "seed",
    "manual_seed",
}

#: ``.seed(...)`` / ``jax.random.key(...)`` share tails with unrelated
#: APIs; require the owner/base to look RNG-ish for these ambiguous ones.
_AMBIGUOUS_SINK_TAILS = {"seed", "key"}
_RNG_BASE_HINTS = ("random", "rng", "jax")

_NUMERIC_AUG_OPS = (ast.Add, ast.Sub, ast.Mult)


def _call_tail(node: ast.Call) -> str:
    name = dotted_name(node.func) or ""
    return name.rsplit(".", 1)[-1] if name else ""


def _is_source_call(node: ast.Call) -> Optional[str]:
    name = dotted_name(node.func) or ""
    if not name:
        return None
    tail = name.rsplit(".", 1)[-1]
    if tail in _SOURCE_CALL_TAILS:
        return name
    for exact in _SOURCE_CALL_EXACT:
        if name == exact or name.endswith("." + exact):
            return name
    return None


def _identity_tail(node: ast.AST) -> Optional[str]:
    """``worker`` / ``self.client.worker`` / ``cfg.host_id`` -> the tail."""
    if isinstance(node, ast.Name) and node.id in _IDENTITY_NAME_TAILS:
        return node.id
    if isinstance(node, ast.Attribute) and node.attr in _IDENTITY_NAME_TAILS:
        return node.attr
    return None


def _expr_taint(node: ast.AST, tainted: Dict[str, str]) -> Optional[str]:
    """First identity/clock source reachable inside ``node``, else None.

    Walking the whole expression covers f-strings (FormattedValue values),
    arithmetic on sources, and tuple/list packing in one pass.
    """
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            src = _is_source_call(sub)
            if src is not None:
                return f"{src}()"
        ident = _identity_tail(sub)
        if ident is not None:
            return f"'{ident}'"
        if isinstance(sub, ast.Name) and sub.id in tainted:
            return tainted[sub.id]
    return None


def _is_rng_sink(node: ast.Call) -> bool:
    name = dotted_name(node.func) or ""
    if not name:
        return False
    tail = name.rsplit(".", 1)[-1]
    if tail not in _RNG_SINK_TAILS:
        return False
    if tail in _AMBIGUOUS_SINK_TAILS:
        base = name[: -(len(tail) + 1)].lower()
        return any(h in base for h in _RNG_BASE_HINTS)
    return True


def _scope_bodies(tree: ast.Module):
    """Yield (body, is_module) for the module and every function, without
    descending into nested scopes twice."""
    yield tree.body, True
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node.body, False


_SCOPE_BARRIERS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)


def _iter_stmts(body: List[ast.stmt]):
    """Statements of a scope, recursing into compound statements (if/for/
    try/with) but never across a def/class boundary — nested scopes get
    their own pass via ``_scope_bodies``."""
    for stmt in body:
        yield stmt
        if isinstance(stmt, _SCOPE_BARRIERS):
            continue
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.stmt):
                yield from _iter_stmts([child])
            elif isinstance(child, ast.excepthandler):
                yield from _iter_stmts(child.body)


def _walk_scope(node: ast.AST):
    """``ast.walk`` pruned at def/class/lambda boundaries."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, _SCOPE_BARRIERS + (ast.Lambda,)):
                continue
            stack.append(child)


class _ScopeLint:
    """One taint + iteration-order pass over a single scope's statements."""

    def __init__(self) -> None:
        self.tainted: Dict[str, str] = {}   # var name -> source description
        self.set_vars: Dict[str, int] = {}  # var name -> def line (set-typed)
        self.out: List[Dict[str, Any]] = []

    # -- sub-rule A: host-identity RNG ------------------------------------

    def _propagate(self, stmts: List[ast.stmt]) -> None:
        # Two fixpoint passes over straight-line assignments are enough for
        # the chains this codebase writes (src -> name -> f-string -> seed).
        for _ in range(2):
            changed = False
            for stmt in stmts:
                targets: List[str] = []
                value: Optional[ast.AST] = None
                if isinstance(stmt, ast.Assign):
                    value = stmt.value
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            targets.append(t.id)
                elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                    value = stmt.value
                    if isinstance(stmt.target, ast.Name):
                        targets.append(stmt.target.id)
                elif isinstance(stmt, ast.AugAssign):
                    value = stmt.value
                    if isinstance(stmt.target, ast.Name):
                        targets.append(stmt.target.id)
                if value is None or not targets:
                    continue
                src = _expr_taint(value, self.tainted)
                if src is not None:
                    for name in targets:
                        if name not in self.tainted:
                            self.tainted[name] = src
                            changed = True
                # Track set-typed definitions for sub-rule B.
                if _is_set_expr(value):
                    for name in targets:
                        self.set_vars.setdefault(name, stmt.lineno)
            if not changed:
                break

    def _check_sinks(self, stmts: List[ast.stmt]) -> None:
        seen_lines = set()
        for stmt in stmts:
            if isinstance(stmt, _SCOPE_BARRIERS):
                continue
            for node in _walk_scope(stmt):
                if not isinstance(node, ast.Call) or not _is_rng_sink(node):
                    continue
                args: List[ast.AST] = list(node.args)
                args.extend(kw.value for kw in node.keywords)
                src = None
                for arg in args:
                    src = _expr_taint(arg, self.tainted)
                    if src is not None:
                        break
                if src is None or node.lineno in seen_lines:
                    continue
                seen_lines.add(node.lineno)
                sink = dotted_name(node.func) or "rng"
                self.out.append({
                    "line": node.lineno,
                    "col": node.col_offset,
                    "kind": "rng-host-identity",
                    "message": (
                        f"RNG seed for {sink}() derives from host identity "
                        f"or wall clock ({src}) — training randomness must "
                        "be a function of the logical batch/shard index so "
                        "it survives rescaling"
                    ),
                })

    # -- sub-rule B: unordered iteration feeding accumulation -------------

    def _check_loops(self, stmts: List[ast.stmt]) -> None:
        for stmt in stmts:
            if not isinstance(stmt, (ast.For, ast.AsyncFor)):
                continue
            why = _unordered_iter(stmt.iter, self.set_vars)
            if why is None:
                continue
            acc = _find_accumulation(stmt.body)
            if acc is None:
                continue
            self.out.append({
                "line": stmt.lineno,
                "col": stmt.col_offset,
                "kind": "unordered-accumulation",
                "message": (
                    f"numeric accumulation into '{acc}' is driven by "
                    f"iteration over {why} — set/dict order varies across "
                    "hosts and rescales; iterate a sorted() or logically "
                    "indexed sequence instead"
                ),
            })

    def run(self, body: List[ast.stmt]) -> List[Dict[str, Any]]:
        stmts = list(_iter_stmts(body))
        self._propagate(stmts)
        self._check_sinks(stmts)
        self._check_loops(stmts)
        return self.out


def _is_set_expr(node: ast.AST) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        tail = _call_tail(node)
        if tail in ("set", "frozenset"):
            return True
        # set arithmetic keeps set-ness: a | b via ``set(...).union(...)``
        if tail in ("union", "intersection", "difference"):
            return isinstance(node.func, ast.Attribute) and _is_set_expr(
                node.func.value
            )
    if isinstance(node, ast.BinOp) and isinstance(
        node.op, (ast.BitOr, ast.BitAnd, ast.Sub)
    ):
        return _is_set_expr(node.left) or _is_set_expr(node.right)
    return False


def _membership_dict_base(node: ast.AST) -> Optional[str]:
    """``self._members.values()`` -> "members" when base smells like a
    membership map (named *members*/*workers*/*hosts*)."""
    name = dotted_name(node) or ""
    tail = name.rsplit(".", 1)[-1].lstrip("_").lower()
    for hint in ("members", "workers", "hosts", "peers"):
        if hint in tail:
            return name
    return None


def _unordered_iter(
    iter_node: ast.AST, set_vars: Dict[str, int]
) -> Optional[str]:
    if _is_set_expr(iter_node):
        return "a set expression"
    if isinstance(iter_node, ast.Name) and iter_node.id in set_vars:
        return f"the set '{iter_node.id}'"
    if isinstance(iter_node, ast.Call) and isinstance(
        iter_node.func, ast.Attribute
    ):
        if iter_node.func.attr in ("values", "items", "keys"):
            base = _membership_dict_base(iter_node.func.value)
            if base is not None:
                return f"unordered membership view {base}.{iter_node.func.attr}()"
            if _is_set_expr(iter_node.func.value):
                return "a set expression"
    return None


def _find_accumulation(body: List[ast.stmt]) -> Optional[str]:
    """First arithmetic accumulation target in the loop body
    (``acc += x`` / ``acc = acc + x``), or None."""
    for stmt in _iter_stmts(body):
        if isinstance(stmt, ast.AugAssign) and isinstance(
            stmt.op, _NUMERIC_AUG_OPS
        ):
            name = dotted_name(stmt.target)
            if name:
                return name
        if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.BinOp):
            if not isinstance(stmt.value.op, _NUMERIC_AUG_OPS):
                continue
            for t in stmt.targets:
                tname = dotted_name(t)
                if tname and any(
                    dotted_name(sub) == tname
                    for sub in ast.walk(stmt.value)
                    if isinstance(sub, (ast.Name, ast.Attribute))
                ):
                    return tname
    return None


class ElasticDeterminismChecker:
    rule = "EDL008"
    name = "elastic-determinism"
    scope = "program"
    info = RuleInfo(
        rule="EDL008",
        name="elastic-determinism",
        description=(
            "training-state computation in runtime//parallel//models/ must "
            "not depend on host identity, world size, wall clocks, or "
            "unordered set/dict iteration — RNG seeds and accumulation "
            "order must be functions of the logical schedule"
        ),
    )

    # -- map phase --------------------------------------------------------

    def _applies(self, sf: SourceFile, ctx) -> bool:
        if ctx.config.get("edl008_all_files"):
            return True
        prefixes = tuple(
            ctx.config.get("edl008_prefixes", DEFAULT_PREFIXES)
        )
        return any(sf.relpath.startswith(p) for p in prefixes)

    def summarize(
        self, sf: SourceFile, ctx
    ) -> Optional[List[Dict[str, Any]]]:
        if not self._applies(sf, ctx):
            return None
        candidates: List[Dict[str, Any]] = []
        for body, _is_module in _scope_bodies(sf.tree):
            candidates.extend(_ScopeLint().run(body))
        return candidates or None

    # -- reduce phase ------------------------------------------------------

    def reduce(
        self,
        summaries: List[Tuple[str, Optional[List[Dict[str, Any]]]]],
        ctx,
    ) -> Iterator[Finding]:
        for relpath, candidates in summaries:
            for c in candidates or ():
                yield Finding(
                    rule=self.rule,
                    path=relpath,
                    line=c["line"],
                    col=c["col"],
                    message=c["message"],
                    symbol="",
                )
