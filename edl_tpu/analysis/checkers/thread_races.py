"""EDL006 — whole-program lockset race detection across thread roots.

EDL001 polices lock discipline *inside one class*: if the class owns a lock,
writes need it. What it cannot see is the question that actually decides
whether the runtime survives a rescale: which **threads** reach a write.
The codebase now runs a small fleet of them — the prefetch pump, the outbox
replayer, the coordinator supervisor, the MetricsServer's per-request
handler threads, registry collector callbacks, the autoscaler/updater/
collector loops — and a write is only a race if two of those roots can
reach it without a common lock.

Analysis (interprocedural, flow-insensitive inside a statement, lockset
dataflow across calls):

1. **Summarize** (per file, pool-safe): every function/method's writes to
   ``self.<attr>`` with the lexically-held locks, every resolvable call
   with the locks held at the call site, lock attribute tables (``Lock``/
   ``RLock``/``Condition``; a ``Condition(self.x)`` aliases its wrapped
   lock), and thread-root registrations: ``threading.Thread(target=...)``
   / ``Timer``, ``register_collector(fn)`` callbacks, and
   ``BaseHTTPRequestHandler`` subclasses (each ``do_*`` runs on a
   per-request server thread).
2. **Reduce** (whole program): link calls across modules via the import
   table, then run one lockset fixpoint per root (meet = set intersection,
   so a lock only counts if it is held on *every* path from that root).
   The main thread is itself a root whose entries are all public
   functions/methods.
3. A ``Class.attr`` written from >= 2 distinct roots whose write sites
   share no common lock is a finding, anchored at the least-guarded write.

Known limits (by design, to stay precise): calls through object attributes
(``self.worker.step()``), dynamic dispatch, and lock aliasing through
locals are not modeled — such edges are dropped, which can only lose
findings, never invent them. ``__init__``-time writes are exempt
(construction happens-before publication); GIL-atomic telemetry should be
``# edl: noqa[EDL006]``'d with a justification, same contract as EDL001.
"""

from __future__ import annotations

import ast
from typing import Any, Dict, FrozenSet, Iterator, List, Optional, Set, Tuple

from edl_tpu.analysis.core import (
    Finding,
    RuleInfo,
    SourceFile,
    dotted_name,
    is_self_attr,
    self_attr_root,
)

LOCK_FACTORIES = {"Lock", "RLock", "Condition"}

_CONSTRUCTION = {"__init__", "__new__", "__post_init__", "__init_subclass__"}

#: call-able factories that hand their target to a fresh thread
_THREAD_FACTORIES = {"Thread", "Timer"}


def _module_of(relpath: str) -> str:
    mod = relpath[:-3] if relpath.endswith(".py") else relpath
    mod = mod.replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


def _callable_ref(node: ast.AST) -> Optional[Tuple[str, str]]:
    """(kind, name) for a reference that may denote a function: ``self.m``
    -> ("self", "m"), bare ``f`` -> ("local", "f"), ``a.b`` -> ("dotted",
    "a.b"). None for lambdas/calls/anything dynamic."""
    attr = is_self_attr(node)
    if attr is not None:
        return ("self", attr)
    if isinstance(node, ast.Name):
        return ("local", node.id)
    dn = dotted_name(node)
    if dn is not None:
        return ("dotted", dn)
    return None


class ThreadRaceChecker:
    rule = "EDL006"
    name = "thread-races"
    scope = "program"
    info = RuleInfo(
        rule="EDL006",
        name="thread-races",
        description=(
            "attributes written from >= 2 thread roots (Thread targets, "
            "HTTP handler threads, collector callbacks, the main thread) "
            "must share a common lock on every write path"
        ),
    )

    # -- map phase -------------------------------------------------------------

    def summarize(self, sf: SourceFile, ctx) -> Dict[str, Any]:
        module = _module_of(sf.relpath)
        summary: Dict[str, Any] = {
            "module": module,
            "imports": {},
            "classes": {},
            "functions": {},
            "roots": [],
        }
        self._scan_imports(sf.tree, summary["imports"])
        module_locks = self._module_locks(sf.tree, module)

        for node in sf.tree.body:
            if isinstance(node, ast.ClassDef):
                self._scan_class(node, module, module_locks, summary)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_function(
                    node, node.name, None, {}, module_locks, summary
                )
        return summary

    @staticmethod
    def _scan_imports(tree: ast.Module, out: Dict[str, str]) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    out[alias.asname or alias.name.split(".")[0]] = alias.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    out[alias.asname or alias.name] = (
                        f"{node.module}.{alias.name}"
                    )

    @staticmethod
    def _lock_call_name(call: ast.Call) -> Optional[str]:
        func = call.func
        fname = (
            func.attr
            if isinstance(func, ast.Attribute)
            else func.id if isinstance(func, ast.Name) else None
        )
        return fname if fname in LOCK_FACTORIES else None

    def _module_locks(self, tree: ast.Module, module: str) -> Dict[str, List[str]]:
        """Module-global ``X = threading.Lock()`` -> {local name: lock ids}."""
        raw: Dict[str, Tuple[str, Optional[str]]] = {}
        for node in tree.body:
            if not (isinstance(node, ast.Assign) and isinstance(node.value, ast.Call)):
                continue
            fname = self._lock_call_name(node.value)
            if fname is None:
                continue
            wrapped = None
            if fname == "Condition" and node.value.args:
                arg = node.value.args[0]
                if isinstance(arg, ast.Name):
                    wrapped = arg.id
            for target in node.targets:
                if isinstance(target, ast.Name):
                    raw[target.id] = (fname, wrapped)
        out: Dict[str, List[str]] = {}
        for name, (_, wrapped) in raw.items():
            ids = [f"{module}.{name}"]
            if wrapped and wrapped in raw:
                ids.append(f"{module}.{wrapped}")
            out[name] = ids
        return out

    def _class_locks(self, cls: ast.ClassDef, module: str) -> Dict[str, List[str]]:
        """``self.X = threading.Lock()`` attrs -> lock ids; a Condition built
        over ``self.Y`` counts as holding both X and Y."""
        raw: Dict[str, Optional[str]] = {}
        for node in ast.walk(cls):
            if not (isinstance(node, ast.Assign) and isinstance(node.value, ast.Call)):
                continue
            fname = self._lock_call_name(node.value)
            if fname is None:
                continue
            wrapped = None
            if fname == "Condition" and node.value.args:
                wrapped = is_self_attr(node.value.args[0])
            for target in node.targets:
                attr = is_self_attr(target)
                if attr:
                    raw[attr] = wrapped
        out: Dict[str, List[str]] = {}
        for attr, wrapped in raw.items():
            ids = [f"{module}.{cls.name}.{attr}"]
            if wrapped and wrapped in raw:
                ids.append(f"{module}.{cls.name}.{wrapped}")
            out[attr] = ids
        return out

    def _scan_class(
        self,
        cls: ast.ClassDef,
        module: str,
        module_locks: Dict[str, List[str]],
        summary: Dict[str, Any],
    ) -> None:
        locks = self._class_locks(cls, module)
        methods = [
            n.name
            for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        summary["classes"][cls.name] = {
            "bases": [dotted_name(b) for b in cls.bases if dotted_name(b)],
            "locks": locks,
            "methods": methods,
        }
        for n in cls.body:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_function(
                    n, f"{cls.name}.{n.name}", cls.name, locks,
                    module_locks, summary,
                )

    def _scan_function(
        self,
        fn: ast.AST,
        qual: str,
        cls_name: Optional[str],
        class_locks: Dict[str, List[str]],
        module_locks: Dict[str, List[str]],
        summary: Dict[str, Any],
    ) -> None:
        writes: List[Tuple[str, int, int, List[str]]] = []
        calls: List[Tuple[str, str, List[str], int]] = []

        def lock_ids(expr: ast.AST) -> List[str]:
            attr = is_self_attr(expr)
            if attr is not None and attr in class_locks:
                return class_locks[attr]
            if isinstance(expr, ast.Name) and expr.id in module_locks:
                return module_locks[expr.id]
            return []

        def note_root(call: ast.Call, kind: str, line: int) -> None:
            target = None
            for kw in call.keywords:
                if kw.arg == "target":
                    target = kw.value
            if target is None and kind == "collector" and call.args:
                target = call.args[0]
            if target is None and kind == "thread":
                return
            ref = _callable_ref(target) if target is not None else None
            if ref is not None:
                summary["roots"].append(
                    (kind, ref[0], ref[1], cls_name, qual, line)
                )

        def visit(node: ast.AST, held: FrozenSet[str]) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # Nested function: its body runs when called, under whatever
                # the *caller* holds — record it as its own function with a
                # scoped qualname; lexical locks at the def site don't apply.
                self._scan_function(
                    node, f"{qual}.{node.name}", cls_name, class_locks,
                    module_locks, summary,
                )
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                acquired: Set[str] = set(held)
                for item in node.items:
                    acquired.update(lock_ids(item.context_expr))
                    visit(item.context_expr, held)
                for stmt in node.body:
                    visit(stmt, frozenset(acquired))
                return
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign) else [node.target]
                )
                for target in targets:
                    for t in self._flatten(target):
                        attr = self_attr_root(t)
                        if attr:
                            writes.append(
                                (attr, t.lineno, t.col_offset, sorted(held))
                            )
                value = getattr(node, "value", None)
                if value is not None:
                    visit(value, held)
                return
            if isinstance(node, ast.Delete):
                for t in node.targets:
                    attr = self_attr_root(t)
                    if attr:
                        writes.append(
                            (attr, t.lineno, t.col_offset, sorted(held))
                        )
                return
            if isinstance(node, ast.Call):
                fname = (
                    node.func.attr
                    if isinstance(node.func, ast.Attribute)
                    else node.func.id if isinstance(node.func, ast.Name) else None
                )
                if fname in _THREAD_FACTORIES:
                    note_root(node, "thread", node.lineno)
                elif fname == "register_collector":
                    note_root(node, "collector", node.lineno)
                ref = _callable_ref(node.func)
                if ref is not None:
                    calls.append((ref[0], ref[1], sorted(held), node.lineno))
                for child in ast.iter_child_nodes(node):
                    visit(child, held)
                return
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in fn.body:
            visit(stmt, frozenset())

        name = qual.rsplit(".", 1)[-1]
        public = not name.startswith("_") or (
            name.startswith("__") and name.endswith("__")
            and name not in _CONSTRUCTION
        )
        summary["functions"][qual] = {
            "cls": cls_name,
            "writes": writes,
            "calls": calls,
            "public": public,
            "construction": name in _CONSTRUCTION,
        }

    @staticmethod
    def _flatten(target: ast.AST) -> Iterator[ast.AST]:
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                yield from ThreadRaceChecker._flatten(elt)
        else:
            yield target

    # -- reduce phase ----------------------------------------------------------

    def reduce(
        self, summaries: List[Tuple[str, Dict[str, Any]]], ctx
    ) -> Iterator[Finding]:
        # Global tables keyed "module:qual".
        funcs: Dict[str, Dict[str, Any]] = {}
        classes: Dict[str, Dict[str, Any]] = {}
        imports: Dict[str, Dict[str, str]] = {}
        relpath_of: Dict[str, str] = {}
        for relpath, s in summaries:
            mod = s["module"]
            relpath_of[mod] = relpath
            imports[mod] = s["imports"]
            for qual, info in s["functions"].items():
                funcs[f"{mod}:{qual}"] = info
            for cname, cinfo in s["classes"].items():
                classes[f"{mod}:{cname}"] = cinfo

        def resolve(mod: str, caller_qual: str, kind: str, name: str,
                    cls: Optional[str]) -> Optional[str]:
            if kind == "self":
                if cls is not None and f"{mod}:{cls}.{name}" in funcs:
                    return f"{mod}:{cls}.{name}"
                return None
            if kind == "local":
                # Nested function in the caller's scope wins, then the
                # enclosing class's namespace-free module scope, then imports.
                scoped = f"{mod}:{caller_qual}.{name}"
                if scoped in funcs:
                    return scoped
                if f"{mod}:{name}" in funcs:
                    return f"{mod}:{name}"
                target = imports.get(mod, {}).get(name)
                if target and ":" not in target:
                    head, _, sym = target.rpartition(".")
                    if head and f"{head}:{sym}" in funcs:
                        return f"{head}:{sym}"
                return None
            # dotted: resolve the head through imports -> module function.
            head, _, rest = name.partition(".")
            target_mod = imports.get(mod, {}).get(head)
            if target_mod and rest and f"{target_mod}:{rest}" in funcs:
                return f"{target_mod}:{rest}"
            return None

        # Thread roots: (label, entry fkeys)
        roots: List[Tuple[str, List[str]]] = []
        for relpath, s in summaries:
            mod = s["module"]
            for kind, rkind, rname, cls, in_qual, _line in s["roots"]:
                fkey = resolve(mod, in_qual, rkind, rname, cls)
                if fkey is None:
                    continue
                label = {
                    "thread": "Thread",
                    "collector": "collector-callback",
                }.get(kind, kind)
                roots.append((f"{label}({fkey.split(':', 1)[1]})", [fkey]))
            # HTTP handler classes: each do_* method runs on a per-request
            # server thread (ThreadingHTTPServer), so each is a root.
            for cname, cinfo in s["classes"].items():
                if not self._is_http_handler(mod, cname, classes, imports):
                    continue
                for m in cinfo["methods"]:
                    if m.startswith("do_"):
                        roots.append(
                            (f"http-handler({cname}.{m})", [f"{mod}:{cname}.{m}"])
                        )

        # The main thread is a root too: every public function/method —
        # except functions that ARE a thread root's entry (a public loop like
        # run_forever is either called inline on the main thread or handed to
        # Thread(), never both; main can still reach it through a real call
        # edge, which the propagation models).
        threaded_entries = {fkey for _label, entries in roots for fkey in entries}
        main_entries = [
            fkey for fkey, info in funcs.items()
            if info["public"]
            and not info["construction"]
            and fkey not in threaded_entries
        ]
        roots.append(("main", main_entries))

        # Dedup root labels (two Thread() sites on one target are one root).
        merged: Dict[str, Set[str]] = {}
        for label, entries in roots:
            merged.setdefault(label, set()).update(entries)

        all_locks: FrozenSet[str] = frozenset(
            lid
            for cinfo in classes.values()
            for ids in cinfo["locks"].values()
            for lid in ids
        ) | frozenset(
            lid
            for _relpath, s in summaries
            for info in s["functions"].values()
            for _a, _l, _c, held in info["writes"]
            for lid in held
        )

        # attr key -> {root label -> guard-set intersection}, and write sites.
        attr_guards: Dict[str, Dict[str, FrozenSet[str]]] = {}
        attr_sites: Dict[str, List[Tuple[str, int, int, int]]] = {}

        for label in sorted(merged):
            entry_locks = self._fixpoint(
                funcs, merged[label], all_locks,
                lambda mod, q, k, n, c: resolve(mod, q, k, n, c),
            )
            for fkey, held_at_entry in entry_locks.items():
                info = funcs[fkey]
                if info["construction"]:
                    continue
                mod, qual = fkey.split(":", 1)
                cls = info["cls"]
                if cls is None:
                    continue  # only self-attribute state is modeled
                cinfo = classes.get(f"{mod}:{cls}", {})
                lock_attrs = set(cinfo.get("locks", {}))
                for attr, line, col, lex in info["writes"]:
                    if attr in lock_attrs:
                        continue
                    akey = f"{mod}:{cls}.{attr}"
                    eff = held_at_entry | frozenset(lex)
                    guards = attr_guards.setdefault(akey, {})
                    guards[label] = guards.get(label, all_locks) & eff
                    attr_sites.setdefault(akey, []).append(
                        (relpath_of[mod], line, col, len(eff))
                    )

        for akey in sorted(attr_guards):
            guards = attr_guards[akey]
            if len(guards) < 2:
                continue
            common = all_locks
            for g in guards.values():
                common &= g
            if common:
                continue
            _mod, cls_attr = akey.split(":", 1)
            root_list = ", ".join(sorted(guards))
            # Anchor at the least-guarded (then earliest) write site.
            path, line, col, _n = min(
                attr_sites[akey], key=lambda s: (s[3], s[1], s[2])
            )
            yield Finding(
                rule=self.rule,
                path=path,
                line=line,
                col=col,
                message=(
                    f"'{cls_attr}' is written from {len(guards)} thread "
                    f"roots ({root_list}) with no common lock"
                ),
            )

    @staticmethod
    def _is_http_handler(
        mod: str,
        cname: str,
        classes: Dict[str, Dict[str, Any]],
        imports: Dict[str, Dict[str, str]],
        _depth: int = 0,
    ) -> bool:
        if _depth > 8:
            return False
        cinfo = classes.get(f"{mod}:{cname}")
        if cinfo is None:
            return False
        for base in cinfo["bases"]:
            if base.rsplit(".", 1)[-1] == "BaseHTTPRequestHandler":
                return True
            # Base defined in this module, or imported from another.
            if ThreadRaceChecker._is_http_handler(
                mod, base, classes, imports, _depth + 1
            ):
                return True
            target = imports.get(mod, {}).get(base)
            if target:
                bmod, _, bcls = target.rpartition(".")
                if bmod and ThreadRaceChecker._is_http_handler(
                    bmod, bcls, classes, imports, _depth + 1
                ):
                    return True
        return False

    @staticmethod
    def _fixpoint(
        funcs: Dict[str, Dict[str, Any]],
        entries: Set[str],
        all_locks: FrozenSet[str],
        resolve,
    ) -> Dict[str, FrozenSet[str]]:
        """Per-root dataflow: fkey -> locks held on EVERY path from the root
        to that function's entry (meet = intersection). Only reachable
        functions appear in the result."""
        state: Dict[str, FrozenSet[str]] = {
            e: frozenset() for e in entries if e in funcs
        }
        work = list(state)
        while work:
            fkey = work.pop()
            info = funcs[fkey]
            held = state[fkey]
            mod, qual = fkey.split(":", 1)
            for kind, name, lex, _line in info["calls"]:
                callee = resolve(mod, qual, kind, name, info["cls"])
                if callee is None or callee not in funcs:
                    continue
                at_call = held | frozenset(lex)
                prev = state.get(callee)
                new = at_call if prev is None else (prev & at_call)
                if prev is None or new != prev:
                    state[callee] = new
                    work.append(callee)
        return state
