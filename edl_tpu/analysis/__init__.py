"""Domain-specific static analysis for the elastic-training codebase.

Generic linters cannot see the invariants elastic training lives or dies
by: shared controller/coordinator state mutated during a rescale must be
lock-guarded (EDL001), the jitted hot path must not retrace or call back
into the host (EDL002), PartitionSpec axis names must exist on the meshes
we actually build (EDL003), coordinator handler paths must never block
while holding the service lock (EDL004), failures must not vanish into
bare ``except`` handlers (EDL005), attributes reached from multiple thread
roots must share a lock (EDL006), the wire protocol's three
implementations must agree (EDL007), training state must not depend on
host identity or unordered iteration (EDL008), and the protocol's declared
state effects must survive bounded model checking against the in-process
coordinator (EDL009). This package is an AST-based engine with one checker
per invariant, a baseline file to ratchet existing debt down, and per-line
suppression via ``# edl: noqa[RULE]``.

Run it as ``python -m edl_tpu.analysis edl_tpu/`` or through
``tests/test_analysis.py`` (tier-1: the committed tree must be clean
against the committed baseline).
"""

from edl_tpu.analysis.core import Finding, SourceFile
from edl_tpu.analysis.engine import AnalysisContext, Report, analyze
from edl_tpu.analysis.baseline import (
    Baseline,
    apply_baseline,
    fingerprint,
    load_baseline,
    write_baseline,
)

__all__ = [
    "AnalysisContext",
    "Baseline",
    "Finding",
    "Report",
    "SourceFile",
    "analyze",
    "apply_baseline",
    "fingerprint",
    "load_baseline",
    "write_baseline",
]
