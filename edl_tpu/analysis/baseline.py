"""Baseline: accepted debt, ratcheted down over time.

The baseline records existing findings by *fingerprint* — rule + file +
enclosing symbol + message, deliberately NOT the line number, so unrelated
edits that shift lines don't churn it. Identical findings in one symbol
(two unguarded writes to the same attribute) share a fingerprint; the
stored ``count`` caps how many occurrences stay accepted — the N+1'th is
new debt and fails the run. Entries whose finding disappeared are reported
as stale so the ratchet only ever tightens.
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Tuple

from edl_tpu.analysis.core import Finding

BASELINE_VERSION = 1

DEFAULT_BASELINE_NAME = "analysis_baseline.json"


def fingerprint(finding: Finding) -> str:
    raw = "|".join((finding.rule, finding.path, finding.symbol, finding.message))
    return hashlib.sha256(raw.encode("utf-8")).hexdigest()[:16]


@dataclass
class Baseline:
    #: fingerprint -> entry dict (rule/path/symbol/message/count)
    entries: Dict[str, dict] = field(default_factory=dict)

    def total(self) -> int:
        return sum(e.get("count", 1) for e in self.entries.values())


def load_baseline(path: str) -> Baseline:
    if not os.path.isfile(path):
        return Baseline()
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path} has version {data.get('version')!r}, "
            f"expected {BASELINE_VERSION}"
        )
    return Baseline(entries=dict(data.get("findings", {})))


def write_baseline(path: str, findings: List[Finding]) -> Baseline:
    entries: Dict[str, dict] = {}
    for f in findings:
        fp = fingerprint(f)
        if fp in entries:
            entries[fp]["count"] += 1
        else:
            entries[fp] = {
                "rule": f.rule,
                "path": f.path,
                "symbol": f.symbol,
                "message": f.message,
                "count": 1,
            }
    baseline = Baseline(entries=entries)
    payload = {
        "version": BASELINE_VERSION,
        "comment": (
            "Accepted static-analysis debt. Regenerate with "
            "`python -m edl_tpu.analysis edl_tpu --write-baseline` after "
            "fixing entries; never hand-add new ones."
        ),
        "findings": {k: entries[k] for k in sorted(entries)},
    }
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=False)
        f.write("\n")
    os.replace(tmp, path)
    return baseline


def apply_baseline(
    findings: List[Finding], baseline: Baseline
) -> Tuple[List[Finding], List[Finding], List[dict]]:
    """Split ``findings`` into (new, accepted) and report stale entries.

    Occurrences beyond an entry's ``count`` are new. Stale = baseline
    entries (or excess counts) no finding matched — fixed debt whose entry
    should be ratcheted out via ``--write-baseline``.
    """
    remaining = {
        fp: e.get("count", 1) for fp, e in baseline.entries.items()
    }
    new: List[Finding] = []
    accepted: List[Finding] = []
    for f in findings:
        fp = fingerprint(f)
        if remaining.get(fp, 0) > 0:
            remaining[fp] -= 1
            accepted.append(f)
        else:
            new.append(f)
    stale = [
        {**baseline.entries[fp], "unmatched": left}
        for fp, left in remaining.items()
        if left > 0
    ]
    return new, accepted, stale
