"""Analysis engine: walk files, run checkers, apply suppressions.

Two checker scopes:

- **file** (the default): ``check(sf, ctx)`` sees one parsed ``SourceFile``
  at a time. EDL001-EDL005.
- **program**: map/reduce over the whole tree. ``summarize(sf, ctx)``
  extracts a small picklable summary per file (runs wherever the file is
  parsed — possibly a pool worker); ``reduce(summaries, ctx)`` sees every
  summary at once and emits the cross-file findings. EDL006 builds its
  repo-wide call graph this way; EDL007 joins the Python summaries against
  the C++ dispatch table it parses itself in ``reduce``.

Per-file work (parse + file checkers + summaries) fans out across a process
pool when ``jobs > 1``; the reduce phase is always in-process. The summary
design is what makes the pool safe: ASTs never cross process boundaries,
only plain dict/tuple summaries and ``Finding`` dataclasses do.

Baseline handling lives in ``baseline.py``; output formatting in ``cli.py``.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

from edl_tpu.analysis.core import Finding, SourceFile

_SKIP_DIRS = {
    "__pycache__",
    ".git",
    ".hg",
    "node_modules",
    "native",
    ".venv",
    "venv",
    ".eggs",
    "build",
    "dist",
}


@dataclass
class AnalysisContext:
    """Shared state handed to every checker.

    ``root`` anchors cross-file lookups (EDL003 reads ``parallel/mesh.py``
    relative to it, EDL007 the native coordinator source); ``config`` carries
    per-run overrides (fixture axis universes, scope widening, fixture
    protocol files); ``cache`` is scratch space checkers use to avoid
    re-parsing shared inputs.
    """

    root: str
    config: Dict[str, Any] = field(default_factory=dict)
    cache: Dict[str, Any] = field(default_factory=dict)


@dataclass
class Report:
    findings: List[Finding]
    suppressed: List[Finding]
    files_checked: int
    parse_errors: List[Tuple[str, str]] = field(default_factory=list)
    #: rule id -> cumulative seconds in the per-file (map) phase: file
    #: checkers sum ``check`` across files; program checkers sum
    #: ``summarize``. With ``--jobs`` this is CPU time across the pool,
    #: not wall clock.
    timings: Dict[str, float] = field(default_factory=dict)
    #: rule id -> seconds in the in-parent reduce phase (program checkers
    #: only). Kept separate from ``timings`` because reduce is serial wall
    #: clock — a slow reduce can't be bought back with more jobs.
    reduce_timings: Dict[str, float] = field(default_factory=dict)
    #: worker processes used for the per-file phase (1 = in-process serial).
    jobs: int = 1

    def by_rule(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out


def iter_python_files(paths: Sequence[str]) -> Iterator[str]:
    """Yield .py files under ``paths`` (files given directly always yield)."""
    seen = set()
    for path in paths:
        path = os.path.abspath(path)
        if os.path.isfile(path):
            if path not in seen:
                seen.add(path)
                yield path
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = sorted(
                d for d in dirnames
                if d not in _SKIP_DIRS and not d.startswith(".")
            )
            for name in sorted(filenames):
                if name.endswith(".py"):
                    full = os.path.join(dirpath, name)
                    if full not in seen:
                        seen.add(full)
                        yield full


def detect_root(paths: Sequence[str]) -> str:
    """Repo root: nearest ancestor of the first path that contains the
    ``edl_tpu`` package (so EDL003 can find ``parallel/mesh.py``); falls
    back to the CWD."""
    for path in paths:
        probe = os.path.abspath(path)
        if os.path.isfile(probe):
            probe = os.path.dirname(probe)
        while True:
            if os.path.isfile(
                os.path.join(probe, "edl_tpu", "parallel", "mesh.py")
            ):
                return probe
            parent = os.path.dirname(probe)
            if parent == probe:
                break
            probe = parent
    return os.getcwd()


def default_jobs(n_files: int) -> int:
    """Pool width: EDL_ANALYZE_JOBS wins; otherwise one worker per core
    (capped), and serial when the tree is too small to amortize fork+pickle."""
    env = os.environ.get("EDL_ANALYZE_JOBS", "").strip()
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    if n_files < 24:
        return 1
    return max(1, min(os.cpu_count() or 1, 8))


def _split_checkers(rules: Optional[Iterable[str]]):
    from edl_tpu.analysis.checkers import ALL_CHECKERS

    wanted = {r.upper() for r in rules} if rules is not None else None
    file_rules: List[str] = []
    program_rules: List[str] = []
    for cls in ALL_CHECKERS:
        if wanted is not None and cls.rule not in wanted:
            continue
        if getattr(cls, "scope", "file") == "program":
            program_rules.append(cls.rule)
        else:
            file_rules.append(cls.rule)
    return file_rules, program_rules


def _checkers_by_rule(rule_ids: Sequence[str]):
    from edl_tpu.analysis.checkers import RULES

    return [RULES[r]() for r in rule_ids]


def _analyze_one(
    path: str,
    root: str,
    file_rules: Sequence[str],
    program_rules: Sequence[str],
    config: Dict[str, Any],
) -> Dict[str, Any]:
    """Per-file unit of work — module-level so a process pool can pickle it.

    Everything returned is plain data: findings (dataclasses), the file's
    noqa/symbol index (so program-checker findings can be suppressed and
    symbol-tagged without re-parsing in the parent), per-rule seconds, and
    each program checker's summary.
    """
    relpath = os.path.relpath(path, root).replace(os.sep, "/")
    out: Dict[str, Any] = {
        "relpath": relpath,
        "findings": [],
        "suppressed": [],
        "error": None,
        "summaries": {},
        "timings": {},
        "index": None,
    }
    ctx = AnalysisContext(root=root, config=dict(config))
    try:
        with open(path, "r", encoding="utf-8") as f:
            sf = SourceFile(path, relpath, f.read())
    except (SyntaxError, UnicodeDecodeError, OSError) as e:
        out["error"] = f"{type(e).__name__}: {e}"
        return out

    for checker in _checkers_by_rule(file_rules):
        t0 = time.perf_counter()
        for finding in checker.check(sf, ctx):
            if not finding.symbol:
                finding = Finding(
                    rule=finding.rule,
                    path=finding.path,
                    line=finding.line,
                    col=finding.col,
                    message=finding.message,
                    symbol=sf.symbol_at(finding.line),
                )
            if sf.is_suppressed(finding):
                out["suppressed"].append(finding)
            else:
                out["findings"].append(finding)
        out["timings"][checker.rule] = (
            out["timings"].get(checker.rule, 0.0) + time.perf_counter() - t0
        )

    for checker in _checkers_by_rule(program_rules):
        t0 = time.perf_counter()
        out["summaries"][checker.rule] = checker.summarize(sf, ctx)
        out["timings"][checker.rule] = (
            out["timings"].get(checker.rule, 0.0) + time.perf_counter() - t0
        )

    # Noqa table + symbol intervals: the parent applies suppression to
    # program-checker findings against this, without holding the AST.
    out["index"] = {
        "noqa": {
            line: (None if rules is None else sorted(rules))
            for line, rules in sf.noqa.items()
        },
        "symbols": list(sf.symbols),
    }
    return out


def _symbol_at(symbols: List[Tuple[int, int, str]], line: int) -> str:
    best, best_span = "", None
    for start, end, qual in symbols:
        if start <= line <= end:
            span = end - start
            if best_span is None or span <= best_span:
                best, best_span = qual, span
    return best


def _is_suppressed(index: Optional[Dict], finding: Finding) -> bool:
    if not index:
        return False
    rules = index["noqa"].get(finding.line, ())
    if rules == ():
        return False
    return rules is None or finding.rule.upper() in rules


def analyze(
    paths: Sequence[str],
    root: Optional[str] = None,
    rules: Optional[Iterable[str]] = None,
    config: Optional[Dict[str, Any]] = None,
    jobs: Optional[int] = None,
) -> Report:
    """Run the checker suite over ``paths``.

    ``rules`` filters to a subset of rule ids (default: all). ``jobs``
    widens the per-file phase across a process pool (default: auto —
    EDL_ANALYZE_JOBS, else cores, serial for small trees). Findings on
    ``# edl: noqa`` lines land in ``report.suppressed``; everything else in
    ``report.findings`` (baseline application is the caller's business).
    """
    root = os.path.abspath(root or detect_root(paths))
    config = dict(config or {})
    file_rules, program_rules = _split_checkers(rules)

    files = list(iter_python_files(paths))
    n_jobs = jobs if jobs is not None else default_jobs(len(files))

    results: List[Dict[str, Any]] = []
    if n_jobs > 1 and len(files) > 1:
        import concurrent.futures

        try:
            with concurrent.futures.ProcessPoolExecutor(
                max_workers=n_jobs
            ) as pool:
                results = list(
                    pool.map(
                        _analyze_one,
                        files,
                        [root] * len(files),
                        [file_rules] * len(files),
                        [program_rules] * len(files),
                        [config] * len(files),
                        chunksize=max(1, len(files) // (n_jobs * 4)),
                    )
                )
        except (OSError, ValueError):
            # Pool unavailable (sandboxed fork, fd limits): fall back rather
            # than fail the lint — serial produces identical findings.
            n_jobs = 1
            results = []
    if not results:
        n_jobs = 1
        results = [
            _analyze_one(p, root, file_rules, program_rules, config)
            for p in files
        ]

    findings: List[Finding] = []
    suppressed: List[Finding] = []
    errors: List[Tuple[str, str]] = []
    timings: Dict[str, float] = {}
    indexes: Dict[str, Dict] = {}
    summaries: Dict[str, List[Tuple[str, Any]]] = {r: [] for r in program_rules}
    n_files = 0
    for res in results:
        if res["error"] is not None:
            errors.append((res["relpath"], res["error"]))
            continue
        n_files += 1
        findings.extend(res["findings"])
        suppressed.extend(res["suppressed"])
        indexes[res["relpath"]] = res["index"]
        for rule, summary in res["summaries"].items():
            summaries[rule].append((res["relpath"], summary))
        for rule, sec in res["timings"].items():
            timings[rule] = timings.get(rule, 0.0) + sec

    ctx = AnalysisContext(root=root, config=config)
    reduce_timings: Dict[str, float] = {}
    for checker in _checkers_by_rule(program_rules):
        t0 = time.perf_counter()
        for finding in checker.reduce(summaries[checker.rule], ctx):
            index = indexes.get(finding.path)
            if not finding.symbol and index:
                finding = Finding(
                    rule=finding.rule,
                    path=finding.path,
                    line=finding.line,
                    col=finding.col,
                    message=finding.message,
                    symbol=_symbol_at(index["symbols"], finding.line),
                )
            if _is_suppressed(index, finding):
                suppressed.append(finding)
            else:
                findings.append(finding)
        reduce_timings[checker.rule] = (
            reduce_timings.get(checker.rule, 0.0) + time.perf_counter() - t0
        )

    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    suppressed.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return Report(
        findings=findings,
        suppressed=suppressed,
        files_checked=n_files,
        parse_errors=errors,
        timings=timings,
        reduce_timings=reduce_timings,
        jobs=n_jobs,
    )
