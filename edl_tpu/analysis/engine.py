"""Analysis engine: walk files, run checkers, apply suppressions.

The engine is deliberately boring: collect ``.py`` files, parse each once,
hand the shared ``SourceFile`` to every enabled checker, and split raw
findings into kept vs ``# edl: noqa``-suppressed. Baseline handling lives
in ``baseline.py``; output formatting in ``cli.py``.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

from edl_tpu.analysis.core import Finding, SourceFile

_SKIP_DIRS = {
    "__pycache__",
    ".git",
    ".hg",
    "node_modules",
    "native",
    ".venv",
    "venv",
    ".eggs",
    "build",
    "dist",
}


@dataclass
class AnalysisContext:
    """Shared state handed to every checker.

    ``root`` anchors cross-file lookups (EDL003 reads ``parallel/mesh.py``
    relative to it); ``config`` carries per-run overrides (fixture axis
    universes, scope widening); ``cache`` is scratch space checkers use to
    avoid re-parsing shared inputs.
    """

    root: str
    config: Dict[str, Any] = field(default_factory=dict)
    cache: Dict[str, Any] = field(default_factory=dict)


@dataclass
class Report:
    findings: List[Finding]
    suppressed: List[Finding]
    files_checked: int
    parse_errors: List[Tuple[str, str]] = field(default_factory=list)

    def by_rule(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out


def iter_python_files(paths: Sequence[str]) -> Iterator[str]:
    """Yield .py files under ``paths`` (files given directly always yield)."""
    seen = set()
    for path in paths:
        path = os.path.abspath(path)
        if os.path.isfile(path):
            if path not in seen:
                seen.add(path)
                yield path
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = sorted(
                d for d in dirnames
                if d not in _SKIP_DIRS and not d.startswith(".")
            )
            for name in sorted(filenames):
                if name.endswith(".py"):
                    full = os.path.join(dirpath, name)
                    if full not in seen:
                        seen.add(full)
                        yield full


def detect_root(paths: Sequence[str]) -> str:
    """Repo root: nearest ancestor of the first path that contains the
    ``edl_tpu`` package (so EDL003 can find ``parallel/mesh.py``); falls
    back to the CWD."""
    for path in paths:
        probe = os.path.abspath(path)
        if os.path.isfile(probe):
            probe = os.path.dirname(probe)
        while True:
            if os.path.isfile(
                os.path.join(probe, "edl_tpu", "parallel", "mesh.py")
            ):
                return probe
            parent = os.path.dirname(probe)
            if parent == probe:
                break
            probe = parent
    return os.getcwd()


def analyze(
    paths: Sequence[str],
    root: Optional[str] = None,
    rules: Optional[Iterable[str]] = None,
    config: Optional[Dict[str, Any]] = None,
) -> Report:
    """Run the checker suite over ``paths``.

    ``rules`` filters to a subset of rule ids (default: all). Findings on
    ``# edl: noqa`` lines land in ``report.suppressed``; everything else in
    ``report.findings`` (baseline application is the caller's business).
    """
    from edl_tpu.analysis.checkers import ALL_CHECKERS

    root = os.path.abspath(root or detect_root(paths))
    ctx = AnalysisContext(root=root, config=dict(config or {}))
    wanted = {r.upper() for r in rules} if rules is not None else None
    checkers = [
        cls() for cls in ALL_CHECKERS if wanted is None or cls.rule in wanted
    ]

    findings: List[Finding] = []
    suppressed: List[Finding] = []
    errors: List[Tuple[str, str]] = []
    n_files = 0
    for path in iter_python_files(paths):
        relpath = os.path.relpath(path, root).replace(os.sep, "/")
        try:
            with open(path, "r", encoding="utf-8") as f:
                sf = SourceFile(path, relpath, f.read())
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            errors.append((relpath, f"{type(e).__name__}: {e}"))
            continue
        n_files += 1
        for checker in checkers:
            for finding in checker.check(sf, ctx):
                if not finding.symbol:
                    finding = Finding(
                        rule=finding.rule,
                        path=finding.path,
                        line=finding.line,
                        col=finding.col,
                        message=finding.message,
                        symbol=sf.symbol_at(finding.line),
                    )
                if sf.is_suppressed(finding):
                    suppressed.append(finding)
                else:
                    findings.append(finding)

    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    suppressed.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return Report(
        findings=findings,
        suppressed=suppressed,
        files_checked=n_files,
        parse_errors=errors,
    )
