"""The LM serving replica: decode-step continuous batching over paged KV.

PR 11's :class:`ServingReplica` batches fixed-shape request/response
inference — admit a request, run one executable, resolve one future. An
autoregressive LM breaks that shape: a request is a *stream* that holds
K/V state across hundreds of device steps, and throughput lives in
per-token scheduling, not per-request. This module is the LM-native
sibling, built from three separations:

- **Continuous batching at decode-step granularity.** One persistent
  loop owns the device. Batch membership changes *per token*: admitted
  streams join at the next step boundary, streams leave the instant they
  hit EOS or their token budget — no waiting for a batch-mate's longer
  generation (the Orca/vLLM scheduling insight, here with fixed-shape
  executables instead of dynamic shapes).
- **Prefill/decode phase separation.** Prompts run through a
  compute-bound prefill executable at their *prompt* seq bucket and hand
  their K/V to the stream; every subsequent token runs a memory-bound
  single-token decode executable at the stream's *capacity* seq bucket.
  Both phases are AOT-compiled per (batch bucket, seq bucket) before the
  first request — ``jit_cache_size() == 0`` holds under LM traffic.
- **Memory as the admission currency.** A stream is admitted iff the
  :class:`~edl_tpu.serving.kvcache.BlockPool` can reserve blocks for its
  full ``prompt + max_new_tokens`` budget (429 otherwise), so decode
  never deadlocks on allocation mid-stream; what that guarantee costs is
  visible as the pool's fragmentation metric.

Cache layout: the device executables are stateless — prefill *returns*
K/V, decode *returns* the one new position's K/V — and this engine keeps
each stream's cache as a host-side array of its capacity bucket. A decode
step stacks member caches into the (L, B, C, H, Dh) batch operand and
scatters the returned position back. That host round-trip is the price of
making join/leave free (no device-side cache compaction when membership
changes); the BlockPool stays the authority on how much HBM the same
streams would pin in a device-resident layout.

Threading (EDL006): one engine thread runs admit/prefill/decode and
status publication; HTTP frontend threads call ``submit``. Shared state —
waiting list, active map, stats — lives behind ``self._lock``; device
dispatch and future resolution happen OUTSIDE it. The BlockPool has its
own lock and is safe from both sides.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from collections import deque
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from edl_tpu.obs.instruments import LMServeInstruments
from edl_tpu.obs.metrics import MetricsRegistry
from edl_tpu.obs.tracing import Tracer, get_tracer
from edl_tpu.serving.batcher import (pad_token_rows, pick_bucket,
                                     pick_seq_bucket, plan_chunks,
                                     validate_buckets)
from edl_tpu.serving.kvcache import BlockPool, KVCacheConfig
from edl_tpu.serving.worker import (SERVING_KV_PREFIX, ServeCompileError,
                                    probe_jit_cache)

__all__ = ["LMServingConfig", "LMServingReplica", "LMStreamHandle"]

log = logging.getLogger("edl_tpu.serving.lm")


@dataclass
class LMServingConfig:
    """Knobs for one LM serving replica."""

    model_dir: str
    #: batch-slot ladder, shared by prefill and decode dispatches
    batch_buckets: Tuple[int, ...] = (1, 4)
    #: token-capacity ladder: a stream's capacity bucket must hold
    #: prompt + max_new_tokens; prompts prefill at their own (smaller)
    #: bucket. The largest entry is the admission ceiling (SeqTooLong
    #: beyond it) and must fit the model's trained seq_len.
    seq_buckets: Tuple[int, ...] = (64, 128, 256)
    #: KV block pool shape (memory admission currency)
    kv_blocks: int = 64
    kv_block_tokens: int = 16
    #: token budget when a request names none
    default_max_new_tokens: int = 32
    #: greedy decode stops on this token id (per-request override wins)
    eos_id: Optional[int] = None
    #: engine idle wait between wake-up checks when no stream is live
    idle_wait_s: float = 0.002
    request_timeout_s: float = 60.0
    #: None: no HTTP frontend; 0: ephemeral port (tests); N: fixed port
    port: Optional[int] = None
    name: str = "lm-0"
    #: coordinator KV status publication period
    publish_interval_s: float = 1.0

    def __post_init__(self):
        self.batch_buckets = validate_buckets(self.batch_buckets)
        self.seq_buckets = validate_buckets(self.seq_buckets)
        if self.default_max_new_tokens <= 0:
            raise ValueError("default_max_new_tokens must be positive")
        if self.kv_blocks * self.kv_block_tokens < self.seq_buckets[0]:
            raise ValueError(
                f"KV pool of {self.kv_blocks}x{self.kv_block_tokens} tokens "
                f"cannot hold even the smallest seq bucket "
                f"{self.seq_buckets[0]}"
            )


@dataclass
class LMStreamHandle:
    """One admitted stream: resolve via ``result()`` to a dict with
    ``tokens`` (generated ids), ``finish_reason`` (eos | length),
    ``prompt_tokens``, and ``model_step``."""

    stream_id: str
    future: Future

    def result(self, timeout: Optional[float] = None) -> Dict[str, Any]:
        return self.future.result(timeout=timeout)

    def done(self) -> bool:
        return self.future.done()


@dataclass
class _Stream:
    id: str
    prompt: np.ndarray  # 1-D int32
    max_new_tokens: int
    eos_id: Optional[int]
    capacity: int  # seq bucket covering prompt + max_new_tokens
    future: Future
    t_admit: float  # monotonic
    generated: List[int] = field(default_factory=list)
    k: Optional[np.ndarray] = None  # (L, C, H, Dh) bf16, host
    v: Optional[np.ndarray] = None
    length: int = 0  # tokens written into the cache
    t_last: Optional[float] = None  # last emit (inter-token latency)


class LMServingReplica:
    """Continuous-batching LM decode engine over one exported transformer.

    Lifecycle mirrors :class:`~edl_tpu.serving.worker.ServingReplica`:
    ``start()`` loads the artifact, AOT-compiles every (batch bucket,
    seq bucket) executable for BOTH phases, then starts the engine thread
    and optional HTTP frontend. ``submit()`` admits one stream (or raises
    the typed rejection) and returns a handle; ``evict_streams()`` hands
    live streams to the router for zero-drop migration; ``stop()`` drains.
    """

    def __init__(self, config: LMServingConfig,
                 client: Optional[Any] = None,
                 registry: Optional[MetricsRegistry] = None,
                 tracer: Optional[Tracer] = None):
        self.config = config
        self.client = client  # coordinator KV surface (status publication)
        self.instruments = LMServeInstruments(registry)
        self.registry = registry
        self.tracer = tracer if tracer is not None else get_tracer()
        self.pool: Optional[BlockPool] = None  # built in start()
        self._lock = threading.Lock()
        self._waiting: List[_Stream] = []
        self._active: Dict[str, _Stream] = {}
        self._counter = 0
        self._completed = 0
        self._rejected = 0
        self._evicted = 0
        self._tokens_generated = 0
        self._emit_times: deque = deque(maxlen=8192)  # monotonic stamps
        self._last_publish = 0.0
        # set once in start() before the engine thread exists
        self._art = None
        self._model_cfg = None
        self._version: Optional[Tuple] = None
        self._jit_prefill = None
        self._jit_decode = None
        self._prefill_execs: Dict[Tuple[int, int], Any] = {}
        self._decode_execs: Dict[Tuple[int, int], Any] = {}
        self._stop = threading.Event()
        self._work = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._server = None
        self._started = False

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> "LMServingReplica":
        if self._started:
            return self
        from edl_tpu.models.transformer import (lm_cache_bytes_per_token,
                                                make_decode_step,
                                                make_prefill_step)
        from edl_tpu.runtime.export import (artifact_version,
                                            load_inference_model)
        import jax

        cfg = self.config
        art = load_inference_model(cfg.model_dir)
        mcfg = getattr(art.model, "config", None)
        if mcfg is None or not hasattr(mcfg, "n_layers"):
            raise TypeError(
                f"model {art.model.name!r} carries no transformer config — "
                f"the LM serving path needs a transformer artifact"
            )
        if cfg.seq_buckets[-1] > mcfg.seq_len:
            raise ValueError(
                f"largest seq bucket {cfg.seq_buckets[-1]} exceeds the "
                f"model's trained seq_len {mcfg.seq_len}"
            )
        pool = BlockPool(KVCacheConfig(
            n_blocks=cfg.kv_blocks, block_tokens=cfg.kv_block_tokens,
            bytes_per_token=lm_cache_bytes_per_token(mcfg),
        ))
        with self._lock:
            self._jit_prefill = jax.jit(make_prefill_step(mcfg))
            self._jit_decode = jax.jit(make_decode_step(mcfg))
            self._art = art
            self._model_cfg = mcfg
            self._version = artifact_version(cfg.model_dir)
            self.pool = pool
        self._compile_all(art)
        self._register()
        thread = threading.Thread(target=self._engine_loop,
                                  name=f"edl-lm-engine-{cfg.name}",
                                  daemon=True)
        with self._lock:
            self._thread = thread
        thread.start()
        if cfg.port is not None:
            from edl_tpu.serving.frontend import make_frontend

            server = make_frontend(self, port=cfg.port,
                                   registry=self.registry,
                                   tracer=self.tracer)
            with self._lock:
                self._server = server
        with self._lock:
            self._started = True
        return self

    def stop(self, drain: bool = True) -> None:
        """Shut down; with ``drain`` every admitted stream decodes to its
        natural finish first (the zero-drop half of a pool-size change —
        the router uses :meth:`evict_streams` when finishing elsewhere is
        the better trade)."""
        if not drain:
            error = RuntimeError("replica stopping")
            for s in self._take_all_streams():
                self.pool.release(s.id)
                self.instruments.streams.inc(outcome="error")
                s.future.set_exception(error)
        self._stop.set()
        self._work.set()
        with self._lock:
            thread, self._thread = self._thread, None
            server, self._server = self._server, None
        if thread is not None:
            thread.join(timeout=60)
        if server is not None:
            server.stop()
        self._publish_status(force=True)
        with self._lock:
            self._started = False

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    @property
    def url(self) -> Optional[str]:
        return self._server.url if self._server is not None else None

    @property
    def started(self) -> bool:
        with self._lock:
            return self._started

    # -- admission -------------------------------------------------------------

    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               eos_id: Optional[int] = None,
               stream_id: Optional[str] = None) -> LMStreamHandle:
        """Admit one stream or raise the typed rejection.

        Raises :class:`~edl_tpu.serving.batcher.SeqTooLongError` when
        ``prompt + max_new_tokens`` outruns the largest seq bucket (400 —
        retrying cannot help) and
        :class:`~edl_tpu.serving.kvcache.KVCacheExhaustedError` when the
        block pool cannot cover the budget (429 — retry elsewhere/later).
        Admitted streams join the decode batch at the next step boundary.
        """
        if not self.started:
            raise RuntimeError("replica not started")
        if self._stop.is_set():
            raise RuntimeError("replica stopping")
        ids = np.asarray(prompt, dtype=np.int32).reshape(-1)
        if ids.size == 0:
            raise ValueError("prompt must contain at least one token")
        budget = int(max_new_tokens if max_new_tokens is not None
                     else self.config.default_max_new_tokens)
        if budget <= 0:
            raise ValueError(f"max_new_tokens must be positive: {budget}")
        total = int(ids.size) + budget
        try:
            capacity = pick_seq_bucket(total, self.config.seq_buckets)
        except ValueError:
            with self._lock:
                self._rejected += 1
            self.instruments.streams.inc(outcome="rejected")
            raise
        with self._lock:
            self._counter += 1
            sid = stream_id or f"{self.config.name}-s{self._counter}"
        try:
            self.pool.reserve(sid, total, capacity=capacity)
        except Exception:
            with self._lock:
                self._rejected += 1
            self.instruments.streams.inc(outcome="rejected")
            raise
        stream = _Stream(
            id=sid, prompt=ids, max_new_tokens=budget,
            eos_id=eos_id if eos_id is not None else self.config.eos_id,
            capacity=capacity, future=Future(), t_admit=time.monotonic(),
        )
        with self._lock:
            self._waiting.append(stream)
            self.instruments.waiting_streams.set(float(len(self._waiting)))
        self._work.set()
        return LMStreamHandle(stream_id=sid, future=stream.future)

    def generate(self, prompt, max_new_tokens: Optional[int] = None,
                 eos_id: Optional[int] = None) -> Dict[str, Any]:
        """Blocking convenience wrapper over :meth:`submit`."""
        return self.submit(prompt, max_new_tokens, eos_id).result(
            timeout=self.config.request_timeout_s
        )

    # -- AOT compilation -------------------------------------------------------

    def _compile_all(self, art) -> None:
        """AOT-compile prefill and decode for every (batch bucket, seq
        bucket), concurrently, all done before the first request. The
        ``Compiled`` objects are dispatched directly — same empty-dispatch-
        cache contract as ``ServingReplica._compile_buckets``."""
        import jax
        import jax.numpy as jnp

        mcfg = self._model_cfg
        L, H, Dh = mcfg.n_layers, mcfg.n_heads, mcfg.head_dim
        param_avals = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(
                x.shape, x.dtype,
                sharding=x.sharding if getattr(x, "_committed", False) else None,
            ),
            art.params,
        )

        def compile_one(job):
            phase, b, s = job
            t0 = time.perf_counter()
            i32 = jnp.int32
            try:
                if phase == "prefill":
                    compiled = self._jit_prefill.lower(
                        param_avals,
                        jax.ShapeDtypeStruct((b, s), i32),
                        jax.ShapeDtypeStruct((b,), i32),
                    ).compile()
                else:
                    cache = jax.ShapeDtypeStruct((L, b, s, H, Dh),
                                                 jnp.bfloat16)
                    compiled = self._jit_decode.lower(
                        param_avals, cache, cache,
                        jax.ShapeDtypeStruct((b,), i32),
                        jax.ShapeDtypeStruct((b,), i32),
                    ).compile()
            except Exception as exc:
                raise ServeCompileError(
                    f"LM {phase} executable (bucket {b}, seq {s}) failed "
                    f"to AOT-compile: {exc}"
                ) from exc
            self.instruments.compile_seconds.set(
                time.perf_counter() - t0, phase=phase,
                bucket=str(b), seq_bucket=str(s),
            )
            return (phase, b, s), compiled

        jobs = [(phase, b, s)
                for phase in ("prefill", "decode")
                for b in self.config.batch_buckets
                for s in self.config.seq_buckets]
        with ThreadPoolExecutor(
            max_workers=min(8, len(jobs)),
            thread_name_prefix=f"edl-lm-compile-{self.config.name}",
        ) as pool:
            compiled_all = list(pool.map(compile_one, jobs))
        with self._lock:
            for (phase, b, s), compiled in compiled_all:
                if phase == "prefill":
                    self._prefill_execs[(b, s)] = compiled
                else:
                    self._decode_execs[(b, s)] = compiled

    def jit_cache_size(self) -> Optional[int]:
        """Compiled-program count across BOTH phase jits' dispatch caches
        (None when the probe is unavailable). Stays 0 under LM traffic:
        prefill and decode only ever dispatch pre-compiled executables."""
        return probe_jit_cache(self._jit_prefill, self._jit_decode)

    # -- the engine loop -------------------------------------------------------

    def _engine_loop(self) -> None:
        while True:
            worked = False
            try:
                worked |= self._prefill_waiting()
                worked |= self._decode_once()
            except Exception:  # edl: noqa[EDL005] logged loudly; a poisoned batch must not kill the engine — affected stream futures already carry the error
                log.exception("LM engine step failed")
            self._publish_status()
            with self._lock:
                idle = not self._waiting and not self._active
            if self._stop.is_set() and idle:
                return
            if not worked:
                self._work.wait(self.config.idle_wait_s)
                self._work.clear()

    def _take_all_streams(self) -> List[_Stream]:
        with self._lock:
            streams = self._waiting + list(self._active.values())
            self._waiting = []
            self._active = {}
            self.instruments.waiting_streams.set(0.0)
            self.instruments.active_streams.set(0.0)
        return streams

    def _claim_waiting(self, chunk: List[_Stream]) -> List[_Stream]:
        """Atomically remove ``chunk``'s still-waiting streams from the
        queue and return them; streams an eviction already took are not
        ours to resolve."""
        with self._lock:
            waiting_ids = {w.id for w in self._waiting}
            owned = [s for s in chunk if s.id in waiting_ids]
            done_ids = {s.id for s in owned}
            self._waiting = [w for w in self._waiting if w.id not in done_ids]
            self.instruments.waiting_streams.set(float(len(self._waiting)))
        return owned

    def _chunked(self, streams: List[_Stream]) -> List[List[_Stream]]:
        """Split a same-seq-bucket group along the batch ladder."""
        out, i = [], 0
        for size in plan_chunks(len(streams), self.config.batch_buckets):
            out.append(streams[i:i + size])
            i += size
        return out

    # -- prefill phase ---------------------------------------------------------

    def _prefill_waiting(self) -> bool:
        # Streams STAY in _waiting until their chunk's post-dispatch commit:
        # an evict_streams() racing with the prefill dispatch must still see
        # them (the commit below re-checks membership, mirroring decode).
        with self._lock:
            waiting = list(self._waiting)
        if not waiting:
            return False
        groups: Dict[int, List[_Stream]] = {}
        for s in waiting:
            # prompts bucket by their own length, not the stream capacity:
            # prefill compute scales with the prompt bucket, and the K/V it
            # returns is copied into the capacity-sized stream cache.
            groups.setdefault(
                pick_seq_bucket(int(s.prompt.size), self.config.seq_buckets),
                [],
            ).append(s)
        for seq_bucket in sorted(groups):
            for chunk in self._chunked(groups[seq_bucket]):
                self._prefill_chunk(chunk, seq_bucket)
        return True

    def _prefill_chunk(self, chunk: List[_Stream], seq_bucket: int) -> None:
        import jax

        n = len(chunk)
        bucket = pick_bucket(n, self.config.batch_buckets)
        tokens, lengths = pad_token_rows(
            [s.prompt for s in chunk], bucket, seq_bucket
        )
        t0 = time.time()
        try:
            with self._lock:
                params = self._art.params
                compiled = self._prefill_execs[(bucket, seq_bucket)]
            next_tokens, k_cache, v_cache = jax.device_get(
                compiled(params, tokens, lengths)
            )
        except Exception as e:  # edl: noqa[EDL005] resolved into every stream future below — the error reaches each caller; the engine must survive one poisoned prefill
            log.exception("prefill of %d (bucket %d, seq %d) failed",
                          n, bucket, seq_bucket)
            owned = self._claim_waiting(chunk)
            for s in owned:
                self.pool.release(s.id)
                self.instruments.streams.inc(outcome="error")
                s.future.set_exception(e)
            return
        self.instruments.prefill_batch.observe(float(n))
        L, H, Dh = k_cache.shape[0], k_cache.shape[3], k_cache.shape[4]
        finished: List[Tuple[_Stream, str]] = []
        owned: List[_Stream] = []
        with self._lock:
            waiting_ids = {w.id for w in self._waiting}
            for i, s in enumerate(chunk):
                if s.id not in waiting_ids:
                    continue  # evicted mid-prefill: the router owns it now
                owned.append(s)
                plen = int(s.prompt.size)
                s.k = np.zeros((L, s.capacity, H, Dh), dtype=k_cache.dtype)
                s.v = np.zeros_like(s.k)
                s.k[:, :plen] = k_cache[:, i, :plen]
                s.v[:, :plen] = v_cache[:, i, :plen]
                s.length = plen
                outcome = self._emit_locked(s, int(next_tokens[i]), "prefill")
                if outcome:
                    finished.append((s, outcome))
                else:
                    self._active[s.id] = s
            done_ids = {s.id for s in owned}
            self._waiting = [w for w in self._waiting if w.id not in done_ids]
            self.instruments.waiting_streams.set(float(len(self._waiting)))
            self.instruments.active_streams.set(float(len(self._active)))
        for s in owned:
            self.pool.note_tokens(s.id, s.length)
            self.tracer.record("lm_prefill", t0, time.time(),
                               component="serving", stream=s.id,
                               bucket=bucket, seq_bucket=seq_bucket)
        for s, outcome in finished:
            self._retire(s, outcome)

    # -- decode phase ----------------------------------------------------------

    def _decode_once(self) -> bool:
        with self._lock:
            groups: Dict[int, List[_Stream]] = {}
            for s in self._active.values():
                groups.setdefault(s.capacity, []).append(s)
        if not groups:
            return False
        for capacity in sorted(groups):
            for chunk in self._chunked(groups[capacity]):
                self._decode_chunk(chunk, capacity)
        return True

    def _decode_chunk(self, chunk: List[_Stream], capacity: int) -> None:
        import jax

        n = len(chunk)
        bucket = pick_bucket(n, self.config.batch_buckets)
        L, C, H, Dh = chunk[0].k.shape[0], capacity, *chunk[0].k.shape[2:]
        k_batch = np.zeros((L, bucket, C, H, Dh), dtype=chunk[0].k.dtype)
        v_batch = np.zeros_like(k_batch)
        tokens = np.zeros((bucket,), dtype=np.int32)
        lengths = np.zeros((bucket,), dtype=np.int32)
        for i, s in enumerate(chunk):
            k_batch[:, i] = s.k
            v_batch[:, i] = s.v
            tokens[i] = s.generated[-1]
            lengths[i] = s.length
        t0 = time.time()
        try:
            with self._lock:
                params = self._art.params
                compiled = self._decode_execs[(bucket, capacity)]
            next_tokens, k_new, v_new = jax.device_get(
                compiled(params, k_batch, v_batch, tokens, lengths)
            )
        except Exception as e:  # edl: noqa[EDL005] resolved into every stream future below — the error reaches each caller; the engine must survive one poisoned decode step
            log.exception("decode step of %d (bucket %d, seq %d) failed",
                          n, bucket, capacity)
            with self._lock:
                owned = [s for s in chunk if s.id in self._active]
                for s in owned:
                    del self._active[s.id]
                self.instruments.active_streams.set(float(len(self._active)))
            for s in owned:
                self.pool.release(s.id)
                self.instruments.streams.inc(outcome="error")
                s.future.set_exception(e)
            return
        self.instruments.decode_batch.observe(float(n))
        self.instruments.decode_steps.inc(bucket=str(bucket),
                                          seq_bucket=str(capacity))
        finished: List[Tuple[_Stream, str]] = []
        with self._lock:
            for i, s in enumerate(chunk):
                if s.id not in self._active:
                    continue  # evicted mid-step: the router owns it now
                s.k[:, s.length] = k_new[:, i]
                s.v[:, s.length] = v_new[:, i]
                s.length += 1
                outcome = self._emit_locked(s, int(next_tokens[i]), "decode")
                if outcome:
                    finished.append((s, outcome))
                    del self._active[s.id]
            self.instruments.active_streams.set(float(len(self._active)))
        for s in chunk:
            self.pool.note_tokens(s.id, s.length)
        self.tracer.record("lm_decode_step", t0, time.time(),
                           component="serving", batch_size=n,
                           bucket=bucket, seq_bucket=capacity)
        for s, outcome in finished:
            self._retire(s, outcome)

    # -- stream lifecycle ------------------------------------------------------

    def _emit_locked(self, s: _Stream, token: int,
                     phase: str) -> Optional[str]:
        """Record one emitted token (caller holds ``self._lock``); returns
        the finish outcome when this token ends the stream, else None."""
        now = time.monotonic()
        if s.t_last is None:
            self.instruments.ttft.observe(now - s.t_admit)
        self.instruments.token_latency.observe(
            now - (s.t_last if s.t_last is not None else s.t_admit)
        )
        self.instruments.tokens.inc(phase=phase)
        s.generated.append(token)
        s.t_last = now
        self._tokens_generated += 1
        self._emit_times.append(now)
        if s.eos_id is not None and token == s.eos_id:
            return "eos"
        if len(s.generated) >= s.max_new_tokens:
            return "length"
        return None

    def _retire(self, s: _Stream, outcome: str) -> None:
        self.pool.release(s.id)
        with self._lock:
            self._completed += 1
            model_step = self._art.step
        self.instruments.streams.inc(outcome=outcome)
        s.future.set_result({
            "stream_id": s.id,
            "tokens": list(s.generated),
            "finish_reason": outcome,
            "prompt_tokens": int(s.prompt.size),
            "model_step": model_step,
        })

    def evict_streams(self) -> List[Dict[str, Any]]:
        """Detach every live stream for migration: blocks are released,
        futures are NOT resolved — the router resubmits each stream's
        remainder elsewhere and stitches the token lists, which is how a
        shrinking pool keeps ``dropped_streams == 0``. Returns one
        snapshot per stream: prompt, generated-so-far, remaining budget,
        eos id, and the unresolved future to fulfil."""
        streams = self._take_all_streams()
        snapshots = []
        for s in streams:
            self.pool.release(s.id)
            self.instruments.streams.inc(outcome="evicted")
            with self._lock:
                self._evicted += 1
            snapshots.append({
                "stream_id": s.id,
                "prompt": s.prompt,
                "generated": list(s.generated),
                "max_new_tokens": s.max_new_tokens - len(s.generated),
                "eos_id": s.eos_id,
                "future": s.future,
            })
        return snapshots

    # -- status ----------------------------------------------------------------

    def tokens_per_s(self, window_s: float = 2.0) -> float:
        """Decode throughput over the trailing window (0 when idle)."""
        now = time.monotonic()
        with self._lock:
            recent = sum(1 for t in self._emit_times if now - t <= window_s)
        return recent / window_s

    def status(self) -> Dict[str, Any]:
        """The replica's LM-serving snapshot: what `edl-tpu status`
        renders and the router's affinity policy reads (kv.free_blocks)."""
        kv = self.pool.stats() if self.pool is not None else {}
        rate = self.tokens_per_s()
        with self._lock:
            return {
                "name": self.config.name,
                "kind": "lm",
                "model_step": self._art.step if self._art else None,
                "version": self._version[2] if self._version else None,
                "active_streams": len(self._active),
                "waiting_streams": len(self._waiting),
                "completed": self._completed,
                "rejected": self._rejected,
                "evicted": self._evicted,
                "tokens_generated": self._tokens_generated,
                "tokens_per_s": round(rate, 2),
                "batch_buckets": list(self.config.batch_buckets),
                "seq_buckets": list(self.config.seq_buckets),
                "kv": kv,
            }

    def _health(self) -> Dict[str, Any]:
        return self.status()

    def _register(self) -> None:
        if self.client is None:
            return
        try:
            self.client.register(takeover=True)
        except Exception:  # edl: noqa[EDL005] status publication is best-effort observability; serving must come up even with the coordinator down
            log.warning("coordinator register failed; status publication "
                        "will retry", exc_info=True)

    def _publish_status(self, force: bool = False) -> None:
        stats = self.pool.stats() if self.pool is not None else None
        if stats is not None:
            self.instruments.kv_blocks_used.set(float(stats["used_blocks"]))
            self.instruments.kv_blocks_free.set(float(stats["free_blocks"]))
            self.instruments.kv_occupancy.set(float(stats["occupancy"]))
            self.instruments.kv_fragmentation.set(
                float(stats["fragmentation"])
            )
        if self.client is None:
            return
        now = time.monotonic()
        with self._lock:
            if (not force and
                    now - self._last_publish < self.config.publish_interval_s):
                return
            self._last_publish = now
        try:
            self.client.heartbeat()
            self.client.kv_put(SERVING_KV_PREFIX + self.config.name,
                               json.dumps(self.status()))
        except Exception:  # edl: noqa[EDL005] best-effort: a coordinator blip must not take the decode loop down with it; the next publish interval retries
            log.debug("LM serving status publish failed", exc_info=True)
