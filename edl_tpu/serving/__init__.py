"""Elastic inference serving tier: the second half of the production loop.

The reference EDL's pitch is one cluster whose capacity flows to wherever
the load is — but training is only half of that story. This package
serves what `runtime/export.py` publishes:

- :mod:`edl_tpu.serving.batcher` — the pure bucket-ladder math under
  continuous batching (pick/pad/split, numpy-only), on TWO axes: batch
  slots, and — for LM traffic — sequence-length capacity.
- :mod:`edl_tpu.serving.worker` — :class:`ServingReplica`: AOT-compiles
  one predict executable per batch bucket before the first request (the
  PR 2 warm-compile contract — the jit dispatch cache stays empty), runs
  the continuous-batching dispatch loop, and hot-swaps model versions
  behind the exporter's atomic ``LATEST`` pointer with zero dropped
  requests.
- :mod:`edl_tpu.serving.lm` — :class:`LMServingReplica`: the LM-native
  sibling. Decode-step continuous batching (batch membership changes per
  token), prefill/decode phase separation (both phases AOT per (batch
  bucket, seq bucket)), and paged-KV admission.
- :mod:`edl_tpu.serving.kvcache` — :class:`BlockPool`: the paged
  KV-cache block allocator; memory, not batch slots, is the LM tier's
  admission currency.
- :mod:`edl_tpu.serving.router` — :class:`Router`: health/affinity
  routing over a mutable replica pool, with zero-drop stream migration
  when the pool shrinks mid-decode.
- :mod:`edl_tpu.serving.frontend` — ``POST /predict`` + ``POST
  /generate`` + the obs surface (`/metrics`, `/healthz`, `/spans`) on one
  stdlib HTTP port.
- :mod:`edl_tpu.serving.autoscale` — the SLO signals the controller
  autoscaler scales serving replicas on: request-latency p99 + queue
  depth for the batch tier, per-token p99 + KV occupancy for the LM tier.

``python -m edl_tpu.serving`` is the serve-smoke deploy gate (add ``lm``
for the LM tier): export an artifact, boot a replica, push traffic
through the real HTTP frontend, scrape `/metrics`, and assert the
metric families and the empty-dispatch-cache AOT contract. See
doc/serving.md.
"""

from edl_tpu.serving.autoscale import (
    LMServeSignal,
    LMServingSLO,
    ServeSignal,
    ServingSLO,
    aggregate_lm_signals,
    aggregate_signals,
    desired_lm_replica_delta,
    desired_replica_delta,
    histogram_quantile,
    scrape_lm_signal,
    scrape_serve_signal,
)
from edl_tpu.serving.batcher import (
    SeqTooLongError,
    pad_batch,
    pad_token_rows,
    pick_bucket,
    pick_seq_bucket,
    plan_chunks,
    split_rows,
    validate_buckets,
)
from edl_tpu.serving.frontend import ServeRequestHandler, make_frontend
from edl_tpu.serving.kvcache import (
    BlockPool,
    KVCacheConfig,
    KVCacheExhaustedError,
)
from edl_tpu.serving.lm import LMServingConfig, LMServingReplica, LMStreamHandle
from edl_tpu.serving.router import NoReplicaError, Router
from edl_tpu.serving.worker import (
    SERVING_KV_PREFIX,
    ServeCompileError,
    ServeOverloadError,
    ServingConfig,
    ServingReplica,
    probe_jit_cache,
)

__all__ = [
    "BlockPool",
    "KVCacheConfig",
    "KVCacheExhaustedError",
    "LMServeSignal",
    "LMServingConfig",
    "LMServingReplica",
    "LMServingSLO",
    "LMStreamHandle",
    "NoReplicaError",
    "Router",
    "SERVING_KV_PREFIX",
    "SeqTooLongError",
    "ServeCompileError",
    "ServeOverloadError",
    "ServeRequestHandler",
    "ServeSignal",
    "ServingConfig",
    "ServingReplica",
    "ServingSLO",
    "aggregate_lm_signals",
    "aggregate_signals",
    "desired_lm_replica_delta",
    "desired_replica_delta",
    "histogram_quantile",
    "make_frontend",
    "pad_batch",
    "pad_token_rows",
    "pick_bucket",
    "pick_seq_bucket",
    "plan_chunks",
    "probe_jit_cache",
    "scrape_lm_signal",
    "scrape_serve_signal",
    "split_rows",
    "validate_buckets",
]
