"""Elastic inference serving tier: the second half of the production loop.

The reference EDL's pitch is one cluster whose capacity flows to wherever
the load is — but training is only half of that story. This package
serves what `runtime/export.py` publishes:

- :mod:`edl_tpu.serving.batcher` — the pure bucket-ladder math under
  continuous batching (pick/pad/split, numpy-only).
- :mod:`edl_tpu.serving.worker` — :class:`ServingReplica`: AOT-compiles
  one predict executable per batch bucket before the first request (the
  PR 2 warm-compile contract — the jit dispatch cache stays empty), runs
  the continuous-batching dispatch loop, and hot-swaps model versions
  behind the exporter's atomic ``LATEST`` pointer with zero dropped
  requests.
- :mod:`edl_tpu.serving.frontend` — ``POST /predict`` + the obs surface
  (`/metrics`, `/healthz`, `/spans`) on one stdlib HTTP port.
- :mod:`edl_tpu.serving.autoscale` — the SLO signal (p99 from scraped
  histogram buckets, queue depth) the controller autoscaler scales
  serving replicas on, instead of cluster utilization.

``python -m edl_tpu.serving`` is the serve-smoke deploy gate: export an
artifact, boot a replica, push requests through the real HTTP frontend,
scrape `/metrics`, and assert the latency/queue families and the
empty-dispatch-cache AOT contract. See doc/serving.md.
"""

from edl_tpu.serving.autoscale import (
    ServeSignal,
    ServingSLO,
    aggregate_signals,
    desired_replica_delta,
    histogram_quantile,
    scrape_serve_signal,
)
from edl_tpu.serving.batcher import (
    pad_batch,
    pick_bucket,
    plan_chunks,
    split_rows,
    validate_buckets,
)
from edl_tpu.serving.frontend import ServeRequestHandler, make_frontend
from edl_tpu.serving.worker import (
    SERVING_KV_PREFIX,
    ServeCompileError,
    ServeOverloadError,
    ServingConfig,
    ServingReplica,
)

__all__ = [
    "SERVING_KV_PREFIX",
    "ServeCompileError",
    "ServeOverloadError",
    "ServeRequestHandler",
    "ServeSignal",
    "ServingConfig",
    "ServingReplica",
    "ServingSLO",
    "aggregate_signals",
    "desired_replica_delta",
    "histogram_quantile",
    "make_frontend",
    "pad_batch",
    "pick_bucket",
    "plan_chunks",
    "scrape_serve_signal",
    "split_rows",
    "validate_buckets",
]
