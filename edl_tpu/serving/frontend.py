"""HTTP frontend for a serving replica: ``POST /predict`` (batch
replicas), ``POST /generate`` (LM replicas), plus the full obs surface
(`/metrics`, `/healthz`, `/spans`) on one port.

Extends the obs plane's request handler rather than growing a web
framework: the serving endpoints are one ``do_POST`` on top of the same
`ThreadingHTTPServer` every worker already runs for scrapes, so one port
per replica serves both traffic and telemetry — exactly what the
autoscaler needs (it scrapes the same address it routes to).

Request wire format (JSON):

    {"features": {"x": [[...13 floats...]]}}        -> one request row
    {"features": [{...}, {...}]}                    -> N independent rows

    {"prompt": [1, 5, 9], "max_new_tokens": 16,     -> one LM stream
     "eos_id": 2}                                      (only prompt req'd)

Each row/stream is submitted to the replica's continuous-batching engine
separately — the server-side batcher, not the client, decides batch
composition (that is the entire point of continuous batching). LM
admission errors map to the HTTP contract: a prompt+budget the seq-bucket
ladder can never hold is 400 (retrying cannot help), an exhausted KV
block pool is 429 (retry elsewhere or later).
"""

from __future__ import annotations

import json
from typing import Optional

from edl_tpu.obs.http import MetricsServer, ObsRequestHandler
from edl_tpu.obs.metrics import MetricsRegistry
from edl_tpu.obs.tracing import Tracer

__all__ = ["ServeRequestHandler", "make_frontend"]


def _to_jsonable(row):
    import numpy as np

    if hasattr(row, "tolist"):
        return row.tolist()
    if isinstance(row, dict):
        return {k: _to_jsonable(v) for k, v in row.items()}
    if isinstance(row, (list, tuple)):
        return [_to_jsonable(v) for v in row]
    if isinstance(row, (np.floating, np.integer)):
        return row.item()
    return row


class ServeRequestHandler(ObsRequestHandler):
    server_version = "edl-serve/1"

    replica = None  # type: ignore[assignment]  # set via handler_attrs

    def do_POST(self) -> None:  # noqa: N802 (stdlib handler contract)
        from edl_tpu.serving.worker import ServeOverloadError

        path = self.path.split("?", 1)[0]
        if path not in ("/predict", "/generate"):
            self.send_error(404, "try POST /predict or /generate")
            return
        try:
            length = int(self.headers.get("Content-Length") or 0)
            payload = json.loads(self.rfile.read(length) or b"{}")
        except (ValueError, TypeError):
            self.send_error(400, "body must be JSON")
            return
        if path == "/generate":
            self._handle_generate(payload)
            return
        features = payload.get("features")
        if features is None:
            self.send_error(400, 'missing "features"')
            return
        rows = features if isinstance(features, list) else [features]
        replica = self.replica
        try:
            futures = [replica.submit(row) for row in rows]
            outputs = [f.result(timeout=replica.config.request_timeout_s)
                       for f in futures]
        except ServeOverloadError as e:
            self.send_error(429, str(e))
            return
        except (KeyError, ValueError, TypeError) as e:
            self.send_error(400, f"bad request: {e}")
            return
        except Exception as e:  # edl: noqa[EDL005] surfaced to the caller as HTTP 500 — a failed batch fails the request loudly instead of killing the server thread
            self.send_error(500, f"prediction failed: {type(e).__name__}: {e}")
            return
        status = replica.status()
        body = {
            "outputs": [_to_jsonable(row) for row in outputs],
            "model_step": status["model_step"],
            "version": status["version"],
        }
        if not isinstance(features, list):
            body["outputs"] = body["outputs"][0]
        self._reply(json.dumps(body).encode(), "application/json")

    def _handle_generate(self, payload) -> None:
        from edl_tpu.serving.batcher import SeqTooLongError
        from edl_tpu.serving.kvcache import KVCacheExhaustedError

        replica = self.replica
        if not hasattr(replica, "generate"):
            self.send_error(404, "this replica serves /predict, not LM "
                                 "generation")
            return
        prompt = payload.get("prompt")
        if not isinstance(prompt, list) or not prompt:
            self.send_error(400, '"prompt" must be a non-empty token-id list')
            return
        try:
            result = replica.generate(
                prompt,
                max_new_tokens=payload.get("max_new_tokens"),
                eos_id=payload.get("eos_id"),
            )
        except KVCacheExhaustedError as e:
            self.send_error(429, str(e))
            return
        except SeqTooLongError as e:
            self.send_error(400, str(e))
            return
        except (KeyError, ValueError, TypeError) as e:
            self.send_error(400, f"bad request: {e}")
            return
        except Exception as e:  # edl: noqa[EDL005] surfaced to the caller as HTTP 500 — a failed stream fails the request loudly instead of killing the server thread
            self.send_error(500, f"generation failed: {type(e).__name__}: {e}")
            return
        self._reply(json.dumps(result).encode(), "application/json")


def make_frontend(replica, port: int = 0,
                  registry: Optional[MetricsRegistry] = None,
                  tracer: Optional[Tracer] = None) -> MetricsServer:
    """Start the replica's HTTP frontend: `/predict` + obs endpoints."""
    server = MetricsServer(
        registry=registry, tracer=tracer, port=port,
        health=replica._health,
        handler_cls=ServeRequestHandler,
        handler_attrs={"replica": replica},
    )
    return server.start()
