"""Bucketed continuous batching: the pure math under the serving frontend.

A jitted predict executable is specialized to one batch shape, so a
frontend that forwards whatever batch size arrived retraces per shape
(the exact bug `InferenceModel.predict` counts as retraces). The classic
fix — XLA serving, batching on TPU pods — is a small ladder of fixed
bucket sizes: coalesce queued requests, pad up to the smallest bucket
that fits, and dispatch an executable compiled once per bucket. This
module holds the ladder math and the pad/split plumbing; it is numpy-pure
(no jax imports at module scope, no threads) so every edge case is
unit-testable in microseconds.

The LM tier adds a SECOND bucket axis: sequence length. A decode or
prefill executable is specialized to (batch slots, token capacity), so
autoregressive requests bucket twice — batch slot count by the ladder
above, token capacity by :func:`pick_seq_bucket`. Unlike the batch axis
(where the dispatcher chunks overflow via :func:`plan_chunks`), sequence
overflow is a hard admission error: a stream longer than the largest
seq bucket can never fit any compiled executable, so it is rejected with
the typed :class:`SeqTooLongError` before any memory is allocated.
"""

from __future__ import annotations

import numpy as np
from typing import Dict, List, Sequence, Tuple

__all__ = ["pick_bucket", "plan_chunks", "pad_batch", "split_rows",
           "validate_buckets", "pick_seq_bucket", "pad_token_rows",
           "SeqTooLongError"]


class SeqTooLongError(ValueError):
    """Request needs more token capacity than the largest seq bucket —
    no compiled (bucket, seq-bucket) executable can ever run it, so the
    admission path rejects it synchronously (HTTP 400, not 429: retrying
    the same request can never succeed)."""


def validate_buckets(buckets: Sequence[int]) -> Tuple[int, ...]:
    """Normalize a bucket ladder: positive, strictly ascending, non-empty."""
    out = tuple(int(b) for b in buckets)
    if not out:
        raise ValueError("bucket ladder must be non-empty")
    if any(b <= 0 for b in out):
        raise ValueError(f"bucket sizes must be positive: {out}")
    if any(b >= c for b, c in zip(out, out[1:])):
        raise ValueError(f"bucket ladder must be strictly ascending: {out}")
    return out


def pick_bucket(n: int, buckets: Sequence[int]) -> int:
    """Smallest bucket that fits ``n`` requests; the largest bucket when
    none does (the caller chunks first via :func:`plan_chunks`)."""
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


def plan_chunks(n: int, buckets: Sequence[int]) -> List[int]:
    """Split ``n`` queued requests into dispatchable chunk sizes: full
    largest-buckets first, remainder in the smallest bucket that fits.
    ``sum(plan_chunks(n, ...)) == n`` always — no request is left behind."""
    chunks: List[int] = []
    largest = buckets[-1]
    while n > largest:
        chunks.append(largest)
        n -= largest
    if n:
        chunks.append(n)
    return chunks


def pad_batch(
    rows: List[Dict[str, np.ndarray]],
    bucket: int,
    feature_avals: Dict[str, Tuple[Tuple[int, ...], np.dtype]],
) -> Dict[str, np.ndarray]:
    """Stack per-request feature rows and zero-pad to ``bucket`` slots.

    ``rows`` are single-example dicts (no batch dim); ``feature_avals``
    maps key -> (per-example shape, dtype) and is the authority for both —
    a row missing a key or shaped differently raises rather than padding
    garbage into the model.
    """
    if len(rows) > bucket:
        raise ValueError(f"{len(rows)} rows exceed bucket {bucket}")
    out: Dict[str, np.ndarray] = {}
    for key, (shape, dtype) in feature_avals.items():
        shape = tuple(shape)
        try:
            # Fast path (the per-batch hot loop): submit() already coerced
            # every row, so one stack + one zero-filled tail covers the
            # whole bucket without a per-row Python loop.
            stacked = np.stack([row[key] for row in rows]).astype(
                dtype, copy=False
            )
            if stacked.shape != (len(rows),) + shape:
                raise ValueError  # shape drift: diagnose per row below
            arr = np.zeros((bucket,) + shape, dtype=dtype)
            arr[: len(rows)] = stacked
        except (KeyError, ValueError, TypeError):
            # Slow path only on mismatch: re-walk row by row to raise the
            # error that names the offending request and feature.
            arr = np.zeros((bucket,) + shape, dtype=dtype)
            for i, row in enumerate(rows):
                if key not in row:
                    raise KeyError(f"request {i} missing feature {key!r}")
                value = np.asarray(row[key], dtype=dtype)
                if value.shape != shape:
                    raise ValueError(
                        f"feature {key!r} of request {i} has shape "
                        f"{value.shape}, expected {shape}"
                    )
                arr[i] = value
        out[key] = arr
    return out


def split_rows(outputs, n: int) -> List:
    """The first ``n`` rows of a (possibly pytree) batched output, one
    entry per real request — the padded tail rows are dropped.

    One device-to-host transfer for the whole tree, then host-side row
    slicing: this sits on the per-batch hot path, and a per-row tree_map
    over device arrays costs one transfer per (row, leaf) instead."""
    import jax

    host = jax.device_get(outputs)
    return [jax.tree_util.tree_map(lambda a: a[i], host) for i in range(n)]


# -- the sequence-length bucket axis (LM serving) ------------------------------


def pick_seq_bucket(tokens: int, seq_buckets: Sequence[int]) -> int:
    """Smallest seq bucket with capacity for ``tokens``; raises
    :class:`SeqTooLongError` when even the largest cannot hold it.

    Unlike :func:`pick_bucket` this never clamps: a batch overflow splits
    into more chunks, but a sequence cannot be split across executables —
    admission must reject what the ladder cannot carry."""
    if tokens <= 0:
        raise ValueError(f"token count must be positive, got {tokens}")
    for b in seq_buckets:
        if tokens <= b:
            return b
    raise SeqTooLongError(
        f"request needs {tokens} token slots but the largest seq bucket "
        f"is {seq_buckets[-1]}"
    )


def pad_token_rows(
    rows: List[np.ndarray], bucket: int, seq_bucket: int,
    pad_id: int = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    """(tokens, lengths) for a prefill dispatch: ``rows`` are 1-D int
    token-id arrays of varying length, right-padded with ``pad_id`` to
    ``seq_bucket`` and stacked into ``bucket`` slots (tail slots all-pad).

    Returns int32 arrays shaped (bucket, seq_bucket) and (bucket,).
    Rows longer than ``seq_bucket`` raise :class:`SeqTooLongError` — the
    caller's admission check should have bucketed them already."""
    if len(rows) > bucket:
        raise ValueError(f"{len(rows)} rows exceed bucket {bucket}")
    tokens = np.full((bucket, seq_bucket), pad_id, dtype=np.int32)
    lengths = np.zeros((bucket,), dtype=np.int32)
    for i, row in enumerate(rows):
        ids = np.asarray(row, dtype=np.int32).reshape(-1)
        if ids.size > seq_bucket:
            raise SeqTooLongError(
                f"prompt of {ids.size} tokens exceeds seq bucket {seq_bucket}"
            )
        tokens[i, : ids.size] = ids
        lengths[i] = ids.size
    return tokens, lengths
