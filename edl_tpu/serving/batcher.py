"""Bucketed continuous batching: the pure math under the serving frontend.

A jitted predict executable is specialized to one batch shape, so a
frontend that forwards whatever batch size arrived retraces per shape
(the exact bug `InferenceModel.predict` counts as retraces). The classic
fix — XLA serving, batching on TPU pods — is a small ladder of fixed
bucket sizes: coalesce queued requests, pad up to the smallest bucket
that fits, and dispatch an executable compiled once per bucket. This
module holds the ladder math and the pad/split plumbing; it is numpy-pure
(no jax, no threads) so every edge case is unit-testable in microseconds.
"""

from __future__ import annotations

import numpy as np
from typing import Dict, List, Sequence, Tuple

__all__ = ["pick_bucket", "plan_chunks", "pad_batch", "split_rows",
           "validate_buckets"]


def validate_buckets(buckets: Sequence[int]) -> Tuple[int, ...]:
    """Normalize a bucket ladder: positive, strictly ascending, non-empty."""
    out = tuple(int(b) for b in buckets)
    if not out:
        raise ValueError("bucket ladder must be non-empty")
    if any(b <= 0 for b in out):
        raise ValueError(f"bucket sizes must be positive: {out}")
    if any(b >= c for b, c in zip(out, out[1:])):
        raise ValueError(f"bucket ladder must be strictly ascending: {out}")
    return out


def pick_bucket(n: int, buckets: Sequence[int]) -> int:
    """Smallest bucket that fits ``n`` requests; the largest bucket when
    none does (the caller chunks first via :func:`plan_chunks`)."""
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


def plan_chunks(n: int, buckets: Sequence[int]) -> List[int]:
    """Split ``n`` queued requests into dispatchable chunk sizes: full
    largest-buckets first, remainder in the smallest bucket that fits.
    ``sum(plan_chunks(n, ...)) == n`` always — no request is left behind."""
    chunks: List[int] = []
    largest = buckets[-1]
    while n > largest:
        chunks.append(largest)
        n -= largest
    if n:
        chunks.append(n)
    return chunks


def pad_batch(
    rows: List[Dict[str, np.ndarray]],
    bucket: int,
    feature_avals: Dict[str, Tuple[Tuple[int, ...], np.dtype]],
) -> Dict[str, np.ndarray]:
    """Stack per-request feature rows and zero-pad to ``bucket`` slots.

    ``rows`` are single-example dicts (no batch dim); ``feature_avals``
    maps key -> (per-example shape, dtype) and is the authority for both —
    a row missing a key or shaped differently raises rather than padding
    garbage into the model.
    """
    if len(rows) > bucket:
        raise ValueError(f"{len(rows)} rows exceed bucket {bucket}")
    out: Dict[str, np.ndarray] = {}
    for key, (shape, dtype) in feature_avals.items():
        arr = np.zeros((bucket,) + tuple(shape), dtype=dtype)
        for i, row in enumerate(rows):
            if key not in row:
                raise KeyError(f"request {i} missing feature {key!r}")
            value = np.asarray(row[key], dtype=dtype)
            if value.shape != tuple(shape):
                raise ValueError(
                    f"feature {key!r} of request {i} has shape "
                    f"{value.shape}, expected {tuple(shape)}"
                )
            arr[i] = value
        out[key] = arr
    return out


def split_rows(outputs, n: int) -> List:
    """The first ``n`` rows of a (possibly pytree) batched output, one
    entry per real request — the padded tail rows are dropped."""
    import jax

    return [jax.tree_util.tree_map(lambda a: a[i], outputs)
            for i in range(n)]
