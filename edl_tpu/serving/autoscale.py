"""Serving-tier autoscaler signal: p99 latency + queue depth from scrapes.

Training jobs scale on cluster utilization (`scale_all_dry_run`'s
throughput fixed point); a serving replica's load is invisible to that
signal — its chips are "busy" whether it meets its latency SLO or not.
The serving tier instead scales on what its users feel: the p99 of
`edl_serve_request_latency_seconds` and the `edl_serve_queue_depth`
backlog, scraped from each replica's `/metrics` (the PR 7 plane — the
autoscaler consumes the same exposition text any Prometheus would).

The p99 comes from the histogram's cumulative buckets, aggregated ACROSS
replicas before the quantile is taken (an overloaded replica must not be
averaged away), with linear interpolation inside the winning bucket —
the standard histogram_quantile estimator.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = ["ServeSignal", "ServingSLO", "histogram_quantile",
           "scrape_serve_signal", "aggregate_signals", "desired_replica_delta",
           "LMServeSignal", "LMServingSLO", "scrape_lm_signal",
           "aggregate_lm_signals", "desired_lm_replica_delta"]

log = logging.getLogger("edl_tpu.serving.autoscale")

_LATENCY_FAMILY = "edl_serve_request_latency_seconds"
_QUEUE_FAMILY = "edl_serve_queue_depth"
_TOKEN_LATENCY_FAMILY = "edl_lm_token_latency_seconds"
_KV_OCCUPANCY_FAMILY = "edl_lm_kv_occupancy"


@dataclass
class ServeSignal:
    """One replica's scraped load state."""

    #: cumulative (le_upper_bound, count) pairs, +inf last
    latency_buckets: List[Tuple[float, float]]
    latency_count: float
    queue_depth: float


@dataclass
class ServingSLO:
    """The serving tier's scaling contract. Defaults target interactive
    inference: grow when p99 breaches, shrink only when comfortably under
    BOTH signals (hysteresis — the gap between grow and shrink thresholds
    is what keeps the replica count from oscillating)."""

    p99_seconds: float = 0.25
    max_queue_per_replica: float = 8.0
    #: shrink only when p99 < shrink_frac * p99_seconds ...
    shrink_frac: float = 0.3
    #: ... and queue/replica < shrink_queue_frac * max_queue_per_replica
    shrink_queue_frac: float = 0.25


def histogram_quantile(
    buckets: Sequence[Tuple[float, float]], q: float
) -> Optional[float]:
    """Quantile estimate from Prometheus-style cumulative buckets.

    ``buckets``: (upper_bound, cumulative_count), ascending, +inf last.
    Linear interpolation within the winning bucket; the +inf bucket clamps
    to the last finite bound (the estimator can't see past it). None when
    the histogram is empty.
    """
    if not buckets:
        return None
    total = buckets[-1][1]
    if total <= 0:
        return None
    rank = q * total
    prev_bound, prev_count = 0.0, 0.0
    for bound, count in buckets:
        if count >= rank:
            if bound == float("inf"):
                return prev_bound  # clamp: everything above the last finite le
            if count == prev_count:
                return bound
            frac = (rank - prev_count) / (count - prev_count)
            return prev_bound + (bound - prev_bound) * frac
        prev_bound, prev_count = bound, count
    return buckets[-1][0]


def _parse_bucket_samples(samples: Dict[str, float],
                          family: str) -> List[Tuple[float, float]]:
    out = []
    prefix = family + "_bucket{"
    for name, value in samples.items():
        if not name.startswith(prefix):
            continue
        # labelset is exactly {le="..."} for unlabelled histograms
        le = name[name.find('le="') + 4:name.rfind('"')]
        out.append((float(le), value))
    out.sort(key=lambda pair: pair[0])
    return out


def scrape_serve_signal(url: str, timeout: float = 2.0) -> Optional[ServeSignal]:
    """Scrape one replica's `/metrics` into a :class:`ServeSignal`; None
    when the replica is unreachable or not yet exporting the families
    (booting replicas don't get to veto the scaling decision)."""
    from edl_tpu.obs.http import scrape_metrics
    from edl_tpu.obs.metrics import parse_prometheus

    try:
        families = parse_prometheus(scrape_metrics(url, timeout=timeout))
    except (OSError, ValueError) as e:
        log.debug("serve scrape of %s failed: %s", url, e)
        return None
    latency = families.get(_LATENCY_FAMILY)
    queue = families.get(_QUEUE_FAMILY)
    if latency is None or queue is None:
        return None
    buckets = _parse_bucket_samples(latency["samples"], _LATENCY_FAMILY)
    count = latency["samples"].get(_LATENCY_FAMILY + "_count", 0.0)
    depth = queue["samples"].get(_QUEUE_FAMILY, 0.0)
    return ServeSignal(latency_buckets=buckets, latency_count=count,
                       queue_depth=depth)


def aggregate_signals(
    signals: Sequence[ServeSignal],
) -> Optional[Tuple[Optional[float], float]]:
    """(p99 across ALL replicas' requests, mean queue depth per replica).

    Buckets are summed across replicas before the quantile: the tier's p99
    is the p99 of the union of requests, not the mean of per-replica p99s
    (which would let one drowning replica hide behind nine idle ones)."""
    if not signals:
        return None
    summed: Dict[float, float] = {}
    for sig in signals:
        for bound, count in sig.latency_buckets:
            summed[bound] = summed.get(bound, 0.0) + count
    buckets = sorted(summed.items())
    p99 = histogram_quantile(buckets, 0.99)
    queue = sum(sig.queue_depth for sig in signals) / len(signals)
    return p99, queue


# -- the LM tier's signal ------------------------------------------------------
#
# An LM replica's user-felt load is per-TOKEN latency (a stream is hundreds
# of device steps; request latency just measures generation length), and
# its capacity ceiling is KV-cache memory, not queue slots. So the LM
# scaling signal pairs the `edl_lm_token_latency_seconds` p99 with the
# `edl_lm_kv_occupancy` gauge — and occupancy aggregates by MAX, not mean:
# streams cannot split across replicas, so one full pool rejects real
# traffic no matter how empty its neighbors are.


@dataclass
class LMServeSignal:
    """One LM replica's scraped load state."""

    #: cumulative (le_upper_bound, count) pairs, +inf last
    token_latency_buckets: List[Tuple[float, float]]
    token_count: float
    kv_occupancy: float


@dataclass
class LMServingSLO:
    """The LM tier's scaling contract: interactive decode targets ~10
    tokens/s/stream felt as <100 ms between tokens; KV headroom keeps
    admission from 429ing bursts."""

    p99_token_seconds: float = 0.1
    max_kv_occupancy: float = 0.85
    shrink_frac: float = 0.3
    shrink_occupancy_frac: float = 0.4


def scrape_lm_signal(url: str, timeout: float = 2.0) -> Optional[LMServeSignal]:
    """Scrape one LM replica's `/metrics` into an :class:`LMServeSignal`;
    None when unreachable or not yet exporting the LM families."""
    from edl_tpu.obs.http import scrape_metrics
    from edl_tpu.obs.metrics import parse_prometheus

    try:
        families = parse_prometheus(scrape_metrics(url, timeout=timeout))
    except (OSError, ValueError) as e:
        log.debug("LM serve scrape of %s failed: %s", url, e)
        return None
    latency = families.get(_TOKEN_LATENCY_FAMILY)
    occupancy = families.get(_KV_OCCUPANCY_FAMILY)
    if latency is None or occupancy is None:
        return None
    buckets = _parse_bucket_samples(latency["samples"], _TOKEN_LATENCY_FAMILY)
    count = latency["samples"].get(_TOKEN_LATENCY_FAMILY + "_count", 0.0)
    occ = occupancy["samples"].get(_KV_OCCUPANCY_FAMILY, 0.0)
    return LMServeSignal(token_latency_buckets=buckets, token_count=count,
                         kv_occupancy=occ)


def aggregate_lm_signals(
    signals: Sequence[LMServeSignal],
) -> Optional[Tuple[Optional[float], float]]:
    """(per-token p99 across ALL replicas' tokens, MAX KV occupancy)."""
    if not signals:
        return None
    summed: Dict[float, float] = {}
    for sig in signals:
        for bound, count in sig.token_latency_buckets:
            summed[bound] = summed.get(bound, 0.0) + count
    buckets = sorted(summed.items())
    p99 = histogram_quantile(buckets, 0.99)
    occupancy = max(sig.kv_occupancy for sig in signals)
    return p99, occupancy


def desired_lm_replica_delta(
    signals: Sequence[LMServeSignal],
    slo: LMServingSLO,
) -> int:
    """+1 / 0 / -1 LM replica from the aggregated signal, same hysteresis
    discipline as :func:`desired_replica_delta`. A shrink hands the
    doomed replica's streams to the router's migration path — the delta
    here only says the pool is oversized, never which streams move."""
    agg = aggregate_lm_signals(signals)
    if agg is None:
        return 0  # no scrapes landed: hold, never flap blind
    p99, occupancy = agg
    if (p99 is not None and p99 > slo.p99_token_seconds) \
            or occupancy > slo.max_kv_occupancy:
        return 1
    if (p99 is None or p99 < slo.shrink_frac * slo.p99_token_seconds) \
            and occupancy < slo.shrink_occupancy_frac * slo.max_kv_occupancy:
        return -1
    return 0


def desired_replica_delta(
    signals: Sequence[ServeSignal],
    slo: ServingSLO,
) -> int:
    """+1 / 0 / -1 replica from the aggregated SLO signal. The caller
    (controller autoscaler) clamps to [min, max] and commits through
    cluster-resource accounting — this function only reads the SLO."""
    agg = aggregate_signals(signals)
    if agg is None:
        return 0  # no scrapes landed: hold, never flap blind
    p99, queue = agg
    if (p99 is not None and p99 > slo.p99_seconds) \
            or queue > slo.max_queue_per_replica:
        return 1
    if (p99 is None or p99 < slo.shrink_frac * slo.p99_seconds) \
            and queue < slo.shrink_queue_frac * slo.max_queue_per_replica:
        return -1
    return 0
