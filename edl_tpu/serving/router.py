"""Request router: the serving tier's control-plane component.

`bench_serve.py`'s rescale arm used a round-robin stand-in; this is its
promotion to a real router. A :class:`Router` fronts a mutable pool of
replicas — fixed-shape batch replicas (:class:`ServingReplica`) and LM
replicas (:class:`LMServingReplica`) side by side — and owns the two
things a stand-in cannot:

- **Health/affinity routing fed from replica status.** Batch requests go
  to the started replica with the shallowest queue (failing over on
  overload); LM streams go to the started replica with the most free KV
  blocks that can admit the stream's full token budget — the same
  ``kv.free_blocks`` number the replicas publish to coordinator KV, read
  here directly from ``status()``.
- **Zero-drop rescale under decode.** Removing a replica mid-decode
  evicts its live streams (:meth:`LMServingReplica.evict_streams` —
  blocks released, futures unresolved), and the router resubmits each
  stream's remainder elsewhere: the accumulated tokens become a prefix,
  ``prompt + generated`` re-prefills on the target, and the caller's
  future resolves with the stitched token list and an exact accounting —
  ``len(tokens)`` is identical to the unmigrated run. ``dropped_streams``
  stays 0 unless the pool ends up with no replica that can admit.

The router is in-process control plane (it holds replica objects, not
URLs): the unit the autoscaler's desired-replica delta acts through, and
what `bench_serve.py` drives for the rescale-under-decode measurement.
"""

from __future__ import annotations

import logging
import threading
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from edl_tpu.serving.kvcache import KVCacheExhaustedError
from edl_tpu.serving.worker import ServeOverloadError

__all__ = ["Router", "NoReplicaError"]

log = logging.getLogger("edl_tpu.serving.router")


class NoReplicaError(RuntimeError):
    """The pool holds no started replica of the kind this request needs."""


@dataclass
class _RoutedStream:
    """One LM stream as the router sees it: the caller-facing future plus
    the prefix accumulated across migrations."""

    sid: str
    prompt: np.ndarray
    max_new_tokens: int
    eos_id: Optional[int]
    future: Future
    prefix: List[int] = field(default_factory=list)
    segment: int = 0
    migrations: int = 0
    replica: Optional[str] = None  # current owner (name)


class Router:
    """Health/affinity router over a mutable replica pool."""

    def __init__(self, replicas=(), name: str = "router"):
        self.name = name
        self._lock = threading.Lock()
        self._replicas: Dict[str, Any] = {}
        self._streams: Dict[str, _RoutedStream] = {}
        self._counter = 0
        self._rr = 0
        self._completed = 0
        self._dropped = 0
        self._migrations = 0
        self._migrated_tokens = 0
        for r in replicas:
            self.add(r)

    # -- pool membership -------------------------------------------------------

    def add(self, replica) -> None:
        with self._lock:
            name = replica.config.name
            if name in self._replicas:
                raise ValueError(f"replica {name!r} already in the pool")
            self._replicas[name] = replica

    def remove(self, name: str, migrate: bool = True):
        """Detach ``name`` from the pool; with ``migrate`` its live LM
        streams are evicted and resubmitted across the remaining pool
        (token lists stitched — the zero-drop contract). Returns the
        replica for the caller to ``stop()``; a batch replica's own
        ``stop(drain=True)`` already resolves everything it accepted."""
        with self._lock:
            replica = self._replicas.pop(name, None)
        if replica is None:
            raise KeyError(f"replica {name!r} not in the pool")
        if migrate and hasattr(replica, "evict_streams"):
            for snap in replica.evict_streams():
                self._remigrate(snap)
        return replica

    def replica_names(self) -> List[str]:
        with self._lock:
            return sorted(self._replicas)

    def _candidates(self, lm: bool) -> List[Any]:
        with self._lock:
            pool = list(self._replicas.values())
        return [r for r in pool
                if getattr(r, "started", False)
                and hasattr(r, "generate") == lm]

    # -- batch path ------------------------------------------------------------

    def submit(self, features: Dict[str, Any]) -> Future:
        """Route one fixed-shape request to the shallowest-queue started
        batch replica, failing over on overload."""
        candidates = self._candidates(lm=False)
        if not candidates:
            raise NoReplicaError("no started batch replica in the pool")
        candidates.sort(key=lambda r: r.status()["queue_depth"])
        last: Optional[Exception] = None
        for r in candidates:
            try:
                return r.submit(features)
            except ServeOverloadError as e:
                last = e
        raise last if last is not None else NoReplicaError("no capacity")

    # -- LM path ---------------------------------------------------------------

    def generate_async(self, prompt, max_new_tokens: Optional[int] = None,
                       eos_id: Optional[int] = None):
        """Admit one LM stream against the pool; returns a handle whose
        result carries the stitched token list (``migrations`` counts the
        rescues it survived). Admission rejections (`SeqTooLongError`,
        `KVCacheExhaustedError` when no replica can hold it) raise
        synchronously, same as a single replica."""
        from edl_tpu.serving.lm import LMStreamHandle

        ids = np.asarray(prompt, dtype=np.int32).reshape(-1)
        with self._lock:
            self._counter += 1
            sid = f"{self.name}-r{self._counter}"
        rs = _RoutedStream(sid=sid, prompt=ids,
                           max_new_tokens=int(max_new_tokens or 0) or None,
                           eos_id=eos_id, future=Future())
        with self._lock:
            self._streams[sid] = rs
        try:
            self._dispatch(rs, ids, rs.max_new_tokens)
        except Exception:
            with self._lock:
                self._streams.pop(sid, None)
            raise
        return LMStreamHandle(stream_id=sid, future=rs.future)

    def generate(self, prompt, max_new_tokens: Optional[int] = None,
                 eos_id: Optional[int] = None,
                 timeout: Optional[float] = 60.0) -> Dict[str, Any]:
        return self.generate_async(prompt, max_new_tokens, eos_id).result(
            timeout=timeout
        )

    def _pick_lm_replica(self):
        """Affinity policy: started LM replicas ordered by free KV blocks
        (descending) — route to headroom, spill to the rest."""
        candidates = self._candidates(lm=True)
        if not candidates:
            raise NoReplicaError("no started LM replica in the pool")

        def free_blocks(r) -> int:
            try:
                return int(r.status().get("kv", {}).get("free_blocks", 0))
            except Exception:  # edl: noqa[EDL005] a replica failing status mid-rescale just sorts last; routing must not die on it
                return -1

        candidates.sort(key=free_blocks, reverse=True)
        return candidates

    def _dispatch(self, rs: _RoutedStream, prompt: np.ndarray,
                  budget: Optional[int]) -> None:
        """Submit one segment of ``rs`` to the best replica; tries the
        pool in affinity order, re-raising the last admission error when
        every replica is out of blocks."""
        last: Optional[Exception] = None
        for r in self._pick_lm_replica():
            rs.segment += 1
            inner_id = f"{rs.sid}/seg{rs.segment}"
            try:
                handle = r.submit(prompt, max_new_tokens=budget,
                                  eos_id=rs.eos_id, stream_id=inner_id)
            except KVCacheExhaustedError as e:
                last = e
                continue
            rs.replica = r.config.name
            handle.future.add_done_callback(
                lambda fut, sid=rs.sid: self._on_segment_done(sid, fut)
            )
            return
        raise last if last is not None else NoReplicaError("no capacity")

    def _on_segment_done(self, sid: str, fut: Future) -> None:
        with self._lock:
            rs = self._streams.pop(sid, None)
        if rs is None:
            return  # mid-migration: the resubmitted segment owns the finish
        error = fut.exception()
        if error is not None:
            with self._lock:
                self._dropped += 1
            rs.future.set_exception(error)
            return
        result = fut.result()
        with self._lock:
            self._completed += 1
        rs.future.set_result({
            "stream_id": rs.sid,
            "tokens": rs.prefix + list(result["tokens"]),
            "finish_reason": result["finish_reason"],
            "prompt_tokens": int(rs.prompt.size),
            "model_step": result.get("model_step"),
            "migrations": rs.migrations,
        })

    def _remigrate(self, snap: Dict[str, Any]) -> None:
        """Resubmit one evicted stream's remainder: generated-so-far joins
        the prefix, prompt+generated re-prefills elsewhere with the
        reduced budget. The eviction released the source replica's blocks;
        admission on the target is a fresh reservation for what is left."""
        sid = str(snap["stream_id"]).split("/", 1)[0]
        with self._lock:
            rs = self._streams.get(sid)
        if rs is None:
            return  # finished in the gap between evict and resubmit
        generated = list(snap["generated"])
        with self._lock:
            rs.prefix.extend(generated)
            rs.migrations += 1
            self._migrations += 1
            self._migrated_tokens += len(generated)
        new_prompt = np.concatenate(
            [snap["prompt"], np.asarray(generated, dtype=np.int32)]
        ) if generated else snap["prompt"]
        try:
            self._dispatch(rs, new_prompt, snap["max_new_tokens"])
        except Exception as e:  # edl: noqa[EDL005] resolved into the caller's future — a pool with no admitting replica left is the one case a stream drops, and it drops loudly
            with self._lock:
                self._streams.pop(sid, None)
                self._dropped += 1
            log.error("stream %s dropped during migration: %s", sid, e)
            rs.future.set_exception(e)

    # -- status ----------------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "replicas": sorted(self._replicas),
                "streams_inflight": len(self._streams),
                "completed_streams": self._completed,
                "dropped_streams": self._dropped,
                "migrations": self._migrations,
                "migrated_tokens": self._migrated_tokens,
            }
