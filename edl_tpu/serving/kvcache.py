"""Paged KV-cache block allocator: memory, not batch slots, is the
admission currency of the LM tier.

A CTR replica admits a request when a queue slot is free; an LM stream
holds key/value state for its whole lifetime, so the scarce resource is
KV-cache HBM. This module manages that memory the way vLLM-style paged
attention does: a **preallocated pool of fixed-size blocks** (one block =
``block_tokens`` token slots of per-layer K/V), a freelist recycling
blocks when streams retire, and a per-stream **block table** mapping the
stream's logical token positions onto pool blocks.

Admission is a reservation against the stream's declared maximum:
``blocks_for(prompt + max_new_tokens)`` blocks are claimed up front, so
an admitted stream can always run to its token budget — decode never
deadlocks on allocation mid-stream (the failure mode lazy allocation
buys in exchange for higher occupancy). The cost of that guarantee is
*internal* fragmentation: reserved-but-unwritten token slots, which
:meth:`BlockPool.fragmentation` reports as a first-class metric
alongside occupancy.

Numpy/stdlib-pure and single-lock, like :mod:`edl_tpu.serving.batcher`:
every edge case (exhaustion, double-free, freelist recycling order) is
unit-testable in microseconds, and the LM replica treats it as the one
authority on "can this stream be admitted?".
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

__all__ = ["KVCacheConfig", "BlockPool", "KVCacheExhaustedError"]


class KVCacheExhaustedError(RuntimeError):
    """Not enough free blocks to cover the stream's token budget. The
    request was rejected, not dropped — the frontend maps this to HTTP
    429 and the router retries against a replica with free blocks."""


@dataclass(frozen=True)
class KVCacheConfig:
    """Shape of the block pool.

    ``n_blocks * block_tokens`` bounds the total token slots live streams
    can hold; ``bytes_per_token`` (2 * layers * heads * head_dim * itemsize
    for K+V) is carried so occupancy can be reported in bytes as well as
    slots — the number capacity planning actually wants.
    """

    n_blocks: int = 64
    block_tokens: int = 16
    bytes_per_token: int = 0

    def __post_init__(self):
        if self.n_blocks <= 0:
            raise ValueError(f"n_blocks must be positive: {self.n_blocks}")
        if self.block_tokens <= 0:
            raise ValueError(
                f"block_tokens must be positive: {self.block_tokens}"
            )

    def blocks_for(self, tokens: int) -> int:
        """Blocks needed to hold ``tokens`` token slots (ceil)."""
        return -(-int(tokens) // self.block_tokens)


@dataclass
class _Reservation:
    blocks: List[int]
    reserved_tokens: int
    used_tokens: int = 0
    meta: dict = field(default_factory=dict)


class BlockPool:
    """The preallocated block pool + freelist.

    ``reserve(stream_id, tokens)`` claims blocks for a stream's full
    token budget or raises :class:`KVCacheExhaustedError` atomically
    (no partial claims to unwind). ``note_tokens`` advances the stream's
    used-token high-water mark (fragmentation accounting only — the
    reservation already owns the memory). ``release`` returns the blocks
    to the freelist in LIFO order, so a hot pool reuses recently-touched
    blocks (the friendly pattern for a real HBM allocator's page tables;
    here it simply makes recycling observable in tests).
    """

    def __init__(self, config: KVCacheConfig):
        self.config = config
        self._lock = threading.Lock()
        self._free: List[int] = list(range(config.n_blocks - 1, -1, -1))
        self._streams: Dict[str, _Reservation] = {}
        self._peak_blocks_used = 0

    # -- admission -------------------------------------------------------------

    def can_admit(self, tokens: int) -> bool:
        """Would ``reserve`` succeed for a ``tokens``-budget stream now?
        Advisory (another thread may win the race); the router's affinity
        policy reads this through replica status rather than calling it."""
        with self._lock:
            return self.config.blocks_for(tokens) <= len(self._free)

    def reserve(self, stream_id: str, tokens: int, **meta) -> List[int]:
        """Claim blocks covering ``tokens`` token slots for ``stream_id``.

        Returns the block table (pool indices, in logical-position order).
        Raises :class:`KVCacheExhaustedError` when the freelist cannot
        cover it and ``ValueError`` on a duplicate stream id.
        """
        need = self.config.blocks_for(tokens)
        with self._lock:
            if stream_id in self._streams:
                raise ValueError(f"stream {stream_id!r} already holds blocks")
            if need > len(self._free):
                raise KVCacheExhaustedError(
                    f"stream {stream_id!r} needs {need} blocks "
                    f"({tokens} tokens) but only {len(self._free)} of "
                    f"{self.config.n_blocks} are free"
                )
            blocks = [self._free.pop() for _ in range(need)]
            self._streams[stream_id] = _Reservation(
                blocks=blocks, reserved_tokens=need * self.config.block_tokens,
                meta=dict(meta),
            )
            used = self.config.n_blocks - len(self._free)
            self._peak_blocks_used = max(self._peak_blocks_used, used)
            return list(blocks)

    def note_tokens(self, stream_id: str, used_tokens: int) -> None:
        """Advance ``stream_id``'s written-token high-water mark (feeds
        the fragmentation metric; never allocates)."""
        with self._lock:
            res = self._streams.get(stream_id)
            if res is None:
                return  # stream already released: racing final update is fine
            res.used_tokens = min(max(res.used_tokens, int(used_tokens)),
                                  res.reserved_tokens)

    def release(self, stream_id: str) -> int:
        """Return ``stream_id``'s blocks to the freelist; returns the
        count recycled (0 when the stream held nothing — release is
        idempotent so retire paths never double-free)."""
        with self._lock:
            res = self._streams.pop(stream_id, None)
            if res is None:
                return 0
            self._free.extend(reversed(res.blocks))
            return len(res.blocks)

    def block_table(self, stream_id: str) -> Optional[List[int]]:
        with self._lock:
            res = self._streams.get(stream_id)
            return list(res.blocks) if res is not None else None

    # -- metrics ---------------------------------------------------------------

    def free_blocks(self) -> int:
        with self._lock:
            return len(self._free)

    def used_blocks(self) -> int:
        with self._lock:
            return self.config.n_blocks - len(self._free)

    def occupancy(self) -> float:
        """Fraction of the pool's blocks currently reserved."""
        return self.used_blocks() / self.config.n_blocks

    def fragmentation(self) -> float:
        """Internal fragmentation: fraction of reserved token slots no
        token has been written to. High values mean admission budgets
        (``max_new_tokens``) run far beyond what streams actually
        generate — the knob to tighten before growing the pool."""
        with self._lock:
            reserved = sum(r.reserved_tokens for r in self._streams.values())
            used = sum(r.used_tokens for r in self._streams.values())
        if reserved == 0:
            return 0.0
        return (reserved - used) / reserved

    def stats(self) -> Dict[str, float]:
        """One snapshot for status publication / the `edl_lm_kv_*`
        gauges: pool shape, live usage, fragmentation, peak."""
        with self._lock:
            free = len(self._free)
            used = self.config.n_blocks - free
            reserved = sum(r.reserved_tokens for r in self._streams.values())
            written = sum(r.used_tokens for r in self._streams.values())
            streams = len(self._streams)
            peak = self._peak_blocks_used
        frag = 0.0 if reserved == 0 else (reserved - written) / reserved
        out = {
            "n_blocks": self.config.n_blocks,
            "block_tokens": self.config.block_tokens,
            "used_blocks": used,
            "free_blocks": free,
            "peak_blocks_used": peak,
            "streams": streams,
            "reserved_tokens": reserved,
            "written_tokens": written,
            "occupancy": round(used / self.config.n_blocks, 4),
            "fragmentation": round(frag, 4),
        }
        if self.config.bytes_per_token:
            out["used_bytes"] = reserved * self.config.bytes_per_token
        return out
