"""The serving replica: AOT-bucketed continuous batching over an exported
artifact, with rolling model-version swap.

One :class:`ServingReplica` is one schedulable unit of the serving tier —
the inference-side sibling of `ElasticWorker`. It loads a
`load_inference_model` artifact, AOT-compiles one predict executable per
batch bucket (reusing the PR 2 warm-compile discipline: lower from avals,
dispatch the ``Compiled`` directly so the jit dispatch cache stays empty),
then runs a continuous-batching dispatch loop: requests queue, coalesce
for at most ``max_batch_delay_s``, pad to the smallest bucket that fits,
and resolve per-request futures. A watcher thread polls the exporter
directory's atomic ``LATEST`` pointer and hot-swaps params between
batches — in-flight requests always run against a complete params tree,
so a version swap drops nothing.

Threading model (EDL006 audits this): the dispatch loop, the version
watcher, and the HTTP frontend's request threads share the replica.
Hand-off points are the queue (its own lock), `concurrent.futures.Future`
(its own lock), and every other mutable field — params/executables/stats —
behind ``self._lock``. Batches read the (params, execs) pair under the
lock but run the device step OUTSIDE it, so a swap never waits on a
dispatch and vice versa.
"""

from __future__ import annotations

import json
import logging
import queue
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from edl_tpu.obs.instruments import ServeInstruments
from edl_tpu.obs.metrics import MetricsRegistry
from edl_tpu.obs.tracing import Tracer, get_tracer
from edl_tpu.serving.batcher import (pad_batch, pick_bucket, split_rows,
                                     validate_buckets)

__all__ = ["ServingConfig", "ServingReplica", "ServeOverloadError",
           "ServeCompileError", "SERVING_KV_PREFIX", "probe_jit_cache"]

log = logging.getLogger("edl_tpu.serving.worker")

#: coordinator KV slot a replica publishes its status to (same pattern as
#: the FT-policy state: `edl/ft_policy/<member>`); `edl-tpu status` joins
#: members() against these keys.
SERVING_KV_PREFIX = "edl/serving/"


def probe_jit_cache(*jit_fns) -> Optional[int]:
    """Total compiled-program count across the given jitted functions'
    dispatch caches, via the private ``_cache_size`` probe; None when any
    probe is unavailable. This is the teeth of the AOT contract: a tier
    that lowers from avals and dispatches ``Compiled`` objects directly
    keeps every one of these at 0 no matter how much traffic it served."""
    total = 0
    for fn in jit_fns:
        probe = getattr(fn, "_cache_size", None)
        if probe is None:
            return None
        try:
            total += int(probe())
        except TypeError:
            return None
    return total


class ServeOverloadError(RuntimeError):
    """Queue at capacity — the request was rejected, not dropped: the
    caller gets this synchronously and can retry against another replica
    (the autoscaler sees the same pressure via the queue-depth gauge)."""


class ServeCompileError(RuntimeError):
    """A bucket executable failed to AOT-compile at startup. Raised from
    `start()` (never on the request path — the AOT contract means compile
    errors fail the replica fast, before it takes traffic). The usual
    cause: a bucket size the model's sharding can't take, e.g. a
    shard_map'd sparse lookup needs batch sizes divisible by the mesh's
    data-axis extent, so `buckets=(1, ...)` is invalid on that mesh."""


@dataclass
class ServingConfig:
    """Knobs for one serving replica."""

    model_dir: str
    buckets: Tuple[int, ...] = (1, 8, 32)
    #: how long the dispatcher waits to fill a batch beyond its first
    #: request. 0 disables coalescing (the batching-off bench arm).
    max_batch_delay_s: float = 0.005
    queue_capacity: int = 1024
    request_timeout_s: float = 30.0
    #: LATEST-pointer poll period for the rolling-swap watcher
    version_poll_s: float = 0.25
    #: None: no HTTP frontend; 0: ephemeral port (tests); N: fixed port
    port: Optional[int] = None
    name: str = "serve-0"
    #: coordinator KV status publication period
    publish_interval_s: float = 1.0

    def __post_init__(self):
        self.buckets = validate_buckets(self.buckets)
        if self.max_batch_delay_s < 0:
            raise ValueError("max_batch_delay_s must be >= 0")
        if self.queue_capacity <= 0:
            raise ValueError("queue_capacity must be positive")


@dataclass
class _Pending:
    features: Dict[str, np.ndarray]
    future: Future
    t_enqueue: float  # epoch seconds (span clock)
    t_mono: float  # monotonic (latency math)


class ServingReplica:
    """Continuous-batching serving worker over one exported artifact.

    Lifecycle: ``start()`` loads the artifact, AOT-compiles every bucket
    (all executables ready BEFORE the first request is accepted), and
    starts the dispatch/watcher threads plus the optional HTTP frontend.
    ``submit()`` enqueues one request and returns a future; ``stop()``
    drains the queue (every accepted request resolves) and shuts down.
    """

    def __init__(self, config: ServingConfig,
                 client: Optional[Any] = None,
                 registry: Optional[MetricsRegistry] = None,
                 tracer: Optional[Tracer] = None):
        self.config = config
        self.client = client  # coordinator KV surface (status publication)
        self.instruments = ServeInstruments(registry)
        self.registry = registry
        self.tracer = tracer if tracer is not None else get_tracer()
        self._queue: "queue.Queue[_Pending]" = queue.Queue(
            maxsize=config.queue_capacity
        )
        self._lock = threading.Lock()
        # swap state + stats, all guarded by _lock
        self._params: Any = None
        self._execs: Dict[int, Any] = {}
        self._bucket_shardings: Dict[int, Any] = {}
        self._params_signature: Any = None
        self._version: Optional[Tuple] = None
        self._model_step: Optional[int] = None
        self._last_swap_step: Optional[int] = None
        self._bucket_hits: Dict[int, int] = {}
        self._swaps = 0
        self._completed = 0
        self._rejected = 0
        self._errors = 0
        self._last_publish = 0.0
        # set once in start() before any worker thread exists
        self._art = None
        self._feature_avals: Dict[str, Tuple[Tuple[int, ...], np.dtype]] = {}
        self._jit_predict = None
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._server = None
        self._started = False

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> "ServingReplica":
        if self._started:
            return self
        from edl_tpu.runtime.export import (artifact_version,
                                            load_inference_model)

        cfg = self.config
        art = load_inference_model(cfg.model_dir)
        if art.model.predict is None:
            raise NotImplementedError(
                f"model {art.model.name!r} defines no predict entrypoint"
            )
        jit_predict = self._build_jit(art)
        with self._lock:
            self._art = art
            self._feature_avals = self._derive_feature_avals(art.model)
            self._jit_predict = jit_predict
        execs, shardings = self._compile_buckets(art, jit_predict)
        from edl_tpu.runtime.train_loop import aval_signature

        with self._lock:
            self._params = art.params
            self._execs = execs
            self._bucket_shardings = shardings
            self._params_signature = aval_signature(art.params)
            self._version = artifact_version(cfg.model_dir)
            self._model_step = art.step
        self.instruments.model_step.set(float(art.step or 0))
        self._register()
        dispatch = threading.Thread(target=self._dispatch_loop,
                                    name=f"edl-serve-dispatch-{cfg.name}",
                                    daemon=True)
        watcher = threading.Thread(target=self._watch_loop,
                                   name=f"edl-serve-watch-{cfg.name}",
                                   daemon=True)
        with self._lock:
            self._threads = [dispatch, watcher]
        for t in (dispatch, watcher):
            t.start()
        if cfg.port is not None:
            from edl_tpu.serving.frontend import make_frontend

            server = make_frontend(self, port=cfg.port,
                                   registry=self.registry,
                                   tracer=self.tracer)
            with self._lock:
                self._server = server
        with self._lock:
            self._started = True
        return self

    def stop(self, drain: bool = True) -> None:
        """Shut down; with ``drain`` every already-accepted request is
        served first — the zero-drop half of a replica-count change."""
        if not drain:
            self._fail_queued(RuntimeError("replica stopping"))
        self._stop.set()
        with self._lock:
            threads, self._threads = self._threads, []
            server, self._server = self._server, None
        for t in threads:  # join OUTSIDE the lock: batches need it to run
            t.join(timeout=30)
        if server is not None:
            server.stop()
        self._publish_status(force=True)
        with self._lock:
            self._started = False

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    @property
    def url(self) -> Optional[str]:
        return self._server.url if self._server is not None else None

    @property
    def started(self) -> bool:
        """True between a successful ``start()`` and ``stop()`` — the
        router's health predicate (an unstarted or stopped replica takes
        no traffic)."""
        with self._lock:
            return self._started

    # -- request path ----------------------------------------------------------

    def submit(self, features: Dict[str, Any]) -> Future:
        """Enqueue one request (a dict of per-example feature arrays, no
        batch dim) and return a future resolving to its output row."""
        if not self._started:
            raise RuntimeError("replica not started")
        row = self._coerce_features(features)
        fut: Future = Future()
        item = _Pending(features=row, future=fut,
                        t_enqueue=time.time(), t_mono=time.monotonic())
        try:
            self._queue.put_nowait(item)
        except queue.Full:
            self.instruments.requests.inc(outcome="rejected")
            with self._lock:
                self._rejected += 1
            raise ServeOverloadError(
                f"queue at capacity ({self.config.queue_capacity})"
            ) from None
        self.instruments.inflight.inc(1.0)
        self.instruments.queue_depth.set(float(self._queue.qsize()))
        return fut

    def predict(self, features: Dict[str, Any]) -> Any:
        """Blocking convenience wrapper over :meth:`submit`."""
        return self.submit(features).result(
            timeout=self.config.request_timeout_s
        )

    def _coerce_features(self, features: Dict[str, Any]) -> Dict[str, np.ndarray]:
        if not isinstance(features, dict):
            raise TypeError("request features must be a dict")
        row = {}
        for key, (shape, dtype) in self._feature_avals.items():
            if key not in features:
                raise KeyError(f"request missing feature {key!r}")
            value = np.asarray(features[key], dtype=dtype)
            if value.shape != shape:
                raise ValueError(
                    f"feature {key!r} has shape {value.shape}, "
                    f"expected {shape}"
                )
            row[key] = value
        return row

    # -- AOT compilation -------------------------------------------------------

    @staticmethod
    def _derive_feature_avals(model) -> Dict[str, Tuple[Tuple[int, ...], np.dtype]]:
        """Per-example feature avals from the model's own synthetic batch,
        minus its label keys — the serving tier learns request shapes from
        the model contract, never from the first request (shapes must be
        known BEFORE any request so every bucket can compile up front)."""
        sample = model.synthetic_batch(np.random.default_rng(0), 1)
        labels = set(getattr(model, "label_keys", ()) or ())
        return {
            key: (tuple(np.shape(value)[1:]), np.asarray(value).dtype)
            for key, value in sample.items() if key not in labels
        }

    @staticmethod
    def _build_jit(art):
        mesh = art.mesh
        pred = art.model.predict
        import jax

        return jax.jit(lambda params, batch: pred(params, batch, mesh))

    def _batch_sharding(self, bucket: int):
        """Leading-dim data sharding when the bucket divides evenly over
        the data axis, replicated otherwise (small buckets on big meshes)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        mesh = self._art.mesh
        data_size = dict(zip(mesh.axis_names, mesh.devices.shape)).get("data", 1)
        spec = (PartitionSpec("data")
                if data_size > 1 and bucket % data_size == 0
                else PartitionSpec())
        return NamedSharding(mesh, spec)

    def _compile_buckets(self, art, jit_predict):
        """AOT-compile one executable per bucket from avals, concurrently on
        background threads, all joined before the replica accepts traffic.
        Same contract as `Trainer.warm_compile`: the ``Compiled`` objects
        are dispatched directly, so the jit dispatch cache stays empty."""
        import jax

        param_avals = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(
                x.shape, x.dtype,
                sharding=x.sharding if getattr(x, "_committed", False) else None,
            ),
            art.params,
        )
        shardings = {b: self._batch_sharding(b) for b in self.config.buckets}

        def compile_one(bucket: int):
            t0 = time.perf_counter()
            batch_avals = {
                key: jax.ShapeDtypeStruct((bucket,) + shape, dtype,
                                          sharding=shardings[bucket])
                for key, (shape, dtype) in self._feature_avals.items()
            }
            try:
                compiled = jit_predict.lower(param_avals, batch_avals).compile()
            except Exception as exc:
                mesh_shape = dict(zip(art.mesh.axis_names,
                                      art.mesh.devices.shape))
                raise ServeCompileError(
                    f"bucket {bucket} failed to AOT-compile on mesh "
                    f"{mesh_shape} — if the model shards over a mesh axis "
                    f"(e.g. a shard_map'd embedding lookup), every bucket "
                    f"size must be divisible by that axis extent; adjust "
                    f"ServingConfig.buckets: {exc}"
                ) from exc
            seconds = time.perf_counter() - t0
            self.instruments.compile_seconds.set(seconds, bucket=str(bucket))
            return bucket, compiled

        with ThreadPoolExecutor(
            max_workers=len(self.config.buckets),
            thread_name_prefix=f"edl-serve-compile-{self.config.name}",
        ) as pool:
            execs = dict(pool.map(compile_one, self.config.buckets))
        return execs, shardings

    def jit_cache_size(self) -> Optional[int]:
        """Compiled-program count inside the jit dispatch cache (None when
        the private probe is unavailable). The AOT contract — every bucket
        pre-compiled, ``Compiled`` dispatched directly — keeps this at 0
        no matter how many requests have been served."""
        return probe_jit_cache(self._jit_predict)

    # -- dispatch loop ---------------------------------------------------------

    def _dispatch_loop(self) -> None:
        while True:
            try:
                first = self._queue.get(timeout=0.05)
            except queue.Empty:
                if self._stop.is_set():
                    return  # drained: stop() only wins once the queue is dry
                continue
            items = [first]
            deadline = time.monotonic() + self.config.max_batch_delay_s
            largest = self.config.buckets[-1]
            while len(items) < largest:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    items.append(self._queue.get(timeout=remaining))
                except queue.Empty:
                    break
            self.instruments.queue_depth.set(float(self._queue.qsize()))
            self._run_batch(items)

    def _run_batch(self, items: List[_Pending]) -> None:
        import jax

        n = len(items)
        bucket = pick_bucket(n, self.config.buckets)
        with self._lock:
            params = self._params
            compiled = self._execs[bucket]
            sharding = self._bucket_shardings[bucket]
            model_step = self._model_step
            self._bucket_hits[bucket] = self._bucket_hits.get(bucket, 0) + 1
        t_batch = time.monotonic()
        try:
            batch = pad_batch([it.features for it in items], bucket,
                              self._feature_avals)
            placed = {key: jax.device_put(value, sharding)
                      for key, value in batch.items()}
            outputs = jax.device_get(compiled(params, placed))
        except Exception as e:  # edl: noqa[EDL005] resolved into every request future below — the error reaches each caller; the dispatch loop must survive one poisoned batch
            log.exception("batch of %d (bucket %d) failed", n, bucket)
            with self._lock:
                self._errors += n
            for it in items:
                it.future.set_exception(e)
                self.instruments.requests.inc(outcome="error")
                self.instruments.inflight.inc(-1.0)
            return
        rows = split_rows(outputs, n)
        now, now_mono = time.time(), time.monotonic()
        for it, row in zip(items, rows):
            it.future.set_result(row)
            self.instruments.requests.inc(outcome="ok")
            self.instruments.inflight.inc(-1.0)
            self.instruments.latency.observe(now_mono - it.t_mono)
            self.instruments.queue_wait.observe(t_batch - it.t_mono)
            self.tracer.record(
                "serve_request", it.t_enqueue, now, component="serving",
                bucket=bucket, batch_size=n, model_step=model_step,
            )
        with self._lock:
            self._completed += n
        self.instruments.batches.inc(bucket=str(bucket))
        self.instruments.batch_occupancy.observe(n / bucket)

    def _fail_queued(self, error: Exception) -> None:
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                return
            item.future.set_exception(error)
            self.instruments.requests.inc(outcome="error")
            self.instruments.inflight.inc(-1.0)

    # -- rolling model swap ----------------------------------------------------

    def _watch_loop(self) -> None:
        while not self._stop.wait(self.config.version_poll_s):
            try:
                self._maybe_swap()
            except Exception:  # edl: noqa[EDL005] logged loudly; a torn export or transient FS error must not kill the watcher — the next poll retries
                log.exception("model-version watch failed")
            self._publish_status()

    def _maybe_swap(self) -> None:
        from edl_tpu.runtime.export import artifact_version, load_inference_model
        from edl_tpu.runtime.train_loop import aval_signature

        version = artifact_version(self.config.model_dir)
        with self._lock:
            current = self._version
        if version is None or version == current:
            return
        art = load_inference_model(self.config.model_dir, mesh=self._art.mesh)
        signature = aval_signature(art.params)
        t0 = time.time()
        with self._lock:
            same_avals = signature == self._params_signature
        if not same_avals:
            # a config change altered param shapes: the old executables are
            # stale, so recompile every bucket against the new avals first —
            # requests keep flowing on the old (params, execs) pair meanwhile
            jit_predict = self._build_jit(art)
            execs, shardings = self._compile_buckets(art, jit_predict)
        with self._lock:
            if not same_avals:
                self._jit_predict = jit_predict
                self._execs = execs
                self._bucket_shardings = shardings
            self._art = art
            self._params = art.params
            self._params_signature = signature
            self._version = version
            self._model_step = art.step
            self._last_swap_step = art.step
            self._swaps += 1
        self.instruments.model_swaps.inc()
        self.instruments.model_step.set(float(art.step or 0))
        self.tracer.record("model_swap", t0, time.time(),
                           component="serving", model_step=art.step,
                           recompiled=not same_avals)
        log.info("swapped to artifact step %s (version %s)", art.step,
                 version[2] if version else None)

    # -- status ----------------------------------------------------------------

    def status(self) -> Dict[str, Any]:
        """The replica's serving-state snapshot: what `edl-tpu status`
        renders and the coordinator KV publication carries."""
        with self._lock:
            return {
                "name": self.config.name,
                "kind": "batch",  # fixed-shape request/response; LM
                                  # replicas publish kind="lm" to the
                                  # same KV slot
                "model_step": self._model_step,
                "version": self._version[2] if self._version else None,
                "queue_depth": self._queue.qsize(),
                "buckets": list(self.config.buckets),
                "bucket_hits": {str(k): v
                                for k, v in sorted(self._bucket_hits.items())},
                "last_swap_step": self._last_swap_step,
                "swaps": self._swaps,
                "completed": self._completed,
                "rejected": self._rejected,
                "errors": self._errors,
            }

    def _health(self) -> Dict[str, Any]:
        return self.status()

    def _register(self) -> None:
        if self.client is None:
            return
        try:
            self.client.register(takeover=True)
        except Exception:  # edl: noqa[EDL005] status publication is best-effort observability; serving must come up even with the coordinator down
            log.warning("coordinator register failed; status publication "
                        "will retry", exc_info=True)

    def _publish_status(self, force: bool = False) -> None:
        if self.client is None:
            return
        now = time.monotonic()
        with self._lock:
            if (not force and
                    now - self._last_publish < self.config.publish_interval_s):
                return
            self._last_publish = now
        try:
            self.client.heartbeat()
            self.client.kv_put(SERVING_KV_PREFIX + self.config.name,
                               json.dumps(self.status()))
        except Exception:  # edl: noqa[EDL005] best-effort: a coordinator blip must not take the serving path down with it; the next publish interval retries
            log.debug("serving status publish failed", exc_info=True)
