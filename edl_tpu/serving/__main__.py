"""The serve smokes: ``python -m edl_tpu.serving`` (``make serve-smoke``)
and ``python -m edl_tpu.serving lm`` (``make serve-lm-smoke``).

Boots the serving tier end to end the way a pod would see it: export a
real artifact (versioned layout, atomic ``LATEST``), start a
:class:`ServingReplica` with its HTTP frontend, push requests through
``POST /predict`` over real sockets, then scrape `/metrics` and assert

- the p99-bearing latency family and the queue-depth family are present
  (the two signals the autoscaler scales the tier on),
- per-bucket dispatch and model-step families are exported,
- the AOT contract held: every bucket executable was compiled before the
  first request and the jit dispatch cache is still empty,
- a model-version swap landed mid-traffic with zero dropped requests.

The ``lm`` mode does the same for the LM tier: export a small transformer,
boot an :class:`LMServingReplica`, decode a prompt batch through ``POST
/generate`` concurrently (continuous batching with per-token membership),
then assert zero dropped streams, exact token accounting, the LM metric
families, a fully-recycled KV block pool, and the empty-dispatch-cache
AOT contract across BOTH phase executables.

Exit 0 only when all of it holds — the deploy gates for the serving
path, chained into ``make verify``.
"""

from __future__ import annotations

import os
import sys

#: a scrape missing any of these means the serving telemetry regressed —
#: the first two are the autoscaler's inputs.
REQUIRED_FAMILIES = (
    "edl_serve_request_latency_seconds",
    "edl_serve_queue_depth",
    "edl_serve_requests_total",
    "edl_serve_batches_total",
    "edl_serve_model_step",
    "edl_serve_model_swaps_total",
)

#: the LM tier's telemetry contract — the first two are the LM
#: autoscaler's inputs, the KV families the router's affinity source.
REQUIRED_LM_FAMILIES = (
    "edl_lm_token_latency_seconds",
    "edl_lm_kv_occupancy",
    "edl_lm_tokens_total",
    "edl_lm_kv_blocks_free",
    "edl_lm_prefill_batch_size",
    "edl_lm_decode_batch_size",
    "edl_lm_decode_steps_total",
)

N_REQUESTS = 48
N_STREAMS = 12
MAX_NEW_TOKENS = 8


def _hermetic_cpu() -> None:
    # Hermetic CPU backend BEFORE jax imports: the smokes must run anywhere.
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()


def main_lm() -> int:
    _hermetic_cpu()

    import json
    import tempfile
    import urllib.request
    from concurrent.futures import ThreadPoolExecutor

    import jax
    import numpy as np

    from edl_tpu.models import transformer
    from edl_tpu.obs.http import scrape_metrics
    from edl_tpu.obs.metrics import parse_prometheus
    from edl_tpu.runtime.export import _serving_mesh, save_inference_model
    from edl_tpu.serving import LMServingConfig, LMServingReplica

    model_kw = dict(vocab_size=64, d_model=32, n_layers=2, n_heads=2,
                    d_ff=64, seq_len=64, flash=False)
    model = transformer.make_model(**model_kw)
    mesh = _serving_mesh(model)
    params = model.init(jax.random.PRNGKey(0), mesh)

    with tempfile.TemporaryDirectory() as td:
        art_dir = os.path.join(td, "artifact")
        save_inference_model(art_dir, "transformer", params,
                             config=model_kw, step=100)
        replica = LMServingReplica(LMServingConfig(
            model_dir=art_dir, batch_buckets=(1, 4), seq_buckets=(16, 32),
            kv_blocks=32, kv_block_tokens=8, port=0, name="smoke-lm",
        )).start()
        try:
            cache0 = replica.jit_cache_size()
            rng = np.random.default_rng(0)

            def one_stream(i: int):
                body = json.dumps({
                    "prompt": rng.integers(1, 60, size=3 + i % 9).tolist(),
                    "max_new_tokens": MAX_NEW_TOKENS,
                }).encode()
                req = urllib.request.Request(
                    replica.url + "/generate", data=body,
                    headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(req, timeout=30) as resp:
                    return json.loads(resp.read())

            # concurrent submission: streams join and leave the decode
            # batch at step boundaries, not request boundaries
            with ThreadPoolExecutor(max_workers=6) as pool:
                results = list(pool.map(one_stream, range(N_STREAMS)))
            status = replica.status()
            text = scrape_metrics(replica.url)
            families = parse_prometheus(text)
        finally:
            replica.stop()

    failures = []
    short = [r for r in results
             if len(r["tokens"]) != MAX_NEW_TOKENS
             or r["finish_reason"] != "length"]
    if short:
        failures.append(f"{len(short)}/{N_STREAMS} streams returned wrong "
                        f"token counts: {short[:2]}")
    missing = [f for f in REQUIRED_LM_FAMILIES if f not in families]
    if missing:
        failures.append(f"missing LM metric families: {missing}")
    cache_now = replica.jit_cache_size()
    if cache0 not in (0, None) or cache_now not in (0, None):
        failures.append(
            f"jit dispatch cache not empty (start={cache0}, end={cache_now})"
            " — a prefill/decode executable was dispatched through jit, "
            "not AOT"
        )
    if status["completed"] != N_STREAMS or status["rejected"]:
        failures.append(f"dropped/rejected streams: {status}")
    kv = status["kv"]
    if kv["used_blocks"] != 0 or kv["free_blocks"] != kv["n_blocks"]:
        failures.append(f"KV block pool leaked: {kv}")
    expected = N_STREAMS * MAX_NEW_TOKENS
    if status["tokens_generated"] != expected:
        failures.append(f"token accounting off: generated "
                        f"{status['tokens_generated']}, expected {expected}")

    if failures:
        print("serve-lm-smoke FAILED:")
        for f in failures:
            print(f"  - {f}")
        return 1
    print(
        f"serve-lm-smoke OK: {N_STREAMS} streams x {MAX_NEW_TOKENS} tokens "
        f"over HTTP /generate, 0 dropped, KV pool fully recycled "
        f"(peak {kv['peak_blocks_used']}/{kv['n_blocks']} blocks), "
        f"jit dispatch cache empty across prefill+decode, "
        f"{len(REQUIRED_LM_FAMILIES)} required families present"
    )
    return 0


def main() -> int:
    _hermetic_cpu()

    import json
    import tempfile
    import time
    import urllib.request

    import jax
    import numpy as np

    from edl_tpu.models import fit_a_line
    from edl_tpu.obs.http import scrape_metrics
    from edl_tpu.obs.metrics import parse_prometheus
    from edl_tpu.runtime.export import _serving_mesh, save_inference_model
    from edl_tpu.serving import ServingConfig, ServingReplica

    model = fit_a_line.MODEL
    mesh = _serving_mesh(model)
    params = model.init(jax.random.PRNGKey(0), mesh)

    with tempfile.TemporaryDirectory() as td:
        art_dir = os.path.join(td, "artifact")
        save_inference_model(art_dir, "fit_a_line", params, step=100,
                             versioned=True)
        replica = ServingReplica(ServingConfig(
            model_dir=art_dir, buckets=(1, 4, 16), max_batch_delay_s=0.002,
            port=0, version_poll_s=0.05, name="smoke-serve",
        )).start()
        try:
            cache0 = replica.jit_cache_size()
            rng = np.random.default_rng(0)
            ok = 0
            for i in range(N_REQUESTS):
                body = json.dumps({"features": {
                    "x": rng.standard_normal(13).tolist()
                }}).encode()
                req = urllib.request.Request(
                    replica.url + "/predict", data=body,
                    headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(req, timeout=10) as resp:
                    reply = json.loads(resp.read())
                if np.isfinite(np.asarray(reply["outputs"])).all():
                    ok += 1
                if i == N_REQUESTS // 2:
                    # rolling swap mid-traffic: publish a newer artifact and
                    # keep the requests flowing
                    save_inference_model(
                        art_dir, "fit_a_line",
                        jax.tree_util.tree_map(lambda x: x * 1.5, params),
                        step=200, versioned=True,
                    )
            deadline = time.monotonic() + 5
            while (replica.status()["swaps"] < 1
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            status = replica.status()
            text = scrape_metrics(replica.url)
            families = parse_prometheus(text)
        finally:
            replica.stop()

    failures = []
    if ok != N_REQUESTS:
        failures.append(f"{N_REQUESTS - ok}/{N_REQUESTS} requests failed")
    missing = [f for f in REQUIRED_FAMILIES if f not in families]
    if missing:
        failures.append(f"missing metric families: {missing}")
    cache_now = replica.jit_cache_size()
    if cache0 not in (0, None) or cache_now not in (0, None):
        failures.append(
            f"jit dispatch cache not empty (start={cache0}, end={cache_now})"
            " — a bucket executable was dispatched through jit, not AOT"
        )
    if status["swaps"] < 1 or status["model_step"] != 200:
        failures.append(f"model swap did not land: {status}")
    if status["completed"] != N_REQUESTS or status["errors"]:
        failures.append(f"dropped/errored requests: {status}")
    buckets_hit = sum(status["bucket_hits"].values())
    if buckets_hit <= 0:
        failures.append("no batches dispatched")

    if failures:
        print("serve-smoke FAILED:")
        for f in failures:
            print(f"  - {f}")
        return 1
    print(
        f"serve-smoke OK: {ok} requests over HTTP, "
        f"bucket hits {status['bucket_hits']}, "
        f"{status['swaps']} rolling swap(s) to step {status['model_step']}, "
        f"jit dispatch cache empty, "
        f"{len(REQUIRED_FAMILIES)} required families present"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main_lm() if "lm" in sys.argv[1:] else main())
