"""Degraded-mode client: buffer side effects during a coordinator outage.

The worker's compute does not depend on the coordinator — batches already
leased and placed keep stepping. What the outage blocks is *bookkeeping*:
``complete_task`` after a covering checkpoint, ``fail_task`` on a bad
shard, KV publishes. This module buffers exactly those, then replays them
in order once the coordinator answers again. Replay is safe because the
server treats every buffered op idempotently:

- ``complete_task``: already-done replies ok+duplicate; requeued-but-
  unleased tasks are accepted (the worker only completes after a durable
  covering checkpoint).
- ``fail_task``: a task whose lease already expired is simply back in the
  queue; the error reply is ignored on replay.
- ``kv_put``: last-writer-wins by design.
- ``kv_incr``: carries an ``op_id`` marker persisted server-side, so a
  replay across even a coordinator *restart* applies exactly once.

:class:`OutboxClient` wraps any client with the ``CoordinatorClient``
method surface (wire or in-process) and adds outage accounting: reads
fail soft (``acquire`` returns ``{"task": None, "unreachable": True}``),
mutations buffer, and ``outage_seconds()`` feeds the worker's park budget.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from edl_tpu.coordinator.client import (
    CoordinatorAuthError,
    CoordinatorError,
    CoordinatorUnreachable,
)

__all__ = ["Outbox", "OutboxClient"]


class Outbox:
    """Ordered buffer of coordinator mutations awaiting replay.

    Thread-safe: with a pipelined input path the lease RPCs run on the pump
    thread while heartbeats/commits stay on the worker's main thread, so
    two threads can observe recovery — and call :meth:`replay` — at once.
    """

    def __init__(self) -> None:
        self._entries: List[Tuple[str, Dict]] = []
        self._lock = threading.Lock()
        #: held by the (single) thread currently draining; a concurrent
        #: replay returns 0 instead of racing the pops.
        self._replaying = threading.Lock()

    def add(self, op: str, **fields) -> None:
        with self._lock:
            self._entries.append((op, fields))

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def pending(self) -> List[Tuple[str, Dict]]:
        with self._lock:
            return list(self._entries)

    #: sub-ops per replay frame: bounds frame size (a 10k-op outage backlog
    #: must not serialize into one multi-megabyte line) while amortizing
    #: the round-trip ~64x versus per-op replay.
    BATCH = 64

    def replay(self, client) -> int:
        """Replay buffered ops in order through the client.

        Uses ``client.call_batch`` when the client has one — ordered frames
        of up to :data:`BATCH` sub-ops, one round-trip each — and falls
        back to per-op ``client.call``. Returns the number of ops drained.
        Stops (keeping the tail) on the first transport failure so a
        mid-replay outage loses nothing: a frame that failed in transit is
        retried whole later, which is safe for the same reason replay is
        safe at all — every buffered op is idempotent or deduped server-
        side (op_id markers), even if the lost frame was partially applied.
        A rejected sub-op ({"ok": False}) is dropped — the server has
        already resolved it (e.g. a fail_task whose lease expired and
        requeued). One replayer at a time: a thread that finds a drain
        already in flight returns 0 (its guarded call proceeds; ops are
        idempotent).
        """
        if not self._replaying.acquire(blocking=False):
            return 0
        try:
            call_batch = getattr(client, "call_batch", None)
            if call_batch is not None:
                return self._replay_batched(call_batch)
            drained = 0
            while True:
                with self._lock:
                    if not self._entries:
                        break
                    op, fields = self._entries[0]
                try:
                    client.call(op, **fields)
                except CoordinatorAuthError:
                    raise
                except CoordinatorError:
                    break
                with self._lock:
                    self._entries.pop(0)
                drained += 1
            return drained
        finally:
            self._replaying.release()

    def _replay_batched(self, call_batch) -> int:
        drained = 0
        while True:
            with self._lock:
                frame = list(self._entries[:self.BATCH])
            if not frame:
                break
            try:
                call_batch(frame)
            except CoordinatorAuthError:
                raise
            except CoordinatorError:
                break
            with self._lock:
                del self._entries[:len(frame)]
            drained += len(frame)
        return drained


class OutboxClient:
    """CoordinatorClient facade that degrades instead of raising.

    Wraps the underlying ``client`` (CoordinatorClient or InProcessClient):

    - **mutations** (``complete_task``/``fail_task``/``kv_put``) land in
      the outbox when the coordinator is unreachable and report
      ``{"ok": True, "buffered": True}``;
    - **acquire** fails soft with ``{"task": None, "unreachable": True}``
      — the lease loop's existing empty-queue poll path absorbs it;
    - **reachability** is tracked across all guarded calls:
      ``outage_seconds()`` is the worker's park-budget input, and any
      successful guarded call replays the outbox first so buffered
      completions land before new ones.

    Auth errors always propagate — a bad token is a deployment bug the
    outage machinery must never absorb.
    """

    def __init__(self, client, outbox: Optional[Outbox] = None) -> None:
        self.client = client
        self.outbox = outbox if outbox is not None else Outbox()
        #: monotonic timestamp of the first failure of the current outage,
        #: None while reachable.
        self.unreachable_since: Optional[float] = None
        self.buffered_ops = 0
        self.replayed_ops = 0
        self.outages = 0
        self.outage_total_seconds = 0.0
        #: called with the incident's duration (seconds) each time an
        #: outage closes — the running total above aggregates per-incident
        #: lengths away, and both the outage-duration histogram and the
        #: adaptive fault-tolerance policy need the distribution. Invoked
        #: from whichever thread's guarded call observed recovery; keep
        #: the callback cheap and thread-safe.
        self.on_outage_close: Optional[Callable[[float], None]] = None

    # -- outage accounting -----------------------------------------------------

    @property
    def worker(self) -> str:
        return self.client.worker

    def outage_seconds(self) -> float:
        if self.unreachable_since is None:
            return 0.0
        return time.monotonic() - self.unreachable_since

    @property
    def unreachable(self) -> bool:
        return self.unreachable_since is not None

    def _mark_down(self) -> None:
        if self.unreachable_since is None:
            self.unreachable_since = time.monotonic()
            self.outages += 1

    def _mark_up(self) -> None:
        if self.unreachable_since is not None:
            duration = time.monotonic() - self.unreachable_since
            self.outage_total_seconds += duration
            self.unreachable_since = None
            if self.on_outage_close is not None:
                self.on_outage_close(duration)

    def replay(self) -> int:
        """Drain the outbox through the underlying client (idempotent)."""
        drained = self.outbox.replay(self.client)
        self.replayed_ops += drained
        return drained

    def _recovered(self) -> None:
        self._mark_up()
        if len(self.outbox):
            self.replay()

    # -- guarded mutations (buffer on outage) ----------------------------------

    def _mutate(self, op: str, **fields) -> Dict:
        try:
            reply = self.client.call(op, **fields)
        except CoordinatorAuthError:
            raise
        except CoordinatorError:
            self._mark_down()
            self.outbox.add(op, **fields)
            self.buffered_ops += 1
            return {"ok": True, "buffered": True}
        self._recovered()
        return reply

    def complete_task(self, task: str) -> Dict:
        # Buffered-first ordering: a completion buffered during the outage
        # must not be reordered behind this one.
        if len(self.outbox) and not self.unreachable:
            self.replay()
        return self._mutate("complete_task", task=task)

    def fail_task(self, task: str) -> Dict:
        return self._mutate("fail_task", task=task)

    def kv_put(self, key: str, value: str) -> None:
        self._mutate("kv_put", key=key, value=value)

    # -- guarded reads (fail soft) ---------------------------------------------

    def acquire(self) -> Dict:
        try:
            reply = self.client.acquire()
        except CoordinatorAuthError:
            raise
        except CoordinatorError:
            self._mark_down()
            # Shape-compatible with the empty-queue reply: the lease loop
            # polls instead of dying, which *is* degraded mode.
            return {"ok": False, "task": None, "exhausted": False,
                    "unreachable": True}
        self._recovered()
        return reply

    def acquire_task(self) -> Optional[str]:
        return self.acquire().get("task")

    def heartbeat(self) -> Dict:
        try:
            reply = self.client.heartbeat()
        except CoordinatorAuthError:
            raise
        except CoordinatorError:
            self._mark_down()
            return {"ok": False, "error": "unreachable", "unreachable": True}
        self._recovered()
        return reply

    def register(self, takeover: bool = False) -> Dict:
        try:
            reply = self.client.register(takeover=takeover)
        except CoordinatorAuthError:
            raise
        except CoordinatorError:
            self._mark_down()
            return {"ok": False, "error": "unreachable", "unreachable": True}
        self._recovered()
        return reply

    # -- transparent passthroughs ----------------------------------------------

    def __getattr__(self, name: str):
        # Everything not explicitly guarded (sync, barrier, kv_get, members,
        # status, ping, leave, add_tasks, bump_epoch, kv_incr, close, ...)
        # keeps the underlying client's semantics, including its retry
        # policy and its error types.
        return getattr(self.client, name)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.client.close()

    def summary(self) -> Dict[str, float]:
        """Outage telemetry for worker run summaries / the collector."""
        out = {
            "outages": float(self.outages),
            "outage_total_seconds": self.outage_total_seconds
            + self.outage_seconds(),
            "buffered_ops": float(self.buffered_ops),
            "replayed_ops": float(self.replayed_ops),
            "outbox_pending": float(len(self.outbox)),
        }
        retries = getattr(self.client, "retry_count", 0)
        out["transport_retries"] = float(retries)
        return out
