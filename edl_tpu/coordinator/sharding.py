"""Shard routing shared by the wire client and the in-process twin.

The control plane hash-partitions its keyspaces (KV keys, checkpoint-plane
owners, task names) across shard servers behind a thin membership root
(native ``--shards``). Both sides of the wire compute the same FNV-1a
64-bit hash — the constants here mirror ``Coordinator::key_shard`` in
``native/coordinator/coordinator.cc``; if they ever diverge the client
routes a key to one shard while the root redirects it to another.
"""

from __future__ import annotations

from typing import Dict, List, Optional

_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_MASK = 0xFFFFFFFFFFFFFFFF


def fnv1a64(key: str) -> int:
    h = _FNV_OFFSET
    for b in key.encode("utf-8"):
        h ^= b
        h = (h * _FNV_PRIME) & _MASK
    return h


def shard_of(key: str, nshards: int) -> int:
    """Owning shard slot for ``key`` — native ``key_shard`` parity."""
    if nshards <= 0:
        return 0
    return fnv1a64(key) % nshards


#: keyspace op -> request field whose value routes the op. Ops absent here
#: (membership, barriers, watch, status...) are served by the root itself.
ROUTED_OPS: Dict[str, str] = {
    "kv_put": "key",
    "kv_get": "key",
    "kv_del": "key",
    "kv_incr": "key",
    "shard_put": "owner",
    "shard_get": "owner",
    "shard_meta": "owner",
    "shard_drop": "owner",
    "complete_task": "task",
    "fail_task": "task",
    # acquire_task rotates over every shard (tasks are hashed by NAME, so a
    # worker's next task can live anywhere); the worker hash only picks the
    # stable starting slot. add_tasks is partitioned by the client before
    # sending. Both still appear here so redirect replies for them resolve.
    "acquire_task": "worker",
}


def route_key(op: str, fields: Dict) -> Optional[str]:
    """The routing key for a request, or None when the op is root-served."""
    field = ROUTED_OPS.get(op)
    if field is None:
        return None
    value = fields.get(field)
    return "" if value is None else str(value)


def partition_tasks(tasks: List[str], nshards: int) -> Dict[int, List[str]]:
    """Split an add_tasks batch by owning shard, preserving order."""
    out: Dict[int, List[str]] = {}
    for t in tasks:
        out.setdefault(shard_of(str(t), nshards), []).append(t)
    return out


class ShardMap:
    """A client's cached view of the partition: the root endpoint plus the
    ordered shard endpoints. Invalidated whenever a redirect reply or a
    reconnect proves it stale."""

    def __init__(self, shards: List[str]):
        self.shards = list(shards)

    @property
    def nshards(self) -> int:
        return len(self.shards)

    def endpoint_for(self, key: str) -> str:
        return self.shards[shard_of(key, self.nshards)]

    def slot_for(self, key: str) -> int:
        return shard_of(key, self.nshards)
