"""Pure-Python twin of the C++ coordinator state machine.

Same semantics as `native/coordinator/coordinator.cc` (membership epochs,
dense re-ranking, 16s-style task leases with requeue, generation-counted
barriers, KV), behind the same client method surface — so tests and the
single-host launcher can run hermetically without the native binary, exactly
the role the reference's in-memory fake clientset plays
(`pkg/client/clientset/versioned/fake/`). Thread-safe; barriers block on a
Condition instead of a parked socket.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from collections import deque
from typing import Dict, List, Optional, Set

from edl_tpu.coordinator.sharding import shard_of


class InProcessCoordinator:
    def __init__(self, task_lease_sec: float = 16.0,
                 heartbeat_ttl_sec: float = 10.0,
                 auth_token: Optional[str] = None,
                 shard_endpoints: Optional[List[str]] = None,
                 state_file: Optional[str] = None,
                 run_id: Optional[str] = None,
                 compact_every: Optional[int] = None,
                 skip_tail_commit_scan: bool = False):
        self.task_lease_sec = task_lease_sec
        self.heartbeat_ttl_sec = heartbeat_ttl_sec
        #: per-job shared secret, same contract as the native binary's
        #: EDL_COORD_TOKEN: empty/None disables auth; set, every client op
        #: except ping must present it (CoordinatorAuthError otherwise).
        self.auth_token = auth_token or ""
        self._lock = threading.RLock()
        self._barrier_cv = threading.Condition(self._lock)
        self._boot_monotonic = time.monotonic()
        self._epoch = 0
        self._next_rank = 0
        self._members: Dict[str, Dict] = {}  # name -> {rank, last_heartbeat}
        self._todo: deque = deque()
        self._leased: Dict[str, Dict] = {}  # task -> {worker, deadline}
        # Last acquire per worker: worker -> (req_id, task), so a retried
        # acquire (lost reply) gets the same lease back (native parity).
        self._acquire_cache: Dict[str, tuple] = {}
        self._done: Set[str] = set()
        self._barriers: Dict[str, Dict] = {}  # name -> {arrived, generation}
        self._sync_arrived: Set[str] = set()
        self._sync_generation = 0
        self._kv: Dict[str, str] = {}
        # Memory-resident checkpoint plane (native parity: op_shard_*).
        # owner -> {step, chunks, nbytes, group, data: {chunk: payload}}.
        # Volatile by design (the native store is not journaled either):
        # the blob-store checkpoint stays the durable tier, and member drop
        # does NOT clear an owner's blob — surviving a dead owner is the
        # whole point of the plane.
        self._shards: Dict[str, Dict] = {}
        self._shard_put_seen: Set[str] = set()
        self._shard_put_order: deque = deque()
        # Sharded-root twin (native --shards): with endpoints configured,
        # every keyspace op answers a redirect instead of being served —
        # EDL009 drives redirect-during-watch schedules through this.
        self._shard_endpoints: List[str] = list(shard_endpoints or [])
        self._shard_index = -1
        self._num_shards = 0
        # Watch subscriptions (native parity, worker-keyed instead of
        # fd-keyed): pending notification frames per subscriber, drained by
        # the shim's watch take path the way the wire server pushes them.
        self._watch_queues: Dict[str, deque] = {}
        # Pending advance-notice revocations (native parity: preempts_),
        # worker -> {notice_s, reason, seq}. Volatile by design — a
        # restarted coordinator forgets notices and the scheduler re-issues
        # them; consumed when the worker actually departs (_drop_member).
        self._preempts: Dict[str, Dict] = {}
        self._preempt_seq = 0
        # Test-only mutation hook: EDL009's model checker flips this on a
        # deliberately-broken twin to prove a dedup regression is caught.
        # Never set outside tests.
        self._test_disable_dedup = False
        # Native-parity status counters. Without a state file the journal
        # trio stays zero, but the fields must exist so status replies are
        # field-identical across backends (EDL007).
        self._ops_count = 0
        self._batch_frames = 0
        self._batch_subops = 0
        # State-file persistence twin (EDL010): a JSONL group-commit journal
        # mirroring the native server's — same record vocabulary (meta /
        # todo / done / lease / kv / kvdel), one frame per event-loop turn,
        # each frame closed by a {"k":"c"} commit-marker line. Recovery
        # replays only the committed prefix (everything after the last
        # marker is a torn tail and is truncated away), restores leases
        # under their holders, rebuilds the acquire req_id cache from the
        # journaled lease records, and bumps the epoch.
        self._state_file = state_file
        self._run_id = run_id or ""
        self._compact_every = compact_every
        # Test-only mutant hook (EDL010 teeth): skip the tail-commit scan
        # during recovery, replaying partial frames the way the journal
        # format's silent-skip predecessor did. Never set outside tests.
        self._skip_tail_commit_scan = skip_tail_commit_scan
        # Test-only crash hook: the next frame commit is dropped on the
        # floor (the on-disk effect of dying inside a snapshot write,
        # after the tmp write and before the rename).
        self._test_crash_before_commit = False
        self._pending_records: List[str] = []
        self._turn_depth = 0  # >0: a batch frame is open; defer commits
        self._fsyncs = 0
        self._snapshots = 0
        self._records_since = 0  # journal lines since last snapshot
        if self._state_file:
            self._load_state()

    # -- state-file persistence (the EDL010 twin journal) ----------------------

    def _record(self, obj: Dict) -> None:
        if self._state_file:
            self._pending_records.append(json.dumps(obj, sort_keys=True))

    def _record_epoch(self) -> None:
        self._record({"k": "meta", "epoch": self._epoch,
                      "run_id": self._run_id})

    def _record_todo(self, tasks: List[str]) -> None:
        if tasks:  # native parity: the empty list is not journaled
            self._record({"k": "todo", "tasks": list(tasks)})

    def _record_done(self, task: str) -> None:
        self._record({"k": "done", "tasks": [task]})

    def _record_lease(self, task: str, worker: str,
                      req_id: str = "") -> None:
        self._record({"k": "lease", "task": task, "worker": worker,
                      "req_id": req_id})

    def _record_kv(self, key: str) -> None:
        self._record({"k": "kv", "key": key,
                      "value": self._kv.get(key, "")})

    def _record_kv_del(self, key: str) -> None:
        self._record({"k": "kvdel", "key": key})

    def _append_frame(self, lines: List[str]) -> None:
        with open(self._state_file, "a", encoding="utf-8") as f:
            for line in lines:
                f.write(line + "\n")
            f.write('{"k": "c"}\n')  # the frame's commit marker
            f.flush()
        self._fsyncs += 1
        self._records_since += len(lines) + 1

    def _commit(self) -> None:
        """Group-commit the turn's records: one append + one fsync per
        event-loop turn — or a snapshot when past the compaction threshold
        (checked BEFORE appending, the native ``maybe_save_state`` shape;
        the snapshot covers the pending effects because in-memory state
        already has them)."""
        if not self._state_file or not self._pending_records:
            return
        if self._turn_depth > 0:
            return  # a batch frame is open: sub-op records ride it
        if self._test_crash_before_commit:
            # dying inside the snapshot write, before the rename: the
            # journal is untouched and the frame never reaches disk.
            self._test_crash_before_commit = False
            self._pending_records = []
            return
        pending = self._pending_records
        self._pending_records = []
        if (self._compact_every is not None
                and self._records_since >= self._compact_every):
            self._save_snapshot()
        else:
            self._append_frame(pending)

    def _save_snapshot(self) -> None:
        """Native ``save_snapshot`` layout: meta, todo (live queue order),
        one lease line per held lease (carrying the holder's cached req_id
        when it names this task), done, kv — tmp write + rename."""
        recs: List[Dict] = [{"k": "meta", "epoch": self._epoch,
                             "run_id": self._run_id}]
        if self._todo:
            recs.append({"k": "todo", "tasks": list(self._todo)})
        req_of = {}
        for w, (req, task) in self._acquire_cache.items():
            req_of[(task, w)] = req
        for task in sorted(self._leased):
            w = self._leased[task]["worker"]
            recs.append({"k": "lease", "task": task, "worker": w,
                         "req_id": req_of.get((task, w), "")})
        for task in sorted(self._done):
            recs.append({"k": "done", "tasks": [task]})
        for key in sorted(self._kv):
            recs.append({"k": "kv", "key": key, "value": self._kv[key]})
        tmp = self._state_file + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            for rec in recs:
                f.write(json.dumps(rec, sort_keys=True) + "\n")
            f.write('{"k": "c"}\n')
            f.flush()
        os.replace(tmp, self._state_file)
        self._snapshots += 1
        self._records_since = 0

    def _load_state(self) -> None:
        """Recovery replay (native ``load_state`` semantics): replay the
        committed journal prefix, restore leases under their holders,
        rebuild the acquire req_id dedup cache from the journaled lease
        records, bump the epoch (a restart IS a membership event), and
        truncate any torn tail away on disk."""
        try:
            with open(self._state_file, "r", encoding="utf-8") as f:
                raw = [ln for ln in f.read().splitlines() if ln.strip()]
        except OSError:
            self._boot_frame()
            return
        # Tail-commit scan: only the prefix up to the last {"k":"c"} marker
        # is durable; everything after it is a torn frame and must be
        # dropped WHOLE (all-or-nothing is the frame contract). Files from
        # the pre-marker format (no "c" records at all) are taken whole.
        committed = raw
        if not self._skip_tail_commit_scan:
            last_c = -1
            for i, line in enumerate(raw):
                try:
                    if json.loads(line).get("k") == "c":
                        last_c = i
                except ValueError:
                    continue
            if last_c >= 0:
                committed = raw[: last_c + 1]
        epoch = 0
        todo_order: List[str] = []
        seen: Set[str] = set()
        lease_of: Dict[str, str] = {}
        cache: Dict[str, tuple] = {}
        done: Set[str] = set()
        kv: Dict[str, str] = {}
        for line in committed:
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # mutant/legacy lane: unparseable lines skipped
            kind = rec.get("k")
            if kind == "meta":
                if self._run_id and rec.get("run_id") \
                        and rec["run_id"] != self._run_id:
                    # another run's journal: discard it, start fresh
                    open(self._state_file, "w", encoding="utf-8").close()
                    self._records_since = 0
                    self._boot_frame()
                    return
                epoch = int(rec.get("epoch", 0))
            elif kind == "todo":
                for t in rec.get("tasks", []):
                    if t not in seen:
                        seen.add(t)
                        todo_order.append(t)
            elif kind == "done":
                for t in rec.get("tasks", []):
                    done.add(t)
            elif kind == "lease":
                t, w = rec.get("task", ""), rec.get("worker", "")
                if t and t not in seen:  # a lease implies the task exists
                    seen.add(t)
                    todo_order.append(t)
                lease_of[t] = w
                if w and rec.get("req_id"):
                    cache[w] = (rec["req_id"], t)
            elif kind == "kv":
                kv[rec.get("key", "")] = rec.get("value", "")
            elif kind == "kvdel":
                kv.pop(rec.get("key", ""), None)
        if len(committed) != len(raw):
            with open(self._state_file, "w", encoding="utf-8") as f:
                for line in committed:
                    f.write(line + "\n")
        self._epoch = epoch + 1
        now = time.monotonic()
        self._todo = deque(
            t for t in todo_order if t not in done and not lease_of.get(t))
        self._leased = {
            t: {"worker": w, "deadline": now + self.task_lease_sec}
            for t, w in lease_of.items() if w and t not in done
        }
        self._done = set(done)
        self._kv = dict(kv)
        self._acquire_cache = dict(cache)
        self._records_since = len(committed)
        self._boot_frame()

    def _boot_frame(self) -> None:
        """A fresh incarnation's first frame: the epoch meta record. The
        native server queues it in load_state and flushes on the next
        turn; the twin flushes synchronously so the file always names the
        live epoch. Bypasses compaction (matching the model's recovery)."""
        self._record_epoch()
        lines = self._pending_records
        self._pending_records = []
        if lines:
            self._append_frame(lines)

    # -- expiry ---------------------------------------------------------------

    def _tick(self) -> None:
        now = time.monotonic()
        dead = [
            n for n, m in self._members.items()
            if m["last_heartbeat"] + self.heartbeat_ttl_sec <= now
        ]
        for name in dead:
            self._drop_member(name)
        expired = [t for t, l in self._leased.items() if l["deadline"] <= now]
        for t in expired:
            del self._leased[t]
            self._todo.append(t)
            self._record_lease(t, "", "")
        if dead or expired:
            # expiry is its own event-loop turn (native: tick()), so its
            # records commit as their own frame, not the caller op's.
            self._commit()

    def _drop_member(self, name: str) -> None:
        if name not in self._members:
            return
        del self._members[name]
        by_rank = sorted(self._members.items(), key=lambda kv: kv[1]["rank"])
        for r, (n, m) in enumerate(by_rank):
            m["rank"] = r
        self._next_rank = len(self._members)
        self._epoch += 1
        self._record_epoch()
        self._notify_watchers()
        self._requeue_worker_leases(name)
        self._acquire_cache.pop(name, None)
        # The departure a notice predicted has happened: the revocation is
        # consumed (a re-registered successor under this name is fresh).
        self._preempts.pop(name, None)
        self._release_sync()

    def _release_sync(self) -> None:
        """Membership moved: wake parked sync waiters so they resync."""
        self._sync_arrived = set()
        self._barrier_cv.notify_all()

    def _membership_reply(self, worker: str) -> Dict:
        m = self._members.get(worker)
        return {
            "ok": True,
            "rank": m["rank"] if m else -1,
            "epoch": self._epoch,
            "world": len(self._members),
        }

    # -- ops (mirror the C++ op_* handlers) -----------------------------------

    def register(self, worker: str, takeover: bool = False) -> Dict:
        with self._lock:
            self._tick()
            if not worker:
                # Same refusal as the native op_register: an anonymous member
                # could never be ranked or dropped.
                return {"ok": False, "error": "worker required",
                        "epoch": self._epoch}
            if takeover:
                # Incarnation boundary: leases held under this name belong
                # to a dead predecessor (same pod name, warm-restarted);
                # requeue them for replay — the successor's heartbeats would
                # otherwise renew them forever and rank 0 would deadlock on
                # its own stale leases. A plain refresh (takeover=False)
                # renews instead: a live mid-run re-register must not
                # forfeit shards it is training.
                self._requeue_worker_leases(worker)
            if worker not in self._members:
                self._members[worker] = {
                    "rank": self._next_rank,
                    "last_heartbeat": time.monotonic(),
                }
                self._next_rank += 1
                self._epoch += 1
                self._record_epoch()
                self._notify_watchers()
                self._release_sync()
            else:
                self._members[worker]["last_heartbeat"] = time.monotonic()
                self._renew_leases(worker)
            self._commit()
            return self._membership_reply(worker)

    def _requeue_worker_leases(self, worker: str) -> None:
        stale = [t for t, l in self._leased.items() if l["worker"] == worker]
        for t in stale:
            del self._leased[t]
            self._todo.append(t)
            self._record_lease(t, "", "")

    def _renew_leases(self, worker: str) -> None:
        """A live worker keeps its leases (etcd-keepalive semantics): renewal
        rides heartbeats, so completion-lag holds can outlive task_lease_sec
        without healthy runs retraining shards; expiry fires only when the
        heartbeat ALSO stopped — a real failure. Mirrors the C++ service."""
        deadline = time.monotonic() + self.task_lease_sec
        for lease in self._leased.values():
            if lease["worker"] == worker:
                lease["deadline"] = deadline

    def heartbeat(self, worker: str) -> Dict:
        with self._lock:
            self._tick()
            if worker not in self._members:
                return {"ok": False, "error": "unknown worker", "epoch": self._epoch}
            self._members[worker]["last_heartbeat"] = time.monotonic()
            self._renew_leases(worker)
            return self._membership_reply(worker)

    def leave(self, worker: str) -> Dict:
        with self._lock:
            self._tick()
            self._drop_member(worker)
            self._commit()
            return {"ok": True, "epoch": self._epoch}

    def members(self) -> List[str]:
        with self._lock:
            self._tick()
            return [
                n for n, _ in sorted(
                    self._members.items(), key=lambda kv: kv[1]["rank"]
                )
            ]

    def epoch(self) -> int:
        with self._lock:
            self._tick()
            return self._epoch

    def add_tasks(self, tasks: List[str]) -> int:
        with self._lock:
            self._tick()
            added = 0
            fresh: List[str] = []
            for t in tasks:
                if t in self._done or t in self._leased or t in self._todo:
                    continue
                self._todo.append(t)
                fresh.append(t)
                added += 1
            self._record_todo(fresh)
            self._commit()
            return added

    def acquire(self, worker: str, req_id: Optional[str] = None) -> Dict:
        with self._lock:
            self._tick()
            # Dedup (native parity): a retried acquire with the same req_id
            # returns the existing lease instead of popping a second task.
            if req_id and not self._test_disable_dedup:
                cached = self._acquire_cache.get(worker)
                if cached and cached[0] == req_id:
                    lease = self._leased.get(cached[1])
                    if lease and lease["worker"] == worker:
                        lease["deadline"] = time.monotonic() + self.task_lease_sec
                        return {"ok": True, "task": cached[1],
                                "lease_sec": self.task_lease_sec,
                                "duplicate": True}
            if not self._todo:
                return {"ok": True, "task": None, "exhausted": not self._leased}
            task = self._todo.popleft()
            self._leased[task] = {
                "worker": worker,
                "deadline": time.monotonic() + self.task_lease_sec,
            }
            if req_id:
                self._acquire_cache[worker] = (req_id, task)
            # The req_id rides the lease record (the EDL010 durability fix:
            # an unjournaled dedup cache would hand a retried acquire a
            # SECOND task after restart — an exactly-once violation).
            self._record_lease(task, worker, req_id or "")
            self._commit()
            return {"ok": True, "task": task, "lease_sec": self.task_lease_sec}

    def acquire_task(self, worker: str) -> Optional[str]:
        return self.acquire(worker).get("task")

    def complete_task(self, worker: str, task: str) -> Dict:
        with self._lock:
            self._tick()
            # Idempotent (native parity): replayed completions are success.
            if task in self._done:
                return {"ok": True, "duplicate": True,
                        "done": len(self._done), "queued": len(self._todo)}
            if task not in self._leased:
                # Requeued-but-unleased after an outage: the completer holds
                # a durable covering checkpoint, so accept rather than
                # retrain. Unknown tasks stay an error.
                if task in self._todo:
                    self._todo.remove(task)
                    self._done.add(task)
                    self._record_done(task)
                    self._commit()
                    return {"ok": True, "requeued": True,
                            "done": len(self._done), "queued": len(self._todo)}
                return {"ok": False, "error": "not leased"}
            if self._leased[task]["worker"] != worker:
                return {"ok": False, "error": "lease not owned"}
            del self._leased[task]
            self._done.add(task)
            self._record_done(task)
            self._commit()
            return {"ok": True, "done": len(self._done), "queued": len(self._todo)}

    def fail_task(self, worker: str, task: str) -> Dict:
        with self._lock:
            self._tick()
            if task not in self._leased:
                return {"ok": False, "error": "not leased"}
            if self._leased[task]["worker"] != worker:
                return {"ok": False, "error": "lease not owned"}
            del self._leased[task]
            self._todo.append(task)
            self._record_lease(task, "", "")
            self._commit()
            return {"ok": True}

    def barrier(self, worker: str, name: str, count: int, timeout: float = 120.0) -> Dict:
        with self._barrier_cv:
            b = self._barriers.setdefault(
                name, {"arrived": set(), "generation": 0, "want": 0}
            )
            if not b["arrived"]:
                # First arrival of a cycle fixes the count; later arrivals
                # must agree (mirrors the native server — last-writer-wins
                # would let mismatched cohorts release each other).
                b["want"] = count
            elif count != b.get("want"):
                return {"ok": False, "error": "barrier count mismatch",
                        "want": b["want"]}
            gen = b["generation"]
            b["arrived"].add(worker)
            if len(b["arrived"]) >= b["want"]:
                b["generation"] += 1
                b["arrived"] = set()
                self._barrier_cv.notify_all()
                return {"ok": True, "barrier": name, "generation": gen}
            deadline = time.monotonic() + timeout
            while b["generation"] == gen:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    b["arrived"].discard(worker)
                    return {"ok": False, "error": "barrier timeout"}
                self._barrier_cv.wait(remaining)
            return {"ok": True, "barrier": name, "generation": gen}

    def sync(self, worker: str, epoch: int, timeout: float = 60.0) -> Dict:
        """Epoch-synchronized rendezvous: released when every current member
        arrives at ``epoch``; membership movement releases with resync=True."""
        with self._barrier_cv:
            self._tick()
            if worker not in self._members:
                return {"ok": False, "error": "unknown worker",
                        "epoch": self._epoch, "world": len(self._members)}
            self._members[worker]["last_heartbeat"] = time.monotonic()
            self._renew_leases(worker)
            if epoch != self._epoch:
                return {"ok": False, "resync": True,
                        "epoch": self._epoch, "world": len(self._members)}
            self._sync_arrived.add(worker)
            if self._sync_arrived >= set(self._members):
                self._sync_generation += 1
                self._sync_arrived = set()
                self._barrier_cv.notify_all()
                return {"ok": True, "epoch": self._epoch, "world": len(self._members)}
            gen = self._sync_generation
            deadline = time.monotonic() + timeout
            while gen == self._sync_generation and epoch == self._epoch:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self._sync_arrived.discard(worker)
                    return {"ok": False, "error": "timeout",
                            "epoch": self._epoch, "world": len(self._members)}
                self._barrier_cv.wait(remaining)
            if epoch != self._epoch:
                return {"ok": False, "resync": True,
                        "epoch": self._epoch, "world": len(self._members)}
            return {"ok": True, "epoch": self._epoch, "world": len(self._members)}

    def bump_epoch(self) -> Dict:
        """Control-plane membership nudge (matches the C++ op_bump_epoch):
        parked sync waiters resync so workers observe a rescale immediately."""
        with self._barrier_cv:
            self._epoch += 1
            self._record_epoch()
            self._notify_watchers()
            self._release_sync()
            self._commit()
            return {"ok": True, "epoch": self._epoch}

    def kv_put(self, key: str, value: str) -> None:
        with self._lock:
            self._kv[key] = value
            self._record_kv(key)
            self._commit()

    def kv_get(self, key: str) -> Optional[str]:
        with self._lock:
            return self._kv.get(key)

    def kv_del(self, key: str) -> None:
        with self._lock:
            if key in self._kv:  # native parity: a no-op del is not journaled
                del self._kv[key]
                self._record_kv_del(key)
                self._commit()

    def kv_incr(self, key: str, delta: int = 1,
                op_id: Optional[str] = None) -> int:
        reply = self.kv_incr_reply(key, delta, op_id=op_id)
        if not reply["ok"]:
            raise ValueError(reply["error"])
        return int(reply["value"])

    def kv_incr_reply(self, key: str, delta: int = 1,
                      op_id: Optional[str] = None) -> Dict:
        """Atomic counter with the full native op_kv_incr reply surface:
        read-modify-write under the lock, so concurrent failure-count bumps
        cannot be lost; ``op_id`` dedups replayed increments exactly-once
        (native parity: the marker lives in the KV namespace) and a replay
        reports ``duplicate`` alongside the previously-returned value."""
        with self._lock:
            if not key:
                return {"ok": False, "error": "key required"}
            marker = f"__edl_op/{op_id}" if op_id else None
            if marker and marker in self._kv and not self._test_disable_dedup:
                return {"ok": True, "value": int(self._kv[marker]),
                        "duplicate": True}
            try:
                cur = int(self._kv.get(key, "0") or "0") + int(delta)
            except ValueError:
                return {"ok": False, "error": "value not an integer"}
            self._kv[key] = str(cur)
            self._record_kv(key)
            if marker:
                self._kv[marker] = str(cur)
                self._record_kv(marker)
            # value + marker share one frame: both durable or neither —
            # a partially-replayed frame here is exactly the torn-tail
            # double-apply EDL010's torn schedule hunts.
            self._commit()
            return {"ok": True, "value": cur}

    #: put_id dedup markers kept (FIFO) before the oldest is forgotten —
    #: mirrors the native kShardPutSeenCap.
    SHARD_PUT_SEEN_CAP = 4096

    def shard_put(self, owner: str, step: int, chunk: int, chunks: int,
                  nbytes: int = 0, data: str = "",
                  put_id: Optional[str] = None,
                  group: Optional[List[str]] = None) -> Dict:
        """Checkpoint-plane replication (native op_shard_put): store one
        chunk of an owner's ZeRO-1 shard; latest step supersedes; ``put_id``
        dedups replayed puts exactly-once (marked seen only after a
        successful apply, so duplicate implies the original landed)."""
        with self._lock:
            if not owner or step < 0 or chunks < 1 or not 0 <= chunk < chunks:
                return {"ok": False,
                        "error": "shard_put requires owner, step>=0, "
                                 "0<=chunk<chunks"}
            if put_id and put_id in self._shard_put_seen \
                    and not self._test_disable_dedup:
                return {"ok": True, "duplicate": True, "stored": True}
            blob = self._shards.setdefault(
                owner, {"step": -1, "chunks": 0, "nbytes": 0,
                        "group": [], "data": {}})
            if step < blob["step"]:
                # Stale chunk racing a newer replication pass: not stored,
                # not an error.
                return {"ok": True, "duplicate": False, "stored": False}
            if step > blob["step"]:
                blob["step"] = step
                blob["data"] = {}
                blob["group"] = []
            blob["chunks"] = int(chunks)
            blob["nbytes"] = int(nbytes)
            if isinstance(group, list):
                blob["group"] = [str(g) for g in group]
            blob["data"][int(chunk)] = data
            if put_id:
                self._shard_put_seen.add(put_id)
                self._shard_put_order.append(put_id)
                if len(self._shard_put_order) > self.SHARD_PUT_SEEN_CAP:
                    self._shard_put_seen.discard(
                        self._shard_put_order.popleft())
            return {"ok": True, "duplicate": False, "stored": True}

    def shard_get(self, owner: str, step: int = -1, chunk: int = 0) -> Dict:
        """Recovery fetch (native op_shard_get): one chunk of a possibly-dead
        owner's replicated shard. step<0 means latest; a specific step must
        match exactly so a restorer never mixes replication passes."""
        with self._lock:
            blob = self._shards.get(owner)
            if blob is None or (step >= 0 and blob["step"] != step):
                return {"ok": True, "found": False, "data": "", "chunks": 0}
            payload = blob["data"].get(int(chunk))
            if payload is None:
                return {"ok": True, "found": False, "data": "",
                        "chunks": int(blob["chunks"])}
            return {"ok": True, "found": True, "data": payload,
                    "chunks": int(blob["chunks"])}

    def shard_meta(self, owner: str) -> Dict:
        """Plane inventory for one owner (native op_shard_meta):
        complete=True only when every chunk of the latest step is present —
        the restorer's go/no-go before pulling chunks."""
        with self._lock:
            blob = self._shards.get(owner)
            if blob is None or blob["step"] < 0:
                return {"ok": True, "found": False, "step": -1, "chunks": 0,
                        "nbytes": 0, "complete": False, "group": []}
            complete = blob["chunks"] > 0 \
                and len(blob["data"]) == blob["chunks"]
            return {"ok": True, "found": True, "step": int(blob["step"]),
                    "chunks": int(blob["chunks"]),
                    "nbytes": int(blob["nbytes"]), "complete": complete,
                    "group": list(blob["group"])}

    def shard_drop(self, owner: str, step: int = -1) -> Dict:
        """Epoch/placement invalidation (native op_shard_drop): step<0 drops
        unconditionally; step>=0 only if the plane holds exactly that step,
        so a drop racing a newer put cannot destroy the newer blob."""
        with self._lock:
            blob = self._shards.get(owner)
            dropped = False
            if blob is not None and (step < 0 or blob["step"] == step):
                del self._shards[owner]
                dropped = True
            return {"ok": True, "dropped": dropped}

    # -- push notifications (native parity: op_watch / push_notify) ------------

    def _notify_frame(self, e: int) -> Dict:
        return {"ok": True, "notify": "epoch", "epoch": int(e),
                "cursor": int(e), "world": len(self._members)}

    def _notify_watchers(self) -> None:
        """Epoch moved: queue one notification frame per subscription (the
        wire server pushes the same frame to every watcher fd)."""
        for q in self._watch_queues.values():
            q.append(self._notify_frame(self._epoch))

    def _preempt_frame(self, worker: str) -> Dict:
        """Targeted revocation frame (native push_preempt): no wall clock —
        the client anchors the drain deadline to its own monotonic arrival
        time plus notice_s, so clock skew never shortens the budget."""
        p = self._preempts[worker]
        return {"ok": True, "notify": "preempt", "worker": worker,
                "notice_s": p["notice_s"], "reason": p["reason"],
                "seq": p["seq"], "epoch": self._epoch,
                "cursor": self._epoch, "world": len(self._members)}

    def preempt_notice(self, targets: List[str], notice_s: float = 0.0,
                       reason: str = "") -> Dict:
        """Advance-notice revocation (native op_preempt_notice): record the
        pending notice per target and push a targeted frame to the target's
        subscription. No membership change here — the drain the notice
        triggers ends in leave/_drop_member like any departure."""
        with self._lock:
            self._tick()
            if not isinstance(targets, list) or not targets:
                return {"ok": False, "error": "targets array required"}
            revoked: List[str] = []
            for t in targets:
                t = str(t)
                self._preempt_seq += 1
                self._preempts[t] = {"notice_s": float(notice_s),
                                     "reason": reason or "preempt",
                                     "seq": self._preempt_seq}
                q = self._watch_queues.get(t)
                if q is not None:
                    q.append(self._preempt_frame(t))
                revoked.append(t)
            return {"ok": True, "revoked": revoked}

    def watch(self, worker: str, cursor: int = -1) -> Dict:
        """Subscribe ``worker`` to epoch-change notifications. cursor >= 0
        resumes after a reconnect: every epoch in (cursor, current] is
        queued exactly once, in order, before the ack — native parity with
        op_watch's deferred replay."""
        with self._lock:
            self._tick()
            q = self._watch_queues.setdefault(worker or "", deque())
            if cursor >= 0:
                for e in range(int(cursor) + 1, self._epoch + 1):
                    q.append(self._notify_frame(e))
            # A notice posted before this subscription is replayed (native
            # parity) — at-least-once delivery; clients dedup on seq.
            if worker in self._preempts:
                q.append(self._preempt_frame(worker))
            return {"ok": True, "watch": True, "cursor": self._epoch,
                    "epoch": self._epoch}

    def watch_take(self, worker: str) -> Dict:
        """Drain one pending notification frame — the in-process stand-in
        for the wire server's unsolicited push (a poll, because a hermetic
        twin has no socket to write to). Empty queue answers notify=None."""
        with self._lock:
            q = self._watch_queues.get(worker or "")
            if not q:
                return {"ok": True, "notify": None, "cursor": self._epoch,
                        "world": len(self._members)}
            frame = q.popleft()
            if frame.get("notify") == "preempt":
                # Rebuilt as a literal rather than aliased: takers must not
                # be able to mutate queued history, and the wire-parity
                # checker reads the reply vocabulary from this shape.
                return {"ok": True, "notify": "preempt",
                        "worker": frame["worker"],
                        "notice_s": frame["notice_s"],
                        "reason": frame["reason"], "seq": frame["seq"],
                        "epoch": frame["epoch"], "cursor": frame["cursor"],
                        "world": frame["world"]}
            return frame

    def watch_cancel(self, worker: str) -> Dict:
        with self._lock:
            cancelled = (worker or "") in self._watch_queues
            self._watch_queues.pop(worker or "", None)
            return {"ok": True, "cancelled": cancelled}

    # -- shard routing (native parity: redirect_reply / op_shard_map) ----------

    def redirect_for(self, key: str) -> Optional[Dict]:
        """Redirect reply when this twin plays a sharded ROOT (endpoints
        configured); None on a plain coordinator — so every keyspace shim
        branch can guard with ``redirect_for(key) or <serve>``."""
        with self._lock:
            if not self._shard_endpoints:
                return None
            s = shard_of(str(key), len(self._shard_endpoints))
            return {"ok": False, "error": "wrong shard",
                    "redirect": self._shard_endpoints[s], "shard": s}

    def shard_map(self) -> Dict:
        with self._lock:
            n = len(self._shard_endpoints) if self._shard_endpoints \
                else self._num_shards
            return {"ok": True, "root": bool(self._shard_endpoints),
                    "nshards": n, "shards": list(self._shard_endpoints),
                    "shard_index": self._shard_index}

    # -- reply-shaped helpers for the wire shim --------------------------------

    def kv_put_reply(self, key: str, value: str) -> Dict:
        with self._lock:
            if not key:
                return {"ok": False, "error": "key required"}
            self._kv[key] = value
            self._record_kv(key)
            self._commit()
            return {"ok": True}

    def kv_del_reply(self, key: str) -> Dict:
        with self._lock:
            if key in self._kv:
                del self._kv[key]
                self._record_kv_del(key)
                self._commit()
            return {"ok": True}

    def add_tasks_reply(self, tasks: List[str]) -> Dict:
        with self._lock:
            added = self.add_tasks(tasks)
            return {"ok": True, "added": added, "queued": len(self._todo)}

    def status(self) -> Dict:
        with self._lock:
            self._tick()
            holders: Dict[str, int] = {}
            for lease in self._leased.values():
                holders[lease["worker"]] = holders.get(lease["worker"], 0) + 1
            return {
                "ok": True,
                "epoch": self._epoch,
                "world": len(self._members),
                "queued": len(self._todo),
                "leased": len(self._leased),
                "done": len(self._done),
                # Wire-parity counters: ops/batch counts are real; the
                # journal trio is real when a state file is configured and
                # structurally zero otherwise (no disk in-process), and
                # "turns" mirrors ops — every op is its own event-loop turn.
                "ops": self._ops_count,
                "batch_frames": self._batch_frames,
                "batch_subops": self._batch_subops,
                "fsyncs": self._fsyncs,
                "snapshots": self._snapshots,
                "journal_records": self._records_since,
                "turns": self._ops_count,
                "uptime_seconds": time.monotonic() - self._boot_monotonic,
                # native-parity encoding: flat "worker=count" strings (the
                # wire writer has no nested objects, so neither do we).
                "lease_holders": sorted(
                    f"{w}={n}" for w, n in holders.items()
                ),
                # pending revocations, same flat encoding; notice_s is
                # integer-truncated to match the native formatting.
                "preempts": sorted(
                    f"{w}={int(p['notice_s'])}"
                    for w, p in self._preempts.items()
                ),
            }

    def ping(self) -> bool:
        return True

    def queued_count(self) -> int:
        with self._lock:
            return len(self._todo)

    def note_batch(self, subops: int) -> None:
        """Batch-frame accounting from the client shim (native parity: the
        server counts frames/sub-ops itself; in-process the framing lives in
        InProcessClient.call_batch, so it reports here)."""
        with self._lock:
            self._batch_frames += 1
            self._batch_subops += subops

    # -- client-compatible facade ---------------------------------------------

    def client(self, worker: str = "",
               token: Optional[str] = None) -> "InProcessClient":
        # None = "use the coordinator's own token": the common single-
        # process case (both ends in one pod share EDL_COORD_TOKEN).
        # Tests pass an explicit wrong/empty token for the negative path.
        return InProcessClient(
            self, worker, self.auth_token if token is None else token
        )

    def authorize(self, token: str) -> None:
        """The wire twin's auth gate (native: coordinator.cc handle())."""
        with self._lock:
            self._ops_count += 1
        if self.auth_token and token != self.auth_token:
            from edl_tpu.coordinator.client import CoordinatorAuthError

            raise CoordinatorAuthError(
                "coordinator rejected call: bad or missing token"
            )


class InProcessClient:
    """Same method surface as CoordinatorClient, bound to one worker name.

    Auth mirrors the native wire: every op except ping passes through the
    coordinator's token gate before touching state.
    """

    def __init__(self, coord: InProcessCoordinator, worker: str,
                 token: str = ""):
        self._c = coord
        self.worker = worker
        self.token = token
        #: coalesced-epoch surface, mirroring CoordinatorClient: workers
        #: read these instead of issuing dedicated epoch polls. In-process
        #: there is no wire to save, but the attributes keep worker code
        #: backend-agnostic.
        self.observed_epoch: Optional[int] = None
        self.observed_epoch_at: float = 0.0
        self.last_membership: Optional[Dict] = None
        self.last_membership_at: float = 0.0
        self.piggyback_heartbeat: float = 0.0
        self.retry_count = 0
        #: per-client nonce for shard_put dedup ids (CoordinatorClient
        #: parity: a fresh client can never replay a predecessor's markers).
        self._nonce = uuid.uuid4().hex[:8]
        self._put_seq = 0

    def _auth(self) -> None:
        self._c.authorize(self.token)

    def _note_reply(self, reply):
        if isinstance(reply, dict) and "epoch" in reply:
            now = time.monotonic()
            self.observed_epoch = int(reply["epoch"])
            self.observed_epoch_at = now
            if reply.get("ok") and "rank" in reply and "world" in reply:
                self.last_membership = dict(reply)
                self.last_membership_at = now
        return reply

    def close(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        pass

    def register(self, takeover: bool = False):
        self._auth()
        return self._note_reply(self._c.register(self.worker, takeover=takeover))

    def heartbeat(self):
        self._auth()
        return self._note_reply(self._c.heartbeat(self.worker))

    def leave(self):
        self._auth()
        return self._c.leave(self.worker)

    def members(self):
        self._auth()
        return self._c.members()

    def epoch(self):
        self._auth()
        return self._c.epoch()

    def add_tasks(self, tasks):
        self._auth()
        return self._c.add_tasks(tasks)

    def acquire_task(self):
        self._auth()
        return self._c.acquire_task(self.worker)

    def acquire(self, req_id=None):
        self._auth()
        return self._c.acquire(self.worker, req_id=req_id)

    def complete_task(self, task):
        self._auth()
        return self._c.complete_task(self.worker, task)

    def fail_task(self, task):
        self._auth()
        return self._c.fail_task(self.worker, task)

    def barrier(self, name, count, timeout=120.0):
        self._auth()
        return self._c.barrier(self.worker, name, count, timeout)

    def sync(self, epoch, timeout=60.0):
        self._auth()
        return self._c.sync(self.worker, epoch, timeout)

    def bump_epoch(self):
        self._auth()
        # int, matching CoordinatorClient.bump_epoch's unwrapped return.
        return int(self._c.bump_epoch()["epoch"])

    def preempt_notice(self, targets, notice_s=30.0, reason="preempt"):
        # list of revoked names, matching CoordinatorClient.preempt_notice's
        # unwrapped return (the straggler detector and chaos scenarios call
        # this surface generically across both transports).
        self._auth()
        return list(self.call("preempt_notice", targets=list(targets),
                              notice_s=float(notice_s),
                              reason=str(reason)).get("revoked", []))

    def kv_put(self, key, value):
        self._auth()
        return self._c.kv_put(key, value)

    def kv_get(self, key):
        self._auth()
        return self._c.kv_get(key)

    def kv_del(self, key):
        self._auth()
        return self._c.kv_del(key)

    def kv_incr(self, key, delta=1):
        self._auth()
        return self._c.kv_incr(key, delta)

    # -- checkpoint plane ------------------------------------------------------

    def shard_put(self, owner, step, chunk, chunks, data, nbytes=0,
                  group=None, put_id=None):
        """Convenience mirror of CoordinatorClient.shard_put: auto-generates
        a per-client put_id when none is given, so bare retries dedup."""
        self._auth()
        if put_id is None:
            put_id = self._next_put_id()
        return self._c.shard_put(owner, int(step), int(chunk), int(chunks),
                                 nbytes=int(nbytes), data=data,
                                 put_id=put_id, group=group)

    def shard_get(self, owner, step=-1, chunk=0):
        self._auth()
        return self._c.shard_get(owner, int(step), int(chunk))

    def shard_meta(self, owner):
        self._auth()
        return self._c.shard_meta(owner)

    def shard_drop(self, owner, step=-1):
        self._auth()
        return self._c.shard_drop(owner, int(step))

    def _next_put_id(self):
        self._put_seq += 1
        return f"{self._nonce}.p{self._put_seq}"

    def _stamp(self, reply):
        """Mirror of the native handle()'s stamp_epoch: every reply carries
        the membership epoch, so clients coalesce epoch observation off any
        traffic (wire parity: EDL007 checks both sides stamp)."""
        reply = dict(reply)
        reply.setdefault("epoch", self._c.epoch())
        return reply

    def call(self, op, timeout=None, **fields):
        """Wire-call shim covering the native dispatch table op-for-op (the
        outbox replays through this); replies are field-identical to the
        C++ server's, including the epoch stamp — EDL007 diffs them."""
        if op == "ping":  # native parity: ping bypasses the token gate
            return self._stamp({"ok": True, "pong": True})
        self._auth()
        if op == "register":
            return self._note_reply(self._c.register(
                self.worker, takeover=bool(fields.get("takeover"))))
        if op == "heartbeat":
            return self._note_reply(self._c.heartbeat(self.worker))
        if op == "leave":
            return self._c.leave(self.worker)
        if op == "members":
            return self._stamp({"ok": True, "members": self._c.members()})
        # Keyspace ops guard with ``redirect_for(key) or <serve>`` — exactly
        # the native handlers' shard-root redirect placement: None (plain
        # coordinator) falls through to serving; a configured root answers
        # the redirect before any validation, same as the C++ order.
        if op == "complete_task":
            return self._stamp(
                self._c.redirect_for(fields["task"])
                or self._c.complete_task(self.worker, fields["task"]))
        if op == "fail_task":
            return self._stamp(
                self._c.redirect_for(fields["task"])
                or self._c.fail_task(self.worker, fields["task"]))
        if op == "kv_put":
            return self._stamp(
                self._c.redirect_for(fields.get("key", ""))
                or self._c.kv_put_reply(fields.get("key", ""),
                                        fields.get("value", "")))
        if op == "kv_incr":
            return self._stamp(
                self._c.redirect_for(fields.get("key", ""))
                or self._c.kv_incr_reply(
                    fields.get("key", ""), fields.get("delta", 1),
                    op_id=fields.get("op_id")))
        if op == "shard_put":
            return self._stamp(
                self._c.redirect_for(fields.get("owner", ""))
                or self._c.shard_put(
                    fields.get("owner", ""), int(fields.get("step", -1)),
                    int(fields.get("chunk", -1)),
                    int(fields.get("chunks", 0)),
                    nbytes=int(fields.get("nbytes", 0)),
                    data=fields.get("data", ""),
                    put_id=fields.get("put_id"), group=fields.get("group")))
        if op == "shard_get":
            return self._stamp(
                self._c.redirect_for(fields.get("owner", ""))
                or self._c.shard_get(
                    fields.get("owner", ""), int(fields.get("step", -1)),
                    int(fields.get("chunk", 0))))
        if op == "shard_meta":
            return self._stamp(
                self._c.redirect_for(fields.get("owner", ""))
                or self._c.shard_meta(fields.get("owner", "")))
        if op == "shard_drop":
            return self._stamp(
                self._c.redirect_for(fields.get("owner", ""))
                or self._c.shard_drop(
                    fields.get("owner", ""), int(fields.get("step", -1))))
        if op == "kv_get":
            return self._stamp(
                self._c.redirect_for(fields.get("key", ""))
                or {"ok": True, "value": self._c.kv_get(fields["key"])})
        if op == "kv_del":
            return self._stamp(
                self._c.redirect_for(fields.get("key", ""))
                or self._c.kv_del_reply(fields.get("key", "")))
        if op == "acquire_task":
            return self._stamp(
                self._c.redirect_for(self.worker)
                or self._c.acquire(self.worker, req_id=fields.get("req_id")))
        if op == "add_tasks":
            tasks = fields.get("tasks")
            if not isinstance(tasks, list):
                return self._stamp(
                    self._c.redirect_for("")
                    or {"ok": False, "error": "tasks array required"})
            return self._stamp(
                self._c.redirect_for(str(tasks[0]) if tasks else "")
                or self._c.add_tasks_reply(tasks))
        if op == "barrier":
            return self._stamp(self._c.barrier(
                self.worker, fields["name"], int(fields["count"]),
                timeout if timeout is not None else 120.0))
        if op == "sync":
            return self._stamp(self._c.sync(
                self.worker, int(fields["epoch"]),
                timeout if timeout is not None else 60.0))
        if op == "bump_epoch":
            return self._c.bump_epoch()
        if op == "preempt_notice":
            return self._stamp(self._c.preempt_notice(
                fields.get("targets"),
                notice_s=float(fields.get("notice_s", 0) or 0),
                reason=fields.get("reason", "")))
        if op == "status":
            return self._c.status()
        if op == "watch":
            if fields.get("take"):
                # In-process delivery: drain one pushed frame — the wire
                # server writes these unsolicited to the subscriber's fd,
                # a hermetic twin has no socket so the model polls instead.
                return self._stamp(self._c.watch_take(self.worker))
            return self._stamp(self._c.watch(
                self.worker, int(fields.get("cursor", -1))))
        if op == "watch_cancel":
            return self._stamp(self._c.watch_cancel(self.worker))
        if op == "shard_map":
            return self._stamp(self._c.shard_map())
        if op == "batch":
            ops_arg = fields.get("ops")
            if not isinstance(ops_arg, list):
                return self._stamp({"ok": False, "error": "ops array required"})
            return self._stamp(
                {"ok": True, "replies": self.call_batch(ops_arg, timeout=timeout)})
        raise ValueError(f"unsupported in-process op {op!r}")

    def call_batch(self, ops, timeout=None):
        """Batched-frame parity with CoordinatorClient.call_batch: the same
        per-sub-op reply list, driven through the shim — so the outbox's
        batched replay and worker piggyback paths run identically against
        the hermetic twin. Sub-op semantics (dedup ids, idempotence) are
        the coordinator's own; framing adds nothing in-process. Accepts the
        wire encoding too (JSON strings with an "op" key)."""
        self._c.note_batch(len(ops))
        # One frame per batch (native parity: the whole batch is one
        # event-loop turn): sub-op records accumulate and group-commit
        # together when the frame closes.
        with self._c._lock:
            self._c._turn_depth += 1
        try:
            replies = self._call_batch_inner(ops, timeout)
        finally:
            with self._c._lock:
                self._c._turn_depth -= 1
                self._c._commit()
        return replies

    def _call_batch_inner(self, ops, timeout=None):
        replies = []
        for item in ops:
            if isinstance(item, str):
                try:
                    fields = json.loads(item)
                except (ValueError, TypeError):
                    replies.append({"ok": False, "error": "bad json"})
                    continue
                op = fields.pop("op", "")
            elif isinstance(item, dict):
                fields = dict(item)
                op = fields.pop("op", "")
            else:
                op, fields = item
                fields = dict(fields)
            if op in ("batch", "barrier", "sync", "watch"):
                replies.append(
                    {"ok": False, "error": f"op not batchable: {op}"})
                continue
            replies.append(self.call(op, timeout=timeout, **fields))
        return replies

    def status(self):
        self._auth()
        return self._c.status()

    def shard_map(self):
        """CoordinatorClient.shard_map parity: the twin's partition layout."""
        return self.call("shard_map")

    def ping(self):
        return True
