"""Retry policy for coordinator RPCs: backoff, jitter, per-call deadline.

The reference tolerates etcd/master blips implicitly — etcd clients retry
and the trainer's task loop just sees an empty queue until the lease
machinery recovers. Our coordinator client historically crashed the worker
on the first transport error instead. This module is the typed core of the
fix: a small, immutable policy object the client consults on every call.

Error taxonomy (see client.py for the exception types):

- ``CoordinatorAuthError`` — fatal. The pod's token disagrees with the
  job's; retrying cannot help and would mask a deployment bug.
- ``CoordinatorTimeout`` — an *outcome*, not a transport failure. The
  request may have been processed (a barrier arrival, a lease grant whose
  reply was slow); blindly re-sending would break request/reply pairing
  semantics for rendezvous ops. Callers that can re-issue safely do so at
  their own layer (LeaseReader, rendezvous loops).
- ``CoordinatorUnreachable`` — connect refused / reset / closed. The
  retryable class: the client re-dials with exponential backoff until the
  policy deadline, then surfaces ``CoordinatorUnreachable`` so degraded-mode
  callers (outbox, park logic) can take over.

Jitter is seeded so chaos tests replay identical schedules.
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Iterator, Optional

__all__ = ["RetryPolicy", "DEFAULT_RETRY"]


@dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff + jitter + per-call deadline.

    ``deadline`` bounds the total time one logical ``call()`` may spend
    across attempts (first try included). It is a *per-call* budget, not
    per-attempt: a worker in its heartbeat loop sees a failure within
    ``deadline`` seconds and can drop to degraded mode instead of hanging.
    """

    #: max seconds one call may spend retrying before raising.
    deadline: float = 20.0
    #: first backoff sleep, seconds.
    initial_backoff: float = 0.05
    #: backoff ceiling, seconds.
    max_backoff: float = 2.0
    #: backoff growth factor per attempt.
    multiplier: float = 2.0
    #: +/- fraction of each sleep randomized (0.5 -> 50%..150% of nominal).
    jitter: float = 0.5
    #: seed for the jitter stream; None draws from the global RNG. Chaos
    #: tests pin this so failure schedules replay byte-identically.
    seed: Optional[int] = None

    def sleeps(self) -> Iterator[float]:
        """Infinite stream of backoff sleeps (jittered, monotone-capped)."""
        rng = random.Random(self.seed)
        backoff = self.initial_backoff
        while True:
            spread = 1.0 + self.jitter * (2.0 * rng.random() - 1.0)
            yield max(0.0, backoff * spread)
            backoff = min(self.max_backoff, backoff * self.multiplier)


#: The client default: ~20 s of re-dialing covers a coordinator restart
#: (state-file reload is sub-second; process supervision adds a few) while
#: staying inside ROADMAP's <30 s recovery budget.
DEFAULT_RETRY = RetryPolicy()
