"""Push-based epoch discovery: the client side of the ``watch`` wire op.

A subscription rides a DEDICATED connection: `CoordinatorClient` pairs
replies to requests by ordering on one socket, so unsolicited notification
frames pushed by the coordinator cannot share it. The coordinator pushes
one frame per epoch bump (``{"ok":true,"notify":"epoch","epoch":N,...}``)
the moment the bump happens — a rescale reaches the worker in one RTT
instead of a heartbeat period.

Resume semantics: the subscribe request carries ``cursor`` (the last epoch
this worker observed); the coordinator replays every missed epoch in
``(cursor, current]`` before acking, so a SIGKILL + restart of either side
loses nothing. The client additionally dedups client-side — delivery is
at-least-once across reconnects, observation is exactly-once because only
epochs strictly above ``last_epoch`` are surfaced.

Degradation: any transport failure just flips ``connected`` off; callers
keep their pull path (heartbeat-piggybacked `observed_epoch`) as the
liveness fallback and `poll()` re-subscribes with bounded backoff.
"""

from __future__ import annotations

import json
import os
import socket
import time
from typing import Dict, List, Optional, Tuple

from edl_tpu.coordinator.client import CoordinatorAuthError


class EpochWatch:
    """One epoch-change subscription with a resume cursor.

    Not thread-safe: owned by the worker loop that polls it. ``poll()``
    returns ``(epoch, arrival_monotonic)`` pairs so the caller can measure
    how stale the push signal was when it finally acted on it
    (`edl_worker_epoch_notify_latency_seconds`).
    """

    #: floor/ceiling for the re-subscribe backoff after a failure.
    _RETRY_MIN = 0.2
    _RETRY_MAX = 5.0

    def __init__(self, host: str = "127.0.0.1", port: int = 7164,
                 worker: str = "", token: Optional[str] = None,
                 connect_timeout: float = 5.0):
        self.host = host
        self.port = port
        self.worker = worker
        self.token = token if token is not None \
            else os.environ.get("EDL_COORD_TOKEN", "")
        self.connect_timeout = connect_timeout
        #: resume cursor: highest epoch ever surfaced to the caller.
        #: -1 means "no epoch seen yet" — the first subscribe replays
        #: everything from epoch 1 if the caller primes it with 0, or
        #: nothing if left at -1 (fresh worker joining mid-run).
        self.last_epoch: int = -1
        self.connected = False
        #: telemetry the workers surface in summaries.
        self.notifies_total = 0
        self.duplicates_dropped = 0
        self.resubscribes = 0
        self.preempts_total = 0
        self._sock: Optional[socket.socket] = None
        self._buf = b""
        self._pending: List[Tuple[int, float]] = []
        #: advance-notice revocations addressed to this worker. Delivery is
        #: at-least-once (live push + replay-on-resubscribe), so dedup on
        #: the server's issue seq; the deadline anchors to local monotonic
        #: arrival + notice_s — frames carry no wall clock.
        self._preempt_pending: List[Dict] = []
        self._preempt_seq_seen = 0
        self._retry_at = 0.0
        self._retry_delay = self._RETRY_MIN

    # -- lifecycle -------------------------------------------------------------

    def subscribe(self, timeout: float = 5.0) -> bool:
        """(Re)establish the subscription; replayed epochs land in the
        pending queue for the next ``poll()``. Returns connected-ness."""
        self._teardown()
        try:
            sock = socket.create_connection(
                (self.host, self.port),
                timeout=min(self.connect_timeout, timeout))
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            req: Dict = {"op": "watch", "cursor": int(self.last_epoch)}
            if self.worker:
                req["worker"] = self.worker
            if self.token:
                req["token"] = self.token
            sock.settimeout(timeout)
            sock.sendall((json.dumps(req) + "\n").encode())
            self._sock = sock
            # Replayed notifications precede the ack frame; absorb them.
            deadline = time.monotonic() + timeout
            while True:
                frame = self._read_frame(max(0.1, deadline - time.monotonic()))
                if frame is None:
                    raise OSError("watch ack did not arrive")
                if frame.get("unauthorized"):
                    raise CoordinatorAuthError(
                        f"coordinator rejected watch: "
                        f"{frame.get('error', 'unauthorized')}")
                if frame.get("notify") == "epoch":
                    self._absorb(frame)
                    continue
                if frame.get("notify") == "preempt":
                    self._absorb_preempt(frame)
                    continue
                if frame.get("watch"):
                    break
                # Unknown frame (older coordinator): treat as unsupported.
                raise OSError(f"unexpected watch reply: {frame}")
        except CoordinatorAuthError:
            self._teardown()
            raise
        except (OSError, ValueError):
            self._teardown()
            self._retry_delay = min(self._retry_delay * 2, self._RETRY_MAX)
            self._retry_at = time.monotonic() + self._retry_delay
            return False
        self.connected = True
        self._retry_delay = self._RETRY_MIN
        return True

    def close(self) -> None:
        """Best-effort cancel + teardown."""
        if self._sock is not None and self.connected:
            try:
                self._sock.settimeout(1.0)
                self._sock.sendall((json.dumps(
                    {"op": "watch_cancel", "worker": self.worker,
                     "token": self.token}) + "\n").encode())
                # Drain until the cancel reply (notifies may race ahead).
                deadline = time.monotonic() + 1.0
                while time.monotonic() < deadline:
                    frame = self._read_frame(0.2)
                    if frame is None or "cancelled" in frame:
                        break
            except (OSError, ValueError):
                pass
        self._teardown()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- polling ---------------------------------------------------------------

    def poll(self, timeout: float = 0.0) -> List[Tuple[int, float]]:
        """Surface newly observed epochs as ``(epoch, arrival_monotonic)``.

        Blocks up to ``timeout`` for the first frame (0 = just drain
        whatever already arrived). Transport failures flip ``connected``
        off and re-subscription is attempted with bounded backoff on
        subsequent polls — the caller's pull path covers the gap.
        """
        if not self.connected:
            if time.monotonic() >= self._retry_at:
                self.resubscribes += 1
                # Bounded: poll() sits on the worker's step-check path, so a
                # partitioned coordinator must cost at most ~1 s per backoff
                # period here — the pull cadence carries liveness meanwhile.
                self.subscribe(timeout=1.0)
            if not self.connected:
                return self._take_pending()
        deadline = time.monotonic() + max(0.0, timeout)
        first = True
        while True:
            wait = deadline - time.monotonic()
            if not first and wait <= 0:
                break
            frame = self._read_frame(max(0.0, wait) if first else 0.0)
            first = False
            if frame is None:
                break
            if frame.get("notify") == "epoch":
                self._absorb(frame)
            elif frame.get("notify") == "preempt":
                self._absorb_preempt(frame)
        return self._take_pending()

    def take_preempts(self) -> List[Dict]:
        """Drain revocation notices observed since the last call. Each dict
        carries worker/notice_s/reason/seq plus ``arrival`` (monotonic) and
        ``deadline`` (= arrival + notice_s) for budget math."""
        out, self._preempt_pending = self._preempt_pending, []
        return out

    # -- internals -------------------------------------------------------------

    def _absorb(self, frame: Dict) -> None:
        try:
            epoch = int(frame["epoch"])
        except (KeyError, TypeError, ValueError):
            return
        self.notifies_total += 1
        if epoch <= self.last_epoch:
            # at-least-once delivery across resubscribes — drop duplicates
            self.duplicates_dropped += 1
            return
        self.last_epoch = epoch
        self._pending.append((epoch, time.monotonic()))

    def _absorb_preempt(self, frame: Dict) -> None:
        try:
            seq = int(frame.get("seq", 0))
            notice_s = float(frame.get("notice_s", 0))
        except (TypeError, ValueError):
            return
        if seq <= self._preempt_seq_seen:
            self.duplicates_dropped += 1
            return
        self._preempt_seq_seen = seq
        self.preempts_total += 1
        now = time.monotonic()
        self._preempt_pending.append({
            "worker": frame.get("worker", ""), "notice_s": notice_s,
            "reason": frame.get("reason", "preempt"), "seq": seq,
            "arrival": now, "deadline": now + notice_s})

    def _take_pending(self) -> List[Tuple[int, float]]:
        out, self._pending = self._pending, []
        return out

    def _read_frame(self, timeout: float) -> Optional[Dict]:
        """One newline-JSON frame, or None on timeout / no data / error.
        Errors mark the subscription disconnected."""
        if self._sock is None:
            return None
        while b"\n" not in self._buf:
            try:
                self._sock.settimeout(timeout if timeout > 0 else 0.000001)
                chunk = self._sock.recv(65536)
            except socket.timeout:
                return None
            except OSError:
                self._mark_disconnected()
                return None
            if not chunk:
                self._mark_disconnected()
                return None
            self._buf += chunk
        line, self._buf = self._buf.split(b"\n", 1)
        try:
            frame = json.loads(line)
        except ValueError:
            self._mark_disconnected()
            return None
        return frame if isinstance(frame, dict) else None

    def _mark_disconnected(self) -> None:
        self.connected = False
        self._retry_at = time.monotonic() + self._retry_delay
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        self._buf = b""

    def _teardown(self) -> None:
        self.connected = False
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        self._buf = b""


class InProcessEpochWatch:
    """`EpochWatch`-shaped adapter over a call-surface client (the
    in-process twin). No dedicated connection exists, so frames queue
    server-side and each ``poll()`` drains them with ``watch`` take
    requests — same resume-cursor and client-side dedup semantics, which
    keeps worker code transport-agnostic."""

    def __init__(self, client):
        self._client = client
        self.last_epoch: int = -1
        self.connected = False
        self.notifies_total = 0
        self.duplicates_dropped = 0
        self.resubscribes = 0
        self.preempts_total = 0
        self._preempt_pending: List[Dict] = []
        self._preempt_seq_seen = 0

    def subscribe(self, timeout: float = 5.0) -> bool:
        try:
            reply = self._client.call("watch", cursor=int(self.last_epoch))
        except Exception:  # edl: noqa[EDL005] push is an optimization — any twin failure degrades to pull discovery, reported via connected=False
            self.connected = False
            return False
        self.connected = bool(reply.get("ok"))
        return self.connected

    def poll(self, timeout: float = 0.0) -> List[Tuple[int, float]]:
        if not self.connected:
            self.resubscribes += 1
            if not self.subscribe():
                return []
        out: List[Tuple[int, float]] = []
        while True:
            try:
                frame = self._client.call("watch", take=True)  # edl: noqa[EDL007] `take` is the in-process twin's drain verb; the wire transport uses a dedicated connection instead, so the native server never sees it
            except Exception:  # edl: noqa[EDL005] same degrade-to-pull contract as subscribe(): the caller's pull path owns liveness
                self.connected = False
                break
            if frame.get("notify") == "preempt":
                try:
                    seq = int(frame.get("seq", 0))
                    notice_s = float(frame.get("notice_s", 0))
                except (TypeError, ValueError):
                    continue
                if seq <= self._preempt_seq_seen:
                    self.duplicates_dropped += 1
                    continue
                self._preempt_seq_seen = seq
                self.preempts_total += 1
                now = time.monotonic()
                self._preempt_pending.append({
                    "worker": frame.get("worker", ""),
                    "notice_s": notice_s,
                    "reason": frame.get("reason", "preempt"), "seq": seq,
                    "arrival": now, "deadline": now + notice_s})
                continue
            if frame.get("notify") != "epoch":
                break
            self.notifies_total += 1
            try:
                epoch = int(frame["epoch"])
            except (KeyError, TypeError, ValueError):
                continue
            if epoch <= self.last_epoch:
                self.duplicates_dropped += 1
                continue
            self.last_epoch = epoch
            out.append((epoch, time.monotonic()))
        return out

    def take_preempts(self) -> List[Dict]:
        """Same contract as `EpochWatch.take_preempts`."""
        out, self._preempt_pending = self._preempt_pending, []
        return out

    def close(self) -> None:
        try:
            self._client.call("watch_cancel")
        except Exception:  # edl: noqa[EDL005] best-effort cancel on teardown — the server reaps the subscription either way
            pass
        self.connected = False

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def make_epoch_watch(client, mode: str = "auto"):
    """Build the right watch for a worker's transport, or None.

    ``client`` may be an OutboxClient wrapper — the raw transport under it
    decides: wire clients (host/port surface) get a dedicated-connection
    `EpochWatch`; in-process twins (call surface only) get the take-polling
    adapter. ``mode="pull"`` disables push discovery outright.
    """
    if mode == "pull":
        return None
    raw = getattr(client, "client", client)
    host = getattr(raw, "host", None)
    port = getattr(raw, "port", None)
    if isinstance(host, str) and isinstance(port, int):
        return EpochWatch(host=host, port=port,
                          worker=getattr(raw, "worker", "") or "",
                          token=getattr(raw, "token", None))
    if hasattr(raw, "call"):
        return InProcessEpochWatch(raw)
    return None
