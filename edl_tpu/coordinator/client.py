"""Blocking TCP client for the coordinator's newline-JSON protocol.

The trainer-side embed: replaces the reference's etcd client + master RPC in
`train_ft.py` (`SGD(pserver_spec=etcd_endpoint, use_etcd=True)`,
`cloud_reader` task pulls, `example/fit_a_line/train_ft.py:105-114`) and the
pod launcher's poll-and-sleep discovery (`docker/k8s_tools.py:70-78`).
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
import uuid
from typing import Dict, List, Optional

from edl_tpu.coordinator.retry import DEFAULT_RETRY, RetryPolicy
from edl_tpu.coordinator.sharding import ShardMap, partition_tasks, route_key
from edl_tpu.obs.metrics import get_registry

# Process-wide client telemetry (all CoordinatorClient instances in this
# process feed the same families — per-connection split isn't worth a label).
_REG = get_registry()
_M_CALLS = _REG.counter(
    "edl_client_calls_total",
    "coordinator RPC transactions completed, by op",
    labelnames=("op",),
)
_M_RETRIES = _REG.counter(
    "edl_client_retries_total",
    "transport-level re-dial attempts (coordinator unreachable)",
)
_M_RECONNECTS = _REG.counter(
    "edl_client_reconnects_total",
    "fresh TCP connections established after a poisoned/closed socket",
)
_M_BATCH_FRAMES = _REG.counter(
    "edl_client_batch_frames_total",
    "batched frames sent (each carries many sub-ops in one round-trip)",
)
_M_CALL_LATENCY = _REG.histogram(
    "edl_client_call_latency_seconds",
    "coordinator RPC round-trip latency (excludes ops parked server-side: "
    "barrier/sync wait time is rendezvous, not transport)",
)
_M_SHARD_REDIRECTS = _REG.counter(
    "edl_client_shard_redirects_total",
    "redirect replies observed (root routing a keyspace op, or a stale "
    "shard map sending an op to the wrong shard)",
)
_M_SHARD_MAP_REFRESHES = _REG.counter(
    "edl_client_shard_map_refreshes_total",
    "shard_map re-resolutions (first redirect, stale-map invalidation, or "
    "reconnect after a shard endpoint became unreachable)",
)
#: parked ops: their round-trip time measures rendezvous latency, which
#: would swamp the transport histogram with multi-second waits.
_PARKED_OPS = frozenset({"barrier", "sync"})


class CoordinatorError(RuntimeError):
    pass


class CoordinatorAuthError(CoordinatorError):
    """The coordinator rejected the call's token (job secret mismatch).

    Typed separately because the right reaction differs from transport
    errors: retrying cannot help — the pod's EDL_COORD_TOKEN disagrees
    with the job's, which is a deployment bug (or an unauthorized peer).
    """


class CoordinatorTimeout(CoordinatorError):
    """The reply did not arrive within the caller's timeout.

    Not retried by the client: the request may have been processed (a
    barrier arrival whose release is still pending, a lease grant with a
    slow reply), so a blind re-send is not safe at this layer. Callers
    with idempotent semantics re-issue at their own layer.
    """


class CoordinatorUnreachable(CoordinatorError):
    """Connection-level failure: refused, reset, or closed mid-call.

    The retryable class — ``call()`` re-dials with backoff until the
    retry policy's deadline, and raises this only once that budget is
    spent. Degraded-mode callers (outbox buffering, checkpoint-and-park)
    key off this type.
    """


class CoordinatorClient:
    """One persistent connection; requests are serialized (1 req -> 1 reply),
    except ``barrier`` which blocks until the coordinator releases it.

    Thread-safe at request granularity: a lock serializes each call's full
    send→recv transaction, so the pipelined data path (`DevicePrefetcher`
    running `LeaseReader` RPCs on a pump thread) can share the client with
    the main loop's heartbeats. Requests from different threads queue
    behind each other — a thread parked in ``barrier``/``sync`` blocks
    other callers, so long rendezvous belong on a dedicated client.

    ``token`` is the per-job shared secret (default: the pod env's
    EDL_COORD_TOKEN, stamped by the controller — jobparser.make_env); it
    rides every request. Auth-rejected calls raise CoordinatorAuthError.

    ``retry`` is the outage policy baked into every ``call()``: connection
    failures re-dial with jittered exponential backoff until the policy
    deadline, then raise CoordinatorUnreachable. Pass ``retry=None`` for
    the legacy crash-on-first-error behavior (some tests want it). Auth
    errors and reply timeouts are never retried — see retry.py's taxonomy.

    Control-plane batching (BENCH_COORD.json): ``call_batch()`` sends many
    sub-ops in ONE frame with positional per-sub-op replies, and
    ``piggyback_heartbeat > 0`` transparently rides a due heartbeat on
    whatever call is going out anyway — one round-trip instead of two, and
    the membership observation lands in ``last_membership`` for workers to
    coalesce on. Every reply's epoch (the server stamps all of them) is
    tracked in ``observed_epoch``, so epoch discovery no longer needs
    dedicated ``status`` polls.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 7164,
                 worker: str = "", connect_timeout: float = 10.0,
                 token: Optional[str] = None,
                 retry: Optional[RetryPolicy] = DEFAULT_RETRY,
                 piggyback_heartbeat: float = 0.0):
        self.host = host
        self.port = port
        self.worker = worker
        self.connect_timeout = connect_timeout
        self.token = token if token is not None \
            else os.environ.get("EDL_COORD_TOKEN", "")
        self.retry = retry
        #: seconds between piggybacked heartbeats; 0 disables. When due, an
        #: eligible call() is wrapped in a batch frame with a leading
        #: heartbeat — the worker stays live without a dedicated RPC.
        self.piggyback_heartbeat = piggyback_heartbeat
        #: transport-level retry attempts performed over this client's
        #: lifetime (outage telemetry; workers surface it in summaries).
        self.retry_count = 0
        #: latest epoch seen on ANY reply (every server reply carries it),
        #: and the monotonic instant it was observed. Workers use this to
        #: skip dedicated epoch polls (coalesced watch-style notification).
        self.observed_epoch: Optional[int] = None
        self.observed_epoch_at: float = 0.0
        #: latest ok membership reply (rank/world/epoch) from a heartbeat /
        #: register / piggybacked heartbeat, with its observation instant.
        self.last_membership: Optional[Dict] = None
        self.last_membership_at: float = 0.0
        self._last_piggyback = 0.0
        self._sock: Optional[socket.socket] = None
        self._buf = b""
        #: sharded-plane routing state, learned lazily from the first
        #: redirect reply — a plain single-process coordinator never sends
        #: one, so unsharded deployments never pay a shard_map round-trip.
        self._shard_map: Optional[ShardMap] = None
        self._shard_clients: Dict[int, "CoordinatorClient"] = {}
        #: per-client nonce namespaces dedup ids (req_id/op_id) so a fresh
        #: process reusing a worker name can never hit a predecessor's
        #: cached replies or persisted kv_incr markers.
        self._nonce = uuid.uuid4().hex[:8]
        self._acquire_seq = 0
        self._op_seq = 0
        self._put_seq = 0
        #: serializes one full request/reply transaction per call() — the
        #: socket and _buf pair replies to requests by ordering, so
        #: interleaved sends from two threads would cross-deliver replies.
        #: RLock: call()'s error paths close() while already holding it.
        self._lock = threading.RLock()
        self._connect(connect_timeout)

    def _connect(self, timeout: float) -> None:
        deadline = time.monotonic() + timeout
        last_err: Optional[Exception] = None
        sleeps = (self.retry or DEFAULT_RETRY).sleeps()
        while time.monotonic() < deadline:
            try:
                sock = socket.create_connection((self.host, self.port), timeout=5.0)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                sock.settimeout(None)
                self._sock = sock
                return
            except OSError as e:
                last_err = e
                time.sleep(min(next(sleeps), max(0.0, deadline - time.monotonic())))
        raise CoordinatorUnreachable(
            f"cannot connect to coordinator at {self.host}:{self.port}: {last_err}"
        )

    def close(self) -> None:
        self._drop_shard_clients()
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                finally:
                    self._sock = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- protocol --------------------------------------------------------------

    def call(self, op: str, timeout: Optional[float] = None, **fields) -> Dict:
        """One request/reply transaction, with the retry policy applied.

        Retries cover only ``CoordinatorUnreachable`` (refused / reset /
        closed): the request was not answered, so re-sending is safe for
        every op — mutating ops carry dedup ids (``req_id``/``op_id``) or
        are idempotent server-side (``complete_task``). Auth rejections
        and reply timeouts propagate immediately.

        Against a sharded control plane, keyspace ops are routed to their
        owning shard via the cached shard map (learned from the root on the
        first redirect reply; see ``sharding.route_key``). Single-process
        coordinators never redirect, so the unsharded path is unchanged.
        """
        smap = self._shard_map
        if smap is not None and smap.nshards > 0:
            if op == "add_tasks":
                return self._sharded_add_tasks(timeout, fields)
            if op == "acquire_task":
                return self._sharded_acquire(timeout, fields)
            key = route_key(op, fields)
            if key is not None:
                return self._shard_call(op, timeout, fields, key)
        reply = self._direct_call(op, timeout, fields)
        if self._is_redirect(reply):
            # First contact with a sharded root: learn the map, re-route.
            _M_SHARD_REDIRECTS.inc()
            self._refresh_shard_map()
            if self._shard_map is not None and self._shard_map.nshards > 0:
                return self.call(op, timeout=timeout, **fields)
        return reply

    def _direct_call(self, op: str, timeout: Optional[float],
                     fields: Dict) -> Dict:
        """The pre-sharding call body: piggyback check + retry loop over one
        request/reply transaction on THIS client's own connection."""
        if self._piggyback_due(op, fields):
            return self._call_with_piggyback(op, timeout, fields)
        if self.retry is None:
            return self._call_once(op, timeout, fields)
        deadline = time.monotonic() + self.retry.deadline
        sleeps = self.retry.sleeps()
        while True:
            try:
                return self._call_once(op, timeout, fields,
                                       connect_deadline=deadline)
            except (CoordinatorAuthError, CoordinatorTimeout):
                raise
            except CoordinatorUnreachable:
                delay = next(sleeps)
                if time.monotonic() + delay >= deadline:
                    raise
                self.retry_count += 1  # edl: noqa[EDL001] telemetry counter; a torn increment under-counts a metric, never corrupts protocol state
                _M_RETRIES.inc()
                time.sleep(delay)

    # -- shard routing ---------------------------------------------------------

    @staticmethod
    def _is_redirect(reply) -> bool:
        return (isinstance(reply, dict) and not reply.get("ok")
                and "redirect" in reply)

    def _shard_call(self, op: str, timeout: Optional[float], fields: Dict,
                    key: str) -> Dict:
        """Route one keyspace op to the shard owning ``key``.

        A redirect reply or an unreachable shard endpoint invalidates the
        cached map and re-resolves it from the root (bounded, with the
        retry policy's backoff) instead of hammering the stale address to
        deadline exhaustion; the op is then re-routed against the fresh
        map. Redirect ping-pong (a genuinely disagreeing root) is capped —
        the last redirect reply is returned rather than looping forever.
        """
        redirects = 0
        refreshes = 0
        while True:
            smap = self._shard_map
            if smap is None or smap.nshards == 0:
                # Routing got disabled mid-flight (root says unsharded).
                return self._direct_call(op, timeout, fields)
            slot = smap.slot_for(key)
            try:
                reply = self._shard_client(slot)._direct_call(
                    op, timeout, fields)
            except (CoordinatorAuthError, CoordinatorTimeout):
                raise
            except CoordinatorUnreachable:
                # Stale endpoint (shard moved or restarting): re-resolve
                # the map rather than retrying the dead address.
                if refreshes >= 3:
                    raise
                refreshes += 1
                self._drop_shard_clients()
                self._refresh_shard_map()
                continue
            if self._is_redirect(reply):
                _M_SHARD_REDIRECTS.inc()
                redirects += 1
                if redirects > 4:
                    return reply
                self._refresh_shard_map()
                continue
            return reply

    def _shard_client(self, slot: int) -> "CoordinatorClient":
        with self._lock:
            sub = self._shard_clients.get(slot)
            if sub is not None:
                return sub
        endpoint = self._shard_map.shards[slot]
        host, _, port = endpoint.rpartition(":")
        # Fail fast on a dead shard (the slot loop's refresh path is the
        # retry mechanism) — no per-sub-client retry policy, short dial.
        sub = CoordinatorClient(
            host=host or "127.0.0.1", port=int(port), worker=self.worker,
            connect_timeout=min(2.0, self.connect_timeout),
            token=self.token, retry=None, piggyback_heartbeat=0.0)
        with self._lock:
            existing = self._shard_clients.get(slot)
            if existing is not None:
                sub.close()
                return existing
            self._shard_clients[slot] = sub
        return sub

    def _drop_shard_clients(self) -> None:
        with self._lock:
            subs, self._shard_clients = self._shard_clients, {}
        for sub in subs.values():
            try:
                sub.close()
            except OSError:
                pass

    def _refresh_shard_map(self) -> None:
        """Bounded shard-map re-resolution against the root.

        Called on the first redirect, on a redirect proving the cached map
        stale, and when a cached shard endpoint stops answering. At most a
        few attempts with the retry policy's backoff between them — the
        root being down is a full control-plane outage and surfaces as
        CoordinatorUnreachable like any other root call.
        """
        _M_SHARD_MAP_REFRESHES.inc()
        sleeps = (self.retry or DEFAULT_RETRY).sleeps()
        last_err: Optional[Exception] = None
        for _attempt in range(4):
            try:
                reply = self._direct_call("shard_map", None, {})
            except CoordinatorUnreachable as e:
                last_err = e
                time.sleep(next(sleeps))
                continue
            if reply.get("ok") and reply.get("root") and reply.get("shards"):
                new = ShardMap([str(s) for s in reply["shards"]])
                with self._lock:
                    old = self._shard_map
                    self._shard_map = new
                if old is None or old.shards != new.shards:
                    self._drop_shard_clients()
                return
            # The endpoint answers but is not a sharded root: disable
            # routing (covers a root replaced by a plain coordinator).
            with self._lock:
                self._shard_map = None
            self._drop_shard_clients()
            return
        raise CoordinatorUnreachable(
            f"shard_map refresh failed against root "
            f"{self.host}:{self.port}: {last_err}")

    def _sharded_add_tasks(self, timeout: Optional[float],
                           fields: Dict) -> Dict:
        """Partition an add_tasks batch by owning shard client-side (tasks
        are hashed by name) and merge the per-shard replies."""
        tasks = fields.get("tasks")
        if not isinstance(tasks, list) or not tasks:
            # Let one shard produce the canonical error/empty reply.
            return self._shard_call("add_tasks", timeout, fields, "")
        parts = partition_tasks([str(t) for t in tasks],
                                self._shard_map.nshards)
        added = 0
        queued = 0
        last: Dict = {}
        for _slot, chunk in sorted(parts.items()):
            sub_fields = dict(fields)
            sub_fields["tasks"] = chunk
            reply = self._shard_call("add_tasks", timeout, sub_fields,
                                     chunk[0])
            if not reply.get("ok"):
                return reply
            last = reply
            added += int(reply.get("added", 0))
            queued += int(reply.get("queued", 0))
        merged = dict(last)
        merged["added"] = added
        merged["queued"] = queued
        return merged

    def _sharded_acquire(self, timeout: Optional[float],
                         fields: Dict) -> Dict:
        """Acquire from the sharded task space: rotate over every shard
        starting at the worker's stable home slot, returning the first
        grant. Drained only when EVERY shard reports exhausted."""
        smap = self._shard_map
        n = smap.nshards
        start = smap.slot_for(str(fields.get("worker") or self.worker or ""))
        exhausted = True
        last: Dict = {}
        for i in range(n):
            slot = (start + i) % n
            reply = self._shard_call_slot("acquire_task", timeout, fields,
                                          slot)
            if not reply.get("ok"):
                return reply
            if reply.get("task") is not None:
                return reply
            last = reply
            exhausted = exhausted and bool(reply.get("exhausted"))
        merged = dict(last) if last else {"ok": True, "task": None}
        merged["task"] = None
        merged["exhausted"] = exhausted
        return merged

    def _shard_call_slot(self, op: str, timeout: Optional[float],
                         fields: Dict, slot: int) -> Dict:
        """Like _shard_call but targeting an explicit slot (acquire's
        rotation) — same refresh-on-unreachable behavior."""
        refreshes = 0
        while True:
            smap = self._shard_map
            if smap is None or smap.nshards == 0:
                return self._direct_call(op, timeout, fields)
            try:
                return self._shard_client(slot % smap.nshards)._direct_call(
                    op, timeout, fields)
            except (CoordinatorAuthError, CoordinatorTimeout):
                raise
            except CoordinatorUnreachable:
                if refreshes >= 3:
                    raise
                refreshes += 1
                self._drop_shard_clients()
                self._refresh_shard_map()

    def call_batch(self, ops: List, timeout: Optional[float] = None) -> List[Dict]:
        """Send many sub-ops in ONE frame; returns per-sub-op replies.

        ``ops`` is a list of ``(op, fields)`` pairs (or dicts carrying an
        ``"op"`` key). The frame's worker identity and token cover every
        sub-op; per-sub-op dedup (``req_id``/``op_id``) and idempotence
        hold exactly as they do for single-op calls, so whole-frame retry
        after a transport failure is as safe as retrying each op — which
        is why the frame rides the same retry policy as ``call()``.
        ``barrier``/``sync`` are not batchable (their replies are parked
        server-side and cannot be threaded into a positional reply array).
        """
        reqs = []
        for item in ops:
            if isinstance(item, dict):
                req = dict(item)
            else:
                op, fields = item
                req = {"op": op, **fields}
            reqs.append(req)
        smap = self._shard_map
        if smap is not None and smap.nshards > 0:
            return self._call_batch_sharded(reqs, timeout)
        encoded = [json.dumps(r, ensure_ascii=False) for r in reqs]
        _M_BATCH_FRAMES.inc()
        reply = self._direct_call("batch", timeout, {"ops": encoded})
        if not reply.get("ok"):
            raise CoordinatorError(f"batch frame rejected: {reply.get('error')}")
        subs = [json.loads(line) for line in reply.get("replies", [])]
        if any(self._is_redirect(s) for s in subs):
            # The root redirected keyspace sub-ops: learn the shard map and
            # re-dispatch the whole frame split by destination.
            _M_SHARD_REDIRECTS.inc()
            self._refresh_shard_map()
            if self._shard_map is not None and self._shard_map.nshards > 0:
                return self._call_batch_sharded(reqs, timeout)
        for sub in subs:
            self._note_reply(sub)
        return subs

    def _call_batch_sharded(self, reqs: List[Dict],
                            timeout: Optional[float]) -> List[Dict]:
        """Split one logical batch by destination (root vs owning shard),
        send one frame per destination, and reassemble replies positionally.
        An add_tasks sub-op whose tasks span shards is executed via the
        routed single-op path and spliced back into its position."""
        smap = self._shard_map
        groups: Dict[int, List] = {}  # dest slot (-1 = root) -> [(pos, req)]
        singles: List = []  # (pos, req) for multi-shard add_tasks
        for pos, req in enumerate(reqs):
            op = req.get("op", "")
            if op == "add_tasks" and isinstance(req.get("tasks"), list):
                parts = partition_tasks([str(t) for t in req["tasks"]],
                                        smap.nshards)
                if len(parts) > 1:
                    singles.append((pos, req))
                    continue
                slot = next(iter(parts)) if parts else 0
                groups.setdefault(slot, []).append((pos, req))
                continue
            key = route_key(op, req)
            dest = -1 if key is None else smap.slot_for(key)
            groups.setdefault(dest, []).append((pos, req))
        out: List[Optional[Dict]] = [None] * len(reqs)
        for dest, items in sorted(groups.items()):
            encoded = [json.dumps(r, ensure_ascii=False) for _, r in items]
            _M_BATCH_FRAMES.inc()
            if dest < 0:
                frame = self._direct_call("batch", timeout, {"ops": encoded})
            else:
                frame = self._shard_call_slot("batch", timeout,
                                              {"ops": encoded}, dest)
            if not frame.get("ok"):
                raise CoordinatorError(
                    f"batch frame rejected: {frame.get('error')}")
            sub_replies = [json.loads(line)
                           for line in frame.get("replies", [])]
            for (pos, req), sub in zip(items, sub_replies):
                if self._is_redirect(sub):
                    # Stale map for this sub-op: refresh and re-route it
                    # individually (keeps the frame's positional contract).
                    _M_SHARD_REDIRECTS.inc()
                    self._refresh_shard_map()
                    fields = {k: v for k, v in req.items() if k != "op"}
                    sub = self.call(req.get("op", ""), timeout=timeout,
                                    **fields)
                out[pos] = sub
                if dest < 0:
                    # Only root replies feed epoch/membership observation:
                    # shard processes don't see membership, so their epoch
                    # stamps (always 0) must not clobber the real one.
                    self._note_reply(sub)
        for pos, req in singles:
            fields = {k: v for k, v in req.items() if k != "op"}
            out[pos] = self.call(req.get("op", ""), timeout=timeout, **fields)
        return out  # type: ignore[return-value]

    #: ops a due heartbeat may NOT ride on: frames/parked ops (reply shape),
    #: and membership ops whose own semantics a heartbeat would perturb.
    _NO_PIGGYBACK = frozenset({"batch", "barrier", "sync",
                               "register", "leave", "heartbeat"})

    def _piggyback_due(self, op: str, fields: Dict) -> bool:
        return (self.piggyback_heartbeat > 0
                and bool(self.worker)
                and op not in self._NO_PIGGYBACK
                and "worker" not in fields
                and time.monotonic() - self._last_piggyback
                >= self.piggyback_heartbeat)

    def _call_with_piggyback(self, op: str, timeout: Optional[float],
                             fields: Dict) -> Dict:
        # Ride the due heartbeat on this call's frame: one round-trip keeps
        # the worker live AND performs the op. The heartbeat sub-reply is
        # absorbed into last_membership by call_batch's _note_reply; the
        # caller sees only its own op's reply, same contract as call().
        hb_reply, main = self.call_batch(
            [("heartbeat", {}), (op, fields)], timeout=timeout)
        if hb_reply.get("ok"):
            self._last_piggyback = time.monotonic()  # edl: noqa[EDL001] telemetry timestamp; a torn write only re-piggybacks early
        return main

    def _note_reply(self, reply: Dict) -> None:
        # Epoch observations are monotonic telemetry: GIL-atomic attribute
        # writes, read opportunistically by workers — no lock needed.
        if not isinstance(reply, dict):
            return
        ep = reply.get("epoch")
        if ep is None:
            return
        try:
            ep = int(ep)
        except (TypeError, ValueError):
            return
        now = time.monotonic()
        self.observed_epoch = ep  # edl: noqa[EDL001] coalesced-epoch telemetry; stale reads only cost one extra heartbeat RPC
        self.observed_epoch_at = now  # edl: noqa[EDL001] coalesced-epoch telemetry; stale reads only cost one extra heartbeat RPC
        if reply.get("ok") and "rank" in reply and "world" in reply:
            self.last_membership = dict(reply)  # edl: noqa[EDL001] coalesced-epoch telemetry; stale reads only cost one extra heartbeat RPC
            self.last_membership_at = now  # edl: noqa[EDL001] coalesced-epoch telemetry; stale reads only cost one extra heartbeat RPC

    def _call_once(self, op: str, timeout: Optional[float],
                   fields: Dict,
                   connect_deadline: Optional[float] = None) -> Dict:
        # The lock intentionally spans the socket round-trip: this is a
        # CLIENT connection whose replies pair to requests by ordering, so
        # the transaction must be atomic per thread — unlike the
        # coordinator's service lock, nothing latency-critical serializes
        # behind it except other requests on this same connection.
        t0 = time.perf_counter()
        with self._lock:
            if self._sock is None:
                _M_RECONNECTS.inc()
                # A previous timeout/error poisoned the connection (a late
                # reply may still be in flight, which would desync
                # request/reply pairing) — start a fresh one. The re-dial
                # budget honors the CONFIGURED connect_timeout, clipped to
                # what remains of the retry policy's deadline when call()
                # is driving retries (a hard-coded 5.0 here used to both
                # overshoot tight deadlines and undershoot generous ones).
                self._buf = b""
                budget = self.connect_timeout
                if connect_deadline is not None:
                    budget = min(budget,
                                 max(0.1, connect_deadline - time.monotonic()))
                self._connect(budget)
            req = {"op": op, **fields}
            if self.worker and "worker" not in req:
                req["worker"] = self.worker
            if self.token and "token" not in req:
                req["token"] = self.token
            payload = (json.dumps(req, ensure_ascii=False) + "\n").encode()
            self._sock.settimeout(timeout)
            try:
                self._sock.sendall(payload)  # edl: noqa[EDL004] client request/reply transaction — the lock exists to make exactly this atomic
                while b"\n" not in self._buf:
                    chunk = self._sock.recv(65536)  # edl: noqa[EDL004] client request/reply transaction — the lock exists to make exactly this atomic
                    if not chunk:
                        # EOF: close now so a retry re-dials instead of
                        # re-sending into the half-closed socket.
                        self.close()
                        raise CoordinatorUnreachable("coordinator closed connection")
                    self._buf += chunk
            except socket.timeout as e:
                self.close()  # poison: the reply may arrive later on this socket
                raise CoordinatorTimeout(f"coordinator call {op!r} timed out") from e
            except OSError as e:
                self.close()
                raise CoordinatorUnreachable(
                    f"coordinator call {op!r} failed: {e}") from e
            finally:
                if self._sock is not None:
                    self._sock.settimeout(None)
            line, self._buf = self._buf.split(b"\n", 1)
        _M_CALLS.inc(op=op)
        if op not in _PARKED_OPS:
            _M_CALL_LATENCY.observe(time.perf_counter() - t0)
        reply = json.loads(line)
        if isinstance(reply, dict) and reply.get("unauthorized"):
            raise CoordinatorAuthError(
                f"coordinator rejected {op!r}: {reply.get('error', 'unauthorized')}"
            )
        self._note_reply(reply)
        return reply

    # -- membership ------------------------------------------------------------

    def register(self, takeover: bool = False) -> Dict:
        """Join (or refresh) membership. ``takeover=True`` marks an
        incarnation boundary — a fresh process claiming this worker name —
        and requeues any leases a dead predecessor still holds; a plain
        refresh renews them instead (a live worker re-registering mid-run
        must not forfeit shards it is training)."""
        return self.call("register", **({"takeover": 1} if takeover else {}))

    def heartbeat(self) -> Dict:
        return self.call("heartbeat")

    def leave(self) -> Dict:
        return self.call("leave")

    def members(self) -> List[str]:
        return self.call("members")["members"]

    def epoch(self) -> int:
        """Fresh epoch via a status round-trip. Hot paths should prefer
        ``observed_epoch`` (stamped on every reply) and let epoch discovery
        coalesce onto traffic that is happening anyway."""
        return int(self.call("status")["epoch"])

    def bump_epoch(self) -> int:
        """Force an epoch bump + sync release (the control plane's rescale
        nudge): live workers parked in sync() resync immediately instead of
        waiting for a membership event. Returns the new epoch."""
        return int(self.call("bump_epoch")["epoch"])

    def preempt_notice(self, targets: List[str], notice_s: float = 30.0,
                       reason: str = "preempt") -> List[str]:
        """Schedule an advance-notice revocation: each target worker gets a
        ``{"notify": "preempt", ...}`` frame pushed on its watch stream (or
        replayed when it next subscribes) and ``notice_s`` seconds to drain.
        The notice is volatile scheduler state — a coordinator restart
        forgets it and the scheduler re-issues. Returns the revoked names."""
        return list(self.call("preempt_notice", targets=list(targets),
                              notice_s=float(notice_s),
                              reason=reason).get("revoked", []))

    # -- task queue ------------------------------------------------------------

    def add_tasks(self, tasks: List[str]) -> int:
        return int(self.call("add_tasks", tasks=list(tasks))["added"])

    def acquire_task(self) -> Optional[str]:
        return self.acquire().get("task")

    def acquire(self) -> Dict:
        """Full acquire reply: {task: str|None, exhausted: bool when drained}.

        Each acquire carries a per-connection ``req_id`` so a retry after a
        lost reply returns the *same* lease instead of popping a second
        task (which would pin a zombie lease renewed by every heartbeat).
        The server answers a repeated (worker, req_id) from its dedup
        cache while the cached task is still leased to this worker.
        """
        with self._lock:
            self._acquire_seq += 1
            req_id = f"{self._nonce}.{self._acquire_seq}"
        return self.call("acquire_task", req_id=req_id)

    def complete_task(self, task: str) -> Dict:
        return self.call("complete_task", task=task)

    def fail_task(self, task: str) -> Dict:
        return self.call("fail_task", task=task)

    # -- synchronization -------------------------------------------------------

    def barrier(self, name: str, count: int, timeout: float = 120.0) -> Dict:
        """Block until ``count`` distinct workers arrive at ``name``.

        Replaces the launcher's sleep-and-poll barriers
        (docker/paddle_k8s:128-130,178) with a real rendezvous. On timeout
        returns {"ok": False, "error": "timeout"} (matching the in-process
        twin) rather than raising; the connection is re-established. A
        transport failure is *not* a timeout — it returns {"ok": False,
        "error": "unreachable"} so callers retry the rendezvous instead of
        proceeding as if peers were merely late on a dead coordinator.
        """
        try:
            return self.call("barrier", timeout=timeout, name=name, count=count)
        except CoordinatorAuthError:
            raise  # deployment bug, not a timeout — never mask it
        except CoordinatorTimeout:
            return {"ok": False, "error": "timeout"}
        except CoordinatorError:
            return {"ok": False, "error": "unreachable"}

    def sync(self, epoch: int, timeout: float = 60.0) -> Dict:
        """Epoch-synchronized rendezvous (the rescale sync point): blocks
        until every current member arrives at ``epoch``. Replies:
        {"ok": True} released; {"ok": False, "resync": True, epoch, world}
        when membership moved (retry with the new epoch); {"ok": False,
        "error": "timeout"} on client-side timeout; {"ok": False,
        "error": "unreachable"} when the coordinator cannot be reached —
        distinct so rendezvous loops re-enter instead of giving up.
        """
        try:
            return self.call("sync", timeout=timeout, epoch=int(epoch))
        except CoordinatorAuthError:
            raise  # deployment bug, not a timeout — never mask it
        except CoordinatorTimeout:
            return {"ok": False, "error": "timeout"}
        except CoordinatorError:
            return {"ok": False, "error": "unreachable"}

    # -- KV (etcd-role subset) -------------------------------------------------

    def kv_put(self, key: str, value: str) -> None:
        self.call("kv_put", key=key, value=value)

    def kv_get(self, key: str) -> Optional[str]:
        return self.call("kv_get", key=key).get("value")

    def kv_del(self, key: str) -> None:
        self.call("kv_del", key=key)

    def kv_incr(self, key: str, delta: int = 1) -> int:
        """Server-side atomic add; returns the new value.

        Carries an ``op_id`` so a retried increment (lost reply, or a
        replay across a coordinator restart) applies exactly once: the
        server persists applied op_ids alongside the KV namespace and
        answers duplicates with the value recorded at first application.
        Failure budgets counted this way cannot double-count an outage.
        """
        with self._lock:
            self._op_seq += 1
            op_id = f"{self._nonce}.{self._op_seq}"
        reply = self.call("kv_incr", key=key, delta=int(delta), op_id=op_id)
        if not reply.get("ok"):
            raise CoordinatorError(f"kv_incr failed: {reply.get('error')}")
        return int(reply["value"])

    # -- checkpoint plane (memory-resident shard replication) ------------------

    def shard_put(self, owner: str, step: int, chunk: int, chunks: int,
                  data: str, nbytes: int = 0,
                  group: Optional[List[str]] = None,
                  put_id: Optional[str] = None) -> Dict:
        """Replicate one chunk of ``owner``'s ZeRO-1 shard into the plane.

        Each put carries a per-connection ``put_id`` so a retried put (lost
        reply, outbox replay) applies exactly once — the server acks the
        replay with ``duplicate`` instead of re-storing. The plane keeps
        only the latest ``step`` per owner; a stale put acks with
        ``stored: False`` and the replicator moves on.
        """
        if put_id is None:
            put_id = self._next_put_id()
        fields: Dict = {"owner": owner, "step": int(step),
                        "chunk": int(chunk), "chunks": int(chunks),
                        "nbytes": int(nbytes), "data": data,
                        "put_id": put_id}
        if group is not None:
            fields["group"] = list(group)
        return self.call("shard_put", **fields)

    def shard_get(self, owner: str, step: int = -1, chunk: int = 0) -> Dict:
        """Fetch one chunk of a (possibly dead) owner's replicated shard;
        ``step < 0`` means latest, a specific step must match exactly."""
        return self.call("shard_get", owner=owner, step=int(step),
                         chunk=int(chunk))

    def shard_meta(self, owner: str) -> Dict:
        """What the plane holds for ``owner``: {found, step, chunks, nbytes,
        complete, group} — ``complete`` is the restorer's go/no-go."""
        return self.call("shard_meta", owner=owner)

    def shard_drop(self, owner: str, step: int = -1) -> Dict:
        """Invalidate ``owner``'s replicated shard (``step < 0``:
        unconditionally; else only that exact step)."""
        return self.call("shard_drop", owner=owner, step=int(step))

    def _next_put_id(self) -> str:
        with self._lock:
            self._put_seq += 1
            return f"{self._nonce}.p{self._put_seq}"

    def status(self) -> Dict:
        return self.call("status")

    def shard_map(self) -> Dict:
        """The control plane's partition layout as the root reports it:
        {root: bool, nshards, shards: [host:port...], shard_index}. A plain
        single-process coordinator answers root=False, nshards=0."""
        return self.call("shard_map")

    def ping(self) -> bool:
        try:
            return bool(self.call("ping", timeout=5.0).get("pong"))
        except (CoordinatorError, OSError):
            return False
