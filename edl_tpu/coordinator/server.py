"""Lifecycle manager for the native coordinator binary.

Builds `native/coordinator` on first use (make), spawns it as a subprocess on
a free port, and tears it down — the role the controller's master-ReplicaSet
materialization plays in the reference (`pkg/controller.go:119-134`,
`pkg/jobparser.go:167-227`), minus Kubernetes.
"""

from __future__ import annotations

import os
import socket
import subprocess
import tempfile
import threading
import time
from typing import Dict, List, Optional

from edl_tpu.coordinator.client import CoordinatorClient, CoordinatorError

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
NATIVE_DIR = os.path.join(_REPO_ROOT, "native", "coordinator")
BINARY = os.path.join(NATIVE_DIR, "edl-coordinator")

#: EDL_COORD_SANITIZER -> (make target, binary name). The sanitizer pytest
#: lane sets the env var so every CoordinatorServer in the process — chaos
#: proxies, supervisors, batch tests — runs against the instrumented binary.
SANITIZER_VARIANTS: Dict[str, str] = {
    "": "edl-coordinator",
    "tsan": "edl-coordinator-tsan",
    "asan": "edl-coordinator-asan",
}


def sanitizer_variant() -> str:
    """Active sanitizer variant ('' when none) from EDL_COORD_SANITIZER."""
    variant = os.environ.get("EDL_COORD_SANITIZER", "").strip().lower()
    if variant not in SANITIZER_VARIANTS:
        raise CoordinatorError(
            f"EDL_COORD_SANITIZER={variant!r} — expected one of "
            f"{sorted(SANITIZER_VARIANTS)}"
        )
    return variant


def ensure_built(timeout: float = 120.0, variant: Optional[str] = None) -> str:
    """Build the coordinator binary (the ``variant``'s, default from
    EDL_COORD_SANITIZER); returns its path.

    Always invokes make — it no-ops in milliseconds when the binary is fresh,
    and rebuilds after source edits (a stale-binary check by existence alone
    would silently keep old protocol semantics live).
    """
    if variant is None:
        variant = sanitizer_variant()
    name = SANITIZER_VARIANTS[variant]
    binary = os.path.join(NATIVE_DIR, name)
    proc = subprocess.run(
        ["make", "-C", NATIVE_DIR, name],
        capture_output=True,
        text=True,
        timeout=timeout,
    )
    if proc.returncode != 0 or not os.path.exists(binary):
        raise CoordinatorError(
            f"failed to build coordinator ({name}): "
            f"{proc.stdout}\n{proc.stderr}"
        )
    return binary


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class CoordinatorServer:
    """Spawn/own one coordinator process."""

    def __init__(
        self,
        port: Optional[int] = None,
        task_lease_sec: float = 16.0,  # ref: -task-timout-dur 16s
        heartbeat_ttl_sec: float = 10.0,
        host: str = "127.0.0.1",
        state_file: Optional[str] = None,
        run_id: Optional[str] = None,
        auth_token: Optional[str] = None,
        shards: Optional[List[str]] = None,
        shard_index: int = -1,
        num_shards: int = 0,
        extra_env: Optional[Dict[str, str]] = None,
    ):
        self.port = port or free_port()
        self.task_lease_sec = task_lease_sec
        self.heartbeat_ttl_sec = heartbeat_ttl_sec
        #: loopback by default — the protocol is unauthenticated, so binding
        #: beyond loopback is an explicit deployment decision (the pod
        #: launcher passes host="0.0.0.0": cross-host trainers must dial in).
        self.host = host
        #: durability log path for queue/done/kv/epoch; a restarted server
        #: with the same state_file (and run_id) resumes instead of replaying
        #: the whole dataset (the reference's etcd-sidecar role).
        self.state_file = state_file
        #: identity stamped into the state file; a mismatched file (another
        #: run's leftovers in the same workspace) is discarded, not resumed.
        self.run_id = run_id
        #: per-job shared secret (EDL_COORD_TOKEN). None inherits whatever
        #: the launching pod's env carries (the controller stamps it into
        #: every pod); "" explicitly disables auth.
        self.auth_token = auth_token if auth_token is not None \
            else os.environ.get("EDL_COORD_TOKEN", "")
        #: sharded-root mode (--shards): host:port per shard server; the
        #: process serves only membership/epoch/watch and redirects every
        #: keyspace op by key hash.
        self.shards = list(shards or [])
        #: shard-server mode (--shard-index/--num-shards): serves its slice
        #: of the keyspace; membership lives on the root.
        self.shard_index = shard_index
        self.num_shards = num_shards
        #: extra environment stamped into the child on every start() —
        #: the EDL010 native-oracle lane injects its crash hooks
        #: (EDL_COORD_CRASH_AFTER_APPENDS, ...) here, and clears them
        #: before the post-crash restart. Mutable between restarts.
        self.extra_env: Dict[str, str] = dict(extra_env or {})
        self._proc: Optional[subprocess.Popen] = None
        self._stderr_path: Optional[str] = None
        #: stderr of the last exited/stopped process (sanitizer reports live
        #: here after stop()) — capped, never None.
        self.last_stderr: str = ""

    @property
    def address(self) -> str:
        return f"127.0.0.1:{self.port}"

    def start(self, wait: float = 10.0) -> "CoordinatorServer":
        binary = ensure_built()
        argv = [
            binary,
            "--port", str(self.port),
            "--host", self.host,
            "--task-lease-sec", str(self.task_lease_sec),
            "--heartbeat-ttl-sec", str(self.heartbeat_ttl_sec),
        ]
        if self.state_file:
            argv += ["--state-file", self.state_file]
        if self.run_id:
            argv += ["--run-id", self.run_id]
        if self.shards:
            argv += ["--shards", ",".join(self.shards)]
        if self.shard_index >= 0 and self.num_shards > 0:
            argv += ["--shard-index", str(self.shard_index),
                     "--num-shards", str(self.num_shards)]
        env = dict(os.environ)
        # Token travels by env, never argv (/proc/<pid>/cmdline is world-
        # readable); an empty token scrubs any inherited one so a
        # no-auth server can't accidentally enforce the pod's secret.
        if self.auth_token:
            env["EDL_COORD_TOKEN"] = self.auth_token
        else:
            env.pop("EDL_COORD_TOKEN", None)
        # Sanitizer runs must fail loudly: a distinct exit code separates
        # "TSan/ASan found something" from crashes the chaos tests inject.
        env.setdefault("TSAN_OPTIONS", "exitcode=66")
        env.setdefault("ASAN_OPTIONS", "exitcode=66")
        env.setdefault("UBSAN_OPTIONS", "print_stacktrace=1")
        env.update(self.extra_env)
        # stderr goes to a file, not DEVNULL: sanitizer reports (and crash
        # diagnostics) must survive the process; sanitizer_report() reads it.
        fd, self._stderr_path = tempfile.mkstemp(
            prefix="edl-coordinator-", suffix=".stderr"
        )
        try:
            self._proc = subprocess.Popen(
                argv,
                stdout=subprocess.DEVNULL,
                stderr=fd,
                env=env,
            )
        finally:
            os.close(fd)
        deadline = time.monotonic() + wait
        while time.monotonic() < deadline:
            try:
                with CoordinatorClient(port=self.port, connect_timeout=0.5) as c:
                    if c.ping():
                        return self
            except CoordinatorError:
                pass
            if self._proc.poll() is not None:
                rc = self._proc.returncode
                self._proc = None
                self._harvest_stderr()
                raise CoordinatorError(
                    f"coordinator exited at startup (rc={rc}): "
                    f"{self.last_stderr[-500:]}"
                )
            time.sleep(0.05)
        self.stop()  # don't leak the subprocess (and its port) on timeout
        raise CoordinatorError("coordinator did not become ready")

    def _harvest_stderr(self) -> None:
        """Fold the child's stderr file into ``last_stderr`` (capped) and
        remove it — no temp-file leaks across chaos restarts."""
        if self._stderr_path is None:
            return
        try:
            with open(self._stderr_path, "r", errors="replace") as f:
                # Accumulate across restarts: a sanitizer report from an
                # earlier incarnation must survive a supervisor's respawn.
                self.last_stderr = (self.last_stderr + f.read())[-65536:]
        except OSError:
            pass
        try:
            os.unlink(self._stderr_path)
        except OSError:
            pass
        self._stderr_path = None

    def sanitizer_report(self) -> str:
        """Stderr of the running process (or of the last one after stop) —
        where TSan/ASan write their reports. Empty string when clean."""
        if self._stderr_path is not None:
            try:
                with open(self._stderr_path, "r", errors="replace") as f:
                    return (self.last_stderr + f.read())[-65536:]
            except OSError:
                return self.last_stderr
        return self.last_stderr

    def poll(self) -> Optional[int]:
        """None while the coordinator process runs; its exit code otherwise."""
        if self._proc is None:
            return -1
        return self._proc.poll()

    def wait(self) -> int:
        """Block until the coordinator process exits; returns its exit code."""
        if self._proc is None:
            return -1
        return self._proc.wait()

    def kill(self) -> None:
        """Hard-kill (SIGKILL) without graceful shutdown — for crash tests
        exercising --state-file durability."""
        if self._proc is not None:
            self._proc.kill()
            self._proc.wait()
            self._proc = None
        self._harvest_stderr()

    def stop(self) -> None:
        if self._proc is not None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self._proc.kill()
                self._proc.wait()
            self._proc = None
        self._harvest_stderr()

    def restart(self, wait: float = 10.0) -> "CoordinatorServer":
        """Bring a dead (or killed) coordinator back on the SAME port with
        the same state_file + run_id, so it resumes its queue/done/kv and
        reconnecting clients need no re-discovery. Stops any still-running
        process first (idempotent under supervision races)."""
        self.stop()
        return self.start(wait=wait)

    def client(self, worker: str = "") -> CoordinatorClient:
        return CoordinatorClient(port=self.port, worker=worker,
                                 token=self.auth_token)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


class ShardedCoordinator:
    """One partitioned control plane: a thin ROOT plus K shard servers.

    The root owns membership, epochs, and watch subscriptions; every
    keyspace op (KV, task leases, checkpoint shards) is redirected by FNV-1a
    key hash to one of the shard servers, which each journal their own
    slice. Clients learn the layout from the root's redirect/``shard_map``
    replies (`CoordinatorClient` caches it and routes directly after the
    first bounce), so the root's per-op work stops growing with keyspace
    traffic — only membership scales on it.

    Start order matters: shard servers come up first so the root never
    advertises an endpoint that refuses connections.
    """

    def __init__(self, num_shards: int = 2,
                 task_lease_sec: float = 16.0,
                 heartbeat_ttl_sec: float = 10.0,
                 auth_token: Optional[str] = None,
                 state_dir: Optional[str] = None,
                 run_id: Optional[str] = None):
        def state(name: str) -> Optional[str]:
            return os.path.join(state_dir, f"{name}.state") \
                if state_dir else None

        self.shards = [
            CoordinatorServer(
                task_lease_sec=task_lease_sec,
                heartbeat_ttl_sec=heartbeat_ttl_sec,
                auth_token=auth_token, run_id=run_id,
                state_file=state(f"shard{i}"),
                shard_index=i, num_shards=num_shards,
            )
            for i in range(num_shards)
        ]
        self.root = CoordinatorServer(
            task_lease_sec=task_lease_sec,
            heartbeat_ttl_sec=heartbeat_ttl_sec,
            auth_token=auth_token, run_id=run_id,
            state_file=state("root"),
            shards=[s.address for s in self.shards],
        )

    @property
    def port(self) -> int:
        return self.root.port

    @property
    def address(self) -> str:
        return self.root.address

    def start(self, wait: float = 10.0) -> "ShardedCoordinator":
        started = []
        try:
            for s in self.shards:
                s.start(wait=wait)
                started.append(s)
            self.root.start(wait=wait)
        except CoordinatorError:
            for s in started:
                s.stop()
            raise
        return self

    def stop(self) -> None:
        self.root.stop()
        for s in self.shards:
            s.stop()

    def client(self, worker: str = "") -> CoordinatorClient:
        return self.root.client(worker)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


class CoordinatorSupervisor:
    """Keep one coordinator process alive — the master-ReplicaSet role.

    The reference delegates this to Kubernetes: the master Deployment's
    ReplicaSet re-creates a dead master pod, and etcd preserves its state
    (`pkg/controller.go:119-134`). Here a watch thread polls the child and
    restarts it through :meth:`CoordinatorServer.restart` — same port, same
    ``state_file``, same ``run_id`` — so the resurrected process resumes
    the journal, bumps the epoch, and requeues live leases exactly as a
    planned restart would. Workers ride the outage on their retry policy.

    Metrics (``restarts``, ``downtime_seconds``, ``last_restart_rc``) feed
    the collector's cluster samples.
    """

    def __init__(self, server: CoordinatorServer, poll_interval: float = 0.2,
                 max_restarts: int = 100):
        self.server = server
        self.poll_interval = poll_interval
        #: crash-loop bound: a coordinator that cannot stay up (bad state
        #: path, port stolen) should fail the job, not flap forever.
        self.max_restarts = max_restarts
        self.restarts = 0
        self.downtime_seconds = 0.0
        self.last_restart_rc: Optional[int] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    def start(self) -> "CoordinatorSupervisor":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(  # edl: noqa[EDL001] lifecycle field; start/stop are owner-thread-only by contract
            target=self._watch, name="edl-coordinator-supervisor", daemon=True
        )
        self._thread.start()
        return self

    def _watch(self) -> None:
        while not self._stop.wait(self.poll_interval):
            rc = self.server.poll()
            if rc is None:
                continue
            if self.restarts >= self.max_restarts:
                return
            down_at = time.monotonic()
            try:
                self.server.restart()
            except CoordinatorError:
                # Startup failed (port race with the dying process, transient
                # fs error): loop and retry until max_restarts — supervision
                # must outlive one bad attempt.
                continue
            finally:
                with self._lock:
                    self.last_restart_rc = rc
                    self.restarts += 1
                    self.downtime_seconds += time.monotonic() - down_at

    def stop(self) -> None:
        """Stop supervising, then stop the coordinator itself."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None  # edl: noqa[EDL001] lifecycle field; start/stop are owner-thread-only by contract
        self.server.stop()

    def summary(self) -> Dict[str, float]:
        with self._lock:
            return {
                "restarts": float(self.restarts),
                "downtime_seconds": self.downtime_seconds,
                "last_restart_rc": float(self.last_restart_rc)
                if self.last_restart_rc is not None else -1.0,
            }

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
