"""Coordinator service: membership epochs, leased task queue, barriers, KV.

Python side of the native C++ coordinator (`native/coordinator/coordinator.cc`)
— the consolidated replacement for the reference's fault-tolerant master +
etcd sidecar + pserver self-registration (SURVEY §2.2). Provides:

- ``CoordinatorClient`` — blocking TCP client speaking the newline-JSON
  protocol; what trainers embed.
- ``CoordinatorServer`` — spawns/manages the C++ binary (builds it on first
  use if the toolchain is present).
- ``InProcessCoordinator`` — pure-Python twin of the C++ state machine for
  hermetic unit tests (the role the fake clientset plays in the reference,
  `pkg/client/.../fake`).
"""

from edl_tpu.coordinator.client import (
    CoordinatorAuthError, CoordinatorClient, CoordinatorError,
)
from edl_tpu.coordinator.inprocess import InProcessCoordinator
from edl_tpu.coordinator.server import CoordinatorServer

__all__ = [
    "CoordinatorClient",
    "CoordinatorAuthError",
    "CoordinatorError",
    "CoordinatorServer",
    "InProcessCoordinator",
]
