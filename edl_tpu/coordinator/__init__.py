"""Coordinator service: membership epochs, leased task queue, barriers, KV.

Python side of the native C++ coordinator (`native/coordinator/coordinator.cc`)
— the consolidated replacement for the reference's fault-tolerant master +
etcd sidecar + pserver self-registration (SURVEY §2.2). Provides:

- ``CoordinatorClient`` — blocking TCP client speaking the newline-JSON
  protocol; what trainers embed.
- ``CoordinatorServer`` — spawns/manages the C++ binary (builds it on first
  use if the toolchain is present).
- ``InProcessCoordinator`` — pure-Python twin of the C++ state machine for
  hermetic unit tests (the role the fake clientset plays in the reference,
  `pkg/client/.../fake`).
- ``RetryPolicy`` / ``OutboxClient`` — outage resilience: typed retries in
  the client, buffered side effects + degraded-mode reads in the worker
  (doc/robustness.md has the failure model).
- ``CoordinatorSupervisor`` — keeps a native coordinator process alive,
  restarting it with the same state_file + run_id.
"""

from edl_tpu.coordinator.client import (
    CoordinatorAuthError, CoordinatorClient, CoordinatorError,
    CoordinatorTimeout, CoordinatorUnreachable,
)
from edl_tpu.coordinator.inprocess import InProcessCoordinator
from edl_tpu.coordinator.outbox import Outbox, OutboxClient
from edl_tpu.coordinator.retry import RetryPolicy
from edl_tpu.coordinator.server import CoordinatorServer, CoordinatorSupervisor

__all__ = [
    "CoordinatorClient",
    "CoordinatorAuthError",
    "CoordinatorError",
    "CoordinatorTimeout",
    "CoordinatorUnreachable",
    "CoordinatorServer",
    "CoordinatorSupervisor",
    "InProcessCoordinator",
    "Outbox",
    "OutboxClient",
    "RetryPolicy",
]
