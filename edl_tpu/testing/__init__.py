"""Fault-injection helpers for resilience tests (not shipped runtime code)."""

from edl_tpu.testing.chaosproxy import ChaosProxy

__all__ = ["ChaosProxy"]
