"""Fault-injection helpers for resilience tests (not shipped runtime code)."""

from edl_tpu.testing.chaosproxy import (
    ChaosProxy, ChaosScenario, StepSlowShim,
)

__all__ = ["ChaosProxy", "ChaosScenario", "StepSlowShim"]
