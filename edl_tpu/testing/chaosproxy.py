"""ChaosProxy: a seeded, deterministic TCP fault-injection proxy.

Sits between a coordinator client and the coordinator service and injects
the transport failures the resilience machinery must survive:

- **delays** — hold a chunk before forwarding (latency spike / GC pause);
- **resets** — close both sides mid-stream (the peer sees ECONNRESET or a
  clean EOF, i.e. ``CoordinatorUnreachable``);
- **drops**  — swallow a chunk (the peer blocks until its read timeout,
  i.e. ``CoordinatorTimeout`` — the "request fate unknown" case that the
  req_id/op_id dedup machinery exists for);
- **partitions** — :meth:`partition` severs every live connection and
  resets new ones on arrival until :meth:`heal`, modeling a network split
  or a coordinator restart window.

Determinism: every fault decision comes from a ``random.Random`` seeded
by ``(seed, connection-index, direction)`` — integers only, so runs are
reproducible regardless of PYTHONHASHSEED or thread scheduling. The same
seed against the same connection/request sequence yields the same faults.

The proxy is transport-level only: it never parses the coordinator
protocol, so it exercises exactly what a real middlebox failure would.
"""

from __future__ import annotations

import logging
import random
import socket
import threading
from typing import Dict, List, Optional, Tuple

log = logging.getLogger("edl_tpu.testing.chaosproxy")

__all__ = ["ChaosProxy"]


def _hard_close(sock: socket.socket) -> None:
    """Close with RST semantics where possible (no lingering FIN handshake),
    so the peer observes the abrupt death a crashed process produces."""
    try:
        sock.setsockopt(
            socket.SOL_SOCKET, socket.SO_LINGER,
            __import__("struct").pack("ii", 1, 0),
        )
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


class _ConnPair:
    """One proxied connection: the client socket and its upstream twin."""

    def __init__(self, client: socket.socket, upstream: socket.socket):
        self.client = client
        self.upstream = upstream
        self._closed = threading.Event()

    def close(self) -> None:
        if self._closed.is_set():
            return
        self._closed.set()
        # shutdown() before close(): the twin pump may be blocked in recv()
        # on the other socket, and its in-kernel syscall pins the file — a
        # bare close() would neither wake it nor send FIN/RST, leaving the
        # proxied peer hung forever. shutdown() tears the connection down
        # and wakes blocked readers regardless of who holds the fd.
        for sock in (self.client, self.upstream):
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            _hard_close(sock)


class ChaosProxy:
    """Deterministic TCP fault injector between one client and one target.

    Fault probabilities are per forwarded chunk and per direction; with all
    probabilities zero the proxy is a transparent relay (useful as the
    baseline of a chaos test: same topology, no faults).

    ``stats`` counts what was actually injected so tests can assert the
    chaos happened (a chaos test whose faults never fired proves nothing).
    """

    def __init__(
        self,
        target_port: int,
        target_host: str = "127.0.0.1",
        port: Optional[int] = None,
        seed: int = 0,
        delay_prob: float = 0.0,
        delay_range: Tuple[float, float] = (0.005, 0.05),
        reset_prob: float = 0.0,
        drop_prob: float = 0.0,
    ):
        self.target = (target_host, target_port)
        self.seed = seed
        self.delay_prob = delay_prob
        self.delay_range = delay_range
        self.reset_prob = reset_prob
        self.drop_prob = drop_prob
        self._lock = threading.Lock()
        self._partitioned = False
        self._conns: List[_ConnPair] = []
        self._conn_seq = 0
        self.stats: Dict[str, int] = {
            "connections": 0, "delays": 0, "resets": 0,
            "drops": 0, "refused": 0,
        }
        self._stop = threading.Event()
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind(("127.0.0.1", port or 0))
        self._lsock.listen(64)
        self.port: int = self._lsock.getsockname()[1]
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -------------------------------------------------------------

    @property
    def address(self) -> str:
        return f"127.0.0.1:{self.port}"

    def start(self) -> "ChaosProxy":
        if self._thread is None:
            self._thread = threading.Thread(  # edl: noqa[EDL001] lifecycle field; start/close are owner-thread-only by contract
                target=self._accept_loop, name="edl-chaosproxy", daemon=True
            )
            self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        _hard_close(self._lsock)
        with self._lock:
            conns = list(self._conns)
            self._conns.clear()
        for pair in conns:
            pair.close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None  # edl: noqa[EDL001] lifecycle field; start/close are owner-thread-only by contract

    def __enter__(self) -> "ChaosProxy":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # -- partition control -----------------------------------------------------

    def partition(self) -> None:
        """Sever every live connection and reset new ones until heal().

        From the client's perspective this is indistinguishable from the
        coordinator process dying: in-flight requests see EOF/RST
        (``CoordinatorUnreachable``) and reconnects are refused."""
        with self._lock:
            self._partitioned = True
            conns = list(self._conns)
            self._conns.clear()
        for pair in conns:  # close outside the lock: peers may be mid-recv
            pair.close()
        log.info("partitioned (%d connections severed)", len(conns))

    def heal(self) -> None:
        with self._lock:
            self._partitioned = False
        log.info("healed")

    @property
    def partitioned(self) -> bool:
        with self._lock:
            return self._partitioned

    # -- data path -------------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                client, _ = self._lsock.accept()
            except OSError:
                return  # listener closed by close()
            with self._lock:
                partitioned = self._partitioned
                self._conn_seq += 1
                cid = self._conn_seq
                if partitioned:
                    self.stats["refused"] += 1
            if partitioned:
                _hard_close(client)
                continue
            try:
                upstream = socket.create_connection(self.target, timeout=5.0)
            except OSError:
                # Target genuinely down: behave like it (reset the client).
                with self._lock:
                    self.stats["refused"] += 1
                _hard_close(client)
                continue
            pair = _ConnPair(client, upstream)
            with self._lock:
                self._conns.append(pair)
                self.stats["connections"] += 1
            # Integer-mixed seeds: deterministic under PYTHONHASHSEED and
            # independent per direction, so thread interleaving between the
            # two pumps cannot perturb either one's fault sequence.
            base = self.seed * 1_000_003 + cid * 2
            for src, dst, rng_seed, name in (
                (client, upstream, base, f"c2s-{cid}"),
                (upstream, client, base + 1, f"s2c-{cid}"),
            ):
                threading.Thread(
                    target=self._pump,
                    args=(pair, src, dst, random.Random(rng_seed)),
                    name=f"edl-chaosproxy-{name}",
                    daemon=True,
                ).start()

    def _pump(self, pair: _ConnPair, src: socket.socket,
              dst: socket.socket, rng: random.Random) -> None:
        import time

        try:
            while not self._stop.is_set():
                try:
                    data = src.recv(65536)
                except OSError:
                    break
                if not data:
                    break
                roll = rng.random()
                if roll < self.reset_prob:
                    with self._lock:
                        self.stats["resets"] += 1
                    pair.close()
                    break
                if roll < self.reset_prob + self.drop_prob:
                    with self._lock:
                        self.stats["drops"] += 1
                    continue  # swallowed: the peer waits out its timeout
                if roll < self.reset_prob + self.drop_prob + self.delay_prob:
                    with self._lock:
                        self.stats["delays"] += 1
                    time.sleep(rng.uniform(*self.delay_range))
                try:
                    dst.sendall(data)
                except OSError:
                    break
        finally:
            pair.close()
            with self._lock:
                if pair in self._conns:
                    self._conns.remove(pair)
