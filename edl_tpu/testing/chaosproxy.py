"""ChaosProxy: a seeded, deterministic TCP fault-injection proxy.

Sits between a coordinator client and the coordinator service and injects
the transport failures the resilience machinery must survive:

- **delays** — hold a chunk before forwarding (latency spike / GC pause);
- **resets** — close both sides mid-stream (the peer sees ECONNRESET or a
  clean EOF, i.e. ``CoordinatorUnreachable``);
- **drops**  — swallow a chunk (the peer blocks until its read timeout,
  i.e. ``CoordinatorTimeout`` — the "request fate unknown" case that the
  req_id/op_id dedup machinery exists for);
- **partitions** — :meth:`partition` severs every live connection and
  resets new ones on arrival until :meth:`heal`, modeling a network split
  or a coordinator restart window.

Determinism: every fault decision comes from a ``random.Random`` seeded
by ``(seed, connection-index, direction)`` — integers only, so runs are
reproducible regardless of PYTHONHASHSEED or thread scheduling. The same
seed against the same connection/request sequence yields the same faults.

The proxy is transport-level only: it never parses the coordinator
protocol, so it exercises exactly what a real middlebox failure would.
"""

from __future__ import annotations

import json
import logging
import random
import socket
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

log = logging.getLogger("edl_tpu.testing.chaosproxy")

__all__ = ["ChaosProxy", "ScenarioStep", "ChaosScenario", "StepSlowShim"]


def _hard_close(sock: socket.socket) -> None:
    """Close with RST semantics where possible (no lingering FIN handshake),
    so the peer observes the abrupt death a crashed process produces."""
    try:
        sock.setsockopt(
            socket.SOL_SOCKET, socket.SO_LINGER,
            __import__("struct").pack("ii", 1, 0),
        )
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


class _ConnPair:
    """One proxied connection: the client socket and its upstream twin."""

    def __init__(self, client: socket.socket, upstream: socket.socket):
        self.client = client
        self.upstream = upstream
        self._closed = threading.Event()

    def close(self) -> None:
        if self._closed.is_set():
            return
        self._closed.set()
        # shutdown() before close(): the twin pump may be blocked in recv()
        # on the other socket, and its in-kernel syscall pins the file — a
        # bare close() would neither wake it nor send FIN/RST, leaving the
        # proxied peer hung forever. shutdown() tears the connection down
        # and wakes blocked readers regardless of who holds the fd.
        for sock in (self.client, self.upstream):
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            _hard_close(sock)


class ChaosProxy:
    """Deterministic TCP fault injector between one client and one target.

    Fault probabilities are per forwarded chunk and per direction; with all
    probabilities zero the proxy is a transparent relay (useful as the
    baseline of a chaos test: same topology, no faults).

    ``stats`` counts what was actually injected so tests can assert the
    chaos happened (a chaos test whose faults never fired proves nothing).
    """

    def __init__(
        self,
        target_port: int,
        target_host: str = "127.0.0.1",
        port: Optional[int] = None,
        seed: int = 0,
        delay_prob: float = 0.0,
        delay_range: Tuple[float, float] = (0.005, 0.05),
        reset_prob: float = 0.0,
        drop_prob: float = 0.0,
    ):
        self.target = (target_host, target_port)
        self.seed = seed
        self.delay_prob = delay_prob
        self.delay_range = delay_range
        self.reset_prob = reset_prob
        self.drop_prob = drop_prob
        self._lock = threading.Lock()
        self._partitioned = False
        self._conns: List[_ConnPair] = []
        self._conn_seq = 0
        self.stats: Dict[str, int] = {
            "connections": 0, "delays": 0, "resets": 0,
            "drops": 0, "refused": 0,
        }
        self._stop = threading.Event()
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind(("127.0.0.1", port or 0))
        self._lsock.listen(64)
        self.port: int = self._lsock.getsockname()[1]
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -------------------------------------------------------------

    @property
    def address(self) -> str:
        return f"127.0.0.1:{self.port}"

    def start(self) -> "ChaosProxy":
        if self._thread is None:
            self._thread = threading.Thread(  # edl: noqa[EDL001] lifecycle field; start/close are owner-thread-only by contract
                target=self._accept_loop, name="edl-chaosproxy", daemon=True
            )
            self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        _hard_close(self._lsock)
        with self._lock:
            conns = list(self._conns)
            self._conns.clear()
        for pair in conns:
            pair.close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None  # edl: noqa[EDL001] lifecycle field; start/close are owner-thread-only by contract

    def __enter__(self) -> "ChaosProxy":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # -- partition control -----------------------------------------------------

    def partition(self) -> None:
        """Sever every live connection and reset new ones until heal().

        From the client's perspective this is indistinguishable from the
        coordinator process dying: in-flight requests see EOF/RST
        (``CoordinatorUnreachable``) and reconnects are refused."""
        with self._lock:
            self._partitioned = True
            conns = list(self._conns)
            self._conns.clear()
        for pair in conns:  # close outside the lock: peers may be mid-recv
            pair.close()
        log.info("partitioned (%d connections severed)", len(conns))

    def heal(self) -> None:
        with self._lock:
            self._partitioned = False
        log.info("healed")

    @property
    def partitioned(self) -> bool:
        with self._lock:
            return self._partitioned

    # -- data path -------------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                client, _ = self._lsock.accept()
            except OSError:
                return  # listener closed by close()
            with self._lock:
                partitioned = self._partitioned
                self._conn_seq += 1
                cid = self._conn_seq
                if partitioned:
                    self.stats["refused"] += 1
            if partitioned:
                _hard_close(client)
                continue
            try:
                upstream = socket.create_connection(self.target, timeout=5.0)
            except OSError:
                # Target genuinely down: behave like it (reset the client).
                with self._lock:
                    self.stats["refused"] += 1
                _hard_close(client)
                continue
            pair = _ConnPair(client, upstream)
            with self._lock:
                self._conns.append(pair)
                self.stats["connections"] += 1
            # Integer-mixed seeds: deterministic under PYTHONHASHSEED and
            # independent per direction, so thread interleaving between the
            # two pumps cannot perturb either one's fault sequence.
            base = self.seed * 1_000_003 + cid * 2
            for src, dst, rng_seed, name in (
                (client, upstream, base, f"c2s-{cid}"),
                (upstream, client, base + 1, f"s2c-{cid}"),
            ):
                threading.Thread(
                    target=self._pump,
                    args=(pair, src, dst, random.Random(rng_seed)),
                    name=f"edl-chaosproxy-{name}",
                    daemon=True,
                ).start()

    def _pump(self, pair: _ConnPair, src: socket.socket,
              dst: socket.socket, rng: random.Random) -> None:
        try:
            while not self._stop.is_set():
                try:
                    data = src.recv(65536)
                except OSError:
                    break
                if not data:
                    break
                roll = rng.random()
                if roll < self.reset_prob:
                    with self._lock:
                        self.stats["resets"] += 1
                    pair.close()
                    break
                if roll < self.reset_prob + self.drop_prob:
                    with self._lock:
                        self.stats["drops"] += 1
                    continue  # swallowed: the peer waits out its timeout
                if roll < self.reset_prob + self.drop_prob + self.delay_prob:
                    with self._lock:
                        self.stats["delays"] += 1
                    time.sleep(rng.uniform(*self.delay_range))
                try:
                    dst.sendall(data)
                except OSError:
                    break
        finally:
            pair.close()
            with self._lock:
                if pair in self._conns:
                    self._conns.remove(pair)


class StepSlowShim:
    """Per-step sleep shim: the straggler injector.

    Installed as a step hook (``ElasticConfig.step_callback``, or called
    once per step from any custom loop). With factor 1.0 it is a no-op;
    :meth:`slow` makes every subsequent step take ~``factor`` x its
    natural duration by sleeping the difference — the shim EMAs the
    observed inter-step interval as its baseline, so the injected
    slowness scales with the real workload instead of a hardcoded sleep
    (the straggler detector must see a RATIO breach, and a fixed pause
    under- or over-shoots depending on step time). Thread-safe: the
    scenario driver flips ``factor`` while the step loop runs.
    """

    def __init__(self, alpha: float = 0.3, max_sleep: float = 5.0):
        self.alpha = alpha
        self.max_sleep = max_sleep
        self.factor = 1.0
        self.injected_steps = 0
        self.injected_seconds = 0.0
        self._ema = 0.0
        self._last = 0.0
        self._lock = threading.Lock()

    def slow(self, factor: float = 2.0) -> None:
        with self._lock:
            self.factor = max(1.0, float(factor))

    def restore(self) -> None:
        self.slow(1.0)

    def __call__(self, *_args, **_kwargs) -> None:
        now = time.monotonic()
        with self._lock:
            if self._last:
                dt = now - self._last
                self._ema = dt if self._ema == 0.0 else (
                    self.alpha * dt + (1.0 - self.alpha) * self._ema)
            self._last = now
            factor, base = self.factor, self._ema
        if factor > 1.0 and base > 0.0:
            pause = min(self.max_sleep, (factor - 1.0) * base)
            time.sleep(pause)
            with self._lock:
                self.injected_steps += 1
                self.injected_seconds += pause
                # Re-anchor so the injected pause never feeds the baseline
                # EMA (the shim would otherwise compound itself).
                self._last = time.monotonic()


# -- scripted scenarios --------------------------------------------------------


@dataclass
class ScenarioStep:
    """One step of a scripted fault timeline.

    ``action`` names a registered callable; ``when`` (optional) names a
    registered predicate the step blocks on before firing — gating on
    *workload state* ("job alpha finished 2 shards") rather than wall
    clock is what makes a composed chaos run deterministic across
    machines of different speeds. ``after`` adds a fixed delay once the
    gate opens (e.g. "partition, hold 5 s, heal"). ``timeout`` bounds the
    gate wait; an expired gate aborts the scenario (a chaos run whose
    trigger never fired proves nothing, and must say so loudly).
    """

    action: str
    when: str = ""
    after: float = 0.0
    timeout: float = 120.0
    note: str = ""
    kwargs: Dict = field(default_factory=dict)

    def to_dict(self) -> Dict:
        return {
            "action": self.action, "when": self.when, "after": self.after,
            "timeout": self.timeout, "note": self.note,
            "kwargs": dict(self.kwargs),
        }


class ChaosScenario:
    """Deterministic multi-axis fault conductor.

    A composed chaos test (trainer SIGKILL × apiserver faults × network
    partition) needs its faults *overlapping in a reproducible order* — ad
    hoc ``sleep``-and-fire threads drift across machines and reorder under
    load. The scenario runs an ordered step list on one driver thread:
    each step optionally blocks on a named predicate (polled), waits a
    fixed delay, then fires a named action. The fired timeline lands in
    ``events`` (scheduled vs actual), and :meth:`spec` round-trips through
    JSON so a failing run's exact fault schedule can be replayed.

    Actions and predicates are registered by name::

        sc = (ChaosScenario("composed")
              .register_proxy("beta", proxy)           # beta.partition/.heal
              .register("kill_alpha", proc.kill)
              .predicate("alpha_warm", lambda: worker.steps_done >= 2)
              .add("beta.partition", when="alpha_warm")
              .add("beta.heal", after=1.5)
              .add("kill_alpha"))
        sc.start()
        ...
        sc.join()
        assert sc.completed, sc.events
    """

    def __init__(self, name: str = "scenario"):
        self.name = name
        self.steps: List[ScenarioStep] = []
        self._actions: Dict[str, Callable[..., object]] = {}
        self._predicates: Dict[str, Callable[[], bool]] = {}
        #: fired-event log: one dict per executed step, appended in order.
        self.events: List[Dict] = []
        self.completed = False
        self.failed: Optional[str] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._t0 = 0.0

    # -- registry --------------------------------------------------------------

    def register(self, name: str, fn: Callable[..., object]) -> "ChaosScenario":
        self._actions[name] = fn
        return self

    def register_proxy(self, name: str, proxy: ChaosProxy) -> "ChaosScenario":
        """Expose a proxy's fault controls as ``<name>.partition`` /
        ``<name>.heal`` actions."""
        self._actions[f"{name}.partition"] = proxy.partition
        self._actions[f"{name}.heal"] = proxy.heal
        return self

    def register_coordinator(self, name: str, client) -> "ChaosScenario":
        """Expose the advance-notice revocation trigger as
        ``<name>.revoke``: a scripted step like
        ``add("coord.revoke", worker="w0", notice_s=5.0)`` pushes the
        doomed worker a preempt frame through the real control plane —
        the scenario models the cloud scheduler, not a transport fault.
        Kwargs ride the spec JSON, so revocation waves replay exactly."""
        def _revoke(worker: str, notice_s: float = 30.0,
                    reason: str = "preempt") -> None:
            client.preempt_notice([worker], notice_s=notice_s, reason=reason)
        self._actions[f"{name}.revoke"] = _revoke
        return self

    def register_slow(self, name: str, shim: StepSlowShim) -> "ChaosScenario":
        """Expose a straggler shim as ``<name>.slow`` (kwargs: factor) and
        ``<name>.restore`` — the slow-host half of the fault vocabulary."""
        self._actions[f"{name}.slow"] = shim.slow
        self._actions[f"{name}.restore"] = shim.restore
        return self

    def predicate(self, name: str, fn: Callable[[], bool]) -> "ChaosScenario":
        self._predicates[name] = fn
        return self

    def add(self, action: str, when: str = "", after: float = 0.0,
            timeout: float = 120.0, note: str = "", **kwargs) -> "ChaosScenario":
        self.steps.append(ScenarioStep(action=action, when=when, after=after,
                                       timeout=timeout, note=note,
                                       kwargs=kwargs))
        return self

    def spec(self) -> str:
        """The schedule as JSON — committed into a failing test's output so
        the exact fault timeline is replayable."""
        return json.dumps(
            {"name": self.name, "steps": [s.to_dict() for s in self.steps]},
            indent=2)

    @classmethod
    def from_spec(cls, raw: str) -> "ChaosScenario":
        data = json.loads(raw)
        sc = cls(data.get("name", "scenario"))
        for s in data.get("steps", []):
            sc.steps.append(ScenarioStep(
                action=s["action"], when=s.get("when", ""),
                after=float(s.get("after", 0.0)),
                timeout=float(s.get("timeout", 120.0)),
                note=s.get("note", ""), kwargs=dict(s.get("kwargs", {}))))
        return sc

    # -- execution -------------------------------------------------------------

    def start(self) -> "ChaosScenario":
        missing = [s.action for s in self.steps if s.action not in self._actions]
        missing += [s.when for s in self.steps
                    if s.when and s.when not in self._predicates]
        if missing:
            raise ValueError(f"unregistered scenario names: {missing}")
        self._t0 = time.monotonic()
        self._thread = threading.Thread(
            target=self._run, name=f"edl-scenario-{self.name}", daemon=True)
        self._thread.start()
        return self

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout=timeout)

    def close(self) -> None:
        self._stop.set()
        self.join(timeout=5.0)

    def __enter__(self) -> "ChaosScenario":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def _wait_for(self, step: ScenarioStep) -> bool:
        pred = self._predicates[step.when]
        deadline = time.monotonic() + step.timeout
        while not self._stop.is_set():
            try:
                if pred():
                    return True
            except Exception:  # edl: noqa[EDL005] a predicate probing a worker being chaos-killed may transiently throw; that is "not yet", not a driver crash
                pass
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.01)
        return False

    def _run(self) -> None:
        for i, step in enumerate(self.steps):
            if self._stop.is_set():
                return
            waited = 0.0
            if step.when:
                t_gate = time.monotonic()
                if not self._wait_for(step):
                    self.failed = (f"step {i} ({step.action}): gate "
                                   f"{step.when!r} never opened")
                    log.error("scenario %s aborted: %s", self.name, self.failed)
                    return
                waited = time.monotonic() - t_gate
            if step.after > 0.0:
                if self._stop.wait(step.after):
                    return
            try:
                self._actions[step.action](**step.kwargs)
            except Exception as e:  # edl: noqa[EDL005] the event log must record WHICH step blew up before the driver dies; tests assert completed/failed
                self.failed = f"step {i} ({step.action}): {e!r}"
                log.exception("scenario %s step %d (%s) failed",
                              self.name, i, step.action)
                return
            self.events.append({
                "step": i, "action": step.action, "when": step.when,
                "note": step.note, "gate_wait": round(waited, 3),
                "at": round(time.monotonic() - self._t0, 3),
            })
            log.info("scenario %s fired %s (step %d, t=%.2fs)",
                     self.name, step.action, i,
                     time.monotonic() - self._t0)
        self.completed = True
