"""The EDL-TPU controller: job watch → per-job actors + global autoscaler.

Merges the reference's two controller generations (SURVEY §1): the legacy
path's informer + autoscaler wiring (`pkg/controller.go:44-161`,
`cmd/edl/edl.go:39-50`) and the newer CRD path's per-job lifecycle actors
(`pkg/updater/trainingJobUpdater.go`) — the merge the reference never shipped
(no caller of `updater.NewUpdater` outside its package).

Event flow (ref: Controller.onAdd, `pkg/controller.go:110-148`):

  store.create(job) ─watch→ controller.on_add
      ├─ admission: set_defaults + validate (reject to Failed, not crash)
      ├─ JobUpdater(job).start()   — materializes coordinator → trainers
      └─ autoscaler.on_add(job)    — elastic jobs join the scaling loop

Deletion mirrors it; update forwards the new spec to both consumers.
"""

from __future__ import annotations

import copy
import logging
import threading
from typing import Dict, Optional

from edl_tpu.api.types import JobPhase, TrainingJob
from edl_tpu.api.validation import ValidationError, normalize
from edl_tpu.controller.actuation import CoordinatorActuator
from edl_tpu.controller.autoscaler import Autoscaler, AutoscalerConfig
from edl_tpu.controller.cluster import ClusterProvider
from edl_tpu.controller.store import FuncWatcher, JobStore
from edl_tpu.controller.updater import JobUpdater, UpdaterConfig

log = logging.getLogger("edl_tpu.controller.controller")


class Controller:
    """Owns the store subscription, one JobUpdater per live job, and the
    autoscaler (ref: edl.New + Run, `pkg/controller.go:51-76`)."""

    def __init__(
        self,
        cluster: ClusterProvider,
        store: Optional[JobStore] = None,
        max_load_desired: float = 0.97,  # ref default, cmd/edl/edl.go:19
        autoscaler_config: Optional[AutoscalerConfig] = None,
        updater_config: Optional[UpdaterConfig] = None,
    ):
        self.cluster = cluster
        self.store = store or JobStore()
        self.updater_config = updater_config
        cfg = autoscaler_config or AutoscalerConfig(max_load_desired=max_load_desired)
        self.autoscaler = Autoscaler(cluster, cfg)
        self.autoscaler.on_scaled = self._on_scaled
        # Rescale targets also flow into each job's coordinator KV so live
        # workers actually observe them (VERDICT r2 gap #2: the elastic
        # story's two halves, now connected).
        self.actuator = CoordinatorActuator()
        self.autoscaler.actuator = self.actuator
        self.updaters: Dict[str, JobUpdater] = {}
        self._lock = threading.Lock()
        self._started = False
        self._watcher: Optional[FuncWatcher] = None

    # -- lifecycle (ref: controller.go:64-76) ----------------------------------

    def start(self) -> "Controller":
        """Subscribe to the store (replaying existing jobs) and start the
        autoscaler loop — the two goroutines of the reference's Run."""
        with self._lock:
            self._started = True
            watcher = FuncWatcher(self.on_add, self.on_update, self.on_del)
            self._watcher = watcher
        # Outside the lock: replay delivers on_add synchronously, and those
        # callbacks re-enter self._lock to register updaters.
        self.store.watch(watcher, replay=True)
        self.autoscaler.start()
        return self

    def stop(self) -> None:
        with self._lock:
            self._started = False
            watcher, self._watcher = self._watcher, None
            updaters = list(self.updaters.values())
            self.updaters.clear()
        if watcher is not None:
            self.store.unwatch(watcher)
        self.autoscaler.stop()
        for u in updaters:
            u.stop()

    # -- convenience API (what kubectl create/delete is to the reference) ------

    def submit(self, job: TrainingJob) -> TrainingJob:
        return self.store.create(job)

    def delete(self, name: str, namespace: str = "default") -> None:
        self.store.delete(name, namespace)

    def job_status(self, name: str, namespace: str = "default") -> TrainingJob:
        return self.store.get(name, namespace)

    def _on_scaled(self, job_name: str, record) -> None:
        """Route autoscaler actuations to the owning updater — the job's sole
        status writer — so scale history lands in the store."""
        with self._lock:
            for key, updater in self.updaters.items():
                if key.split("/", 1)[1] == job_name:
                    updater.record_scale(record)
                    return

    # -- watch callbacks (ref: onAdd/onUpdate/onDelete, controller.go:110-161) --

    def on_add(self, job: TrainingJob) -> None:
        key = f"{job.namespace}/{job.name}"
        if job.status.phase.terminal():
            # Watch replay after a controller restart: a finished job must not
            # be re-materialized (its updater would reset the phase and
            # re-create roles).
            return
        try:
            job = normalize(job)
            # Duplicate-name check and updater insertion must be one atomic
            # section, or two concurrent submits could both pass the scan.
            # The data plane (ClusterProvider, autoscaler, coordinator) keys
            # by bare job name, so a name reused across namespaces would
            # alias workloads; reject it at admission instead of misrouting.
            with self._lock:
                if key in self.updaters:
                    return
                for existing in self.updaters:
                    if existing.split("/", 1)[1] == job.name:
                        raise ValidationError(
                            f"job name {job.name!r} already in use by {existing!r}"
                        )
                updater = JobUpdater(job, self.cluster, self.store, self.updater_config)
                self.updaters[key] = updater
        except ValidationError as e:
            # Admission failure is a status, not a controller crash
            # (the reference logs and skips, controller.go:115-118).
            log.error("job %s rejected: %s", key, e)
            job.status.phase = JobPhase.FAILED
            job.status.reason = f"admission: {e}"
            try:
                self.store.update_status(job.name, job.status, job.namespace)
            except KeyError:
                pass
            return
        updater.start()
        self.actuator.track(job)
        # The updater owns (and mutates) `job`; the autoscaler gets its own
        # copy so a shared scale_history list can't collect duplicate records.
        self.autoscaler.on_add(copy.deepcopy(job))
        log.info("job %s admitted (elastic=%s)", key, job.elastic())

    def on_update(self, job: TrainingJob) -> None:
        key = f"{job.namespace}/{job.name}"
        with self._lock:
            updater = self.updaters.get(key)
        if updater is None:
            return  # never admitted (e.g. rejected duplicate) — the
            # name-keyed autoscaler must not see its events
        updater.notify_update(job)
        # Refresh the actuator's view too: the updater mints spec.auth_token
        # AFTER admission (its store write echoes back as this update), and
        # the actuator's dials must authenticate once the token exists.
        self.actuator.track(job)
        self.autoscaler.on_update(job)

    def on_del(self, job: TrainingJob) -> None:
        key = f"{job.namespace}/{job.name}"
        with self._lock:
            updater = self.updaters.pop(key, None)
        if updater is None:
            return
        updater.notify_delete()
        updater.stop()
        self.autoscaler.on_del(job)
        self.actuator.forget(job.name)
        log.info("job %s deleted", key)
