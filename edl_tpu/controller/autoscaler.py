"""Elastic autoscaler: pure fixed-point dry-run core + event loop.

Re-design of the reference autoscaler (`pkg/autoscaler.go:34-511`) with TPU
slice quota as the scarce resource. The structure is kept deliberately
identical in spirit because it is the reference's best idea:

- a **pure** single-step decision function ``scale_dry_run`` that mutates only
  a passed-in ClusterResource snapshot (ref: `pkg/autoscaler.go:201-291`),
- an iterative **fixed point** ``scale_all_dry_run`` that scales the
  most-starved job up first and the least-starved down first until nothing
  changes (ref: `pkg/autoscaler.go:296-337`),
- a thin actuation loop that writes the resulting parallelism targets through
  the ClusterProvider with retries (ref: `pkg/autoscaler.go:339-376`),
- a 5 s tick + event channel main loop (ref: `pkg/autoscaler.go:451-485`).

TPU-specific decisions (SURVEY §7 hard part 3):
- The scheduling granule is ``chips_per_trainer`` on a single host; scale-up
  requires a node-fit search over per-node idle chips, not just global totals.
- ``max_load_desired`` caps CPU load as in the reference; TPU chips are
  never oversubscribed (they are integer granules, there is no "load").
"""

from __future__ import annotations


import logging
import queue
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from edl_tpu.api.quantity import ResourceList
from edl_tpu.api.types import ScaleRecord, TrainingJob
from edl_tpu.controller.cluster import ClusterProvider, ClusterResource
from edl_tpu.obs.metrics import get_registry

log = logging.getLogger("edl_tpu.controller.autoscaler")

# Controller-plane scale telemetry: decision counts split by why (autoscale
# vs make-room — the shrink-to-admit path) and which way replicas moved.
_REG = get_registry()
_M_SCALE_EVENTS = _REG.counter(
    "edl_controller_scale_events_total",
    "actuated rescale decisions, by trigger and direction",
    labelnames=("reason", "direction"),
)
_M_PLAN_JOBS = _REG.gauge(
    "edl_controller_plan_jobs",
    "jobs in the last scaling plan (0 = steady state)",
)


@dataclass
class JobState:
    """Autoscaler-side view of one job (ref: `job` wrapper, autoscaler.go:34-64)."""

    job: TrainingJob
    #: current trainer replica count as last actuated/observed.
    current: int = 0

    @property
    def name(self) -> str:
        return self.job.name

    def min_instance(self) -> int:
        return self.job.spec.trainer.min_instance

    def max_instance(self) -> int:
        return self.job.spec.trainer.max_instance

    def request(self) -> ResourceList:
        return self.job.trainer_request()

    def limit(self) -> ResourceList:
        return self.job.trainer_limit()


def fulfillment(state: JobState, additional: int = 0) -> float:
    """Scale-range satisfaction in [0,1] (ref: Fulfillment, autoscaler.go:54-64).

    1.0 when at max_instance, 0.0 when at min_instance; jobs at their floor are
    the most starved and scale up first.
    """
    lo, hi = state.min_instance(), state.max_instance()
    cur = state.current + additional
    if hi == lo:
        return 1.0
    return max(0.0, min(1.0, (cur - lo) / float(hi - lo)))


def sorted_jobs_by_fulfillment(
    states: Iterable[JobState], diff: Dict[str, int] | None = None
) -> List[JobState]:
    """Ascending fulfillment with resource-hunger tiebreaks
    (ref: sortedJobs + Less, autoscaler.go:97-129,175-189): ties broken by
    TPU-chips request desc, then CPU desc, then memory desc — the hungrier job
    goes first so the big granules get placed while fragmentation is lowest.
    """
    diff = diff or {}

    def key(s: JobState) -> Tuple:
        r = s.request()
        return (
            fulfillment(s, diff.get(s.name, 0)),
            -r.get_q("tpu"),
            -r.get_q("cpu"),
            -r.get_q("memory"),
            s.name,
        )

    return sorted(states, key=key)


def scale_dry_run(
    resource: ClusterResource,
    state: JobState,
    additional: int,
    max_load_desired: float,
    scale_down: bool,
) -> int:
    """Single-step scale decision for one job (ref: scaleDryRun, autoscaler.go:201-291).

    Returns -1, 0 or +1 and accounts the change into ``resource`` so the
    fixed-point iteration sees the consequences of its own decisions. Pure:
    touches nothing but its arguments.
    """
    plus = 0
    request = state.request()
    cur = state.current + additional

    def commit(delta: int) -> int:
        if delta > 0:
            node = resource.search_assignable_node(request)
            if node is None:
                return 0
            resource.assign(node, request)
        elif delta < 0:
            resource.release_any(request)
        return delta

    cpu_req = request.get_q("cpu")
    tpu_req = request.get_q("tpu")
    mem_req = request.get_q("memory")

    if scale_down:
        # Scale-down triggers when CPU demand exceeds the load ceiling, or TPU
        # demand exceeds physical chips (ref: autoscaler.go:230-249). TPU has
        # no oversubscription, so only an over-committed queue trips it.
        cpu_over = resource.total.get_q("cpu") > 0 and (
            resource.requested.get_q("cpu")
            > max_load_desired * resource.total.get_q("cpu")
        )
        tpu_over = resource.requested.get_q("tpu") > resource.total.get_q("tpu")
        if (cpu_over or tpu_over) and cur > state.min_instance():
            return commit(-1)
        return 0

    # -- scale up --------------------------------------------------------------
    if cur >= state.max_instance():  # cap (ref: :252-257)
        return 0
    if mem_req > 0 and resource.free("memory") < mem_req:  # memory feasibility (:259-263)
        return 0
    if cpu_req > 0 and (
        resource.requested.get_q("cpu") + cpu_req
        > max_load_desired * resource.total.get_q("cpu")
    ):  # CPU headroom vs ceiling (:271-273)
        return 0
    if tpu_req > 0 and resource.free("tpu") < tpu_req:  # chip availability (:275-288)
        return 0
    plus = commit(1)  # node-fit search inside commit (:264-267)
    return plus


def scale_all_dry_run(
    resource: ClusterResource,
    states: List[JobState],
    max_load_desired: float,
) -> Dict[str, int]:
    """Iterate single-step decisions to a fixed point
    (ref: scaleAllJobsDryRun, autoscaler.go:296-337).

    Each round: scale UP starting from the most-starved job, then scale DOWN
    starting from the least-starved, until a full round changes nothing. This
    converges: scale-up never pushes demand past the ceiling, and scale-down
    only fires while demand is over it, so the two arms cannot ping-pong.
    """
    diff: Dict[str, int] = {s.name: 0 for s in states}
    r = resource.copy()
    changed = True
    guard = 0
    while changed and guard < 1000:
        changed = False
        guard += 1
        for s in sorted_jobs_by_fulfillment(states, diff):
            d = scale_dry_run(r, s, diff[s.name], max_load_desired, scale_down=False)
            if d:
                diff[s.name] += d
                changed = True
        for s in reversed(sorted_jobs_by_fulfillment(states, diff)):
            d = scale_dry_run(r, s, diff[s.name], max_load_desired, scale_down=True)
            if d:
                diff[s.name] += d
                changed = True
    return dict(diff)


def make_room_dry_run(
    resource: ClusterResource,
    states: List[JobState],
    pending_requests: List[ResourceList],
) -> Dict[str, int]:
    """Shrink running elastic jobs so pending pods can be placed
    (ref: findPendingJob + reschedulable set, autoscaler.go:406-422,487-511;
    narrative doc/boss_tutorial.md:289-301).

    Greedily place each pending pod against per-node idle resources (their
    requests are already counted in ``resource.requested`` by inquire, so
    placement consumes node_idle only); while any remain unplaceable, shrink
    the least-starved job that is above its floor by one and retry. No
    scale-up arm runs in this mode, so the plan cannot oscillate. Terminates:
    every iteration either places a pod or shrinks a replica, both finite.
    """
    diff: Dict[str, int] = {s.name: 0 for s in states}
    r = resource.copy()
    remaining = [req.copy() for req in pending_requests]
    while remaining:
        placed_any = True
        while placed_any:
            placed_any = False
            for req in list(remaining):
                node = r.search_assignable_node(req)
                if node is not None:
                    r.node_idle[node].sub(req)
                    remaining.remove(req)
                    placed_any = True
        if not remaining:
            break
        shrinkable = [
            s
            for s in reversed(sorted_jobs_by_fulfillment(states, diff))
            if s.current + diff[s.name] > s.min_instance()
        ]
        if not shrinkable:
            break  # floors reached; remaining pods stay pending
        victim = shrinkable[0]
        r.release_any(victim.request())
        diff[victim.name] -= 1
    return dict(diff)


# ---------------------------------------------------------------------------
# Event loop
# ---------------------------------------------------------------------------


@dataclass
class AutoscalerConfig:
    #: control-loop period (ref: defaultLoopDur 5 s, autoscaler.go:30-32).
    loop_seconds: float = 5.0
    #: CPU load ceiling (ref: cmd/edl/edl.go:19 default 0.97, deployed 0.9).
    max_load_desired: float = 0.97
    #: actuation retries (ref: retry x5, autoscaler.go:346-370).
    update_retries: int = 5


@dataclass
class _Event:
    kind: str  # "add" | "update" | "del"
    job: TrainingJob


class Autoscaler:
    """Event-driven scaling loop (ref: Autoscaler, autoscaler.go:66-95,451-485).

    Jobs arrive via on_add/on_update/on_del (informer callbacks in the
    reference, controller callbacks here); a single loop thread owns all state
    — the actor pattern the reference used to avoid locking its job map.
    """

    def __init__(self, cluster: ClusterProvider, config: AutoscalerConfig | None = None):
        self.cluster = cluster
        self.config = config or AutoscalerConfig()
        self.jobs: Dict[str, JobState] = {}
        self._events: "queue.Queue[_Event]" = queue.Queue()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: serving-job replica endpoints (job -> urls or callable returning
        #: urls): where the SLO pass scrapes `edl_serve_*` from. Registered
        #: by whoever launches the replicas (deploy glue, tests, bench).
        self._serve_endpoints: Dict[str, object] = {}
        #: injectable scrape (tests/bench swap in fakes; the default hits
        #: each replica's /metrics over HTTP).
        self.serve_scrape: Optional[Callable] = None
        #: most recent plan, for observability/collector (job -> target).
        self.last_plan: Dict[str, int] = {}
        #: optional actuation listener (job_name, ScaleRecord) — the controller
        #: routes these to the job's updater, the sole status writer.
        self.on_scaled: Optional[Callable[[str, ScaleRecord], None]] = None
        #: optional CoordinatorActuator: publishes edl/expected_world before
        #: the provider actuates and nudges the membership epoch after, so
        #: live workers warm-restart into the new world
        #: (edl_tpu/controller/actuation.py; ref: autoscaler.go:339-376).
        self.actuator = None

    # -- informer-style callbacks (ref: autoscaler.go:158-171) -----------------

    def register_serving_endpoints(self, job_name: str, endpoints) -> None:
        """Tell the SLO pass where ``job_name``'s replicas expose /metrics.
        ``endpoints``: a list of base URLs, or a callable returning one
        (live replica sets change as this very autoscaler scales them)."""
        self._serve_endpoints[job_name] = endpoints

    def _serving_urls(self, job_name: str) -> List[str]:
        endpoints = self._serve_endpoints.get(job_name)
        if endpoints is None:
            return []
        if callable(endpoints):
            try:
                return list(endpoints())
            except Exception:  # edl: noqa[EDL005] a broken endpoint resolver reads as "no scrapes" — the SLO pass then holds rather than flaps; logged for the operator
                log.exception("serving endpoint resolver for %s failed",
                              job_name)
                return []
        return list(endpoints)

    def on_add(self, job: TrainingJob) -> None:
        self._events.put(_Event("add", job))

    def on_update(self, job: TrainingJob) -> None:
        self._events.put(_Event("update", job))

    def on_del(self, job: TrainingJob) -> None:
        self._events.put(_Event("del", job))

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self.run_forever, name="edl-autoscaler", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=10)

    def run_forever(self) -> None:
        while not self._stop.is_set():
            try:
                evt = self._events.get(timeout=self.config.loop_seconds)
                self._apply_event(evt)
                # Drain any queued events before a scaling pass.
                while True:
                    try:
                        self._apply_event(self._events.get_nowait())
                    except queue.Empty:
                        break
            except queue.Empty:
                pass
            try:
                self.step()
            except Exception:  # keep the loop alive like the reference's logged errors
                log.exception("autoscaler step failed")

    # -- one scaling pass (ref: autoscaler.go:461-485) -------------------------

    def step(self) -> Dict[str, int]:
        # Terminal jobs keep their JobState for history but are never scaled
        # (the reference releases completed jobs from the scaler via OnDel).
        elastic = [
            s for s in self.jobs.values()
            if s.job.elastic() and not s.job.status.phase.terminal()
        ]
        if not elastic:
            return {}
        for s in elastic:
            s.current = self.cluster.get_trainer_parallelism(s.name)
        snapshot = self.cluster.inquire()
        pending = self._pending_jobs()
        reasons: Dict[str, str] = {}
        if pending:
            # Make-room mode: shrink running jobs so pending pods can place;
            # pending jobs themselves are never shrink victims. Serving jobs
            # participate on BOTH sides — a pending serving job triggers
            # shrinks, and a serving job above its floor is a valid victim
            # (shrink-to-admit does not care what a replica computes).
            pending_reqs = [
                p.requests
                for name in pending
                for p in self.cluster.job_pods(name, "trainer")
                if p.phase == "Pending"
            ]
            shrink_states = [s for s in elastic if s.name not in pending]
            diff = make_room_dry_run(snapshot, shrink_states, pending_reqs)
            reasons = {name: "make-room" for name in diff}
        else:
            # Serving jobs scale on their scraped SLO signal, never on
            # cluster utilization — the pass runs FIRST and accounts its
            # grows/shrinks into the snapshot, so the training fixed point
            # sees serving demand as already-spent capacity.
            serving_states = [s for s in elastic if s.job.serving()]
            training_states = [s for s in elastic if not s.job.serving()]
            diff = self._serving_pass(snapshot, serving_states)
            reasons = {name: "serving-slo" for name in diff}
            training_diff = scale_all_dry_run(
                snapshot, training_states, self.config.max_load_desired
            )
            for name, d in training_diff.items():
                diff[name] = diff.get(name, 0) + d
                reasons.setdefault(name, "autoscale")
        target = {
            s.name: s.current + diff.get(s.name, 0)
            for s in elastic
            if diff.get(s.name, 0) != 0
        }
        self.last_plan = dict(target)  # edl: noqa[EDL006] atomic reference swap under the GIL; observers (CLI/status) read the previous complete plan or the new one, never a partial dict
        _M_PLAN_JOBS.set(float(len(target)))
        if target:
            log.info("scaling plan: %s (%s)", target,
                     {n: reasons.get(n, "autoscale") for n in target})
        for reason in sorted(set(reasons.get(n, "autoscale") for n in target)):
            self._actuate(
                {n: t for n, t in target.items()
                 if reasons.get(n, "autoscale") == reason},
                reason,
            )
        return target

    def _serving_pass(self, resource: ClusterResource,
                      states: List[JobState]) -> Dict[str, int]:
        """SLO-driven replica deltas for serving jobs, committed through the
        same node-fit accounting as training scale-ups (a serving grow that
        doesn't fit stays 0 — make-room picks it up once the pod pends).
        Mutates ``resource`` so the caller's later passes see the spend."""
        diff: Dict[str, int] = {}
        if not states:
            return diff
        from edl_tpu.serving.autoscale import (ServingSLO,
                                               desired_replica_delta,
                                               scrape_serve_signal)

        scrape = self.serve_scrape or scrape_serve_signal
        for s in states:
            urls = self._serving_urls(s.name)
            signals = [sig for sig in (scrape(u) for u in urls)
                       if sig is not None]
            if not signals:
                continue  # nothing scraped: hold, never flap blind
            spec = s.job.spec.serving
            slo = ServingSLO(
                p99_seconds=spec.slo_p99_seconds,
                max_queue_per_replica=spec.max_queue_per_replica,
            )
            delta = desired_replica_delta(signals, slo)
            if delta > 0 and s.current < s.max_instance():
                node = resource.search_assignable_node(s.request())
                if node is not None:
                    resource.assign(node, s.request())
                    diff[s.name] = 1
            elif delta < 0 and s.current > s.min_instance():
                resource.release_any(s.request())
                diff[s.name] = -1
        return diff

    def _pending_jobs(self) -> List[str]:
        """Jobs whose trainer pods are all pending — they need room made
        (ref: findPendingJob, autoscaler.go:406-422)."""
        out = []
        for s in self.jobs.values():
            pods = self.cluster.job_pods(s.name, "trainer")
            if pods and all(p.phase == "Pending" for p in pods):
                out.append(s.name)
        return out

    def _actuate(self, target: Dict[str, int], reason: str = "autoscale") -> None:
        """Write parallelism targets with retries (ref: autoscaler.go:339-376).

        Unknown jobs (deleted between plan and actuation) are dropped without
        retrying; only transient provider errors are retried.
        """
        for name, parallelism in target.items():
            state = self.jobs.get(name)
            for attempt in range(self.config.update_retries):
                try:
                    before = self.cluster.get_trainer_parallelism(name)
                    shrink = parallelism < before
                    if self.actuator is not None:
                        # Target world goes to the coordinator FIRST: a worker
                        # (re)starting mid-actuation must already see it. On
                        # scale-DOWN the epoch also moves before any pod gets
                        # SIGTERM (one combined dial): every member then
                        # dissolves the gang at its next round boundary via
                        # the ordinary rescale path — killing first would
                        # race a survivor into publishing a round whose
                        # collectives wait on the dead peer forever.
                        # Scale-up keeps nudge-last (the join itself is what
                        # must not be missed).
                        if shrink:
                            self.actuator.publish_and_nudge(name, parallelism)
                        else:
                            self.actuator.publish_expected_world(name, parallelism)
                    self.cluster.set_trainer_parallelism(name, parallelism)
                    if self.actuator is not None and not shrink:
                        self.actuator.nudge(name)
                    _M_SCALE_EVENTS.inc(
                        reason=reason,
                        direction="shrink" if shrink else "grow",
                    )
                    record = ScaleRecord(
                        timestamp=time.time(),
                        from_replicas=before,
                        to_replicas=parallelism,
                        reason=reason,
                    )
                    if state is not None:
                        state.current = parallelism
                        state.job.status.parallelism = parallelism
                        state.job.status.scale_history.append(record)
                    if self.on_scaled is not None:
                        self.on_scaled(name, record)
                    break
                except KeyError:
                    log.info("job %s vanished before actuation; dropping", name)
                    break
                except Exception:
                    if attempt == self.config.update_retries - 1:
                        log.exception("failed to scale %s after retries", name)
                    else:
                        time.sleep(0.05)

    def _apply_event(self, evt: _Event) -> None:
        if evt.kind in ("add", "update"):
            st = self.jobs.get(evt.job.name)
            if st is None:
                cur = evt.job.status.parallelism or evt.job.spec.trainer.min_instance
                self.jobs[evt.job.name] = JobState(job=evt.job, current=cur)
            else:
                st.job = evt.job
        elif evt.kind == "del":
            self.jobs.pop(evt.job.name, None)
