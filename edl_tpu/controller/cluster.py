"""Cluster resource accounting.

Equivalent of the reference's `pkg/cluster.go:32-291`: a snapshot type
(``ClusterResource``) the scheduler does arithmetic on, produced by scanning
nodes and non-terminated pods (``InquiryResource``, `pkg/cluster.go:176-242`),
plus the thin actuation edge (get/update trainer replica counts, create/delete
role workloads) behind a ``ClusterProvider`` interface.

TPU-native difference: alongside divisible cpu/memory, nodes carry an integer
``tpu`` chip count, and trainers consume chips in indivisible slice granules on
a single host (SURVEY §7 hard part 3) — so per-node idle accounting, which the
reference only used for memory node-fit (`pkg/autoscaler.go:191-199`), is
load-bearing for TPU placement.

The in-memory ``FakeCluster`` plays the role of the reference's generated fake
clientset (`pkg/client/clientset/versioned/fake/`): full controller loops are
testable with no real cluster behind them.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Protocol

from edl_tpu.api.quantity import ResourceList


@dataclass
class NodeInfo:
    """Allocatable capacity of one host (TPU VM or CPU node)."""

    name: str
    allocatable: ResourceList = field(default_factory=ResourceList)


@dataclass
class PodInfo:
    """One running/pending workload replica, as the scheduler sees it."""

    name: str
    job_name: str
    role: str  # "trainer" | "coordinator"
    phase: str  # "Pending" | "Running" | "Succeeded" | "Failed"
    requests: ResourceList = field(default_factory=ResourceList)
    limits: ResourceList = field(default_factory=ResourceList)
    node: str = ""  # assigned node, "" if unscheduled


@dataclass
class ClusterResource:
    """Whole-cluster totals + per-node idle maps (ref: pkg/cluster.go:32-61).

    All quantities in base units. ``node_idle`` maps node name -> free
    ResourceList; the TPU scheduler's node-fit search runs over it.
    """

    total: ResourceList = field(default_factory=ResourceList)
    requested: ResourceList = field(default_factory=ResourceList)
    limited: ResourceList = field(default_factory=ResourceList)
    node_idle: Dict[str, ResourceList] = field(default_factory=dict)

    def copy(self) -> "ClusterResource":
        return ClusterResource(
            total=self.total.copy(),
            requested=self.requested.copy(),
            limited=self.limited.copy(),
            node_idle={k: v.copy() for k, v in self.node_idle.items()},
        )

    # -- scheduler arithmetic helpers -----------------------------------------

    def free(self, key: str) -> float:
        return self.total.get_q(key) - self.requested.get_q(key)

    def util(self, key: str) -> float:
        total = self.total.get_q(key)
        return self.requested.get_q(key) / total if total > 0 else 0.0

    def search_assignable_node(self, request: ResourceList) -> Optional[str]:
        """First node whose idle resources fit the request
        (ref: pkg/autoscaler.go:191-199). For TPU jobs this enforces the
        slice-granule constraint: all chips of one trainer on one host."""
        for name, idle in self.node_idle.items():
            if request.fits_within(idle):
                return name
        return None

    def assign(self, node: str, request: ResourceList) -> None:
        """Account a placement decision into the snapshot (dry-run mutation)."""
        self.requested.add(request)
        self.node_idle[node].sub(request)

    def release_any(self, request: ResourceList) -> None:
        """Account a scale-down: return resources to the emptiest-fit node.

        The reference adjusts only the global pools on scale-down
        (`pkg/autoscaler.go:209-217`); with indivisible TPU granules we must
        also return chips to a node pool so subsequent dry-run placements see
        them. Which node is approximate in a dry run — we pick the node with
        the least idle TPU (the fullest), emulating removing its trainer.
        """
        self.requested.sub(request)
        if not self.node_idle:
            return
        tpu_need = request.get_q("tpu")
        if tpu_need > 0:
            node = min(self.node_idle, key=lambda n: self.node_idle[n].get_q("tpu"))
        else:
            node = min(self.node_idle, key=lambda n: self.node_idle[n].get_q("cpu"))
        self.node_idle[node].add(request)


def inquire_resource(nodes: List[NodeInfo], pods: List[PodInfo]) -> ClusterResource:
    """Build a ClusterResource snapshot (ref: pkg/cluster.go:176-242).

    Scans allocatable capacity over nodes, accumulates requests/limits of all
    non-terminated pods (phase not in Succeeded/Failed), and derives per-node
    idle resources (ref: updateNodesIdleResource, pkg/cluster.go:156-173).
    """
    snap = ClusterResource()
    for node in nodes:
        snap.total.add(node.allocatable)
        snap.node_idle[node.name] = node.allocatable.copy()
    for pod in pods:
        if pod.phase in ("Succeeded", "Failed"):
            continue
        snap.requested.add(pod.requests)
        snap.limited.add(pod.limits)
        if pod.node and pod.node in snap.node_idle:
            snap.node_idle[pod.node].sub(pod.requests)
    return snap


class ClusterProvider(Protocol):
    """The I/O edge the controller/autoscaler drive (ref: pkg/cluster.go:91-291).

    Implementations: FakeCluster (tests / single-host), a Kubernetes provider
    (gated on the kubernetes client being installed), or a local process pool.
    """

    def inquire(self) -> ClusterResource: ...

    def job_pods(self, job_name: str, role: str = "trainer") -> List[PodInfo]: ...

    def get_trainer_parallelism(self, job_name: str) -> int: ...

    def set_trainer_parallelism(self, job_name: str, parallelism: int) -> None: ...

    def create_role(self, job_name: str, role: str, replicas: int,
                    requests: ResourceList, limits: ResourceList,
                    workload: Optional[object] = None) -> None:
        """Materialize a role. ``workload`` is the full RoleWorkload (image,
        entrypoint, env) — required by providers that launch real containers
        (K8sCluster); accounting-only providers may ignore it."""
        ...

    def delete_role(self, job_name: str, role: str) -> None: ...


class FakeCluster:
    """In-memory ClusterProvider with a toy bin-packing scheduler.

    Plays the role of the reference's fake clientset + the K8s scheduler: pods
    created here are placed first-fit onto nodes; unplaceable pods stay
    Pending — which is exactly the signal the autoscaler's pending-job logic
    (`pkg/autoscaler.go:406-422`) needs to trigger rebalancing.
    """

    def __init__(self, nodes: List[NodeInfo]):
        self._lock = threading.RLock()
        self.nodes = list(nodes)
        self.pods: List[PodInfo] = []
        self._parallelism: Dict[str, int] = {}
        self._role_templates: Dict[str, Dict[str, tuple]] = {}
        self._counter = 0

    # -- provider interface ----------------------------------------------------

    def inquire(self) -> ClusterResource:
        with self._lock:
            self._reschedule()
            return inquire_resource(self.nodes, self.pods)

    def job_pods(self, job_name: str, role: str = "trainer") -> List[PodInfo]:
        with self._lock:
            return [p for p in self.pods if p.job_name == job_name and p.role == role]

    def get_trainer_parallelism(self, job_name: str) -> int:
        with self._lock:
            return self._parallelism.get(job_name, 0)

    def set_trainer_parallelism(self, job_name: str, parallelism: int) -> None:
        """The actual scale actuator (ref: pkg/cluster.go:91-113): reconcile
        the trainer pod set of the job to the new replica count."""
        with self._lock:
            if job_name not in self._parallelism:
                raise KeyError(f"unknown trainer job {job_name}")
            self._parallelism[job_name] = parallelism
            self._reconcile(job_name)

    def create_role(self, job_name: str, role: str, replicas: int,
                    requests: ResourceList, limits: ResourceList,
                    workload: Optional[object] = None) -> None:
        with self._lock:
            if role == "trainer":
                self._parallelism[job_name] = replicas
            self._role_templates.setdefault(job_name, {})[role] = (requests, limits)
            for _ in range(replicas):
                self._spawn(job_name, role, requests, limits)

    def delete_role(self, job_name: str, role: str) -> None:
        with self._lock:
            self.pods = [p for p in self.pods
                         if not (p.job_name == job_name and p.role == role)]
            if role == "trainer":
                self._parallelism.pop(job_name, None)

    # -- internals -------------------------------------------------------------

    def _spawn(self, job_name: str, role: str, requests: ResourceList,
               limits: ResourceList) -> PodInfo:
        self._counter += 1
        pod = PodInfo(
            name=f"{job_name}-{role}-{self._counter}",
            job_name=job_name, role=role, phase="Pending",
            requests=requests.copy(), limits=limits.copy(),
        )
        self.pods.append(pod)
        self._place(pod)
        return pod

    def _reconcile(self, job_name: str) -> None:
        want = self._parallelism[job_name]
        trainers = [p for p in self.pods if p.job_name == job_name and p.role == "trainer"
                    and p.phase in ("Pending", "Running")]
        if len(trainers) > want:
            # Evict newest-first, like K8s Job parallelism reduction.
            for pod in trainers[want:]:
                self.pods.remove(pod)
        elif len(trainers) < want:
            req, lim = self._role_templates.get(job_name, {}).get(
                "trainer", (ResourceList(), ResourceList()))
            for _ in range(want - len(trainers)):
                self._spawn(job_name, "trainer", req, lim)

    def _place(self, pod: PodInfo) -> None:
        snap = inquire_resource(self.nodes, [p for p in self.pods if p is not pod])
        node = snap.search_assignable_node(pod.requests)
        if node is not None:
            pod.node = node
            pod.phase = "Running"

    def _reschedule(self) -> None:
        for pod in self.pods:
            if pod.phase == "Pending":
                self._place(pod)
