"""TrainingJob store with watch semantics.

The analog of the reference's typed CRD client + shared informer + fake
clientset stack (`pkg/client/clientset/versioned/typed/paddlepaddle/v1/
trainingjob.go:33-153`, `pkg/client/informers/externalversions/factory.go:43-117`,
`pkg/client/clientset/versioned/fake/clientset_generated.go:32-69`): typed
CRUD + status writeback over an in-memory object map, with registered watchers
receiving add/update/delete callbacks synchronously — the delivery contract
`cache.NewInformer` gives the reference controller (`pkg/controller.go:79-108`).

A Kubernetes-backed implementation would satisfy the same ``JobStore``
protocol via the CRD REST API; everything above this interface (controller,
updaters, autoscaler) is oblivious to which one it runs on.
"""

from __future__ import annotations

import copy
import threading
from typing import Callable, Dict, List, Optional, Protocol

from edl_tpu.api.types import TrainingJob, TrainingJobStatus


class Watcher(Protocol):
    """Informer-style event sink (ref: cache.ResourceEventHandler)."""

    def on_add(self, job: TrainingJob) -> None: ...

    def on_update(self, job: TrainingJob) -> None: ...

    def on_del(self, job: TrainingJob) -> None: ...


class FuncWatcher:
    """Adapter: build a Watcher from plain callables (any may be None)."""

    def __init__(
        self,
        on_add: Optional[Callable[[TrainingJob], None]] = None,
        on_update: Optional[Callable[[TrainingJob], None]] = None,
        on_del: Optional[Callable[[TrainingJob], None]] = None,
    ):
        self._add, self._update, self._del = on_add, on_update, on_del

    def on_add(self, job: TrainingJob) -> None:
        if self._add:
            self._add(job)

    def on_update(self, job: TrainingJob) -> None:
        if self._update:
            self._update(job)

    def on_del(self, job: TrainingJob) -> None:
        if self._del:
            self._del(job)


class JobStore:
    """In-memory TrainingJob apiserver: CRUD + status subresource + watch.

    Objects are deep-copied on the way in and out (the k8s client convention),
    so a caller mutating its copy cannot corrupt the stored object — status
    changes flow only through ``update_status``, spec changes through
    ``update``.
    """

    def __init__(self):
        self._lock = threading.RLock()
        self._jobs: Dict[str, TrainingJob] = {}
        self._watchers: List[Watcher] = []

    @staticmethod
    def _key(name: str, namespace: str) -> str:
        return f"{namespace}/{name}"

    # -- watch ----------------------------------------------------------------

    def watch(self, watcher: Watcher, replay: bool = True) -> None:
        """Register a watcher; with ``replay`` it receives on_add for every
        existing job first (informer initial-list semantics)."""
        with self._lock:
            self._watchers.append(watcher)
            existing = [copy.deepcopy(j) for j in self._jobs.values()] if replay else []
        for job in existing:
            watcher.on_add(job)

    def unwatch(self, watcher: Watcher) -> None:
        """Deregister; a stopped consumer must not keep receiving events."""
        with self._lock:
            self._watchers = [w for w in self._watchers if w is not watcher]

    def _notify(self, kind: str, job: TrainingJob) -> None:
        for w in list(self._watchers):
            getattr(w, f"on_{kind}")(copy.deepcopy(job))

    # -- CRUD (ref: typed/paddlepaddle/v1/trainingjob.go:33-153) ---------------

    def create(self, job: TrainingJob) -> TrainingJob:
        with self._lock:
            key = self._key(job.name, job.namespace)
            if key in self._jobs:
                raise KeyError(f"trainingjob {key} already exists")
            self._jobs[key] = copy.deepcopy(job)
            stored = copy.deepcopy(self._jobs[key])
        self._notify("add", stored)
        return stored

    def get(self, name: str, namespace: str = "default") -> TrainingJob:
        with self._lock:
            key = self._key(name, namespace)
            if key not in self._jobs:
                raise KeyError(f"trainingjob {key} not found")
            return copy.deepcopy(self._jobs[key])

    def list(self, namespace: Optional[str] = None) -> List[TrainingJob]:
        with self._lock:
            return [
                copy.deepcopy(j)
                for j in self._jobs.values()
                if namespace is None or j.namespace == namespace
            ]

    def update(self, job: TrainingJob) -> TrainingJob:
        """Replace the spec/metadata; the stored status is preserved
        (status is a subresource, ref: UpdateStatus :102-115)."""
        with self._lock:
            key = self._key(job.name, job.namespace)
            if key not in self._jobs:
                raise KeyError(f"trainingjob {key} not found")
            kept_status = self._jobs[key].status
            stored = copy.deepcopy(job)
            stored.status = kept_status
            self._jobs[key] = stored
            out = copy.deepcopy(stored)
        self._notify("update", out)
        return out

    def update_status(
        self, name: str, status: TrainingJobStatus, namespace: str = "default"
    ) -> TrainingJob:
        with self._lock:
            key = self._key(name, namespace)
            if key not in self._jobs:
                raise KeyError(f"trainingjob {key} not found")
            self._jobs[key].status = copy.deepcopy(status)
            out = copy.deepcopy(self._jobs[key])
        self._notify("update", out)
        return out

    def delete(self, name: str, namespace: str = "default") -> TrainingJob:
        with self._lock:
            key = self._key(name, namespace)
            if key not in self._jobs:
                raise KeyError(f"trainingjob {key} not found")
            job = self._jobs.pop(key)
        self._notify("del", job)
        return job
