"""ProcessCluster: a ClusterProvider that runs roles as local subprocesses.

The third backend next to ``FakeCluster`` (accounting only) and ``K8sCluster``
(real cluster): role workloads become real OS processes on this machine, with
node-granular TPU-chip accounting kept like the fake's. This is the
single-host "minikube mode" the reference demos its elasticity tutorial on
(`/root/reference/doc/boss_tutorial.md:163-301`) — the control plane's scale
decisions spawn and reap actual trainer processes, so autoscaler → coordinator
→ warm-restart is exercisable end-to-end with no Kubernetes.

Mapping (ref: pkg/cluster.go:91-113,245-291):

- ``create_role``            — spawn ``replicas`` processes from the
  workload's entrypoint + env (each gets ``EDL_POD_NAME``).
- ``set_trainer_parallelism``— reconcile the live process count: spawn more,
  or SIGTERM the newest extras (K8s Job parallelism-reduction order).
- ``job_pods``               — phase from the process state: Running while
  alive, Succeeded/Failed from the exit code, Pending when unplaceable.
- ``delete_role``            — terminate everything carrying the label.
"""

from __future__ import annotations

import logging
import os
import shlex
import subprocess
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from edl_tpu.api.quantity import ResourceList
from edl_tpu.controller.cluster import NodeInfo, PodInfo, inquire_resource

log = logging.getLogger("edl_tpu.controller.process_cluster")


@dataclass
class _ProcPod:
    info: PodInfo
    proc: Optional[subprocess.Popen] = None
    log_path: str = ""
    #: spawn spec, kept for Pending pods that place later.
    entrypoint: str = ""
    env: Dict[str, str] = field(default_factory=dict)
    workspace: str = ""


class ProcessCluster:
    """Local-process ClusterProvider with FakeCluster-style chip accounting."""

    def __init__(self, nodes: List[NodeInfo], log_dir: Optional[str] = None):
        self._lock = threading.RLock()
        self.nodes = list(nodes)
        self.pods: List[_ProcPod] = []
        self._parallelism: Dict[str, int] = {}
        self._templates: Dict[str, Dict[str, object]] = {}  # job -> role -> workload
        self._counter = 0
        self.log_dir = log_dir
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)

    # -- provider interface ----------------------------------------------------

    def inquire(self):
        with self._lock:
            self._reap()
            self._reschedule()
            return inquire_resource(self.nodes, [p.info for p in self.pods])

    def job_pods(self, job_name: str, role: str = "trainer") -> List[PodInfo]:
        with self._lock:
            self._reap()
            return [
                p.info for p in self.pods
                if p.info.job_name == job_name and p.info.role == role
            ]

    def get_trainer_parallelism(self, job_name: str) -> int:
        with self._lock:
            if job_name not in self._parallelism:
                raise KeyError(f"unknown trainer job {job_name}")
            return self._parallelism[job_name]

    def set_trainer_parallelism(self, job_name: str, parallelism: int) -> None:
        with self._lock:
            if job_name not in self._parallelism:
                raise KeyError(f"unknown trainer job {job_name}")
            self._parallelism[job_name] = parallelism
            self._reconcile(job_name)

    def create_role(self, job_name: str, role: str, replicas: int,
                    requests: ResourceList, limits: ResourceList,
                    workload=None) -> None:
        with self._lock:
            if role == "trainer":
                self._parallelism[job_name] = replicas
            self._templates.setdefault(job_name, {})[role] = (
                replicas, requests, limits, workload
            )
            for _ in range(replicas):
                self._spawn(job_name, role, requests, limits, workload)

    def delete_role(self, job_name: str, role: str) -> None:
        with self._lock:
            doomed = [p for p in self.pods
                      if p.info.job_name == job_name and p.info.role == role]
            for pod in doomed:
                self._terminate(pod)
                self.pods.remove(pod)
            if role == "trainer":
                self._parallelism.pop(job_name, None)

    # -- process management ----------------------------------------------------

    def wait_all(self, timeout: float = 300.0) -> None:
        """Block until every live process exits (test/driver convenience)."""
        with self._lock:
            procs = [p.proc for p in self.pods if p.proc is not None]
        for proc in procs:
            proc.wait(timeout=timeout)
        with self._lock:
            self._reap()

    def shutdown(self) -> None:
        with self._lock:
            for pod in self.pods:
                self._terminate(pod)
            self.pods.clear()

    def _spawn(self, job_name: str, role: str, requests: ResourceList,
               limits: ResourceList, workload) -> _ProcPod:
        self._counter += 1
        name = f"{job_name}-{role}-{self._counter}"
        pod = _ProcPod(
            info=PodInfo(name=name, job_name=job_name, role=role,
                         phase="Pending", requests=requests.copy(),
                         limits=limits.copy()),
        )
        if workload is not None:
            pod.entrypoint = workload.entrypoint
            pod.env = dict(workload.env)
            pod.workspace = getattr(workload, "workspace", "") or ""
        self.pods.append(pod)
        self._place_and_start(pod)
        return pod

    def _place_and_start(self, pod: _ProcPod) -> None:
        snap = inquire_resource(
            self.nodes, [p.info for p in self.pods if p is not pod]
        )
        node = snap.search_assignable_node(pod.info.requests)
        if node is None:
            return  # stays Pending; _reschedule retries
        pod.info.node = node
        if not pod.entrypoint:
            pod.info.phase = "Running"  # accounting-only pod (no workload)
            return
        env = dict(os.environ)
        env.update(pod.env)
        env["EDL_POD_NAME"] = pod.info.name
        stdout = subprocess.DEVNULL
        if self.log_dir:
            pod.log_path = os.path.join(self.log_dir, f"{pod.info.name}.log")
            stdout = open(pod.log_path, "w")
        try:
            # Each pod is a process GROUP (session): a pod kill must take the
            # launcher AND its training children down together, the way a
            # K8s pod sandbox teardown does — an orphaned trainer would keep
            # heartbeating and holding leases for a "deleted" pod.
            pod.proc = subprocess.Popen(
                shlex.split(pod.entrypoint), env=env,
                cwd=pod.workspace or None,
                stdout=stdout, stderr=subprocess.STDOUT,
                start_new_session=True,
            )
            pod.info.phase = "Running"
            log.info("spawned %s: %s (pid %d)",
                     pod.info.name, pod.entrypoint, pod.proc.pid)
        except OSError as e:
            log.error("spawn of %s failed: %s", pod.info.name, e)
            pod.info.phase = "Failed"
        finally:
            if stdout is not subprocess.DEVNULL:
                stdout.close()

    def _terminate(self, pod: _ProcPod, grace: float = 10.0) -> None:
        if pod.proc is None or pod.proc.poll() is not None:
            return
        # SIGTERM to the leader only (K8s signals PID 1; the launcher
        # forwards to its entry for the graceful drain)...
        pod.proc.terminate()
        try:
            pod.proc.wait(timeout=grace)
        except subprocess.TimeoutExpired:
            # ...but the grace-expiry escalation kills the whole pod group,
            # like a sandbox teardown: killing only a wedged leader would
            # orphan trainer children that keep heartbeating and holding
            # leases while the cluster re-books their chips.
            import signal

            try:
                os.killpg(os.getpgid(pod.proc.pid), signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pod.proc.kill()
            pod.proc.wait()

    def _reap(self) -> None:
        for pod in self.pods:
            if pod.proc is not None and pod.info.phase == "Running":
                rc = pod.proc.poll()
                if rc is not None:
                    pod.info.phase = "Succeeded" if rc == 0 else "Failed"

    def _reconcile(self, job_name: str) -> None:
        want = self._parallelism[job_name]
        live = [p for p in self.pods
                if p.info.job_name == job_name and p.info.role == "trainer"
                and p.info.phase in ("Pending", "Running")]
        if len(live) > want:
            # Newest-first eviction (K8s Job parallelism reduction). SIGTERM
            # gives the worker its leave()/checkpoint path; survivors observe
            # the membership epoch bump and rescale.
            for pod in live[want:]:
                self._terminate(pod)
                self.pods.remove(pod)
        elif len(live) < want:
            template = self._templates.get(job_name, {}).get("trainer")
            if template is None:
                return
            _, requests, limits, workload = template
            for _ in range(want - len(live)):
                self._spawn(job_name, "trainer", requests, limits, workload)

    def _reschedule(self) -> None:
        for pod in self.pods:
            if pod.info.phase == "Pending":
                self._place_and_start(pod)

    # -- chaos / failure-recovery surface --------------------------------------

    def kill_pod(self, pod_name: str) -> None:
        """SIGKILL the whole pod (process group) — a node crash / OOM kill /
        forced eviction: no SIGTERM, no drain, no termination log. The pod
        reaps to Failed; `restart_failed` models the Job controller's
        replacement."""
        import signal

        with self._lock:
            for pod in self.pods:
                if pod.info.name == pod_name and pod.proc is not None:
                    delivered = True
                    try:
                        os.killpg(os.getpgid(pod.proc.pid), signal.SIGKILL)
                    except ProcessLookupError:
                        pass  # already gone; reap below
                    except PermissionError:
                        delivered = False  # never block the cluster lock
                        pod.proc.kill()    # waiting on an unkilled group
                    if delivered:
                        pod.proc.wait()
                    else:
                        try:
                            pod.proc.wait(timeout=5.0)
                        except subprocess.TimeoutExpired:
                            pass
                    self._reap()
                    return
        raise KeyError(f"no live pod {pod_name}")

    def restart_failed(self, job_name: str, role: str = "trainer") -> int:
        """The K8s controller's reconcile for crashed pods: replace Failed
        pods of ``role`` with fresh ones up to the role's target count —
        the job's parallelism for trainers, the created replica count for
        every other role. A replaced trainer registers as a new worker and
        the dead one's membership/leases expire by TTL; a replaced
        COORDINATOR pod re-runs its workload with the same EDL_* env —
        same port, state_file, and run_id — so it resumes its journal
        (the master-ReplicaSet recovery, `pkg/controller.go:119-134`).
        Returns pods spawned."""
        with self._lock:
            self._reap()
            template = self._templates.get(job_name, {}).get(role)
            if template is None:
                return 0
            if role == "trainer" and job_name not in self._parallelism:
                return 0
            failed = [p for p in self.pods
                      if p.info.job_name == job_name
                      and p.info.role == role
                      and p.info.phase == "Failed"]
            for pod in failed:  # terminal records: GC like a Job controller
                self.pods.remove(pod)
            before = len(self.pods)
            if role == "trainer":
                self._reconcile(job_name)  # the spawn-up half lives there
            else:
                replicas, requests, limits, workload = template
                live = [p for p in self.pods
                        if p.info.job_name == job_name and p.info.role == role
                        and p.info.phase in ("Pending", "Running")]
                for _ in range(max(0, replicas - len(live))):
                    self._spawn(job_name, role, requests, limits, workload)
            return len(self.pods) - before
