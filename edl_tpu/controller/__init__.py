"""Control plane: cluster accounting, autoscaler, controller, per-job updater.

TPU-native re-design of the reference Go control plane (`pkg/controller.go`,
`pkg/autoscaler.go`, `pkg/cluster.go`, `pkg/updater/`): same split — a pure,
exhaustively-testable scheduling core; thin I/O edges behind a provider
interface; one actor goroutine-equivalent (thread) per job.
"""

from edl_tpu.controller.cluster import ClusterProvider, ClusterResource, FakeCluster, NodeInfo, PodInfo
from edl_tpu.controller.autoscaler import (
    Autoscaler,
    AutoscalerConfig,
    JobState,
    fulfillment,
    make_room_dry_run,
    scale_all_dry_run,
    scale_dry_run,
    sorted_jobs_by_fulfillment,
)

__all__ = [
    "Autoscaler",
    "AutoscalerConfig",
    "ClusterProvider",
    "ClusterResource",
    "FakeCluster",
    "JobState",
    "NodeInfo",
    "PodInfo",
    "fulfillment",
    "make_room_dry_run",
    "scale_all_dry_run",
    "scale_dry_run",
    "sorted_jobs_by_fulfillment",
]
