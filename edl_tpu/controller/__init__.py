"""Control plane: cluster accounting, autoscaler, controller, per-job updater.

TPU-native re-design of the reference Go control plane (`pkg/controller.go`,
`pkg/autoscaler.go`, `pkg/cluster.go`, `pkg/updater/`): same split — a pure,
exhaustively-testable scheduling core; thin I/O edges behind a provider
interface; one actor goroutine-equivalent (thread) per job.
"""

from edl_tpu.controller.cluster import ClusterProvider, ClusterResource, FakeCluster, NodeInfo, PodInfo
from edl_tpu.controller.autoscaler import (
    Autoscaler,
    AutoscalerConfig,
    JobState,
    fulfillment,
    make_room_dry_run,
    scale_all_dry_run,
    scale_dry_run,
    sorted_jobs_by_fulfillment,
)
from edl_tpu.controller.controller import Controller
from edl_tpu.controller.jobparser import (
    ROLE_COORDINATOR,
    ROLE_TRAINER,
    RoleWorkload,
    coordinator_endpoint,
    make_env,
    parse_job,
    parse_to_coordinator,
    parse_to_trainer,
    role_labels,
)
from edl_tpu.controller.store import FuncWatcher, JobStore, Watcher
from edl_tpu.controller.updater import JobUpdater, UpdaterConfig

__all__ = [
    "Autoscaler",
    "AutoscalerConfig",
    "ClusterProvider",
    "ClusterResource",
    "Controller",
    "FakeCluster",
    "FuncWatcher",
    "JobState",
    "JobStore",
    "JobUpdater",
    "NodeInfo",
    "PodInfo",
    "ROLE_COORDINATOR",
    "ROLE_TRAINER",
    "RoleWorkload",
    "UpdaterConfig",
    "Watcher",
    "coordinator_endpoint",
    "fulfillment",
    "make_env",
    "make_room_dry_run",
    "parse_job",
    "parse_to_coordinator",
    "parse_to_trainer",
    "role_labels",
    "scale_all_dry_run",
    "scale_dry_run",
    "sorted_jobs_by_fulfillment",
]
