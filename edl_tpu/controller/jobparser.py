"""Materialize a TrainingJob into role workloads + the env-var protocol.

Equivalent of the reference job parsers (`pkg/jobparser.go:74-311`,
`pkg/updater/jobparser.go:67-335`): given an admitted spec, produce per-role
workload descriptions (replica counts, resources, labels) and the environment
protocol every pod receives. The reference speaks ``PADDLE_*``
(`pkg/jobparser.go:263-311`); ours is ``EDL_*`` and TPU-shaped — instead of
pserver endpoint lists and sparse-port blocks (`pkg/jobparser.go:232-247`),
pods get the coordinator endpoint, the mesh-axis layout, and the TPU slice
shape; rank/world come from the coordinator at runtime, not from static env.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List

from edl_tpu.api.quantity import ResourceList
from edl_tpu.api.types import ReplicaSpec, TrainingJob

#: label keys (ref: pkg/updater/labels.go:9-18)
LABEL_JOB = "edl.tpu/job-name"
LABEL_ROLE = "edl.tpu/role"

ROLE_COORDINATOR = "coordinator"
ROLE_TRAINER = "trainer"


def role_labels(job_name: str, role: str) -> Dict[str, str]:
    """Selector labels for one role's pods (ref: pkg/updater/labels.go:9-18)."""
    return {LABEL_JOB: job_name, LABEL_ROLE: role}


@dataclass
class RoleWorkload:
    """One role's materialized workload: what the cluster provider creates.

    The analog of the reference's ReplicaSet/Job manifests
    (`pkg/jobparser.go:74-227`), reduced to what a ClusterProvider needs.
    """

    job_name: str
    role: str
    replicas: int
    image: str
    entrypoint: str
    requests: ResourceList
    limits: ResourceList
    env: Dict[str, str] = field(default_factory=dict)
    labels: Dict[str, str] = field(default_factory=dict)
    #: PVC to mount at the role's workspace (coordinator durability across
    #: pod rescheduling); empty -> pod-lifetime emptyDir.
    state_pvc: str = ""


def coordinator_endpoint(job: TrainingJob) -> str:
    """Stable coordinator address pods dial: a service-DNS-style name.

    The reference publishes MASTER_IP by resolving the master pod
    (`docker/paddle_k8s:131-134`); a headless-service name avoids that lookup.
    """
    return f"{job.name}-coordinator.{job.namespace}:{job.spec.port}"


def make_env(job: TrainingJob, role: str) -> Dict[str, str]:
    """The controller→pod env protocol (ref: pkg/jobparser.go:263-311).

    Deliberately rank-free: the reference bakes PADDLE_INIT_TRAINER_ID into
    the pod env via the sorted-pod-name trick (`docker/k8s_tools.py:127-151`),
    which breaks when pods churn. Here ranks are leased from the coordinator
    at register time (`edl_tpu.coordinator`), so a replaced pod can't collide.
    """
    spec = job.spec
    env = {
        "EDL_JOB_NAME": job.name,
        "EDL_NAMESPACE": job.namespace,
        "EDL_ROLE": role,
        "EDL_COORDINATOR_ENDPOINT": coordinator_endpoint(job),
        "EDL_PORT": str(spec.port),
        "EDL_NUM_TRAINERS": str(spec.trainer.min_instance),
        "EDL_MAX_TRAINERS": str(spec.trainer.max_instance),
        "EDL_FAULT_TOLERANT": "1" if spec.fault_tolerant else "0",
        "EDL_PASSES": str(spec.passes),
        "EDL_TPU_ACCELERATOR": spec.tpu.accelerator_type,
        "EDL_TPU_CHIPS": str(spec.tpu.chips_per_trainer),
        "EDL_MESH_AXES": json.dumps(spec.parallelism),
        "EDL_CHECKPOINT_DIR": spec.checkpoint_dir,
        "EDL_CHECKPOINT_INTERVAL": str(spec.checkpoint_interval),
        # Run identity for the coordinator's state file: the K8s object UID
        # when the apiserver assigned one, else namespace/name (in-memory
        # stores). Keeps a re-created job from resuming its predecessor's
        # done-set out of a reused workspace volume.
        "EDL_RUN_ID": job.uid or f"{job.namespace}/{job.name}",
    }
    if spec.auth_token:
        # Per-job coordinator secret: the coordinator binary reads it at
        # startup, CoordinatorClient attaches it to every call. Same value
        # in every pod of the job by construction.
        env["EDL_COORD_TOKEN"] = spec.auth_token
    replica: ReplicaSpec = spec.trainer if role == ROLE_TRAINER else spec.coordinator
    if replica.entrypoint:
        env["EDL_ENTRY"] = replica.entrypoint
    if replica.workspace:
        env["EDL_WORKSPACE"] = replica.workspace
    if spec.data_shards:
        env["EDL_DATA_SHARDS"] = json.dumps(spec.data_shards)
    env.update(replica.env)  # user env wins, like container env override order
    return env


def parse_to_coordinator(job: TrainingJob) -> RoleWorkload:
    """Coordinator workload (ref: ParseToMaster + etcd sidecar,
    `pkg/jobparser.go:167-227`) — one replica owning membership, leases, KV.
    The etcd sidecar has no analog: the native coordinator keeps its own state
    and restarts are survivable via the trainers' durable checkpoints.
    """
    spec = job.spec
    requests = spec.coordinator.resources.requests.copy()
    limits = spec.coordinator.resources.limits.copy()
    if not requests:  # fixed small footprint (ref: pkg/updater/jobparser.go:180-192)
        requests = ResourceList.make({"cpu": "250m", "memory": "128Mi"})
    return RoleWorkload(
        job_name=job.name,
        role=ROLE_COORDINATOR,
        replicas=1,
        image=spec.coordinator.image or spec.image,
        entrypoint=spec.coordinator.entrypoint
        or f"edl-launch start_coordinator --port {spec.port}",
        requests=requests,
        limits=limits,
        env=make_env(job, ROLE_COORDINATOR),
        labels=role_labels(job.name, ROLE_COORDINATOR),
        state_pvc=spec.coordinator.state_pvc,
    )


def parse_to_trainer(job: TrainingJob) -> RoleWorkload:
    """Trainer workload (ref: ParseToTrainer, `pkg/jobparser.go:120-165`).

    Starts at min_instance like the reference's initial Parallelism; the
    autoscaler raises it toward max_instance. Restart policy is the FakeCluster
    reconcile loop's job (ref: RestartPolicy Never + K8s Job replacement).
    """
    spec = job.spec
    return RoleWorkload(
        job_name=job.name,
        role=ROLE_TRAINER,
        replicas=spec.trainer.min_instance,
        image=spec.trainer.image or spec.image,
        entrypoint=spec.trainer.entrypoint or "edl-launch start_trainer",
        requests=job.trainer_request(),
        limits=job.trainer_limit(),
        env=make_env(job, ROLE_TRAINER),
        labels=role_labels(job.name, ROLE_TRAINER),
    )


def parse_job(job: TrainingJob) -> List[RoleWorkload]:
    """All workloads for a job, in creation order: coordinator first — trainers
    dial it at startup (ref creation order master→pserver→trainer,
    `pkg/updater/trainingJobUpdater.go:282-293`)."""
    return [parse_to_coordinator(job), parse_to_trainer(job)]
