"""Per-job lifecycle actor.

Re-design of the reference's per-job updater
(`pkg/updater/trainingJobUpdater.go:44-481`): one thread per TrainingJob owns
all of that job's control-plane state (the actor pattern the reference uses to
avoid locking its job map, `:74-75`), driven by a bounded event queue with a
high-water warning (`:19-26,80-86`), and a periodic status conversion tick
(10 s in the reference, `:22`).

Lifecycle: create coordinator, poll until ready, create trainers
(`:209-293` creation order master→pserver→trainer), then run the phase machine
None→Creating→Running→Succeeded/Failed (`:384-449`) with the reference's
fault-tolerance rules (`:359-380`): a strict job fails on ANY trainer failure;
a fault-tolerant job fails only when ALL trainers have failed. On completion
the coordinator role is released while trainer history is kept (`:343-382`);
deletion tears down both roles (`:99-207`).
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from dataclasses import dataclass
from typing import Dict, Optional

from edl_tpu.api.types import JobPhase, TrainerStatus, TrainingJob
from edl_tpu.api.validation import normalize
from edl_tpu.controller.cluster import ClusterProvider
from edl_tpu.controller.jobparser import (
    ROLE_COORDINATOR,
    ROLE_TRAINER,
    parse_to_coordinator,
    parse_to_trainer,
)
from edl_tpu.controller.store import JobStore

log = logging.getLogger("edl_tpu.controller.updater")

#: event-queue capacity + warning threshold (ref: trainingJobUpdater.go:19-26).
EVENT_QUEUE_CAP = 1000
EVENT_QUEUE_HIGH_WATER = 800


@dataclass
class UpdaterConfig:
    #: status conversion period (ref: 10 s, trainingJobUpdater.go:22).
    convert_seconds: float = 10.0
    #: readiness poll period while creating roles (ref: 5 s, :209-257).
    poll_seconds: float = 5.0
    #: give up on role creation after this long and fail the job.
    create_timeout: float = 600.0


class JobUpdater:
    """Actor owning one job's materialization, status, and teardown."""

    def __init__(
        self,
        job: TrainingJob,
        cluster: ClusterProvider,
        store: JobStore,
        config: Optional[UpdaterConfig] = None,
    ):
        self.job = normalize(job)
        self.cluster = cluster
        self.store = store
        self.config = config or UpdaterConfig()
        self._events: "queue.Queue[str]" = queue.Queue(maxsize=EVENT_QUEUE_CAP)
        self._stop = threading.Event()
        self._deleted = threading.Event()  # deletion requested
        self._gc_done = threading.Event()  # resources torn down
        self._gc_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._last_written_status: Optional[tuple] = None
        self.done = threading.Event()  # set once the actor exits

    # -- external surface (ref: Notify/Modify/Delete, :88-97) ------------------

    def start(self) -> "JobUpdater":
        self._thread = threading.Thread(  # edl: noqa[EDL001] started exactly once by the controller before the updater is shared
            target=self._run, name=f"edl-updater-{self.job.name}", daemon=True
        )
        self._thread.start()
        return self

    def notify_update(self, job: TrainingJob) -> None:
        self.job.spec = job.spec  # edl: noqa[EDL001,EDL006] atomic reference swap under the GIL; the actor thread reads it on its next tick
        self._enqueue("update")

    def record_scale(self, record) -> None:
        """Append an autoscaler actuation to status history. List append is
        atomic under the GIL; the actor persists it on its next status write."""
        self.job.status.scale_history.append(record)
        self._enqueue("update")

    def notify_delete(self) -> None:
        """Request teardown. The actor GCs in its exit path; if it already
        exited (terminal phase), GC runs on the caller's thread instead."""
        self._deleted.set()
        self._enqueue("delete")
        if self.done.is_set():
            self._gc_resources()

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        self._enqueue("stop")
        deadline = time.monotonic() + timeout
        if self._thread:
            self._thread.join(timeout=timeout)
        # A delete requested but never processed (actor raced past the event)
        # must still tear down — but within stop()'s remaining time budget:
        # if the actor thread is wedged inside _gc_resources holding the
        # lock, we return without waiting unboundedly for it.
        if self._deleted.is_set():
            self._gc_resources(lock_timeout=max(0.0, deadline - time.monotonic()))

    def _enqueue(self, kind: str) -> None:
        if self._events.qsize() >= EVENT_QUEUE_HIGH_WATER:
            log.warning(
                "updater %s event queue high water (%d)", self.job.name, self._events.qsize()
            )
        try:
            self._events.put_nowait(kind)
        except queue.Full:  # drop like the reference's full channel would block
            log.error("updater %s event queue full; dropping %s", self.job.name, kind)

    # -- status writeback (ref: updateCRDStatus, :295-307) ---------------------

    def _status_fingerprint(self) -> tuple:
        st = self.job.status
        return (
            st.phase,
            st.reason,
            st.parallelism,
            tuple(sorted((k, v.value) for k, v in st.replica_statuses.items())),
            len(st.scale_history),
        )

    def _set_phase(self, phase: JobPhase, reason: str = "") -> None:
        """Write status to the store only when it actually changed. The store
        echoes every write back as a watch event (informer semantics), so an
        unconditional write per tick would turn the convert loop into a
        busy loop: write -> echo -> event -> convert -> write ..."""
        self.job.status.phase = phase
        self.job.status.reason = reason
        fp = self._status_fingerprint()
        if fp == self._last_written_status:
            return
        try:
            self.store.update_status(self.job.name, self.job.status, self.job.namespace)
            self._last_written_status = fp
        except KeyError:
            pass  # job deleted from the store mid-flight
        except Exception as e:  # noqa: BLE001 — e.g. ApiError after the
            # store's conflict retries ran dry. The fingerprint stays
            # unrecorded, so the next convert tick rewrites; an actor crash
            # here would take the whole job down over a status blip.
            log.warning("status writeback for %s failed (will retry): %s",
                        self.job.name, e)

    # -- materialization (ref: createTrainingJob, :282-293) --------------------

    def _create_resources(self) -> bool:
        """Coordinator first, poll ready, then trainers. Returns success.

        Roles that already exist are adopted, not re-created — a controller
        restart replays running jobs through on_add, and duplicating pods of
        a live job would double its resource footprint.
        """
        self._set_phase(JobPhase.CREATING)
        self._ensure_auth_token()
        if not self.cluster.job_pods(self.job.name, ROLE_COORDINATOR):
            coord = parse_to_coordinator(self.job)
            self.cluster.create_role(
                self.job.name, ROLE_COORDINATOR, coord.replicas,
                coord.requests, coord.limits, workload=coord,
            )
        deadline = time.monotonic() + self.config.create_timeout
        while not self._coordinator_ready():
            if self._stop.is_set():
                return False
            if time.monotonic() > deadline:
                self._set_phase(JobPhase.FAILED, "coordinator never became ready")
                return False
            time.sleep(max(0.01, min(self.config.poll_seconds, deadline - time.monotonic())))
        existing = self.cluster.job_pods(self.job.name, ROLE_TRAINER)
        if existing:
            self.job.status.parallelism = self.cluster.get_trainer_parallelism(self.job.name)
        else:
            trainer = parse_to_trainer(self.job)
            self.cluster.create_role(
                self.job.name, ROLE_TRAINER, trainer.replicas,
                trainer.requests, trainer.limits, workload=trainer,
            )
            self.job.status.parallelism = trainer.replicas
        self._set_phase(JobPhase.RUNNING)
        return True

    def _ensure_auth_token(self) -> None:
        """Stamp a per-job coordinator secret into the spec at admission.

        Persisted through the store BEFORE any pod materializes, so a
        controller restart replays the same token instead of minting a new
        one under running pods (which would lock every trainer out of its
        own coordinator). Pods receive it as EDL_COORD_TOKEN (make_env).
        """
        if self.job.spec.auth_token:
            return
        import secrets

        self.job.spec.auth_token = secrets.token_hex(16)  # edl: noqa[EDL001] actor-thread-owned state; only the updater's own loop reaches admission
        try:
            self.job = normalize(self.store.update(self.job))  # edl: noqa[EDL001] atomic reference swap under the GIL, same as notify_update
        except KeyError:
            pass  # job deleted from the store mid-flight; actor will exit

    def _coordinator_ready(self) -> bool:
        pods = self.cluster.job_pods(self.job.name, ROLE_COORDINATOR)
        return bool(pods) and all(p.phase == "Running" for p in pods)

    # -- status conversion (ref: GetStatus/Convert, :343-414) ------------------

    def _convert(self) -> None:
        """Fold pod phases into job status; apply terminal-phase rules."""
        if self.job.status.phase.terminal():
            return
        pods = self.cluster.job_pods(self.job.name, ROLE_TRAINER)
        statuses: Dict[str, TrainerStatus] = {}
        counts = {"Pending": 0, "Running": 0, "Succeeded": 0, "Failed": 0}
        for p in pods:
            counts[p.phase] = counts.get(p.phase, 0) + 1
            statuses[p.name] = TrainerStatus(p.phase)
        self.job.status.replica_statuses = statuses
        self.job.status.parallelism = self.cluster.get_trainer_parallelism(self.job.name)

        total = len(pods)
        fault_tolerant = self.job.spec.fault_tolerant
        if total == 0:
            self._set_phase(self.job.status.phase)  # just refresh statuses
            return
        if not fault_tolerant and counts["Failed"] > 0:
            # Strict job: any failure fails the job (ref: :369-380).
            self._finish(JobPhase.FAILED, f"{counts['Failed']}/{total} trainers failed")
        elif fault_tolerant and counts["Failed"] == total:
            # FT job: dead only when everyone is (ref: :359-367).
            self._finish(JobPhase.FAILED, "all trainers failed")
        elif counts["Succeeded"] > 0 and counts["Running"] + counts["Pending"] == 0:
            # Work exhausted: remaining pods all terminal, at least one trainer
            # completed the task queue (FT) / all did (strict, no failures).
            self._finish(JobPhase.SUCCEEDED, "")
        else:
            self._set_phase(self.job.status.phase)

    def _finish(self, phase: JobPhase, reason: str) -> None:
        """Terminal transition: release the coordinator, keep trainer history
        (ref: releaseMaster/releasePserver on completion, :343-382)."""
        self._set_phase(phase, reason)
        try:
            self.cluster.delete_role(self.job.name, ROLE_COORDINATOR)
        except Exception:
            log.exception("releasing coordinator of %s failed", self.job.name)

    # -- teardown (ref: deleteTrainingJob + pod GC, :99-207) -------------------

    def _gc_resources(self, lock_timeout: Optional[float] = None) -> None:
        # Lock held through the teardown itself, not just the flag: a caller
        # returning from notify_delete must observe resources GONE, not
        # in-flight (the loser of the race blocks until the winner finishes).
        # ``lock_timeout`` bounds that wait for callers with their own
        # deadline (stop()); None preserves the block-until-done contract.
        acquired = self._gc_lock.acquire(
            timeout=lock_timeout if lock_timeout is not None else -1
        )
        if not acquired:
            log.warning(
                "gc of %s still in flight elsewhere; not waiting", self.job.name
            )
            return
        try:
            if self._gc_done.is_set():
                return
            for role in (ROLE_TRAINER, ROLE_COORDINATOR):
                try:
                    self.cluster.delete_role(self.job.name, role)
                except Exception:
                    log.exception(
                        "deleting role %s of %s failed", role, self.job.name
                    )
            self._gc_done.set()
        finally:
            self._gc_lock.release()

    # -- actor loop (ref: start, :453-481) -------------------------------------

    def _run(self) -> None:
        try:
            if not self._create_resources():
                if self._stop.is_set():
                    return
                # creation failed: leave resources for debugging, like the
                # reference leaves the failed RS; deletion GCs them.
            while not self._stop.is_set():
                try:
                    evt = self._events.get(timeout=self.config.convert_seconds)
                except queue.Empty:
                    evt = "tick"
                if evt in ("delete", "stop"):
                    return
                try:
                    self._convert()
                except Exception:
                    log.exception("convert failed for %s", self.job.name)
                if self.job.status.phase.terminal():
                    return
        finally:
            # Set done BEFORE checking _deleted, mirroring notify_delete's
            # set-_deleted-then-check-done: whichever thread writes second is
            # guaranteed to see the other's flag, so GC cannot be skipped when
            # notify_delete races the actor's exit. (_gc_resources is
            # lock-idempotent, so both seeing it is fine.)
            self.done.set()
            if self._deleted.is_set():
                self._gc_resources()
