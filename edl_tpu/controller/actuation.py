"""Actuation bridge: control-plane scale decisions → the job's coordinator.

The reference's scale actuation is one write — `Spec.Parallelism` on the
trainer Job (`/root/reference/pkg/autoscaler.go:339-376`) — because its data
plane discovers world size from Kubernetes itself. Ours is two writes: the
provider reconciles the pod count, but live workers rendezvous at the world
size read from the coordinator KV (``edl/expected_world``,
`edl_tpu/runtime/distributed.py:86-93`). This module is the second write:

1. **publish** the target world under ``edl/expected_world`` *before* the
   provider actuates, so a worker (re)starting mid-rescale already sees the
   new target;
2. **nudge** the membership epoch after actuation (``bump_epoch``), so
   workers parked in ``sync()`` resync immediately instead of waiting for a
   pod-churn membership event — this is what turns an autoscaler decision
   into a live-job warm restart.

Endpoints default to the controller-stamped DNS name
(`jobparser.coordinator_endpoint`); hermetic tests and local process pools
override per-job with ``set_endpoint``.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, Tuple

from edl_tpu.api.types import TrainingJob
from edl_tpu.controller.jobparser import coordinator_endpoint
from edl_tpu.obs.metrics import get_registry
from edl_tpu.obs.tracing import get_tracer, rescale_trace_id

#: rescale actuations that reached the job's coordinator (epoch bumped).
_M_NUDGES = get_registry().counter(
    "edl_controller_nudges_total",
    "epoch-bump actuations delivered to job coordinators, by kind",
    labelnames=("kind",),  # nudge | publish_and_nudge
)

log = logging.getLogger("edl_tpu.controller.actuation")

#: KV key the runtime reads its target world size from
#: (must match edl_tpu/runtime/distributed.py:EXPECTED_WORLD_KEY).
EXPECTED_WORLD_KEY = "edl/expected_world"


class CoordinatorActuator:
    """Dials per-job coordinators to publish rescale targets."""

    def __init__(self, dial_timeout: float = 3.0, dial_backoff: float = 5.0):
        self.dial_timeout = dial_timeout
        #: after a dial failure, skip dialing that job this long — an
        #: unreachable coordinator (still materializing, or a DNS name that
        #: only resolves in-cluster) must not stall every autoscaler loop
        #: for the full dial timeout
        self.dial_backoff = dial_backoff
        self._lock = threading.Lock()
        self._endpoints: Dict[str, Tuple[str, int]] = {}
        self._backoff_until: Dict[str, float] = {}
        #: per-job coordinator secrets (spec.auth_token): the controller's
        #: own writes must authenticate like any pod's, or every rescale
        #: publish/nudge would be rejected the moment a job has auth on.
        self._tokens: Dict[str, str] = {}

    # -- endpoint registry -----------------------------------------------------

    def track(self, job: TrainingJob) -> None:
        """Derive the job's coordinator endpoint from its spec (the stable
        service DNS name the pods themselves dial) and record its auth
        token (the updater may mint it after the first track call, so the
        token refreshes on every call even though the endpoint is sticky)."""
        host, _, port = coordinator_endpoint(job).rpartition(":")
        with self._lock:
            # An explicit endpoint (set_endpoint) wins over the derived one:
            # tests and local pools register the real host:port first.
            self._endpoints.setdefault(job.name, (host, int(port)))
            if job.spec.auth_token:
                self._tokens[job.name] = job.spec.auth_token

    def set_endpoint(self, job_name: str, host: str, port: int,
                     token: str = "") -> None:
        with self._lock:
            self._endpoints[job_name] = (host, int(port))
            if token:
                self._tokens[job_name] = token

    def forget(self, job_name: str) -> None:
        with self._lock:
            self._endpoints.pop(job_name, None)
            # a re-created same-name job must not inherit this backoff
            self._backoff_until.pop(job_name, None)
            self._tokens.pop(job_name, None)

    def _dial(self, job_name: str, force: bool = False):
        import time

        with self._lock:
            endpoint = self._endpoints.get(job_name)
            token = self._tokens.get(job_name, "")
            if endpoint is None:
                return None
            if (not force
                    and time.monotonic() < self._backoff_until.get(job_name, 0.0)):
                return None
        from edl_tpu.coordinator.client import CoordinatorClient

        try:
            client = CoordinatorClient(
                host=endpoint[0], port=endpoint[1],
                worker=f"controller/{job_name}",
                connect_timeout=self.dial_timeout,
                token=token,
            )
        except Exception:
            with self._lock:
                self._backoff_until[job_name] = (
                    time.monotonic() + self.dial_backoff
                )
            raise
        with self._lock:
            self._backoff_until.pop(job_name, None)
        return client

    # -- the two writes --------------------------------------------------------

    def publish_expected_world(self, job_name: str, world: int) -> bool:
        """Write the rescale target. Failures (including dial failures — the
        coordinator may still be materializing, or the DNS name may not
        resolve outside the cluster) are non-fatal: workers fall back to
        membership-driven convergence (`EDL_NUM_TRAINERS` + epoch events),
        and the provider actuation must never be blocked by this write."""
        try:
            client = self._dial(job_name)
            if client is None:
                return False
            with client:
                client.kv_put(EXPECTED_WORLD_KEY, str(int(world)))
            return True
        except Exception as e:
            log.debug("publish expected_world=%d to %s failed: %s",
                      world, job_name, e)
            return False

    def nudge(self, job_name: str) -> bool:
        """Bump the membership epoch so parked workers resync now."""
        t0 = time.time()
        try:
            client = self._dial(job_name)
            if client is None:
                return False
            with client:
                epoch = client.bump_epoch()
            # The bump_epoch reply hands us the SAME epoch every worker will
            # adopt on re-register — the cross-process rescale correlator.
            get_tracer().record("actuate", t0, time.time(),
                                trace_id=rescale_trace_id(epoch),
                                component="controller", job=job_name)
            _M_NUDGES.inc(kind="nudge")
            log.info("nudged %s to epoch %d", job_name, epoch)
            return True
        except Exception as e:
            log.debug("nudge of %s failed: %s", job_name, e)
            return False

    def publish_and_nudge(self, job_name: str, world: int) -> bool:
        """Both writes over ONE dial — the scale-down path needs the epoch
        moved before any pod is killed, and two sequential dial timeouts
        against an unreachable coordinator would stall the autoscaler loop
        twice as long for nothing.

        Ignores the dial backoff (``force``): shrinks are rare and this
        write is correctness-relevant (it dissolves the gang at a round
        boundary before the SIGTERMs land), so it always deserves a fresh
        dial attempt. A *still*-unreachable coordinator logs a warning —
        the caller proceeds anyway (the controller may legitimately sit
        outside the coordinator's network, e.g. a DNS name that only
        resolves in-cluster; workers then fall back to termination-driven
        membership events and poll/TTL timeouts)."""
        t0 = time.time()
        try:
            client = self._dial(job_name, force=True)
            if client is None:
                self._warn_unreachable(job_name, world)
                return False
            with client:
                client.kv_put(EXPECTED_WORLD_KEY, str(int(world)))
                epoch = client.bump_epoch()
            get_tracer().record("actuate", t0, time.time(),
                                trace_id=rescale_trace_id(epoch),
                                component="controller", job=job_name,
                                world=int(world))
            _M_NUDGES.inc(kind="publish_and_nudge")
            log.info("published world=%d and nudged %s to epoch %d",
                     world, job_name, epoch)
            return True
        except Exception as e:
            self._warn_unreachable(job_name, world, e)
            return False

    def _warn_unreachable(self, job_name, world, err=None):
        log.warning(
            "scale-down of %s to world=%d proceeds WITHOUT the "
            "epoch-before-SIGTERM handshake (coordinator unreachable%s); "
            "victims that miss their graceful drain leave survivors to "
            "recover via poll timeouts / membership TTL",
            job_name, world, f": {err}" if err else "",
        )
