"""Actuation bridge: control-plane scale decisions → the job's coordinator.

The reference's scale actuation is one write — `Spec.Parallelism` on the
trainer Job (`/root/reference/pkg/autoscaler.go:339-376`) — because its data
plane discovers world size from Kubernetes itself. Ours is two writes: the
provider reconciles the pod count, but live workers rendezvous at the world
size read from the coordinator KV (``edl/expected_world``,
`edl_tpu/runtime/distributed.py:86-93`). This module is the second write:

1. **publish** the target world under ``edl/expected_world`` *before* the
   provider actuates, so a worker (re)starting mid-rescale already sees the
   new target;
2. **nudge** the membership epoch after actuation (``bump_epoch``), so
   workers parked in ``sync()`` resync immediately instead of waiting for a
   pod-churn membership event — this is what turns an autoscaler decision
   into a live-job warm restart.

Endpoints default to the controller-stamped DNS name
(`jobparser.coordinator_endpoint`); hermetic tests and local process pools
override per-job with ``set_endpoint``.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, Optional, Tuple

from edl_tpu.api.types import TrainingJob
from edl_tpu.controller.jobparser import coordinator_endpoint

log = logging.getLogger("edl_tpu.actuation")

#: KV key the runtime reads its target world size from
#: (must match edl_tpu/runtime/distributed.py:EXPECTED_WORLD_KEY).
EXPECTED_WORLD_KEY = "edl/expected_world"


class CoordinatorActuator:
    """Dials per-job coordinators to publish rescale targets."""

    def __init__(self, dial_timeout: float = 3.0):
        self.dial_timeout = dial_timeout
        self._lock = threading.Lock()
        self._endpoints: Dict[str, Tuple[str, int]] = {}

    # -- endpoint registry -----------------------------------------------------

    def track(self, job: TrainingJob) -> None:
        """Derive the job's coordinator endpoint from its spec (the stable
        service DNS name the pods themselves dial)."""
        host, _, port = coordinator_endpoint(job).rpartition(":")
        with self._lock:
            # An explicit endpoint (set_endpoint) wins over the derived one:
            # tests and local pools register the real host:port first.
            self._endpoints.setdefault(job.name, (host, int(port)))

    def set_endpoint(self, job_name: str, host: str, port: int) -> None:
        with self._lock:
            self._endpoints[job_name] = (host, int(port))

    def forget(self, job_name: str) -> None:
        with self._lock:
            self._endpoints.pop(job_name, None)

    def _dial(self, job_name: str):
        with self._lock:
            endpoint = self._endpoints.get(job_name)
        if endpoint is None:
            return None
        from edl_tpu.coordinator.client import CoordinatorClient

        return CoordinatorClient(
            host=endpoint[0], port=endpoint[1],
            worker=f"controller/{job_name}", connect_timeout=self.dial_timeout,
        )

    # -- the two writes --------------------------------------------------------

    def publish_expected_world(self, job_name: str, world: int) -> bool:
        """Write the rescale target. Failures (including dial failures — the
        coordinator may still be materializing, or the DNS name may not
        resolve outside the cluster) are non-fatal: workers fall back to
        membership-driven convergence (`EDL_NUM_TRAINERS` + epoch events),
        and the provider actuation must never be blocked by this write."""
        try:
            client = self._dial(job_name)
            if client is None:
                return False
            with client:
                client.kv_put(EXPECTED_WORLD_KEY, str(int(world)))
            return True
        except Exception as e:
            log.debug("publish expected_world=%d to %s failed: %s",
                      world, job_name, e)
            return False

    def nudge(self, job_name: str) -> bool:
        """Bump the membership epoch so parked workers resync now."""
        try:
            client = self._dial(job_name)
            if client is None:
                return False
            with client:
                epoch = client.bump_epoch()
            log.info("nudged %s to epoch %d", job_name, epoch)
            return True
        except Exception as e:
            log.debug("nudge of %s failed: %s", job_name, e)
            return False
