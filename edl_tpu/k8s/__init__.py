"""Kubernetes backend for the EDL-TPU control plane.

The reference controller talks to kube-apiserver through the generated
clientset (`/root/reference/pkg/client/clientset/versioned/typed/paddlepaddle/
v1/trainingjob.go:33-153`) and `client-go` REST machinery. This package is the
from-scratch TPU-native equivalent, built on the stdlib only (the environment
has no `kubernetes` pip package and installs are off-limits):

- :mod:`edl_tpu.k8s.config`  — kubeconfig / in-cluster credential loading
  (ref: `cmd/edl/edl.go:31-36` rest.InClusterConfig | BuildConfigFromFlags).
- :mod:`edl_tpu.k8s.client`  — minimal REST client: CRUD + PATCH + chunked
  watch streams against the apiserver.
- :mod:`edl_tpu.k8s.cluster` — ``K8sCluster``: the real ``ClusterProvider``
  (node/pod scans à la `pkg/cluster.go:176-242`, role creation as
  Deployments/Jobs, `spec.parallelism` patch as the scale actuator).
- :mod:`edl_tpu.k8s.store`   — ``K8sJobStore``: TrainingJob CRD client +
  informer-style list/watch with status-subresource writeback
  (ref: `pkg/client/.../trainingjob.go:102-115`, `pkg/controller.go:79-108`).
"""

from edl_tpu.k8s.client import ApiClient, ApiError
from edl_tpu.k8s.cluster import K8sCluster
from edl_tpu.k8s.config import KubeConfig
from edl_tpu.k8s.store import K8sJobStore

__all__ = [
    "ApiClient",
    "ApiError",
    "K8sCluster",
    "K8sJobStore",
    "KubeConfig",
]
