"""K8sJobStore: the TrainingJob CRD client + informer.

Production implementation of the ``JobStore`` surface
(`edl_tpu/controller/store.py`), equivalent to the reference's generated typed
client + shared informer (`/root/reference/pkg/client/clientset/versioned/
typed/paddlepaddle/v1/trainingjob.go:33-153`, `pkg/client/informers/
externalversions/factory.go:43-117`) driving `cache.NewInformer`
(`pkg/controller.go:79-108`):

- CRUD against ``/apis/edl.tpu/v1/.../trainingjobs`` (the CRD installed by
  `deploy/crd.yaml`), status writes through the ``/status`` subresource
  (ref: UpdateStatus, `trainingjob.go:102-115`).
- A single background list+watch loop maintaining a local cache and fanning
  add/update/delete events out to registered watchers; 410 Gone triggers a
  relist with diff-based event replay (informer resync semantics).

Errors map onto the in-memory store's contract: missing objects raise
``KeyError`` so the controller/updater code runs unchanged on either backend.
"""

from __future__ import annotations

import copy
import logging
import threading
import time
from typing import Dict, List, Optional

from edl_tpu.api.types import TrainingJob, TrainingJobStatus
from edl_tpu.controller.store import Watcher
from edl_tpu.k8s.client import ApiClient, ApiError

log = logging.getLogger("edl_tpu.k8s.store")

GROUP_VERSION = "edl.tpu/v1"
PLURAL = "trainingjobs"


def to_crd(job: TrainingJob) -> dict:
    body = job.to_dict()
    body["apiVersion"] = GROUP_VERSION
    body["kind"] = "TrainingJob"
    return body


def from_crd(obj: dict) -> TrainingJob:
    return TrainingJob.from_dict(obj)


class K8sJobStore:
    """TrainingJob CRUD + watch over the CRD REST API."""

    def __init__(
        self,
        api: ApiClient,
        namespace: Optional[str] = None,
        watch_timeout_seconds: float = 300.0,
    ):
        self.api = api
        self.namespace = namespace or api.config.namespace or "default"
        self.watch_timeout_seconds = watch_timeout_seconds
        self._lock = threading.RLock()
        self._watchers: List[Watcher] = []
        self._cache: Dict[str, TrainingJob] = {}  # ns/name -> last seen
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- paths -----------------------------------------------------------------

    def _path(self, name: str = "", namespace: Optional[str] = None) -> str:
        ns = namespace or self.namespace
        path = f"/apis/{GROUP_VERSION}/namespaces/{ns}/{PLURAL}"
        return f"{path}/{name}" if name else path

    @property
    def _all_ns_path(self) -> str:
        return f"/apis/{GROUP_VERSION}/{PLURAL}"

    @property
    def _watch_path(self) -> str:
        """The informer's scope: the managed namespace only. A controller
        watching all namespaces but materializing workloads into its own
        (K8sCluster is namespace-scoped) would adopt foreign jobs and drop
        their pods in the wrong place."""
        return self._path()

    @staticmethod
    def _key(name: str, namespace: str) -> str:
        return f"{namespace}/{name}"

    # -- CRUD (ref: typed/paddlepaddle/v1/trainingjob.go:44-153) ---------------

    def create(self, job: TrainingJob) -> TrainingJob:
        try:
            out = self.api.post(self._path(namespace=job.namespace), to_crd(job))
        except ApiError as e:
            if e.conflict:
                raise KeyError(
                    f"trainingjob {job.namespace}/{job.name} already exists"
                ) from e
            raise
        return from_crd(out)

    def get(self, name: str, namespace: str = "default") -> TrainingJob:
        try:
            return from_crd(self.api.get(self._path(name, namespace)))
        except ApiError as e:
            if e.not_found:
                raise KeyError(f"trainingjob {namespace}/{name} not found") from e
            raise

    def list(self, namespace: Optional[str] = None) -> List[TrainingJob]:
        path = self._all_ns_path if namespace is None else self._path(
            namespace=namespace
        )
        return [from_crd(o) for o in self.api.get(path).get("items", [])]

    def update(self, job: TrainingJob) -> TrainingJob:
        """Replace spec/labels; status is a subresource and survives untouched
        (a merge patch cannot write it through the main resource)."""
        try:
            out = self.api.patch(
                self._path(job.name, job.namespace),
                {
                    "metadata": {"labels": dict(job.labels)},
                    "spec": job.spec.to_dict(),
                },
            )
        except ApiError as e:
            if e.not_found:
                raise KeyError(
                    f"trainingjob {job.namespace}/{job.name} not found"
                ) from e
            raise
        return from_crd(out)

    def update_status(
        self, name: str, status: TrainingJobStatus, namespace: str = "default"
    ) -> TrainingJob:
        body = to_crd(TrainingJob(name=name, namespace=namespace, status=status))
        last: Optional[ApiError] = None
        for attempt in range(4):
            try:
                out = self.api.patch(
                    self._path(name, namespace) + "/status",
                    {"status": body["status"]},
                )
                return from_crd(out)
            except ApiError as e:
                if e.not_found:
                    raise KeyError(
                        f"trainingjob {namespace}/{name} not found"
                    ) from e
                if not e.conflict:
                    raise
                # 409 on the status subresource: a concurrent writer moved
                # the rv between our read and write. A merge patch carries
                # no rv, so the retry applies our intent to the fresh
                # object — the standard controller-side conflict loop.
                last = e
                time.sleep(0.02 * (attempt + 1))
        raise last  # conflicts 4x in a row: surface it

    def delete(self, name: str, namespace: str = "default") -> TrainingJob:
        try:
            existing = self.get(name, namespace)
            self.api.delete(self._path(name, namespace))
        except ApiError as e:
            if e.not_found:
                raise KeyError(f"trainingjob {namespace}/{name} not found") from e
            raise
        return existing

    # -- watch / informer ------------------------------------------------------

    def watch(self, watcher: Watcher, replay: bool = True) -> None:
        """Register a watcher; replays the current cache (after a synchronous
        initial list on first use) as on_add, then streams live events."""
        with self._lock:
            first = self._thread is None
            if first:
                self._initial_list()
            self._watchers.append(watcher)
            snapshot = (
                [copy.deepcopy(j) for j in self._cache.values()] if replay else []
            )
            if first:
                self._thread = threading.Thread(
                    target=self._run, name="edl-k8s-informer", daemon=True
                )
                self._thread.start()
        for job in snapshot:
            watcher.on_add(job)

    def unwatch(self, watcher: Watcher) -> None:
        with self._lock:
            self._watchers = [w for w in self._watchers if w is not watcher]

    def stop(self) -> None:
        self._stop.set()

    def _notify(self, kind: str, job: TrainingJob) -> None:
        with self._lock:
            watchers = list(self._watchers)
        for w in watchers:
            try:
                getattr(w, f"on_{kind}")(copy.deepcopy(job))
            except Exception:
                log.exception("watcher %s failed on %s", w, kind)

    # -- informer internals ----------------------------------------------------

    def _initial_list(self) -> None:
        data = self.api.get(self._watch_path)
        self._resource_version = (data.get("metadata", {}) or {}).get(
            "resourceVersion", ""
        )
        self._cache = {
            self._key(j.name, j.namespace): j
            for j in (from_crd(o) for o in data.get("items", []))
        }

    def _relist(self) -> None:
        """List from scratch and emit the diff vs the cache (post-410 resync)."""
        data = self.api.get(self._watch_path)
        fresh = {
            self._key(j.name, j.namespace): j
            for j in (from_crd(o) for o in data.get("items", []))
        }
        with self._lock:
            self._resource_version = (data.get("metadata", {}) or {}).get(
                "resourceVersion", ""
            )
            old = self._cache
            self._cache = fresh
        for key, job in fresh.items():
            if key not in old:
                self._notify("add", job)
            elif job.to_dict() != old[key].to_dict():
                self._notify("update", job)
        for key, job in old.items():
            if key not in fresh:
                self._notify("del", job)

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                for event in self.api.watch(
                    self._watch_path,
                    params={"resourceVersion": self._resource_version},
                    timeout_seconds=self.watch_timeout_seconds,
                ):
                    if self._stop.is_set():
                        return
                    self._handle(event)
                # normal stream end → rewatch from last seen version
            except ApiError as e:
                if e.gone:
                    try:
                        self._relist()
                    except Exception:
                        log.exception("informer relist failed")
                        self._stop.wait(1.0)
                else:
                    log.warning("watch failed (%s); retrying", e)
                    self._stop.wait(1.0)
            except Exception as e:
                log.warning("watch stream error (%s); retrying", e)
                self._stop.wait(1.0)

    def _handle(self, event: dict) -> None:
        obj = event.get("object", {}) or {}
        rv = (obj.get("metadata", {}) or {}).get("resourceVersion")
        if rv:
            with self._lock:
                self._resource_version = rv
        kind = event.get("type")
        if kind == "BOOKMARK":
            # rv-progress marker (metadata-only object): advance the
            # cursor — already done above — and deliver nothing.
            return
        job = from_crd(obj)
        key = self._key(job.name, job.namespace)
        if kind == "ADDED":
            with self._lock:
                known = key in self._cache
                self._cache[key] = job
            # A re-watch can replay an ADDED for an object the cache already
            # has; deliver it as an update so consumers stay idempotent.
            self._notify("update" if known else "add", job)
        elif kind == "MODIFIED":
            with self._lock:
                self._cache[key] = job
            self._notify("update", job)
        elif kind == "DELETED":
            with self._lock:
                self._cache.pop(key, None)
            self._notify("del", job)
