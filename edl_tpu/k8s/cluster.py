"""K8sCluster: the Kubernetes-backed ClusterProvider.

The production implementation of the `ClusterProvider` protocol
(`edl_tpu/controller/cluster.py:135-153`), mirroring what the reference's
`Cluster` does against a live apiserver (`/root/reference/pkg/cluster.go`):

- ``inquire``           — scan node allocatables + non-terminated pod
  requests/limits into a ``ClusterResource`` snapshot (`cluster.go:176-242`).
- ``job_pods``          — label-selector pod listing (`cluster.go:117-136`).
- ``get/set_trainer_parallelism`` — the scale actuator: read/patch the trainer
  Job's ``spec.parallelism`` (`cluster.go:91-113`).
- ``create_role`` / ``delete_role`` — materialize the coordinator as a
  Deployment+Service and trainers as a batch Job, GC pods by label
  (`cluster.go:245-291`, `pkg/updater/trainingJobUpdater.go:99-207`).

TPU-native difference: the schedulable accelerator is the node resource
``google.com/tpu`` (chips on this host's slice), surfaced internally under the
``tpu`` key the autoscaler's granule-aware dry run consumes — where the
reference counted ``nvidia.com/gpu`` (`pkg/cluster.go:224-232`).
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional

from edl_tpu.api.quantity import ResourceList, format_quantity, parse_quantity
from edl_tpu.controller.cluster import NodeInfo, PodInfo, inquire_resource
from edl_tpu.controller.jobparser import (
    LABEL_JOB,
    LABEL_ROLE,
    ROLE_COORDINATOR,
    ROLE_TRAINER,
    RoleWorkload,
    role_labels,
)
from edl_tpu.k8s.client import ApiClient, ApiError

log = logging.getLogger("edl_tpu.k8s.cluster")

#: the TPU chip resource as GKE exposes it; mapped to the internal "tpu" key.
TPU_RESOURCE = "google.com/tpu"

#: internal key -> K8s resource name (identity except the accelerator).
_TO_K8S_KEY = {"tpu": TPU_RESOURCE}
_FROM_K8S_KEY = {TPU_RESOURCE: "tpu"}


def resources_from_k8s(spec: Optional[dict]) -> ResourceList:
    """K8s resource map (``{"cpu": "2", "google.com/tpu": "4"}``) → ResourceList."""
    out = ResourceList()
    for key, value in (spec or {}).items():
        out[_FROM_K8S_KEY.get(key, key)] = float(parse_quantity(value))
    return out


def resources_to_k8s(rl: ResourceList) -> dict:
    """ResourceList → K8s resource map, chips as integer counts."""
    out = {}
    for key, value in rl.items():
        k8s_key = _TO_K8S_KEY.get(key, key)
        if key == "tpu":
            out[k8s_key] = str(int(value))
        else:
            out[k8s_key] = format_quantity(value)
    return out


def _selector(labels: Dict[str, str]) -> str:
    return ",".join(f"{k}={v}" for k, v in sorted(labels.items()))


def _pod_info(pod: dict) -> PodInfo:
    meta = pod.get("metadata", {})
    labels = meta.get("labels", {}) or {}
    requests = ResourceList()
    limits = ResourceList()
    spec = pod.get("spec", {}) or {}
    for container in spec.get("containers", []) or []:
        res = container.get("resources", {}) or {}
        requests.add(resources_from_k8s(res.get("requests")))
        limits.add(resources_from_k8s(res.get("limits")))
    return PodInfo(
        name=meta.get("name", ""),
        job_name=labels.get(LABEL_JOB, ""),
        role=labels.get(LABEL_ROLE, ""),
        phase=(pod.get("status", {}) or {}).get("phase", "Pending"),
        requests=requests,
        limits=limits,
        node=spec.get("nodeName", "") or "",
    )


class K8sCluster:
    """ClusterProvider over a live (or fake, in tests) kube-apiserver."""

    def __init__(self, api: ApiClient, namespace: Optional[str] = None):
        self.api = api
        self.namespace = namespace or api.config.namespace or "default"

    # -- naming ----------------------------------------------------------------

    @staticmethod
    def workload_name(job_name: str, role: str) -> str:
        return f"{job_name}-{role}"

    def _ns_path(self, group_version: str, kind: str, name: str = "") -> str:
        base = (
            f"/api/{group_version}" if group_version == "v1"
            else f"/apis/{group_version}"
        )
        path = f"{base}/namespaces/{self.namespace}/{kind}"
        return f"{path}/{name}" if name else path

    # -- inquiry (ref: InquiryResource, pkg/cluster.go:176-242) ----------------

    def inquire(self):
        nodes = [
            NodeInfo(
                name=n.get("metadata", {}).get("name", ""),
                allocatable=resources_from_k8s(
                    (n.get("status", {}) or {}).get("allocatable")
                ),
            )
            for n in self.api.get("/api/v1/nodes").get("items", [])
        ]
        # All namespaces: other tenants' pods consume capacity too
        # (ref: Pods(all ns) listing, cluster.go:202-210).
        pods = [
            _pod_info(p) for p in self.api.get("/api/v1/pods").get("items", [])
        ]
        live = [p for p in pods if p.phase not in ("Succeeded", "Failed")]
        return inquire_resource(nodes, live)

    def job_pods(self, job_name: str, role: str = ROLE_TRAINER) -> List[PodInfo]:
        data = self.api.get(
            self._ns_path("v1", "pods"),
            params={"labelSelector": _selector(role_labels(job_name, role))},
        )
        return [_pod_info(p) for p in data.get("items", [])]

    # -- scale actuation (ref: Get/UpdateTrainerJob, pkg/cluster.go:91-113) ----

    def get_trainer_parallelism(self, job_name: str) -> int:
        try:
            job = self.api.get(
                self._ns_path(
                    "batch/v1", "jobs", self.workload_name(job_name, ROLE_TRAINER)
                )
            )
        except ApiError as e:
            if e.not_found:
                return 0
            raise
        return int((job.get("spec", {}) or {}).get("parallelism", 0))

    def set_trainer_parallelism(self, job_name: str, parallelism: int) -> None:
        name = self.workload_name(job_name, ROLE_TRAINER)
        try:
            self.api.patch(
                self._ns_path("batch/v1", "jobs", name),
                {"spec": {"parallelism": int(parallelism)}},
            )
        except ApiError as e:
            if e.not_found:
                raise KeyError(f"unknown trainer job {job_name}") from e
            raise

    # -- role materialization (ref: CreateJob/CreateReplicaSet,
    #    pkg/cluster.go:245-267; manifests pkg/jobparser.go:74-227) ------------

    def create_role(
        self,
        job_name: str,
        role: str,
        replicas: int,
        requests: ResourceList,
        limits: ResourceList,
        workload: Optional[RoleWorkload] = None,
    ) -> None:
        """Create the role's workload. ``workload`` carries image/entrypoint/
        env; without it a bare pause-style manifest is created (enough for
        accounting tests, not for a real job — the updater always passes it).
        """
        labels = role_labels(job_name, role)
        container = {
            "name": role,
            "image": workload.image if workload else "edl-tpu:latest",
            "resources": {
                "requests": resources_to_k8s(requests),
                "limits": resources_to_k8s(limits),
            },
        }
        if workload:
            if workload.entrypoint:
                container["command"] = ["/bin/sh", "-c", workload.entrypoint]
            container["env"] = [
                {"name": k, "value": v} for k, v in sorted(workload.env.items())
            ]
        pod_template = {
            "metadata": {"labels": labels},
            "spec": {
                "containers": [container],
                # Ref: trainer RestartPolicy Never (`pkg/jobparser.go:160`) —
                # the Job controller replaces failed pods up to parallelism;
                # per-process retry policy lives in our launcher.
                "restartPolicy": "Never" if role == ROLE_TRAINER else "Always",
            },
        }
        if role == ROLE_COORDINATOR and workload:
            # Back the coordinator's state file (launch.py start_coordinator
            # keeps the task queue/done-set/KV there). With
            # spec.coordinator.state_pvc the volume is a PersistentVolumeClaim
            # — state survives pod RESCHEDULING, the full etcd-sidecar
            # durability story; otherwise a pod-lifetime emptyDir still
            # covers container crashes.
            workspace = workload.env.get("EDL_WORKSPACE")
            if workspace:
                if workload.state_pvc:
                    volume = {
                        "name": "coordinator-state",
                        "persistentVolumeClaim": {"claimName": workload.state_pvc},
                    }
                else:
                    volume = {"name": "coordinator-state", "emptyDir": {}}
                pod_template["spec"]["volumes"] = [volume]
                container["volumeMounts"] = [
                    {"name": "coordinator-state", "mountPath": workspace}
                ]
        name = self.workload_name(job_name, role)
        if role == ROLE_COORDINATOR:
            self._create(
                self._ns_path("apps/v1", "deployments"),
                {
                    "apiVersion": "apps/v1",
                    "kind": "Deployment",
                    "metadata": {"name": name, "labels": labels},
                    "spec": {
                        "replicas": int(replicas),
                        "selector": {"matchLabels": labels},
                        "template": pod_template,
                    },
                },
            )
            # Headless service = the stable coordinator DNS name pods dial
            # (jobparser.coordinator_endpoint), replacing the reference's
            # resolve-the-master-pod-IP dance (`docker/paddle_k8s:131-134`).
            self._create(
                self._ns_path("v1", "services"),
                {
                    "apiVersion": "v1",
                    "kind": "Service",
                    "metadata": {"name": name, "labels": labels},
                    "spec": {
                        "clusterIP": "None",
                        "selector": labels,
                        "ports": [{"name": "coordinator", "port": 7164}],
                    },
                },
            )
        else:
            self._create(
                self._ns_path("batch/v1", "jobs"),
                {
                    "apiVersion": "batch/v1",
                    "kind": "Job",
                    "metadata": {"name": name, "labels": labels},
                    "spec": {
                        "parallelism": int(replicas),
                        # No `completions`: like the reference's elastic
                        # trainer Job, done-ness is decided by our updater's
                        # phase rules, not by a fixed completion count.
                        "backoffLimit": 1000000,
                        "template": pod_template,
                    },
                },
            )

    def _create(self, path: str, manifest: dict) -> None:
        try:
            self.api.post(path, manifest)
        except ApiError as e:
            if e.conflict:  # already exists → adopt (controller restart replay)
                log.info("adopting existing %s", manifest["metadata"]["name"])
                return
            raise

    def delete_role(self, job_name: str, role: str) -> None:
        """Delete the role workload and GC its pods by label selector
        (ref: pod GC, pkg/updater/trainingJobUpdater.go:99-154)."""
        name = self.workload_name(job_name, role)
        targets = (
            [("apps/v1", "deployments"), ("v1", "services")]
            if role == ROLE_COORDINATOR
            else [("batch/v1", "jobs")]
        )
        for group_version, kind in targets:
            try:
                self.api.delete(
                    self._ns_path(group_version, kind, name),
                    params={"propagationPolicy": "Background"},
                )
            except ApiError as e:
                if not e.not_found:
                    raise
        try:
            self.api.delete(
                self._ns_path("v1", "pods"),
                params={"labelSelector": _selector(role_labels(job_name, role))},
            )
        except ApiError as e:
            if not e.not_found:
                raise
