"""Minimal Kubernetes REST client: CRUD, PATCH, and chunked watch streams.

Plays the role client-go's rest.Client plays for the reference controller
(every `clientset.*` call in `/root/reference/pkg/cluster.go:91-291` and
`pkg/client/clientset/versioned/typed/paddlepaddle/v1/trainingjob.go:44-153`
is an HTTPS round trip built by machinery like this). Stdlib-only:
`http.client` + `ssl` + `json`.

Connections are per-request — simple, thread-safe, and proxy-free; watch
streams hold their connection open and yield decoded events line by line
(the apiserver emits one JSON watch event per newline-delimited chunk).
"""

from __future__ import annotations

import http.client
import json
import socket
import urllib.parse
from typing import Any, Dict, Iterator, Optional, Tuple

from edl_tpu.k8s.config import KubeConfig

#: media types the apiserver distinguishes PATCH flavors by.
MERGE_PATCH = "application/merge-patch+json"
STRATEGIC_PATCH = "application/strategic-merge-patch+json"
JSON = "application/json"


class ApiError(Exception):
    """Non-2xx apiserver response, carrying the Status body when present."""

    def __init__(self, status: int, reason: str, body: Any = None):
        self.status = status
        self.reason = reason
        self.body = body
        message = reason
        if isinstance(body, dict) and body.get("message"):
            message = body["message"]
        super().__init__(f"{status} {message}")

    @property
    def not_found(self) -> bool:
        return self.status == 404

    @property
    def conflict(self) -> bool:
        return self.status == 409

    @property
    def gone(self) -> bool:  # watch resourceVersion too old → relist
        return self.status == 410


class ApiClient:
    """One apiserver endpoint, dialed with a :class:`KubeConfig`."""

    def __init__(self, config: KubeConfig, timeout: float = 30.0):
        self.config = config
        self.timeout = timeout
        parsed = urllib.parse.urlsplit(config.host)
        self._https = parsed.scheme == "https"
        self._netloc = parsed.netloc
        self._base_path = parsed.path.rstrip("/")

    # -- connection plumbing ---------------------------------------------------

    def _connect(self, timeout: float) -> http.client.HTTPConnection:
        if self._https:
            return http.client.HTTPSConnection(
                self._netloc, timeout=timeout, context=self.config.ssl_context()
            )
        return http.client.HTTPConnection(self._netloc, timeout=timeout)

    def _url(self, path: str, params: Optional[Dict[str, Any]] = None) -> str:
        url = self._base_path + path
        if params:
            filtered = {k: v for k, v in params.items() if v is not None}
            if filtered:
                url += "?" + urllib.parse.urlencode(filtered)
        return url

    def _issue(
        self,
        method: str,
        path: str,
        body: Optional[dict],
        params: Optional[Dict[str, Any]],
        content_type: str,
        timeout: float,
    ) -> Tuple[http.client.HTTPConnection, http.client.HTTPResponse]:
        conn = self._connect(timeout)
        headers = {"Accept": JSON, **self.config.auth_headers()}
        payload = None
        if body is not None:
            payload = json.dumps(body).encode()
            headers["Content-Type"] = content_type
        try:
            conn.request(method, self._url(path, params), body=payload, headers=headers)
            resp = conn.getresponse()
        except (OSError, http.client.HTTPException):
            conn.close()
            raise
        return conn, resp

    @staticmethod
    def _decode(resp: http.client.HTTPResponse) -> Any:
        raw = resp.read()
        if not raw:
            return None
        try:
            return json.loads(raw)
        except json.JSONDecodeError:
            return raw.decode(errors="replace")

    # -- request surface -------------------------------------------------------

    def request(
        self,
        method: str,
        path: str,
        body: Optional[dict] = None,
        params: Optional[Dict[str, Any]] = None,
        content_type: str = JSON,
    ) -> Any:
        conn, resp = self._issue(method, path, body, params, content_type, self.timeout)
        try:
            data = self._decode(resp)
            if resp.status >= 300:
                raise ApiError(resp.status, resp.reason or "", data)
            return data
        finally:
            conn.close()

    def get(self, path: str, params: Optional[Dict[str, Any]] = None) -> Any:
        return self.request("GET", path, params=params)

    def post(self, path: str, body: dict) -> Any:
        return self.request("POST", path, body=body)

    def put(self, path: str, body: dict) -> Any:
        return self.request("PUT", path, body=body)

    def patch(self, path: str, body: dict, content_type: str = MERGE_PATCH) -> Any:
        return self.request("PATCH", path, body=body, content_type=content_type)

    def delete(
        self, path: str, params: Optional[Dict[str, Any]] = None,
        body: Optional[dict] = None,
    ) -> Any:
        return self.request("DELETE", path, body=body, params=params)

    # -- watch -----------------------------------------------------------------

    def watch(
        self,
        path: str,
        params: Optional[Dict[str, Any]] = None,
        timeout_seconds: float = 300.0,
    ) -> Iterator[dict]:
        """Stream watch events: yields ``{"type": ..., "object": {...}}``.

        The socket read timeout is padded past the server-side
        ``timeoutSeconds`` so a quiet-but-healthy stream is ended by the
        server's graceful close, not a client-side socket error. Ends
        normally at stream close; callers loop with the last seen
        resourceVersion (informer relist/rewatch semantics,
        ref: `pkg/controller.go:79-108`).
        """
        params = dict(params or {})
        params["watch"] = "true"
        params.setdefault("timeoutSeconds", int(timeout_seconds))
        conn, resp = self._issue(
            "GET", path, None, params, JSON, timeout_seconds + 30.0
        )
        try:
            if resp.status >= 300:
                raise ApiError(resp.status, resp.reason or "", self._decode(resp))
            buffer = b""
            while True:
                try:
                    chunk = resp.read1(65536)
                except (socket.timeout, TimeoutError):
                    return
                if not chunk:
                    return
                buffer += chunk
                while b"\n" in buffer:
                    line, buffer = buffer.split(b"\n", 1)
                    line = line.strip()
                    if not line:
                        continue
                    event = json.loads(line)
                    if event.get("type") == "ERROR":
                        obj = event.get("object", {}) or {}
                        raise ApiError(
                            int(obj.get("code", 500)),
                            obj.get("reason", "watch error"),
                            obj,
                        )
                    yield event
        finally:
            conn.close()
