"""Cluster credential loading: kubeconfig files and in-cluster serviceaccounts.

The reference builds its REST config with client-go's two standard paths
(`/root/reference/cmd/edl/edl.go:31-36`): ``rest.InClusterConfig()`` when no
``--kubeconfig`` flag is given, else ``clientcmd.BuildConfigFromFlags``. This
module reimplements both on the stdlib: YAML kubeconfig parsing with contexts,
bearer tokens, basic auth, client certificates (file or inline base64 data),
and the in-cluster serviceaccount mount.
"""

from __future__ import annotations

import base64
import os
import ssl
import tempfile
from dataclasses import dataclass, field
from typing import Optional

#: default serviceaccount mount (the same well-known path client-go uses).
SERVICEACCOUNT_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class ConfigError(Exception):
    """Credential material missing or malformed."""


@dataclass
class KubeConfig:
    """Everything needed to dial one apiserver.

    ``token_file`` (when set) is re-read on every request so rotated
    serviceaccount tokens keep working across long controller runs.
    """

    host: str  # base URL, e.g. "https://10.0.0.1:6443"
    token: Optional[str] = None
    token_file: Optional[str] = None
    username: Optional[str] = None
    password: Optional[str] = None
    ca_cert_path: Optional[str] = None
    ca_cert_data: Optional[str] = None  # PEM text
    client_cert_path: Optional[str] = None
    client_key_path: Optional[str] = None
    verify_tls: bool = True
    namespace: str = "default"
    #: temp files backing inline cert data; held so they outlive the config.
    _tempfiles: list = field(default_factory=list, repr=False)

    # -- constructors ----------------------------------------------------------

    @classmethod
    def in_cluster(cls, sa_dir: str = SERVICEACCOUNT_DIR) -> "KubeConfig":
        """Serviceaccount credentials from the pod filesystem
        (ref: rest.InClusterConfig, `cmd/edl/edl.go:32`)."""
        host = os.environ.get("KUBERNETES_SERVICE_HOST")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        if not host:
            raise ConfigError(
                "KUBERNETES_SERVICE_HOST not set; not running inside a cluster"
            )
        token_file = os.path.join(sa_dir, "token")
        if not os.path.exists(token_file):
            raise ConfigError(f"serviceaccount token missing at {token_file}")
        ns_file = os.path.join(sa_dir, "namespace")
        namespace = "default"
        if os.path.exists(ns_file):
            with open(ns_file) as f:
                namespace = f.read().strip() or "default"
        ca = os.path.join(sa_dir, "ca.crt")
        if ":" in host and not host.startswith("["):  # bare IPv6
            host = f"[{host}]"
        return cls(
            host=f"https://{host}:{port}",
            token_file=token_file,
            ca_cert_path=ca if os.path.exists(ca) else None,
            namespace=namespace,
        )

    @classmethod
    def from_kubeconfig(
        cls, path: Optional[str] = None, context: Optional[str] = None
    ) -> "KubeConfig":
        """Parse a kubeconfig file (ref: BuildConfigFromFlags, `edl.go:34-36`).

        Honors ``$KUBECONFIG`` and falls back to ``~/.kube/config``; selects
        ``context`` or the file's ``current-context``.
        """
        import yaml

        path = path or os.environ.get("KUBECONFIG") or os.path.expanduser(
            "~/.kube/config"
        )
        if not os.path.exists(path):
            raise ConfigError(f"kubeconfig not found at {path}")
        with open(path) as f:
            doc = yaml.safe_load(f) or {}

        def by_name(section: str, name: str) -> dict:
            for entry in doc.get(section) or []:
                if entry.get("name") == name:
                    return entry.get(section.rstrip("s"), {}) or {}
            raise ConfigError(f"kubeconfig has no {section!r} entry named {name!r}")

        ctx_name = context or doc.get("current-context")
        if not ctx_name:
            raise ConfigError("kubeconfig has no current-context and none was given")
        ctx = by_name("contexts", ctx_name)
        cluster = by_name("clusters", ctx["cluster"])
        user = by_name("users", ctx["user"]) if ctx.get("user") else {}

        cfg = cls(
            host=cluster.get("server", "").rstrip("/"),
            namespace=ctx.get("namespace", "default"),
            verify_tls=not cluster.get("insecure-skip-tls-verify", False),
        )
        if not cfg.host:
            raise ConfigError(f"cluster {ctx['cluster']!r} has no server URL")

        cfg.ca_cert_path = cluster.get("certificate-authority")
        if cluster.get("certificate-authority-data"):
            cfg.ca_cert_data = base64.b64decode(
                cluster["certificate-authority-data"]
            ).decode()

        cfg.token = user.get("token")
        if user.get("tokenFile"):
            cfg.token_file = user["tokenFile"]
        cfg.username = user.get("username")
        cfg.password = user.get("password")
        cfg.client_cert_path = user.get("client-certificate")
        cfg.client_key_path = user.get("client-key")
        # Inline cert data must land in files: ssl.load_cert_chain takes paths.
        if user.get("client-certificate-data"):
            cfg.client_cert_path = cfg._materialize(
                user["client-certificate-data"], "client.crt"
            )
        if user.get("client-key-data"):
            cfg.client_key_path = cfg._materialize(user["client-key-data"], "client.key")
        return cfg

    def _materialize(self, b64data: str, suffix: str) -> str:
        # delete=True + a live handle in _tempfiles: the path stays valid for
        # ssl.load_cert_chain while the config lives, and close (explicit, GC,
        # or interpreter exit) unlinks it — key material never outlives us.
        tf = tempfile.NamedTemporaryFile(mode="wb", suffix=f"-{suffix}")
        tf.write(base64.b64decode(b64data))
        tf.flush()
        self._tempfiles.append(tf)
        return tf.name

    # -- request-time material -------------------------------------------------

    def bearer_token(self) -> Optional[str]:
        if self.token_file:
            try:
                with open(self.token_file) as f:
                    return f.read().strip()
            except OSError as e:
                raise ConfigError(f"cannot read token file {self.token_file}: {e}")
        return self.token

    def auth_headers(self) -> dict:
        tok = self.bearer_token()
        if tok:
            return {"Authorization": f"Bearer {tok}"}
        if self.username is not None:
            cred = base64.b64encode(
                f"{self.username}:{self.password or ''}".encode()
            ).decode()
            return {"Authorization": f"Basic {cred}"}
        return {}

    def ssl_context(self) -> Optional[ssl.SSLContext]:
        """Build the TLS context for an https host; None for plain http."""
        if not self.host.startswith("https"):
            return None
        ctx = ssl.create_default_context()
        if not self.verify_tls:
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        else:
            if self.ca_cert_path:
                ctx.load_verify_locations(cafile=self.ca_cert_path)
            elif self.ca_cert_data:
                ctx.load_verify_locations(cadata=self.ca_cert_data)
        if self.client_cert_path:
            ctx.load_cert_chain(self.client_cert_path, self.client_key_path)
        return ctx
