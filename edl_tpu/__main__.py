"""``python -m edl_tpu`` — the framework CLI (ref: cmd/edl/edl.go:16-51)."""

import sys

from edl_tpu.cli import main

sys.exit(main())
