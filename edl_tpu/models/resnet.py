"""ResNet image classifiers (ResNet-18/34/50/101), ImageNet-shaped.

ResNet-50/ImageNet is a named target configuration in the driver brief
(`BASELINE.json` configs: "ResNet-50 / ImageNet (data-parallel, elastic
4<->16 TPU workers)" and "CTR + ResNet concurrent"); the reference repo
itself ships no vision models, so this is a capability extension built to
the same functional convention as the rest of the zoo.

TPU-first choices:

- **NHWC + bfloat16 compute** throughout so XLA tiles every conv onto the
  MXU; parameters stay float32 (the optimizer and normalizations want the
  precision), cast at use.
- **GroupNorm instead of BatchNorm.** BatchNorm carries mutable running
  stats and needs cross-replica moment sync under data parallelism — both
  at odds with the zoo's pure ``init``/``loss_fn`` convention and with an
  elastic world size (running stats keyed to a batch size that rescales
  mid-run). GroupNorm is stateless, batch-size-independent, and the
  standard substitution in functional JAX vision stacks.
- Residual adds and pooling in float32 to keep long skip chains stable.

Data parallel by design: ``param_spec`` replicates everything (no tensor
axis — at ResNet scale, DP is the right sharding and matches the
BASELINE.json config). The batch's leading dim shards over the trainer's
batch axis via the default ``batch_spec``.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from edl_tpu.models.base import Model

#: depth -> (blocks per stage, bottleneck expansion)
_STAGES = {
    18: ((2, 2, 2, 2), 1),
    34: ((3, 4, 6, 3), 1),
    50: ((3, 4, 6, 3), 4),
    101: ((3, 4, 23, 3), 4),
}


@dataclass(frozen=True)
class ResNetConfig:
    depth: int = 50
    num_classes: int = 1000
    image_size: int = 224
    width: int = 64  # stem channels; stage c = width * 2**stage * expansion
    gn_groups: int = 32

    @property
    def stages(self) -> Tuple[int, ...]:
        return _STAGES[self.depth][0]

    @property
    def expansion(self) -> int:
        return _STAGES[self.depth][1]


def _conv_init(key, kh, kw, cin, cout):
    scale = np.sqrt(2.0 / (kh * kw * cin))
    return jax.random.normal(key, (kh, kw, cin, cout), jnp.float32) * scale


def _gn_init(c):
    return {"scale": jnp.ones((c,), jnp.float32),
            "bias": jnp.zeros((c,), jnp.float32)}


def _group_count(groups: int, c: int) -> int:
    g = min(groups, c)
    while c % g:
        g -= 1
    return g


def _gn(x: jax.Array, p: dict, groups: int) -> jax.Array:
    """GroupNorm over (H, W, channel-group) in float32; shape-static."""
    b, h, w, c = x.shape
    g = _group_count(groups, c)
    x32 = x.astype(jnp.float32).reshape(b, h, w, g, c // g)
    mean = x32.mean(axis=(1, 2, 4), keepdims=True)
    var = x32.var(axis=(1, 2, 4), keepdims=True)
    xn = ((x32 - mean) * lax.rsqrt(var + 1e-5)).reshape(b, h, w, c)
    return (xn * p["scale"] + p["bias"]).astype(x.dtype)


def _conv(x: jax.Array, w: jax.Array, stride: int = 1, padding="SAME") -> jax.Array:
    return lax.conv_general_dilated(
        x,
        w.astype(x.dtype),
        window_strides=(stride, stride),
        padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _block_init(key, cfg: ResNetConfig, cin: int, cmid: int, stride: int) -> dict:
    cout = cmid * cfg.expansion
    ks = jax.random.split(key, 4)
    if cfg.expansion == 1:  # basic block (ResNet-18/34)
        p = {
            "conv1": _conv_init(ks[0], 3, 3, cin, cmid), "gn1": _gn_init(cmid),
            "conv2": _conv_init(ks[1], 3, 3, cmid, cout), "gn2": _gn_init(cout),
        }
    else:  # bottleneck (ResNet-50/101)
        p = {
            "conv1": _conv_init(ks[0], 1, 1, cin, cmid), "gn1": _gn_init(cmid),
            "conv2": _conv_init(ks[1], 3, 3, cmid, cmid), "gn2": _gn_init(cmid),
            "conv3": _conv_init(ks[2], 1, 1, cmid, cout), "gn3": _gn_init(cout),
        }
    if stride != 1 or cin != cout:
        p["proj"] = _conv_init(ks[3], 1, 1, cin, cout)
        p["gn_proj"] = _gn_init(cout)
    return p


def _block_apply(x: jax.Array, p: dict, cfg: ResNetConfig, stride: int) -> jax.Array:
    g = cfg.gn_groups
    if "proj" in p:
        shortcut = _gn(_conv(x, p["proj"], stride), p["gn_proj"], g)
    else:
        shortcut = x
    if cfg.expansion == 1:
        y = jax.nn.relu(_gn(_conv(x, p["conv1"], stride), p["gn1"], g))
        y = _gn(_conv(y, p["conv2"]), p["gn2"], g)
    else:
        y = jax.nn.relu(_gn(_conv(x, p["conv1"]), p["gn1"], g))
        y = jax.nn.relu(_gn(_conv(y, p["conv2"], stride), p["gn2"], g))
        y = _gn(_conv(y, p["conv3"]), p["gn3"], g)
    # Residual add in f32: ~16 GN'd adds chain through a ResNet-50; keeping
    # the skip path bf16 visibly drifts logits between mesh layouts.
    return jax.nn.relu(
        (y.astype(jnp.float32) + shortcut.astype(jnp.float32))
    ).astype(x.dtype)


def _init(cfg: ResNetConfig, key: jax.Array, mesh) -> dict:
    n_blocks = sum(cfg.stages)
    ks = jax.random.split(key, n_blocks + 2)
    params = {
        "stem": {"conv": _conv_init(ks[0], 7, 7, 3, cfg.width),
                 "gn": _gn_init(cfg.width)},
        "blocks": [],
        "head": {
            "w": jax.random.normal(
                ks[1], (cfg.width * 8 * cfg.expansion, cfg.num_classes),
                jnp.float32,
            ) * 0.01,
            "b": jnp.zeros((cfg.num_classes,), jnp.float32),
        },
    }
    cin = cfg.width
    ki = 2
    for stage, blocks in enumerate(cfg.stages):
        cmid = cfg.width * (2 ** stage)
        for b in range(blocks):
            stride = 2 if (b == 0 and stage > 0) else 1
            params["blocks"].append(
                _block_init(ks[ki], cfg, cin, cmid, stride)
            )
            cin = cmid * cfg.expansion
            ki += 1
    replicated = NamedSharding(mesh, P())
    return jax.device_put(
        params, jax.tree_util.tree_map(lambda _: replicated, params)
    )


def _apply(cfg: ResNetConfig, params: dict, images: jax.Array) -> jax.Array:
    """images (B, S, S, 3) float32 -> logits (B, num_classes) float32."""
    x = images.astype(jnp.bfloat16)
    x = _conv(x, params["stem"]["conv"], stride=2)
    x = jax.nn.relu(_gn(x, params["stem"]["gn"], cfg.gn_groups))
    x = lax.reduce_window(  # 3x3/2 max pool
        x, -jnp.inf, lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME"
    )
    bi = 0
    for stage, blocks in enumerate(cfg.stages):
        for b in range(blocks):
            stride = 2 if (b == 0 and stage > 0) else 1
            x = _block_apply(x, params["blocks"][bi], cfg, stride)
            bi += 1
    x = x.astype(jnp.float32).mean(axis=(1, 2))  # global average pool
    return jnp.dot(x, params["head"]["w"]) + params["head"]["b"]


def _loss(cfg: ResNetConfig, params: dict, batch: dict, mesh) -> jax.Array:
    logits = _apply(cfg, params, batch["image"])
    labels = jax.nn.one_hot(batch["label"], cfg.num_classes, dtype=jnp.float32)
    return -jnp.mean(jnp.sum(labels * jax.nn.log_softmax(logits), axis=-1))


def _param_spec(cfg: ResNetConfig, mesh) -> dict:
    """Replicated specs mirroring the params tree (pure DP): the block
    topology lives only in ``_init``; this just maps P() over its shape."""
    shapes = jax.eval_shape(lambda k: _init(cfg, k, mesh),  # mesh is static
                            jax.random.PRNGKey(0))
    return jax.tree_util.tree_map(lambda _: P(), shapes)


def _synthetic_batch(cfg: ResNetConfig, rng: np.random.Generator,
                     batch_size: int) -> dict:
    """ImageNet-shaped separable data: each class adds a distinct 2-D
    frequency pattern, so loss/accuracy trends are meaningful (zero-egress
    image: real datasets are out of reach, BASELINE.md)."""
    s = cfg.image_size
    label = rng.integers(0, cfg.num_classes, size=batch_size).astype(np.int32)
    image = rng.standard_normal((batch_size, s, s, 3)).astype(np.float32) * 0.1
    t = np.linspace(0, 2 * np.pi, s, dtype=np.float32)
    # 25 x 40 = 1000 distinct (fx, fy) pairs: every ImageNet-config class
    # gets its own pattern (and small-class configs use low, sub-Nyquist
    # frequencies even at 32 px).
    fx = 1 + (label % 25)
    fy = 1 + ((label // 25) % 40)
    pattern = (
        np.sin(fx[:, None, None] * t[None, :, None])
        * np.cos(fy[:, None, None] * t[None, None, :])
    ).astype(np.float32)
    image += pattern[..., None] * 0.7
    return {"image": image, "label": label}


def accuracy(model: Model, params: dict, batch: dict) -> jax.Array:
    cfg = model.config
    logits = _apply(cfg, params, jnp.asarray(batch["image"]))
    return jnp.mean(
        (jnp.argmax(logits, axis=-1) == jnp.asarray(batch["label"])).astype(
            jnp.float32
        )
    )


def _flops_fwd_per_image(cfg: ResNetConfig) -> float:
    """Conv/matmul forward FLOPs per image (2 per MAC), walking the same
    stage topology as ``_init``/``_apply``. ResNet-50 @ 224 lands at 8.2
    GFLOPs — the published ~4.1 "GFLOPs" (really GMACs) at 2 FLOPs/MAC.
    GroupNorm/relu/pool are not MAC FLOPs."""
    s = -(-cfg.image_size // 2)  # stem conv, stride 2, SAME
    fl = 2.0 * s * s * 7 * 7 * 3 * cfg.width
    s = -(-s // 2)  # 3x3/2 max pool, SAME
    cin = cfg.width
    for stage, blocks in enumerate(cfg.stages):
        cmid = cfg.width * (2 ** stage)
        cout = cmid * cfg.expansion
        for b in range(blocks):
            stride = 2 if (b == 0 and stage > 0) else 1
            s_out = -(-s // stride)
            if cfg.expansion == 1:
                fl += 2.0 * s_out * s_out * 9 * cin * cmid
                fl += 2.0 * s_out * s_out * 9 * cmid * cout
            else:
                fl += 2.0 * s * s * cin * cmid  # 1x1 (stride lives in conv2)
                fl += 2.0 * s_out * s_out * 9 * cmid * cmid
                fl += 2.0 * s_out * s_out * cmid * cout
            if stride != 1 or cin != cout:
                fl += 2.0 * s_out * s_out * cin * cout
            cin, s = cout, s_out
    return fl + 2.0 * cin * cfg.num_classes  # head


def make_model(cfg: ResNetConfig | None = None, **overrides) -> Model:
    cfg = cfg or ResNetConfig(**overrides)
    return Model(
        name=f"resnet{cfg.depth}",
        init=partial(_init, cfg),
        loss_fn=partial(_loss, cfg),
        param_spec=partial(_param_spec, cfg),
        synthetic_batch=partial(_synthetic_batch, cfg),
        label_keys=("label",),
        predict=lambda params, batch, mesh: _apply(cfg, params, batch["image"]),
        config=cfg,
        flops_per_step=lambda bs: 3.0 * _flops_fwd_per_image(cfg) * bs,
    )


def forward(model: Model, params: dict, images) -> jax.Array:
    """Inference entrypoint: logits for (B, S, S, 3) float32 images."""
    return _apply(model.config, params, jnp.asarray(images))


#: ResNet-50 / ImageNet — the BASELINE.json configuration.
MODEL = make_model()

#: small config for CPU-mesh tests and examples (fits an 8-virtual-device
#: host: 32px, width 8, 10 classes — still exercises every block variant).
TINY = ResNetConfig(depth=50, num_classes=10, image_size=32, width=8,
                    gn_groups=4)
