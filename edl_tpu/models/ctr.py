"""CTR deep-wide DNN — the flagship workload (sparse-embedding parity case).

Re-design of `example/ctr/ctr/train.py:28-239` (Criteo-style click-through
prediction: 13 dense + 26 hashed categorical features, sparse dim 1e6+1
`train.py:60-64`, deep 400-400-400 MLP, sigmoid logloss) built TPU-first:

- The two sparse tables (deep embeddings + wide linear weights) that the
  reference serves from C++ pservers over dedicated sparse ports
  (`pkg/jobparser.go:234`) are `edl_tpu.parallel.ShardedEmbedding` arrays,
  row-sharded across the mesh; lookups are shard_map collectives on ICI.
- The MLP runs in bfloat16 (MXU-native) with float32 params and loss; the
  26 per-slot lookups are one batched gather on a single shared table —
  large, static-shaped, fusion-friendly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from edl_tpu.models.base import Model
from edl_tpu.parallel.embedding import ShardedEmbedding

NUM_DENSE = 13
NUM_SPARSE = 26
#: reference: --sparse_feature_dim 1000001 (example/ctr/ctr/train.py:60-64).
SPARSE_DIM = 1000001
EMBED_DIM = 10
HIDDEN = (400, 400, 400)
#: default mesh axis the sparse tables are sharded over (pserver-shard equiv).
SHARD_AXIS = "data"


def _init_impl(key: jax.Array, mesh, deep: ShardedEmbedding, wide: ShardedEmbedding) -> dict:
    keys = jax.random.split(key, 3 + len(HIDDEN))
    replicated = NamedSharding(mesh, P())
    params = {
        "deep_table": deep.init(keys[0], mesh, scale=1.0 / np.sqrt(EMBED_DIM)),
        "wide_table": wide.init(keys[1], mesh, scale=0.01),
        "wide_dense": jax.device_put(jnp.zeros((NUM_DENSE, 1), jnp.float32), replicated),
        "mlp": [],
        "out": None,
    }
    fan_in = NUM_DENSE + NUM_SPARSE * EMBED_DIM
    mlp = []
    for i, width in enumerate(HIDDEN):
        w = jax.random.normal(keys[2 + i], (fan_in, width), jnp.float32)
        w = w * jnp.sqrt(2.0 / fan_in)
        mlp.append(
            {
                "w": jax.device_put(w, replicated),
                "b": jax.device_put(jnp.zeros((width,), jnp.float32), replicated),
            }
        )
        fan_in = width
    params["mlp"] = mlp
    out_w = jax.random.normal(keys[-1], (fan_in, 1), jnp.float32) * 0.01
    params["out"] = {
        "w": jax.device_put(out_w, replicated),
        "b": jax.device_put(jnp.zeros((1,), jnp.float32), replicated),
    }
    return params


def _forward_impl(
    params: dict,
    dense: jax.Array,
    sparse_ids: jax.Array,
    mesh,
    deep: ShardedEmbedding,
    wide: ShardedEmbedding,
) -> jax.Array:
    """Logits for a batch. dense: (B, 13) f32; sparse_ids: (B, 26) int32."""
    # Deep path: one batched lookup over the shared sharded table -> bf16 MLP.
    emb = deep.apply(mesh, params["deep_table"], sparse_ids)  # (B, 26, D)
    deep_in = jnp.concatenate(
        [dense, emb.reshape(emb.shape[0], -1)], axis=-1
    ).astype(jnp.bfloat16)
    h = deep_in
    for layer in params["mlp"]:
        h = jnp.dot(h, layer["w"].astype(jnp.bfloat16)) + layer["b"].astype(jnp.bfloat16)
        h = jax.nn.relu(h)
    deep_logit = jnp.dot(h, params["out"]["w"].astype(jnp.bfloat16))
    deep_logit = deep_logit.astype(jnp.float32) + params["out"]["b"]
    # Wide path: sparse linear weights + dense linear, all f32 (tiny).
    wide_sparse = wide.apply(mesh, params["wide_table"], sparse_ids)  # (B, 26, 1)
    wide_logit = wide_sparse.sum(axis=(1, 2), keepdims=False)[:, None]
    wide_logit = wide_logit + dense @ params["wide_dense"]
    return (deep_logit + wide_logit).squeeze(-1)


def _loss_impl(params, batch, mesh, deep, wide) -> jax.Array:
    logits = _forward_impl(params, batch["dense"], batch["sparse"], mesh, deep, wide)
    labels = batch["label"].astype(jnp.float32)
    # sigmoid binary cross-entropy in f32 (logloss, ref train.py objective)
    return jnp.mean(
        jnp.maximum(logits, 0) - logits * labels + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    )


def _spec_impl(deep: ShardedEmbedding, wide: ShardedEmbedding) -> dict:
    return {
        "deep_table": deep.table_spec(),
        "wide_table": wide.table_spec(),
        "wide_dense": P(),
        "mlp": [{"w": P(), "b": P()} for _ in HIDDEN],
        "out": {"w": P(), "b": P()},
    }


def synthetic_batch(
    rng: np.random.Generator, batch_size: int, sparse_dim: int = SPARSE_DIM
) -> dict:
    """Criteo-shaped synthetic batch: gaussian dense, zipf-ish sparse ids
    (hashed feature distributions are heavy-tailed), bernoulli labels."""
    dense = rng.standard_normal((batch_size, NUM_DENSE)).astype(np.float32)
    sparse = (
        rng.zipf(1.3, size=(batch_size, NUM_SPARSE)).astype(np.int64) % sparse_dim
    ).astype(np.int32)
    label = (rng.random(batch_size) < 0.25).astype(np.int32)
    return {"dense": dense, "sparse": sparse, "label": label}


def _flops_per_step(batch_size: int) -> float:
    """Train-step model FLOPs (MFU numerator, models.base convention).
    The deep MLP dominates; table gathers and the wide path are lookups
    and tiny reductions, not matmul FLOPs."""
    dims = [NUM_DENSE + NUM_SPARSE * EMBED_DIM, *HIDDEN, 1]
    fwd = sum(2 * a * b for a, b in zip(dims[:-1], dims[1:]))
    fwd += 2 * NUM_DENSE  # wide dense linear
    return 3.0 * fwd * batch_size


def make_model(
    shard_axis: str = SHARD_AXIS,
    batch_axis: str = "data",
    sparse_dim: int = SPARSE_DIM,
) -> Model:
    """CTR variant with explicit table sharding — e.g. a dedicated ``expert``
    axis (the reference's "more pservers than trainers" shape) or a smaller
    vocab for dry runs."""
    deep = ShardedEmbedding(sparse_dim, EMBED_DIM, shard_axis, batch_axis)
    wide = ShardedEmbedding(sparse_dim, 1, shard_axis, batch_axis)
    return Model(
        name="ctr",
        init=lambda key, mesh: _init_impl(key, mesh, deep, wide),
        loss_fn=lambda params, batch, mesh: _loss_impl(params, batch, mesh, deep, wide),
        param_spec=lambda mesh: _spec_impl(deep, wide),
        synthetic_batch=lambda rng, bs: synthetic_batch(rng, bs, sparse_dim),
        label_keys=("label",),
        # serving entrypoint: click logit (pre-sigmoid), ref's saved
        # inference program (`ctr/train.py:169-180`)
        predict=lambda params, batch, mesh: _forward_impl(
            params, batch["dense"], batch["sparse"], mesh, deep, wide
        ),
        flops_per_step=_flops_per_step,
    )


MODEL = make_model()


def forward(params: dict, dense: jax.Array, sparse_ids: jax.Array, mesh) -> jax.Array:
    """Default-config forward pass (inference entrypoint)."""
    deep = ShardedEmbedding(SPARSE_DIM, EMBED_DIM, SHARD_AXIS, "data")
    wide = ShardedEmbedding(SPARSE_DIM, 1, SHARD_AXIS, "data")
    return _forward_impl(params, dense, sparse_ids, mesh, deep, wide)
