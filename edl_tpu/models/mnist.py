"""MNIST digit recognition: conv and MLP variants.

Re-design of `example/fit_a_line/fluid/recognize_digits.py:20-52` (softmax /
MLP / conv-pool variants). The conv variant mirrors the reference's
conv5x5(20) -> pool2 -> conv5x5(50) -> pool2 -> fc(500) -> softmax(10)
structure, implemented NHWC with `lax.conv_general_dilated` in bfloat16 so XLA
tiles it onto the MXU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from edl_tpu.models.base import Model

IMAGE = 28
NUM_CLASSES = 10


def _conv_init(key, kh, kw, cin, cout):
    scale = np.sqrt(2.0 / (kh * kw * cin))
    return jax.random.normal(key, (kh, kw, cin, cout), jnp.float32) * scale


def init(key: jax.Array, mesh) -> dict:
    ks = jax.random.split(key, 4)
    replicated = NamedSharding(mesh, P())
    params = {
        "conv1": {"w": _conv_init(ks[0], 5, 5, 1, 20), "b": jnp.zeros((20,))},
        "conv2": {"w": _conv_init(ks[1], 5, 5, 20, 50), "b": jnp.zeros((50,))},
        "fc1": {
            "w": jax.random.normal(ks[2], (4 * 4 * 50, 500), jnp.float32)
            * np.sqrt(2.0 / (4 * 4 * 50)),
            "b": jnp.zeros((500,)),
        },
        "fc2": {
            "w": jax.random.normal(ks[3], (500, NUM_CLASSES), jnp.float32) * 0.01,
            "b": jnp.zeros((NUM_CLASSES,)),
        },
    }
    return jax.device_put(
        jax.tree_util.tree_map(lambda x: jnp.asarray(x, jnp.float32), params),
        jax.tree_util.tree_map(lambda _: replicated, params),
    )


def _conv_block(x, layer):
    x = lax.conv_general_dilated(
        x,
        layer["w"].astype(x.dtype),
        window_strides=(1, 1),
        padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    x = x + layer["b"].astype(x.dtype)
    x = jax.nn.relu(x)
    return lax.reduce_window(
        x, -jnp.inf, lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID"
    )


def apply(params: dict, images: jax.Array) -> jax.Array:
    """images (B, 28, 28, 1) float32 -> logits (B, 10)."""
    x = images.astype(jnp.bfloat16)
    x = _conv_block(x, params["conv1"])  # -> (B, 12, 12, 20)
    x = _conv_block(x, params["conv2"])  # -> (B, 4, 4, 50)
    x = x.reshape(x.shape[0], -1)
    x = jax.nn.relu(
        jnp.dot(x, params["fc1"]["w"].astype(x.dtype)) + params["fc1"]["b"].astype(x.dtype)
    )
    logits = jnp.dot(x, params["fc2"]["w"].astype(x.dtype)).astype(jnp.float32)
    return logits + params["fc2"]["b"]


def loss_fn(params: dict, batch: dict, mesh) -> jax.Array:
    logits = apply(params, batch["image"])
    labels = jax.nn.one_hot(batch["label"], NUM_CLASSES, dtype=jnp.float32)
    return -jnp.mean(jnp.sum(labels * jax.nn.log_softmax(logits), axis=-1))


def accuracy(params: dict, batch: dict) -> jax.Array:
    return jnp.mean(
        (jnp.argmax(apply(params, batch["image"]), axis=-1) == batch["label"]).astype(
            jnp.float32
        )
    )


def param_spec(mesh) -> dict:
    return {k: {"w": P(), "b": P()} for k in ("conv1", "conv2", "fc1", "fc2")}


def synthetic_batch(rng: np.random.Generator, batch_size: int) -> dict:
    """Digit-shaped blobs: class k lights up a distinct quadrant pattern, so a
    real decision boundary exists and test-time accuracy is meaningful."""
    label = rng.integers(0, NUM_CLASSES, size=batch_size).astype(np.int32)
    image = rng.standard_normal((batch_size, IMAGE, IMAGE, 1)).astype(np.float32) * 0.1
    for k in range(NUM_CLASSES):
        rows = label == k
        r, c = divmod(k, 4)
        image[rows, 7 * r : 7 * r + 7, 7 * c : 7 * c + 7, :] += 1.0
    return {"image": image, "label": label}


#: MFU numerator per image: conv1 (24^2 out, 5x5x1 -> 20) + conv2 (8^2 out,
#: 5x5x20 -> 50) + fc 800 -> 500 -> 10, at 2 FLOPs per MAC.
_FWD_FLOPS = (
    2 * 24 * 24 * 5 * 5 * 1 * 20
    + 2 * 8 * 8 * 5 * 5 * 20 * 50
    + 2 * 800 * 500
    + 2 * 500 * 10
)

MODEL = Model(
    name="mnist",
    init=init,
    loss_fn=loss_fn,
    param_spec=param_spec,
    synthetic_batch=synthetic_batch,
    label_keys=("label",),
    predict=lambda params, batch, mesh: apply(params, batch["image"]),
    flops_per_step=lambda bs: 3.0 * _FWD_FLOPS * bs,
)
