"""Model zoo: TPU-native re-implementations of the reference workloads.

Reference examples (SURVEY C14-C18) and their equivalents here:

- `example/fit_a_line/train_local.py` / `train_ft.py` (linear regression)
  -> ``fit_a_line``
- `example/fit_a_line/train_ft.py:41-99` (5-gram word embedding)
  -> ``word2vec`` (N-gram neural LM with a mesh-sharded embedding table)
- `example/fit_a_line/fluid/recognize_digits.py:20-52` (softmax/MLP/conv MNIST)
  -> ``mnist``
- `example/ctr/ctr/train.py` (deep-wide CTR, 1e6+1 sparse features)
  -> ``ctr`` — the flagship; its sparse tables are row-sharded over the mesh
  (`edl_tpu.parallel.ShardedEmbedding`) instead of living on C++ pservers
- ResNet-50 (BASELINE.json config list) -> ``resnet``

Every model follows the same functional convention (``models.base.Model``):
pure ``init``/``loss_fn`` plus sharding specs, so the elastic runtime can
build a jit-compiled SPMD train step for any of them on any mesh.

All models generate deterministic synthetic data shaped like the reference
datasets (UCI housing, PTB-style ids, MNIST, Criteo-style CTR) — this image
has zero egress, and the elasticity/throughput story does not depend on real
data values.
"""

from edl_tpu.models.base import Model
from edl_tpu.models import fit_a_line, mnist, word2vec, ctr, resnet, transformer


_MODULES = {
    "fit_a_line": fit_a_line,
    "mnist": mnist,
    "word2vec": word2vec,
    "ctr": ctr,
    "resnet": resnet,
    "transformer": transformer,
}

#: default instances, keyed by each model's own name (module name and
#: model name differ where one module serves a family: resnet -> resnet50)
_REGISTRY = {mod.MODEL.name: mod.MODEL for mod in _MODULES.values()}


def get(name: str) -> Model:
    """Look up a zoo model's default instance by name."""
    if name not in _REGISTRY:
        raise KeyError(f"unknown model {name!r}; have {sorted(_REGISTRY)}")
    return _REGISTRY[name]


def resolve(ref: str, config=None) -> Model:
    """Rebuild a zoo model from (module ref, make_model kwargs) — the model
    half of an inference artifact (`runtime.export`). ``ref`` names a zoo
    module; with no config, registry names (e.g. ``resnet50``) work too."""
    if not config:
        if ref in _MODULES:
            return _MODULES[ref].MODEL
        return get(ref)
    if ref not in _MODULES:
        raise KeyError(f"unknown model module {ref!r}; have {sorted(_MODULES)}")
    mod = _MODULES[ref]
    if not hasattr(mod, "make_model"):
        raise TypeError(f"model {ref!r} is not configurable (no make_model)")
    return mod.make_model(**config)


__all__ = ["Model", "ctr", "fit_a_line", "get", "mnist", "resnet", "resolve",
           "transformer", "word2vec"]
