"""Model zoo: TPU-native re-implementations of the reference workloads.

Reference examples (SURVEY C14-C18) and their equivalents here:

- `example/fit_a_line/train_local.py` / `train_ft.py` (linear regression)
  -> ``fit_a_line``
- `example/fit_a_line/train_ft.py:41-99` (5-gram word embedding)
  -> ``word2vec`` (N-gram neural LM with a mesh-sharded embedding table)
- `example/fit_a_line/fluid/recognize_digits.py:20-52` (softmax/MLP/conv MNIST)
  -> ``mnist``
- `example/ctr/ctr/train.py` (deep-wide CTR, 1e6+1 sparse features)
  -> ``ctr`` — the flagship; its sparse tables are row-sharded over the mesh
  (`edl_tpu.parallel.ShardedEmbedding`) instead of living on C++ pservers
- ResNet-50 (BASELINE.json config list) -> ``resnet``

Every model follows the same functional convention (``models.base.Model``):
pure ``init``/``loss_fn`` plus sharding specs, so the elastic runtime can
build a jit-compiled SPMD train step for any of them on any mesh.

All models generate deterministic synthetic data shaped like the reference
datasets (UCI housing, PTB-style ids, MNIST, Criteo-style CTR) — this image
has zero egress, and the elasticity/throughput story does not depend on real
data values.
"""

from edl_tpu.models.base import Model
from edl_tpu.models import fit_a_line, mnist, word2vec, ctr, resnet, transformer


_REGISTRY = {
    "fit_a_line": fit_a_line.MODEL,
    "mnist": mnist.MODEL,
    "word2vec": word2vec.MODEL,
    "ctr": ctr.MODEL,
    "resnet50": resnet.MODEL,
    "transformer": transformer.MODEL,
}


def get(name: str) -> Model:
    """Look up a zoo model's default instance by name."""
    if name not in _REGISTRY:
        raise KeyError(f"unknown model {name!r}; have {sorted(_REGISTRY)}")
    return _REGISTRY[name]


__all__ = ["Model", "ctr", "fit_a_line", "get", "mnist", "resnet",
           "transformer", "word2vec"]
