"""The model convention the elastic runtime trains against.

A ``Model`` is a bundle of pure functions — no hidden state, no framework
classes — so the runtime can jit/shard/checkpoint it uniformly:

- ``init(key, mesh)`` -> params pytree (created sharded on the mesh).
- ``loss_fn(params, batch, mesh)`` -> scalar loss (jit-traceable; the runtime
  differentiates it and applies the optimizer under one jit).
- ``param_spec(mesh)`` -> PartitionSpec pytree matching params (replicated by
  default; big tables row-sharded).
- ``synthetic_batch(rng, batch_size)`` -> host-side numpy batch for tests and
  benchmarks.

This replaces the reference's Paddle program construction + transpiler
contract (`example/ctr/ctr/train.py:119-151`): there, distribution rewrites
the graph; here, the same loss function runs on any mesh and only the specs
change.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

Params = Any
Batch = Dict[str, np.ndarray]


@dataclass(frozen=True)
class Model:
    name: str
    init: Callable  # (key, mesh) -> params
    loss_fn: Callable  # (params, batch, mesh) -> scalar
    param_spec: Callable  # (mesh) -> PartitionSpec pytree
    synthetic_batch: Callable  # (np.random.Generator, batch_size) -> Batch
    #: optional (mesh) -> {batch key: PartitionSpec}. Default None = every
    #: array sharded on dim 0 over the trainer's batch axis; models with
    #: sequence-sharded inputs (transformer: tokens (B, S) over data x seq)
    #: override this so `Trainer.place_batch` places dims on the right axes.
    batch_spec: Optional[Callable] = None
    #: batch keys holding the training objective (labels/targets/weights).
    #: Wire transport never applies lossy encodings to these — a float
    #: regression target consumed by a float32 loss must cross exactly
    #: (integer labels keep their exact u8/u24 encodings).
    label_keys: Tuple[str, ...] = ()
    #: optional inference entrypoint (params, batch, mesh) -> outputs, the
    #: serving twin of loss_fn (jit-traceable; batch omits label keys).
    #: Drives `runtime.export.load_inference_model(...).predict` — the
    #: reference's save_inference_model program (`ctr/train.py:169-180`).
    predict: Optional[Callable] = None
    #: optional structured config the model was built from (e.g. a
    #: ResNetConfig/TransformerConfig) for forward helpers and export.
    config: Optional[Any] = None
    #: optional analytic (batch_size) -> train-step model FLOPs. Convention:
    #: matmul/conv FLOPs only (2*M*N*K per matmul), causal attention halved,
    #: backward = 2x forward (so train = 3x forward), rematerialization
    #: recompute EXCLUDED — i.e. the numerator of "model FLOPs utilization"
    #: in the standard (PaLM-appendix) sense, so bench MFU numbers are
    #: comparable to published ones. `edl_tpu.tools.mfu` falls back to XLA
    #: cost analysis when absent.
    flops_per_step: Optional[Callable] = None
