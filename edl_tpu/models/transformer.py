"""Decoder-only transformer LM — the long-context / multi-axis flagship.

The reference's model zoo tops out at a 5-gram embedding model
(`example/fit_a_line/train_ft.py:41-99`); a modern elastic-training framework
must schedule transformer jobs, so this model exists to exercise every mesh
axis the parallel layer supports, together and composably:

- ``data``  — batch sharding; gradient all-reduce inserted by the optimizer jit.
- ``seq``   — sequence/context parallelism: activations sharded on the
  sequence dimension, attention via `ring_attention` (K/V blocks rotating on
  ICI with blockwise online softmax).
- ``model`` — megatron-style tensor parallelism: QKV/up projections
  column-sharded, output/down projections row-sharded, one `psum` after each
  (two per block), heads split across the axis.
- ``pipe``  — pipeline parallelism: the block stack's leading layer dim is
  sharded over the axis and executed with one of three microbatch schedules
  (GPipe via `edl_tpu.parallel.pipeline._pipeline_local`; plain or
  interleaved 1F1B via `pipeline_train_1f1b` — with ``virtual_stages > 1``
  each rank holds v NONCONTIGUOUS chunks of blocks, packed chunk-major by
  `interleaved_layout` at init), composing with ring attention and the TP
  psums inside each stage. MoE's load-balance aux loss rides every
  schedule (per-stage accumulation, psum over the pipe axis).

The whole forward/loss is ONE `shard_map` kernel, manual over the mesh: every
matmul below is written against local shards, so the collectives are explicit
and auditable rather than left to the partitioner — this is the pattern the
scaling-book recipe recommends once sequence parallelism enters, because the
partitioner cannot infer a ring schedule. Matmuls run in bfloat16 (MXU), norms
and softmax/loss in float32.

Token/position embeddings and the LM head are replicated (vocab is small next
to the block stack); the big sharded-table machinery lives in
`edl_tpu.parallel.ShardedEmbedding` and the CTR/word2vec models.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial
from typing import Dict, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from edl_tpu.parallel.compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from edl_tpu.models.base import Model
from edl_tpu.parallel.pipeline import (
    _pipeline_local,
    interleaved_layout,
    pipeline_train_1f1b,
)
from edl_tpu.parallel.ring_attention import _ring_attention_local
from edl_tpu.parallel.sharding import present_axes


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    d_ff: int = 2048
    seq_len: int = 1024
    #: one mesh axis or a hierarchy (e.g. ("dcn", "data") for multi-slice
    #: data parallelism — gradient reductions then ride DCN, everything
    #: else stays on ICI; see parallel.mesh.build_hierarchical_mesh)
    batch_axis: Union[str, Tuple[str, ...]] = "data"
    seq_axis: str = "seq"
    tp_axis: str = "model"
    pp_axis: str = "pipe"
    #: microbatches for the pipeline schedule; None = stage count.
    microbatches: Optional[int] = None
    #: "gpipe" (default: autodiff through the forward schedule, O(M)
    #: activation stash), "1f1b" (combined fwd/bwd scan, O(pp) stash), or
    #: "1f1b-interleaved" (combined scan over ``virtual_stages`` chunks per
    #: rank — bubble shrinks ~v-fold at fixed microbatches; see the
    #: edl_tpu.parallel.pipeline docstring and the committed
    #: BENCH_PIPELINE.json sweep for the measured economics).
    pipeline_schedule: str = "gpipe"
    #: virtual stage chunks per pipe rank, >1 only with
    #: pipeline_schedule="1f1b-interleaved". Requires n_layers divisible by
    #: pp * virtual_stages and microbatches divisible by pp. Block storage
    #: is then packed chunk-major (interleaved_layout) at init.
    virtual_stages: int = 1
    #: per-block rematerialization (`jax.checkpoint` around each block under
    #: the scan): the backward pass recomputes block activations instead of
    #: storing them, cutting live activation memory from O(n_layers) to O(1)
    #: per stage — the standard HBM-for-FLOPs trade that makes long-context
    #: training fit (scaling-book recipe; the reference has no analog).
    remat: bool = False
    #: Pallas flash-attention kernel (`edl_tpu.ops.flash_attention`):
    #: blockwise online softmax in VMEM, no (S, S) score materialization.
    #: Serves BOTH attention paths — the unsharded-sequence case directly,
    #: and the seq-sharded ring as its per-hop block engine (hops merge
    #: associatively in (out, lse) form, gradients flow through the
    #: kernel's differentiable lse). Interpret mode on CPU. The kernel
    #: blocks over the batch dim, so changing the per-call batch (e.g.
    #: `grad_accum_microbatches` slicing) reassociates the softmax/grad
    #: accumulation order — bit-exact single-step-vs-accumulated
    #: comparisons need `flash=False` (see tests/test_collective.py).
    flash: bool = True
    #: mixture-of-experts FFN: >0 replaces every block's dense FFN with
    #: `moe_experts` switch-routed (top-1) experts whose weights shard over
    #: ``expert_axis`` — token dispatch is an `all_to_all` on ICI, the
    #: dense-model completion of the embedding layer's expert story
    #: (`parallel.embedding`). 0 = dense FFN. Experts do not split over the
    #: tp axis (attention still does); capacity-dropped tokens pass through
    #: on the residual; `moe_aux_weight` adds the load-balance term.
    moe_experts: int = 0
    moe_capacity_factor: float = 1.25
    #: experts per token: 1 = switch routing, 2 = GShard-style top-2 with
    #: renormalized gates (choices slot in priority order — every token's
    #: first choice outranks any second choice for capacity).
    moe_top_k: int = 1
    expert_axis: str = "expert"
    #: switch load-balance auxiliary loss weight (Shazeer/Fedus form:
    #: E * sum_e f_e * p_e per layer, f = routed-token fraction, p = mean
    #: router prob). 0 = off. Works on every mesh, pipelined or not: under
    #: a pipe axis each stage accumulates its layers' aux over its real
    #: (stage, microbatch) executions, the schedules psum it over the pipe
    #: axis and fold the microbatch-mean into the loss. Note the pipelined
    #: form averages PER-MICROBATCH aux (routing fractions computed over
    #: batch/microbatches tokens) — statistically the same balance pressure
    #: as the whole-batch form, not bit-identical.
    moe_aux_weight: float = 0.0

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def _axis_size(mesh: Mesh, name: str) -> int:
    return mesh.shape[name] if name in mesh.axis_names else 1


def _rmsnorm(x: jax.Array, scale: jax.Array) -> jax.Array:
    xf = x.astype(jnp.float32)
    norm = xf * jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + 1e-6)
    return (norm * scale).astype(x.dtype)


def _maybe_psum(x: jax.Array, mesh: Mesh, axis: str) -> jax.Array:
    return jax.lax.psum(x, axis) if axis in mesh.axis_names else x


def _block_spec(cfg: TransformerConfig, mesh: Mesh) -> Dict[str, P]:
    """Specs for the stacked (leading dim = n_layers) block params. The
    leading layer dim shards over the pipe axis: each pipeline rank holds
    its contiguous chunk of blocks."""
    tp = cfg.tp_axis if cfg.tp_axis in mesh.axis_names else None
    pp = cfg.pp_axis if cfg.pp_axis in mesh.axis_names else None
    spec = {
        "ln1": P(pp, None),
        "wqkv": P(pp, None, None, tp, None),  # (L, D, 3, H, Dh) col-sharded
        "bqkv": P(pp, None, tp, None),
        "wo": P(pp, tp, None, None),  # (L, H, Dh, D) row-sharded -> psum
        "bo": P(pp, None),
        "ln2": P(pp, None),
    }
    if cfg.moe_experts > 0:
        ep = cfg.expert_axis if cfg.expert_axis in mesh.axis_names else None
        spec.update({
            "router": P(pp, None, None),      # (L, D, E) replicated
            "w_up": P(pp, ep, None, None),    # (L, E, D, F) expert-sharded
            "b_up": P(pp, ep, None),
            "w_down": P(pp, ep, None, None),  # (L, E, F, D)
            "b_down": P(pp, ep, None),
        })
    else:
        spec.update({
            "win": P(pp, None, tp),  # (L, D, F) col-sharded
            "bin": P(pp, tp),
            "wout": P(pp, tp, None),  # (L, F, D) row-sharded -> psum
            "bout": P(pp, None),
        })
    return spec


def _param_spec(cfg: TransformerConfig, mesh: Mesh) -> dict:
    return {
        "embed": P(None, None),
        "pos": P(None, None),
        "blocks": _block_spec(cfg, mesh),
        "lnf": P(None),
        "head": P(None, None),
    }


def _init(cfg: TransformerConfig, key: jax.Array, mesh: Mesh) -> dict:
    tp = _axis_size(mesh, cfg.tp_axis)
    if cfg.n_heads % tp or cfg.d_ff % tp:
        raise ValueError(
            f"n_heads={cfg.n_heads} and d_ff={cfg.d_ff} must be divisible by tp={tp}"
        )
    if cfg.seq_len % _axis_size(mesh, cfg.seq_axis):
        raise ValueError(
            f"seq_len={cfg.seq_len} must be divisible by "
            f"sp={_axis_size(mesh, cfg.seq_axis)}"
        )
    n_pp = _axis_size(mesh, cfg.pp_axis)
    if cfg.n_layers % n_pp:
        raise ValueError(
            f"n_layers={cfg.n_layers} must be divisible by pp={n_pp}"
        )
    if cfg.pipeline_schedule not in ("gpipe", "1f1b", "1f1b-interleaved"):
        raise ValueError(
            f"unknown pipeline_schedule {cfg.pipeline_schedule!r}; "
            "expected 'gpipe', '1f1b' or '1f1b-interleaved'"
        )
    v = cfg.virtual_stages
    if v < 1:
        raise ValueError(f"virtual_stages={v} must be >= 1")
    if v > 1 and cfg.pipeline_schedule != "1f1b-interleaved":
        raise ValueError(
            f"virtual_stages={v} requires pipeline_schedule="
            f"'1f1b-interleaved', got {cfg.pipeline_schedule!r}"
        )
    if cfg.pipeline_schedule == "1f1b-interleaved" and n_pp > 1:
        if cfg.n_layers % (n_pp * v):
            raise ValueError(
                f"n_layers={cfg.n_layers} must be divisible by "
                f"pp*virtual_stages={n_pp * v} for the interleaved schedule"
            )
        if v > 1 and (cfg.microbatches or n_pp) % n_pp:
            raise ValueError(
                f"microbatches={cfg.microbatches} must be divisible by "
                f"pp={n_pp} for the interleaved schedule (microbatches are "
                f"injected in groups of pp)"
            )
    E = cfg.moe_experts
    if E > 0 and E % _axis_size(mesh, cfg.expert_axis):
        raise ValueError(
            f"moe_experts={E} must be divisible by "
            f"ep={_axis_size(mesh, cfg.expert_axis)}"
        )
    if E > 0 and not 1 <= cfg.moe_top_k <= E:
        raise ValueError(
            f"moe_top_k={cfg.moe_top_k} must be in [1, moe_experts={E}]"
        )
    D, H, Dh, F, L, V = (
        cfg.d_model, cfg.n_heads, cfg.head_dim, cfg.d_ff, cfg.n_layers,
        cfg.vocab_size,
    )
    ks = jax.random.split(key, 8)
    blocks = {
        "ln1": jnp.ones((L, D), jnp.float32),
        "wqkv": jax.random.normal(ks[2], (L, D, 3, H, Dh), jnp.float32)
        * math.sqrt(1.0 / D),
        "bqkv": jnp.zeros((L, 3, H, Dh), jnp.float32),
        "wo": jax.random.normal(ks[3], (L, H, Dh, D), jnp.float32)
        * math.sqrt(1.0 / D),
        "bo": jnp.zeros((L, D), jnp.float32),
        "ln2": jnp.ones((L, D), jnp.float32),
    }
    if E > 0:
        blocks.update({
            "router": jax.random.normal(ks[7], (L, D, E), jnp.float32) * 0.02,
            "w_up": jax.random.normal(ks[4], (L, E, D, F), jnp.float32)
            * math.sqrt(2.0 / D),
            "b_up": jnp.zeros((L, E, F), jnp.float32),
            "w_down": jax.random.normal(ks[5], (L, E, F, D), jnp.float32)
            * math.sqrt(1.0 / F),
            "b_down": jnp.zeros((L, E, D), jnp.float32),
        })
    else:
        blocks.update({
            "win": jax.random.normal(ks[4], (L, D, F), jnp.float32)
            * math.sqrt(2.0 / D),
            "bin": jnp.zeros((L, F), jnp.float32),
            "wout": jax.random.normal(ks[5], (L, F, D), jnp.float32)
            * math.sqrt(1.0 / F),
            "bout": jnp.zeros((L, D), jnp.float32),
        })
    if cfg.pipeline_schedule == "1f1b-interleaved" and v > 1 and n_pp > 1:
        # Chunk-major storage for the interleaved schedule: the row held at
        # storage position p is logical layer perm[p], so rank r's P(pipe)
        # shard carries its v noncontiguous chunks back to back. The
        # permutation depends on this mesh's pp — checkpoints restored onto
        # a mesh with a different pp (or schedule) need re-permuting, the
        # same caveat contiguous stage sharding already has.
        perm = interleaved_layout(L, n_pp, v)
        blocks = jax.tree_util.tree_map(lambda a: a[perm], blocks)
    host = {
        "embed": jax.random.normal(ks[0], (V, D), jnp.float32) * 0.02,
        "pos": jax.random.normal(ks[1], (cfg.seq_len, D), jnp.float32) * 0.02,
        "blocks": blocks,
        "lnf": jnp.ones((D,), jnp.float32),
        "head": jax.random.normal(ks[6], (D, V), jnp.float32) * 0.02,
    }
    spec = _param_spec(cfg, mesh)
    return jax.tree_util.tree_map(
        lambda a, s: jax.device_put(a, NamedSharding(mesh, s)),
        host,
        spec,
        is_leaf=lambda x: isinstance(x, P),
    )


def _moe_ffn(cfg: TransformerConfig, mesh: Mesh, h: jax.Array, bp: dict):
    """Switch (top-1) mixture-of-experts FFN on local shards.

    ``h``: (Bl, Sl, D) bf16 normed activations. Expert weights arrive
    expert-sharded: (E_local, D, F) where E_local = E/ep. The classic
    einsum-dispatch formulation (Mesh-TensorFlow / Switch):

      1. route: per-token top-k experts (k=1 switch: gate = raw router
         prob, its only gradient path; k>1 GShard: gates renormalized
         over the surviving choices, first choices outranking seconds
         for capacity);
      2. dispatch einsum packs each expert's first-C tokens into static
         (E, C, D) slots (capacity-dropped tokens contribute nothing and
         ride the residual unchanged);
      3. `all_to_all` over the expert axis turns expert-major slots into
         device-major: every device receives ITS experts' slots from all
         ep peers — the MoE shuffle, on ICI;
      4. batched expert FFN over the E_local dim;
      5. reverse `all_to_all`, combine einsum (dispatch x gate) unpacks
         slots back to token positions.

    Without an expert axis (ep=1) the two collectives vanish and the same
    math runs locally — layout changes, math doesn't (tested invariant).
    """
    B, S, D = h.shape
    E, F = cfg.moe_experts, cfg.d_ff
    ep = _axis_size(mesh, cfg.expert_axis)
    T = B * S
    cap = max(1, math.ceil(cfg.moe_top_k * T / E * cfg.moe_capacity_factor))
    tok = h.reshape(T, D)

    logits = jnp.einsum(
        "td,de->te", tok.astype(jnp.float32), bp["router"]
    )  # (T, E) f32 — routing decisions deserve full precision
    probs = jax.nn.softmax(logits, axis=-1)
    k = cfg.moe_top_k
    # k successive argmaxes (masking each choice out) instead of top_k:
    # the one-hots are needed anyway and the loop is tiny and static
    remaining = probs
    onehots, gates = [], []
    for _ in range(k):
        choice = remaining.argmax(axis=-1)  # (T,)
        oh = jax.nn.one_hot(choice, E, dtype=jnp.int32)
        onehots.append(oh)
        gates.append(jnp.sum(probs * oh, axis=-1))
        remaining = remaining * (1 - oh)
    # switch load-balance aux on FIRST choices (the standard form):
    # E * sum_e f_e p_e is minimized (=1) by uniform routing
    aux = E * jnp.sum(
        jnp.mean(onehots[0].astype(jnp.float32), axis=0)
        * jnp.mean(probs, axis=0)
    )
    # capacity slots assigned in priority order: the cumsum runs over all
    # first choices before any second choice, so an oversubscribed expert
    # sheds k>1 traffic first (GShard semantics)
    oh_all = jnp.concatenate(onehots, axis=0)  # (k*T, E)
    pos = jnp.cumsum(oh_all, axis=0) * oh_all - 1  # slot index or -1
    keep = (pos >= 0) & (pos < cap)
    dispatch_all = (
        jax.nn.one_hot(jnp.clip(pos, 0, cap - 1), cap, dtype=jnp.bfloat16)
        * keep[..., None].astype(jnp.bfloat16)
    )  # (k*T, E, C)
    alive = dispatch_all.sum(axis=(1, 2)).reshape(k, T)  # 1 if slotted
    gate_k = jnp.stack(gates) * alive.astype(jnp.float32)  # (k, T)
    if k > 1:
        # GShard: renormalize over the surviving choices. NOT at k=1 —
        # switch scales by the raw router prob (that product is the
        # router's only gradient path; argmax has none).
        gate_k = gate_k / jnp.maximum(gate_k.sum(axis=0, keepdims=True),
                                      1e-9)
    dispatch = dispatch_all.reshape(k, T, E, cap).sum(axis=0)  # (T, E, C)
    combine_k = (
        dispatch_all.reshape(k, T, E, cap)
        * gate_k[:, :, None, None].astype(jnp.bfloat16)
    )
    combine = combine_k.sum(axis=0)  # (T, E, C)

    slots = jnp.einsum("tec,td->ecd", dispatch, tok)  # (E, C, D)
    if ep > 1:
        # expert-major -> device-major: each device keeps rows for its own
        # E_local experts and receives the matching rows from every peer,
        # concatenated along the slot dim -> (E_local, ep*C, D).
        slots = jax.lax.all_to_all(
            slots, cfg.expert_axis, split_axis=0, concat_axis=1, tiled=True
        )
    up = jnp.einsum("ecd,edf->ecf", slots, bp["w_up"].astype(jnp.bfloat16))
    act = jax.nn.gelu(up + bp["b_up"][:, None, :].astype(jnp.bfloat16))
    down = jnp.einsum(
        "ecf,efd->ecd", act, bp["w_down"].astype(jnp.bfloat16)
    ) + bp["b_down"][:, None, :].astype(jnp.bfloat16)
    if ep > 1:
        down = jax.lax.all_to_all(
            down, cfg.expert_axis, split_axis=1, concat_axis=0, tiled=True
        )
    out = jnp.einsum("ecd,tec->td", down, combine)  # (T, D)
    return out.reshape(B, S, D).astype(jnp.float32), aux


def _block(cfg: TransformerConfig, mesh: Mesh, n_sp: int, x: jax.Array, bp: dict):
    """One decoder block on local shards. x: (Bl, Sl, D) bf16."""
    Dh = cfg.head_dim
    B, S, D = x.shape
    h = _rmsnorm(x, bp["ln1"])
    qkv = (
        jnp.einsum(
            "bsd,dthe->bsthe", h, bp["wqkv"].astype(jnp.bfloat16)
        )
        + bp["bqkv"].astype(jnp.bfloat16)
    )  # (Bl, Sl, 3, Hl, Dh)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    attn = _ring_attention_local(
        q, k, v, seq_axis=cfg.seq_axis, n_shards=n_sp, causal=True,
        scale=1.0 / math.sqrt(Dh), flash=cfg.flash,
    )  # (Bl, Sl, Hl, Dh)
    out = jnp.einsum("bshe,hed->bsd", attn, bp["wo"].astype(jnp.bfloat16))
    out = _maybe_psum(out.astype(jnp.float32), mesh, cfg.tp_axis) + bp["bo"]
    x = x + out.astype(jnp.bfloat16)
    h = _rmsnorm(x, bp["ln2"])
    aux = jnp.zeros((), jnp.float32)
    if cfg.moe_experts > 0:
        o, aux = _moe_ffn(cfg, mesh, h, bp)
    else:
        f = jnp.einsum("bsd,df->bsf", h, bp["win"].astype(jnp.bfloat16))
        f = jax.nn.gelu(f + bp["bin"].astype(jnp.bfloat16))
        o = jnp.einsum("bsf,fd->bsd", f, bp["wout"].astype(jnp.bfloat16))
        o = _maybe_psum(o.astype(jnp.float32), mesh, cfg.tp_axis) + bp["bout"]
    return x + o.astype(jnp.bfloat16), aux


def _kernel(cfg: TransformerConfig, mesh: Mesh, params: dict, tokens, targets):
    """Full forward + mean cross-entropy on local shards."""
    n_sp = _axis_size(mesh, cfg.seq_axis)
    Sl = tokens.shape[1]
    my_sp = (
        jax.lax.axis_index(cfg.seq_axis) if cfg.seq_axis in mesh.axis_names else 0
    )
    pos = my_sp * Sl + jnp.arange(Sl)  # global positions of local tokens
    x = params["embed"][tokens] + params["pos"][pos]
    x = x.astype(jnp.bfloat16)

    block_fn = partial(_block, cfg, mesh, n_sp)
    if cfg.remat:
        # Checkpoint at block granularity: under the scan this stores only
        # each block's INPUT carry and recomputes its internals in backward.
        # prevent_cse=False: scan already provides the staging that makes
        # checkpoint's CSE barriers necessary elsewhere; keeping them would
        # block XLA fusion inside the block body for nothing.
        block_fn = jax.checkpoint(block_fn, prevent_cse=False)

    def stage(blocks_local, h):
        """Apply this rank's chunk of blocks — activation-only form (the
        per-block aux scalar is dropped; the schedules use stage_with_aux
        when a nonzero moe_aux_weight needs it carried)."""
        h, _ = jax.lax.scan(
            lambda c, bp: (block_fn(c, bp)[0], None),
            h,
            blocks_local,
        )
        return h

    def stage_with_aux(blocks_local, h):
        """Aux-carrying form: accumulates the MoE load-balance aux through
        the scan carry alongside the activations. Doubles as the pipeline
        stage function under moe_aux_weight > 0 — the schedules accumulate
        the returned per-stage value across real (stage, microbatch)
        executions and psum it over the pipe axis. The accumulator is
        shape (1,), not scalar: jax 0.4's shard_map transpose assigns
        residuals a leading-dim sharding, which a rank-0 residual cannot
        carry (_SpecError) — any input-dependent scalar in a
        differentiated scan carry trips it."""

        def body(carry, bp):
            h, aux_acc = carry
            h, aux = block_fn(h, bp)
            return (h, aux_acc + aux), None

        (h, aux), _ = jax.lax.scan(
            body, (h, jnp.zeros((1,), jnp.float32)), blocks_local
        )
        return h, aux

    def tail_loss(lnf, head, y, tgt):
        """Final norm + LM head + mean token cross-entropy (f32)."""
        h = _rmsnorm(y, lnf).astype(jnp.float32)
        logits = jnp.einsum("bsd,dv->bsv", h, head)
        lse = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, tgt[..., None], axis=-1)[..., 0]
        return jnp.mean(lse - gold)

    n_pp = _axis_size(mesh, cfg.pp_axis)
    use_aux = cfg.moe_experts > 0 and cfg.moe_aux_weight > 0
    # per-LAYER weight: stages accumulate per-block aux sums, the no-pipe
    # path sums over the whole stack — dividing by n_layers makes the term
    # a per-layer mean under every composition.
    aux_w = cfg.moe_aux_weight / cfg.n_layers if use_aux else 0.0
    if n_pp > 1 and cfg.pipeline_schedule in ("1f1b", "1f1b-interleaved"):
        # Combined-schedule pipeline: per-microbatch tail loss inside the
        # scan (the seed cotangent must exist while later microbatches are
        # still in forward — that interleaving is what bounds the
        # activation stash at O(pp * virtual_stages); see parallel.pipeline).
        v_eff = (
            cfg.virtual_stages
            if cfg.pipeline_schedule == "1f1b-interleaved" else 1
        )
        loss = pipeline_train_1f1b(
            stage_with_aux if use_aux else stage,
            lambda tp, y, tgt: tail_loss(tp[0], tp[1], y, tgt),
            cfg.pp_axis,
            n_pp,
            cfg.microbatches or n_pp,
            v_eff,
            aux_w,
            params["blocks"],
            (params["lnf"], params["head"]),
            x,
            targets,
        )
    else:
        if n_pp > 1:
            out = _pipeline_local(
                stage_with_aux if use_aux else stage,
                params["blocks"],
                x,
                pipe_axis=cfg.pp_axis,
                n_stages=n_pp,
                microbatches=cfg.microbatches or n_pp,
                stage_aux=use_aux,
            )
            x, aux = out if use_aux else (out, jnp.zeros((1,), jnp.float32))
        else:
            x, aux = stage_with_aux(params["blocks"], x)
        loss = tail_loss(params["lnf"], params["head"], x, targets)
        if use_aux:
            loss = loss + aux_w * aux[0]
    reduce_axes = (*present_axes(mesh, cfg.batch_axis),
                   *present_axes(mesh, cfg.seq_axis))
    return jax.lax.pmean(loss, reduce_axes) if reduce_axes else loss


def _batch_specs(cfg: TransformerConfig, mesh: Mesh) -> Dict[str, P]:
    dp = present_axes(mesh, cfg.batch_axis) or None  # P takes the tuple
    sp = cfg.seq_axis if cfg.seq_axis in mesh.axis_names else None
    return {"tokens": P(dp, sp), "targets": P(dp, sp)}


def _loss(cfg: TransformerConfig, params: dict, batch: dict, mesh: Mesh):
    specs = _batch_specs(cfg, mesh)
    return shard_map(
        partial(_kernel, cfg, mesh),
        mesh=mesh,
        in_specs=(_param_spec(cfg, mesh), specs["tokens"], specs["targets"]),
        out_specs=P(),
        check_vma=False,
    )(params, batch["tokens"], batch["targets"])


def synthetic_batch(cfg: TransformerConfig, rng: np.random.Generator, batch_size: int):
    """PTB-style id streams: next-token prediction over seq_len tokens."""
    ids = rng.integers(
        0, cfg.vocab_size, (batch_size, cfg.seq_len + 1), dtype=np.int64
    ).astype(np.int32)
    return {"tokens": ids[:, :-1], "targets": ids[:, 1:]}


def _flops_per_step(cfg: TransformerConfig, batch_size: int) -> float:
    """Train-step model FLOPs (MFU numerator; see models.base convention).

    Per token forward: qkv 6D^2 + out-proj 2D^2 + ffn 4DF per layer, plus
    causal attention (QK^T and PV are 2*S*D each, halved by the mask) and
    the LM head 2DV. Backward = 2x forward; remat recompute excluded.
    """
    D, F, L = cfg.d_model, cfg.d_ff, cfg.n_layers
    # MoE: each token visits moe_top_k experts' 4DF FFNs plus the router
    # matmul (capacity-dropped tokens still count — MFU numerator
    # convention, like remat).
    if cfg.moe_experts:
        ffn = cfg.moe_top_k * 4 * D * F + 2 * D * cfg.moe_experts
    else:
        ffn = 4 * D * F
    per_token = (
        L * (6 * D * D + 2 * D * D + ffn + 0.5 * (4 * cfg.seq_len * D))
        + 2 * D * cfg.vocab_size
    )
    return 3.0 * per_token * cfg.seq_len * batch_size


# -- LM serving: prefill / single-token decode --------------------------------
#
# Training runs the whole forward as one shard_map kernel; serving an LM is
# a different shape of work. Autoregressive traffic splits into two phases
# with opposite hardware profiles (the prefill/decode separation every
# production LM server makes):
#
# - **prefill** — the prompt's full causal forward, compute-bound, shaped
#   (batch bucket, seq bucket). It returns the per-layer K/V it computed so
#   decode never re-touches prompt tokens, plus the prompt's next token.
# - **decode** — one token per step, memory-bound: each call reads the
#   whole K/V cache once and appends one position. Its K/V write-back is
#   returned to the caller (shaped (L, B, H, Dh)) instead of updating a
#   cache in place, so the serving engine owns cache layout — per-stream
#   host caches make per-token batch-membership changes free.
#
# Both are pure fixed-shape functions of (params, int32 arrays), AOT-
# compilable per (batch bucket, seq bucket) with jit(...).lower().compile()
# — the serve tier's empty-dispatch-cache contract extends to LM traffic.
# They run replicated (the serving mesh gives non-data axes size 1), so no
# collectives appear; matmuls in bf16, norms/softmax/logits in f32, same
# discipline as the training kernel. Dense FFN only: MoE decode needs the
# expert all_to_all plumbed through the cache path (not yet built).


def lm_cache_shape(cfg: TransformerConfig) -> Tuple[int, int, int]:
    """(n_layers, n_heads, head_dim) — the per-token K/V geometry the
    serving tier sizes its block pool from."""
    return (cfg.n_layers, cfg.n_heads, cfg.head_dim)


def lm_cache_bytes_per_token(cfg: TransformerConfig) -> int:
    """HBM bytes one token slot of K+V occupies (bf16 cache)."""
    L, H, Dh = lm_cache_shape(cfg)
    return 2 * L * H * Dh * 2  # K and V, 2 bytes each (bfloat16)


def _check_lm_servable(cfg: TransformerConfig) -> None:
    if cfg.moe_experts > 0:
        raise NotImplementedError(
            "LM serving path covers dense FFN configs only (MoE decode "
            "needs the expert all_to_all plumbed through the cache path)"
        )


def _decode_attention(q, k_cache, v_cache, k_new, v_new, lengths, scale):
    """One token's attention over its cache plus itself.

    q/k_new/v_new: (B, H, Dh) bf16; caches (B, C, H, Dh) bf16; lengths
    (B,) int32 = tokens already IN the cache (the new token's position).
    Cache positions >= length are dead slots (pad garbage or not yet
    written) and are masked out; the new token always attends to itself.
    """
    C = k_cache.shape[1]
    scores = jnp.einsum(
        "bhe,bche->bhc", q.astype(jnp.float32), k_cache.astype(jnp.float32)
    ) * scale
    valid = jnp.arange(C)[None, :] < lengths[:, None]  # (B, C)
    scores = jnp.where(valid[:, None, :], scores, -jnp.inf)
    self_score = jnp.sum(
        q.astype(jnp.float32) * k_new.astype(jnp.float32), axis=-1
    )[..., None] * scale  # (B, H, 1)
    w = jax.nn.softmax(jnp.concatenate([scores, self_score], axis=-1), axis=-1)
    out = jnp.einsum(
        "bhc,bche->bhe", w[..., :C], v_cache.astype(jnp.float32)
    ) + w[..., C:] * v_new.astype(jnp.float32)
    return out.astype(jnp.bfloat16)


def make_decode_step(cfg: TransformerConfig):
    """Single-token decode: (params, k_cache, v_cache, tokens, lengths) ->
    (next_tokens, k_new, v_new).

    Shapes: caches (L, B, C, H, Dh) bf16 — C is the stream's seq-bucket
    capacity; ``tokens`` (B,) the last emitted token ids; ``lengths`` (B,)
    the token count already cached (== the new token's position). Returns
    greedy-argmax next tokens (B,) int32 and the new position's per-layer
    K/V (L, B, H, Dh) for the caller to append at index ``lengths``.
    """
    _check_lm_servable(cfg)
    scale = 1.0 / math.sqrt(cfg.head_dim)

    def step(params, k_cache, v_cache, tokens, lengths):
        x = (params["embed"][tokens] + params["pos"][lengths]).astype(
            jnp.bfloat16
        )  # (B, D)

        def body(x, layer):
            bp, k_c, v_c = layer
            h = _rmsnorm(x, bp["ln1"])
            qkv = (
                jnp.einsum("bd,dthe->bthe", h, bp["wqkv"].astype(jnp.bfloat16))
                + bp["bqkv"].astype(jnp.bfloat16)
            )  # (B, 3, H, Dh)
            q, k_new, v_new = qkv[:, 0], qkv[:, 1], qkv[:, 2]
            attn = _decode_attention(q, k_c, v_c, k_new, v_new, lengths, scale)
            out = jnp.einsum("bhe,hed->bd", attn, bp["wo"].astype(jnp.bfloat16))
            x = x + (out.astype(jnp.float32) + bp["bo"]).astype(jnp.bfloat16)
            h = _rmsnorm(x, bp["ln2"])
            f = jnp.einsum("bd,df->bf", h, bp["win"].astype(jnp.bfloat16))
            f = jax.nn.gelu(f + bp["bin"].astype(jnp.bfloat16))
            o = jnp.einsum("bf,fd->bd", f, bp["wout"].astype(jnp.bfloat16))
            x = x + (o.astype(jnp.float32) + bp["bout"]).astype(jnp.bfloat16)
            return x, (k_new.astype(jnp.bfloat16), v_new.astype(jnp.bfloat16))

        x, (k_appended, v_appended) = jax.lax.scan(
            body, x, (params["blocks"], k_cache, v_cache)
        )
        h = _rmsnorm(x, params["lnf"]).astype(jnp.float32)
        logits = jnp.einsum("bd,dv->bv", h, params["head"])
        return (
            jnp.argmax(logits, axis=-1).astype(jnp.int32),
            k_appended,
            v_appended,
        )

    return step


def make_prefill_step(cfg: TransformerConfig):
    """Prompt prefill: (params, tokens, lengths) ->
    (next_tokens, k_cache, v_cache).

    ``tokens`` (B, S) right-padded int32 prompts, ``lengths`` (B,) real
    token counts. Full causal attention over the padded bucket (pad
    positions compute dead K/V the decode mask never reads); returns the
    per-layer K/V for all S positions as (L, B, S, H, Dh) bf16 and the
    greedy next token read at position ``lengths - 1``.
    """
    _check_lm_servable(cfg)
    scale = 1.0 / math.sqrt(cfg.head_dim)

    def step(params, tokens, lengths):
        B, S = tokens.shape
        pos = jnp.arange(S)
        x = (params["embed"][tokens] + params["pos"][pos]).astype(jnp.bfloat16)
        causal = pos[None, :] <= pos[:, None]  # (S, S) keys <= queries

        def body(x, bp):
            h = _rmsnorm(x, bp["ln1"])
            qkv = (
                jnp.einsum("bsd,dthe->bsthe",
                           h, bp["wqkv"].astype(jnp.bfloat16))
                + bp["bqkv"].astype(jnp.bfloat16)
            )
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            scores = jnp.einsum(
                "bshe,bthe->bhst", q.astype(jnp.float32),
                k.astype(jnp.float32)
            ) * scale
            scores = jnp.where(causal[None, None], scores, -jnp.inf)
            w = jax.nn.softmax(scores, axis=-1)
            attn = jnp.einsum(
                "bhst,bthe->bshe", w, v.astype(jnp.float32)
            ).astype(jnp.bfloat16)
            out = jnp.einsum("bshe,hed->bsd",
                             attn, bp["wo"].astype(jnp.bfloat16))
            x = x + (out.astype(jnp.float32) + bp["bo"]).astype(jnp.bfloat16)
            h = _rmsnorm(x, bp["ln2"])
            f = jnp.einsum("bsd,df->bsf", h, bp["win"].astype(jnp.bfloat16))
            f = jax.nn.gelu(f + bp["bin"].astype(jnp.bfloat16))
            o = jnp.einsum("bsf,fd->bsd", f, bp["wout"].astype(jnp.bfloat16))
            x = x + (o.astype(jnp.float32) + bp["bout"]).astype(jnp.bfloat16)
            return x, (k.astype(jnp.bfloat16), v.astype(jnp.bfloat16))

        x, (k_cache, v_cache) = jax.lax.scan(body, x, params["blocks"])
        last = jnp.clip(lengths - 1, 0, S - 1)
        h_last = jnp.take_along_axis(x, last[:, None, None], axis=1)[:, 0]
        h_last = _rmsnorm(h_last, params["lnf"]).astype(jnp.float32)
        logits = jnp.einsum("bd,dv->bv", h_last, params["head"])
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), k_cache, v_cache

    return step


def make_model(cfg: Optional[TransformerConfig] = None, **overrides) -> Model:
    cfg = cfg or TransformerConfig(**overrides)
    return Model(
        name="transformer",
        init=lambda key, mesh: _init(cfg, key, mesh),
        loss_fn=lambda params, batch, mesh: _loss(cfg, params, batch, mesh),
        param_spec=lambda mesh: _param_spec(cfg, mesh),
        synthetic_batch=lambda rng, bs: synthetic_batch(cfg, rng, bs),
        batch_spec=lambda mesh: _batch_specs(cfg, mesh),
        label_keys=("targets",),
        config=cfg,
        flops_per_step=lambda bs: _flops_per_step(cfg, bs),
    )


#: default zoo instance — a small LM whose shapes still tile the MXU (512/8
#: heads, 2048 ff) and divide cleanly over dp/sp/tp meshes up to 8x8x8.
MODEL = make_model()
