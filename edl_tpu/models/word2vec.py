"""word2vec: N-gram neural word embedding with a mesh-sharded input table.

Re-design of the reference's fault-tolerant elastic example
(`example/fit_a_line/train_ft.py:41-99`): a 5-gram model — embed 4 context
words, concat, hidden layer, softmax over the vocabulary. This was the
reference's sparse-update pserver workload; here the input embedding is a
`ShardedEmbedding` and the (small) softmax projection is replicated.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from edl_tpu.models.base import Model
from edl_tpu.parallel.embedding import ShardedEmbedding

#: imikolov-style dict size (ref: paddle.dataset.imikolov, train_ft.py:100-104)
VOCAB = 2074
CONTEXT = 4  # 5-gram: 4 context words -> next word
EMBED_DIM = 32
HIDDEN = 256

_table = ShardedEmbedding(VOCAB, EMBED_DIM, "data", "data")


def init(key: jax.Array, mesh) -> dict:
    k_emb, k_h, k_out = jax.random.split(key, 3)
    replicated = NamedSharding(mesh, P())
    fan_in = CONTEXT * EMBED_DIM
    return {
        "table": _table.init(k_emb, mesh, scale=1.0 / np.sqrt(EMBED_DIM)),
        "hidden": {
            "w": jax.device_put(
                jax.random.normal(k_h, (fan_in, HIDDEN), jnp.float32)
                * jnp.sqrt(2.0 / fan_in),
                replicated,
            ),
            "b": jax.device_put(jnp.zeros((HIDDEN,), jnp.float32), replicated),
        },
        "out": {
            "w": jax.device_put(
                jax.random.normal(k_out, (HIDDEN, VOCAB), jnp.float32) * 0.01,
                replicated,
            ),
            "b": jax.device_put(jnp.zeros((VOCAB,), jnp.float32), replicated),
        },
    }


def predict(params: dict, batch: dict, mesh) -> jax.Array:
    """Context ids (B, 4) -> next-word logits (B, VOCAB) — the serving
    entrypoint; loss_fn is cross-entropy over the same forward."""
    ctx = _table.apply(mesh, params["table"], batch["context"])  # (B, 4, D)
    h = ctx.reshape(ctx.shape[0], -1).astype(jnp.bfloat16)
    h = jax.nn.relu(
        jnp.dot(h, params["hidden"]["w"].astype(jnp.bfloat16))
        + params["hidden"]["b"].astype(jnp.bfloat16)
    )
    logits = jnp.dot(h, params["out"]["w"].astype(jnp.bfloat16)).astype(jnp.float32)
    return logits + params["out"]["b"]


def loss_fn(params: dict, batch: dict, mesh) -> jax.Array:
    logits = predict(params, batch, mesh)
    labels = jax.nn.one_hot(batch["target"], VOCAB, dtype=jnp.float32)
    return -jnp.mean(jnp.sum(labels * jax.nn.log_softmax(logits), axis=-1))


def param_spec(mesh) -> dict:
    return {
        "table": _table.table_spec(),
        "hidden": {"w": P(), "b": P()},
        "out": {"w": P(), "b": P()},
    }


def synthetic_batch(rng: np.random.Generator, batch_size: int) -> dict:
    context = (rng.zipf(1.2, size=(batch_size, CONTEXT)) % VOCAB).astype(np.int32)
    target = (rng.zipf(1.2, size=(batch_size,)) % VOCAB).astype(np.int32)
    return {"context": context, "target": target}


MODEL = Model(
    name="word2vec",
    init=init,
    loss_fn=loss_fn,
    param_spec=param_spec,
    synthetic_batch=synthetic_batch,
    label_keys=("target",),
    predict=predict,
    # MFU numerator: hidden (128 -> 256) + softmax projection (256 -> vocab);
    # the sharded table lookup is a gather, not matmul FLOPs.
    flops_per_step=lambda bs: 3.0 * bs * (
        2 * CONTEXT * EMBED_DIM * HIDDEN + 2 * HIDDEN * VOCAB
    ),
)
