"""fit_a_line: linear regression, the minimum end-to-end workload.

Re-design of `example/fit_a_line/train_local.py:41-109` (Paddle v2 linear
regression on 13 housing features, SGD) as a pure-JAX model. Data is synthetic
housing-like: y = x @ w* + noise with a fixed hidden w*, so loss convergence is
verifiable in tests without the UCI download.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from edl_tpu.models.base import Model

NUM_FEATURES = 13

_TRUE_W = np.linspace(-1.0, 1.0, NUM_FEATURES).astype(np.float32)
_TRUE_B = 0.5


def init(key: jax.Array, mesh) -> dict:
    wkey, _ = jax.random.split(key)
    params = {
        "w": jax.random.normal(wkey, (NUM_FEATURES, 1), jnp.float32) * 0.01,
        "b": jnp.zeros((1,), jnp.float32),
    }
    sharding = jax.tree_util.tree_map(
        lambda _: NamedSharding(mesh, P()), params
    )
    return jax.device_put(params, sharding)


def loss_fn(params: dict, batch: dict, mesh) -> jax.Array:
    pred = batch["x"] @ params["w"] + params["b"]
    return jnp.mean((pred - batch["y"]) ** 2)


def param_spec(mesh) -> dict:
    return {"w": P(), "b": P()}


def synthetic_batch(rng: np.random.Generator, batch_size: int) -> dict:
    x = rng.standard_normal((batch_size, NUM_FEATURES), dtype=np.float32)
    noise = 0.01 * rng.standard_normal((batch_size, 1), dtype=np.float32)
    y = x @ _TRUE_W[:, None] + _TRUE_B + noise
    return {"x": x, "y": y.astype(np.float32)}


def predict(params: dict, batch: dict, mesh) -> jax.Array:
    """(B, 13) features -> (B, 1) predicted price (serving entrypoint)."""
    return batch["x"] @ params["w"] + params["b"]


MODEL = Model(
    name="fit_a_line",
    init=init,
    loss_fn=loss_fn,
    param_spec=param_spec,
    synthetic_batch=synthetic_batch,
    label_keys=("y",),
    predict=predict,
    # MFU numerator (models.base convention): one (B, 13) @ (13, 1) matmul.
    flops_per_step=lambda bs: 3.0 * 2 * NUM_FEATURES * bs,
)
