"""Memory-resident checkpoint plane: peer-replicated ZeRO-1 shards.

The blob-store ``Checkpointer`` makes state durable; this plane makes the
common recovery *fast*. Each worker pushes its 1/N ZeRO shard of the train
state — chunked, epoch-stamped, ``put_id``-deduped — into the coordinator's
memory-resident shard store (``shard_put``/``shard_get``/``shard_meta``/
``shard_drop`` on the wire), with a ring replica-placement map published
through coordinator KV per membership epoch. On worker loss or rescale the
survivors assemble the full state from the plane in memory and re-shard it
onto the new mesh — zero blob reads. Only a whole-replica-group death (or
a coordinator restart: the store is deliberately unjournaled) demotes
recovery to the blob restore. See doc/robustness.md (checkpoint plane).
"""

from edl_tpu.ckpt_plane.placement import (
    PLACEMENT_KEY,
    placement_map,
    publish_placement,
    read_placement,
    replica_group,
)
from edl_tpu.ckpt_plane.recovery import assemble_leaves, peer_restore
from edl_tpu.ckpt_plane.replicator import (
    CHUNK_BYTES,
    CkptPlane,
    chunk_blob,
    host_leaves,
    leaf_slice,
    owner_key,
    parse_shard,
    serialize_shard,
)

__all__ = [
    "CkptPlane",
    "CHUNK_BYTES",
    "PLACEMENT_KEY",
    "assemble_leaves",
    "chunk_blob",
    "host_leaves",
    "leaf_slice",
    "owner_key",
    "parse_shard",
    "peer_restore",
    "placement_map",
    "publish_placement",
    "read_placement",
    "replica_group",
    "serialize_shard",
]
