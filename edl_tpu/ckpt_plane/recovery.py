"""Zero-blob recovery: assemble a full state from peer-replicated shards.

The restorer after a worker loss (or a rescale into a different world)
pulls every owner's latest shard from the coordinator's memory-resident
store, verifies they all belong to one step, concatenates the ZeRO slices
back into full host leaves, and places them onto the NEW mesh through the
same ``state_shardings`` machinery the blob restore uses — so re-sharding
across world-size changes (including non-dividing ones like 6 -> 4) is the
spec layer's job here exactly as it is orbax's on the blob path.

Any gap — a missing owner, an incomplete chunk set, owners disagreeing on
the step, a stale step older than the blob store's — returns None, and the
caller falls back to the durable ``Checkpointer``. ``shard_meta``'s
``complete`` flag is the go/no-go: a replica-group death shows up as an
incomplete (or absent) owner and cleanly demotes recovery one rung down
the ladder (doc/robustness.md, checkpoint plane).
"""

from __future__ import annotations

import base64
import logging
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from edl_tpu.ckpt_plane.replicator import OWNER_PREFIX, owner_key, parse_shard

log = logging.getLogger("edl_tpu.ckpt_plane")


def _pull_shard(client, owner: str) -> Optional[Tuple[Dict, bytes, int]]:
    """Fetch one owner's latest complete shard: (manifest, payload, bytes
    moved). None when absent or incomplete (group death / torn write)."""
    meta = client.shard_meta(owner)
    if not meta.get("ok") or not meta.get("found") or not meta.get("complete"):
        return None
    step = int(meta["step"])
    chunks = int(meta["chunks"])
    encoded: List[str] = []
    call_batch = getattr(client, "call_batch", None)
    if callable(call_batch) and chunks > 1:
        window = 8
        for base in range(0, chunks, window):
            ops = [{"op": "shard_get", "owner": owner, "step": step,
                    "chunk": c}
                   for c in range(base, min(base + window, chunks))]
            for sub in call_batch(ops):
                if not sub.get("ok") or not sub.get("found"):
                    return None
                encoded.append(sub.get("data", ""))
    else:
        for c in range(chunks):
            sub = client.shard_get(owner, step=step, chunk=c)
            if not sub.get("ok") or not sub.get("found"):
                return None
            encoded.append(sub.get("data", ""))
    blob = b"".join(base64.b64decode(e) for e in encoded)
    manifest, payload = parse_shard(blob)
    if int(manifest.get("step", -1)) != step:
        return None  # torn across a concurrent newer put
    return manifest, payload, len(blob)


def assemble_leaves(parts: Dict[int, Tuple[Dict, bytes]]) -> List[np.ndarray]:
    """Concatenate per-rank slices back into full host leaves.

    ``parts`` maps rank -> (manifest, payload) for EVERY rank of the world
    the shards were written under. Leaf layout comes from rank 0's manifest
    (all ranks derive the identical one); sliced leaves concatenate along
    their recorded dim in rank order, unsliced leaves are rank 0's whole
    copy.
    """
    world = len(parts)
    manifest0 = parts[0][0]
    offsets = {r: 0 for r in parts}
    leaves: List[np.ndarray] = []
    for i, meta in enumerate(manifest0["leaves"]):
        shape = tuple(meta["shape"])
        dtype = np.dtype(meta["dtype"])
        dim = meta["dim"]
        if dim is None:
            raw = _take(parts, offsets, 0, i)
            leaves.append(np.frombuffer(raw, dtype=dtype).reshape(shape))
            continue
        pieces = []
        per = shape[dim] // world
        piece_shape = list(shape)
        piece_shape[dim] = per
        for r in range(world):
            raw = _take(parts, offsets, r, i)
            pieces.append(np.frombuffer(raw, dtype=dtype).reshape(piece_shape))
        leaves.append(np.concatenate(pieces, axis=dim))
    return leaves


def _take(parts: Dict[int, Tuple[Dict, bytes]], offsets: Dict[int, int],
          rank: int, leaf_idx: int) -> bytes:
    """Rank ``rank``'s byte range for leaf ``leaf_idx`` (per its manifest)."""
    manifest, payload = parts[rank]
    want = int(manifest["leaves"][leaf_idx]["nbytes"])
    start = offsets[rank]
    offsets[rank] = start + want
    raw = payload[start:start + want]
    if len(raw) != want:
        raise ValueError(
            f"shard payload truncated: rank {rank} leaf {leaf_idx} wanted "
            f"{want} bytes, had {len(raw)}")
    return raw


def peer_restore(client, template: Any, mesh=None, spec_tree=None,
                 min_step: Optional[int] = None,
                 owner_prefix: str = OWNER_PREFIX,
                 instruments=None, tracer=None) -> Optional[Tuple[Any, Dict]]:
    """Assemble the full state from the plane, re-sharded for ``mesh``.

    ``template`` fixes the pytree structure (and the leaf placement when
    ``mesh``/``spec_tree`` are given — the same arguments the blob restore
    takes). ``min_step`` rejects a plane older than the blob store's best:
    recovery must never move training backwards past the durable copy.
    Returns ``(state, {step, bytes, seconds, world_at_save})`` or None.
    """
    import jax

    t0 = time.perf_counter()
    t0_wall = time.time()
    try:
        first = _pull_shard(client, owner_key(0, owner_prefix))
        if first is None:
            return None
        manifest0, payload0, nbytes = first
        step = int(manifest0["step"])
        if min_step is not None and step < int(min_step):
            log.info("ckpt-plane step %d older than blob step %d; using blob",
                     step, int(min_step))
            return None
        world_at_save = int(manifest0["world"])
        parts: Dict[int, Tuple[Dict, bytes]] = {0: (manifest0, payload0)}
        total = nbytes
        for r in range(1, world_at_save):
            got = _pull_shard(client, owner_key(r, owner_prefix))
            if got is None or int(got[0]["step"]) != step:
                log.warning(
                    "ckpt-plane owner %s missing/incomplete/stale at step "
                    "%d — replica group lost; falling back to blob restore",
                    owner_key(r, owner_prefix), step)
                return None
            parts[r] = (got[0], got[1])
            total += got[2]
        host = assemble_leaves(parts)
        _, treedef = jax.tree_util.tree_flatten(template)
        state = jax.tree_util.tree_unflatten(treedef, host)
        # The reshard window: host leaves -> device arrays laid out for the
        # TARGET mesh (which need not match the world the shards were saved
        # under — a 8-chip {dcn:2,data:4} plane restores onto a 6-chip
        # {data:6} mesh through exactly this device_put). Timed separately
        # so the rescale timeline can attribute it as its own phase.
        reshard_start = reshard_end = time.time()
        if mesh is not None and spec_tree is not None:
            from edl_tpu.runtime.checkpoint import abstract_like, state_shardings

            shardings = state_shardings(abstract_like(template), mesh,
                                        spec_tree)
            state = jax.tree_util.tree_map(jax.device_put, state, shardings)
            jax.block_until_ready(state)  # the window must cover the copies
            reshard_end = time.time()
    except Exception:  # edl: noqa[EDL005] the plane is the fast rung of the fallback ladder; any defect in it must demote to the blob restore, never fail recovery outright
        log.warning("ckpt-plane restore failed; falling back to blob restore",
                    exc_info=True)
        return None
    seconds = time.perf_counter() - t0
    if instruments is not None:
        instruments.restores.inc(source="peer")
        instruments.restore_bytes.inc(float(total), source="peer")
    if tracer is not None:
        tracer.record("peer_restore", t0_wall, time.time(),
                      component="worker", step=step, bytes=total,
                      world_at_save=world_at_save)
    return state, {"step": step, "bytes": total, "seconds": seconds,
                   "world_at_save": world_at_save, "source": "peer",
                   "reshard_start": reshard_start,
                   "reshard_end": reshard_end}
