"""Replica placement for the memory-resident checkpoint plane.

Placement is a ring over the membership ranks: the owner of shard ``r``
replicates to the ``k`` successors ``(r+1 .. r+k) mod world``. The map is
deterministic from ``(world, k)`` — every worker derives the same groups
with no coordination — but it is still *published* through the coordinator
KV under an epoch-scoped key, because the restorer after a rescale runs in
a NEW world and must know which layout the surviving shard data was written
under. A membership epoch change invalidates the previous epoch's key (the
ranks it names no longer exist); the shard *data* is deliberately NOT
dropped — serving a dead owner's bytes to its successor is the plane's
whole point.
"""

from __future__ import annotations

import json
from typing import Dict, Iterable, List, Optional

#: coordinator KV key the placement map lives under, scoped by membership
#: epoch so a rescale's new map never aliases the old one.
PLACEMENT_KEY = "edl/ckpt_plane/placement/e{epoch}"


def replica_group(rank: int, world: int, k: int,
                  exclude: Optional[Iterable[int]] = None) -> List[int]:
    """Holder ranks for ``rank``'s shard: the ``k`` ring successors.

    ``k`` is clamped to ``world - 1`` (a peer cannot replicate to itself,
    and more holders than peers is meaningless). world=1 yields no holders:
    a lone worker's plane degenerates to the coordinator's own copy.

    ``exclude`` is the revocation override: ranks under an advance-notice
    drain are skipped when walking the ring — a doomed host may still OWN
    a shard (that data is exactly what must be copied off it) but never
    HOLDS a replica. The walk continues past excluded ranks so the group
    keeps ``k`` holders whenever enough survivors exist.
    """
    if world <= 1:
        return []
    banned = {int(x) % world for x in exclude} if exclude else set()
    k = max(0, min(k, world - 1 - len(banned - {rank % world})))
    out: List[int] = []
    for i in range(1, world):
        if len(out) >= k:
            break
        cand = (rank + i) % world
        if cand in banned:
            continue
        out.append(cand)
    return out


def placement_map(world: int, k: int,
                  exclude: Optional[Iterable[int]] = None
                  ) -> Dict[int, List[int]]:
    """owner rank -> holder ranks, for every rank in ``world``."""
    ex = list(exclude) if exclude else None
    return {r: replica_group(r, world, k, exclude=ex)
            for r in range(world)}


def publish_placement(client, epoch: int, world: int, k: int,
                      prev_epoch: Optional[int] = None,
                      exclude: Optional[Iterable[int]] = None) -> Dict:
    """Publish the epoch's placement map to coordinator KV and invalidate
    the previous epoch's (epoch change = rank renumbering = every group in
    the old map is stale). Idempotent: every member writes the identical
    JSON, so concurrent publishes are harmless. ``exclude`` (revoked ranks)
    is recorded in the doc so late readers reproduce the same override."""
    ex = sorted({int(x) for x in exclude}) if exclude else []
    doc = {
        "epoch": int(epoch),
        "world": int(world),
        "replicas": int(k),
        "excluded": ex,
        "groups": {str(r): g for r, g in
                   placement_map(world, k, exclude=ex).items()},
    }
    client.kv_put(PLACEMENT_KEY.format(epoch=int(epoch)), json.dumps(doc))
    if prev_epoch is not None and int(prev_epoch) != int(epoch):
        client.kv_del(PLACEMENT_KEY.format(epoch=int(prev_epoch)))
    return doc


def read_placement(client, epoch: int) -> Optional[Dict]:
    """The published map for ``epoch``, or None when absent/invalidated."""
    raw = client.kv_get(PLACEMENT_KEY.format(epoch=int(epoch)))
    if not raw:
        return None
    doc = json.loads(raw)
    doc["groups"] = {int(r): g for r, g in doc.get("groups", {}).items()}
    return doc
