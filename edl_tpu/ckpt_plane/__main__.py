"""Checkpoint-plane smoke: replicate, kill, peer-restore, match the twin.

``python -m edl_tpu.ckpt_plane`` (the ``make ckpt-plane-smoke`` target)
drives the full fallback ladder on a host-device mesh and proves the
plane is *invisible to the optimizer trajectory*:

1. TWIN — train ``TOTAL_STEPS`` straight through; record the final loss.
2. PEER — train half, replicate every rank's ZeRO shard to the plane and
   write the durable blob, then throw the live state away (the "killed
   worker"), peer-restore from coordinator memory onto the same mesh, and
   finish on the identical batch stream. Byte-exact shards mean the final
   loss must EQUAL the twin's, and zero blob reads happen.
3. GROUP DEATH — drop every owner's shard (a whole replica group dying),
   watch ``restore`` demote to None, fall back to the blob store, finish,
   and match the twin again.

Deterministic CPU math makes "matches" exact float equality, not a
tolerance — any divergence is a serialization bug, not noise.
"""

from __future__ import annotations

import json
import shutil
import sys
import tempfile

import jax

jax.config.update("jax_platforms", "cpu")  # sitecustomize ignores the env var

import numpy as np

from edl_tpu.ckpt_plane import CkptPlane
from edl_tpu.coordinator.inprocess import InProcessCoordinator
from edl_tpu.models import fit_a_line
from edl_tpu.parallel import MeshSpec, build_mesh
from edl_tpu.runtime.checkpoint import (Checkpointer, abstract_like,
                                        live_state_specs)
from edl_tpu.runtime.train_loop import Trainer, TrainerConfig

TOTAL_STEPS = 6
KILL_AFTER = 3
WORLD = 2  # plane owners per covered checkpoint


def main() -> int:
    ndev = min(4, jax.device_count())
    mesh = build_mesh(MeshSpec({"data": ndev}), jax.devices()[:ndev])
    model = fit_a_line.MODEL
    tcfg = TrainerConfig(optimizer="adam", shard_opt_state=True)

    # One batch stream, fixed up front, replayed by every run: the twin and
    # both recovery runs must see byte-identical data or "loss matches" is
    # meaningless.
    rng = np.random.default_rng(7)
    batches = [model.synthetic_batch(rng, 16) for _ in range(TOTAL_STEPS)]

    def run_steps(trainer, state, lo, hi):
        loss = None
        for i in range(lo, hi):
            state, loss = trainer.train_step(state,
                                             trainer.place_batch(batches[i]))
        return state, float(loss)

    # 1) twin: straight through
    trainer = Trainer(model, mesh, tcfg)
    _, twin_loss = run_steps(trainer, trainer.init_state(), 0, TOTAL_STEPS)

    coord = InProcessCoordinator()
    client = coord.client("smoke")
    client.register()
    plane = CkptPlane(client, replicas=1)
    plane.on_epoch(1, world=WORLD, rank=0)
    ckpt_dir = tempfile.mkdtemp(prefix="edl-ckpt-plane-smoke-")
    result = {"twin_loss": twin_loss}
    try:
        ckpt = Checkpointer(ckpt_dir)

        # 2) train half, cover it (plane + blob), kill, peer-restore, finish
        state, _ = run_steps(trainer, trainer.init_state(), 0, KILL_AFTER)
        rep = plane.replicate_all(state, KILL_AFTER, world=WORLD)
        assert rep is not None, "replication failed"
        ckpt.save(KILL_AFTER, state)
        ckpt.wait()
        del state  # the killed worker's memory is gone

        fresh = trainer.init_state()
        got = plane.restore(fresh, mesh, live_state_specs(fresh),
                            min_step=ckpt.latest_step())
        assert got is not None, "peer restore should have succeeded"
        restored, info = got
        assert info["world_at_save"] == WORLD
        _, peer_loss = run_steps(trainer, restored, KILL_AFTER, TOTAL_STEPS)
        result["peer"] = {"loss": peer_loss, "bytes": info["bytes"],
                          "source": info["source"]}

        # 3) whole replica group dies: plane demotes, blob finishes the job
        for r in range(WORLD):
            plane.drop_owner(r)
        assert plane.restore(fresh) is None, \
            "group death must demote the plane to None"
        blob_state = ckpt.restore(abstract_like(fresh), mesh,
                                  live_state_specs(fresh))
        _, blob_loss = run_steps(trainer, blob_state, KILL_AFTER, TOTAL_STEPS)
        result["blob_fallback"] = {"loss": blob_loss}
    finally:
        shutil.rmtree(ckpt_dir, ignore_errors=True)

    ok = (peer_loss == twin_loss) and (blob_loss == twin_loss)
    result["pass"] = ok
    print(json.dumps(result, indent=2))
    if not ok:
        print("ckpt-plane smoke FAILED: recovery diverged from the twin",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
