"""Shard replication: host-level ZeRO-1 slices pushed through the wire.

Each worker owns a 1/``world`` slice of every state leaf — the same
largest-divisible-dim layout ``parallel.collective.zero_shard_spec`` pins
device-side (``zero_shard_dim`` picks the dim here too, so the host slice
IS the ZeRO shard). Leaves no dim of which divides by ``world`` (scalars,
odd shapes) are owned whole by rank 0. The slices serialize into one blob:

    manifest JSON line  \\n  raw little-endian leaf-slice bytes, leaf order

and the blob rides the coordinator wire base64-encoded in ~256 KB chunks
(``shard_put`` — epoch-stamped, ``put_id``-deduped, batched through the
``batch`` frame when the transport supports it). The coordinator's shard
store is memory-resident and deliberately unjournaled: losing the
coordinator loses the plane, and recovery falls back to the blob-store
``Checkpointer`` — the fallback ladder doc/robustness.md describes.
"""

from __future__ import annotations

import base64
import json
import logging
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from edl_tpu.ckpt_plane.placement import publish_placement, replica_group
from edl_tpu.parallel.collective import zero_shard_dim

log = logging.getLogger("edl_tpu.ckpt_plane")

#: wire chunk size BEFORE base64 (the encoded line grows 4/3): large enough
#: to amortize per-op framing, small enough that one chunk never stalls the
#: coordinator's single-threaded event loop noticeably.
CHUNK_BYTES = 256 * 1024

#: owner key prefix in the coordinator shard store; owners are named by the
#: membership rank that wrote them (``z0``, ``z1``, ...), which is exactly
#: the identity the manifest's ``world`` lets a restorer re-enumerate.
OWNER_PREFIX = "z"


def owner_key(rank: int, prefix: str = OWNER_PREFIX) -> str:
    return f"{prefix}{int(rank)}"


def leaf_slice(arr: np.ndarray, rank: int, world: int
               ) -> Tuple[Optional[np.ndarray], Optional[int]]:
    """``rank``'s ZeRO slice of ``arr`` under ``world``, and the sliced dim.

    Mirrors ``zero_shard_spec``'s placement: the largest dim divisible by
    ``world`` is split evenly; when none divides (or world==1) the whole
    leaf belongs to rank 0 and every other rank contributes nothing.
    """
    dim = zero_shard_dim(arr.shape, world)
    if dim is None:
        return (arr if rank == 0 else None), None
    per = arr.shape[dim] // world
    index: List[Any] = [slice(None)] * arr.ndim
    index[dim] = slice(rank * per, (rank + 1) * per)
    return np.ascontiguousarray(arr[tuple(index)]), dim


def serialize_shard(leaves: List[np.ndarray], step: int, rank: int,
                    world: int) -> bytes:
    """One rank's shard blob: manifest line + concatenated slice bytes."""
    metas: List[Dict] = []
    payload: List[bytes] = []
    for arr in leaves:
        arr = np.asarray(arr)
        piece, dim = leaf_slice(arr, rank, world)
        raw = piece.tobytes() if piece is not None else b""
        metas.append({
            "shape": list(arr.shape),
            "dtype": arr.dtype.str,
            "dim": dim,
            "nbytes": len(raw),
        })
        payload.append(raw)
    manifest = {
        "v": 1,
        "step": int(step),
        "rank": int(rank),
        "world": int(world),
        "leaves": metas,
    }
    return json.dumps(manifest).encode() + b"\n" + b"".join(payload)


def parse_shard(blob: bytes) -> Tuple[Dict, bytes]:
    """Split a shard blob back into (manifest, payload bytes)."""
    head, sep, payload = blob.partition(b"\n")
    if not sep:
        raise ValueError("shard blob has no manifest line")
    return json.loads(head.decode()), payload


def chunk_blob(blob: bytes, chunk_bytes: int = CHUNK_BYTES) -> List[str]:
    """Base64-encoded wire chunks (at least one, even for an empty blob)."""
    chunks = [
        base64.b64encode(blob[i:i + chunk_bytes]).decode("ascii")
        for i in range(0, len(blob), chunk_bytes)
    ] or [base64.b64encode(b"").decode("ascii")]
    return chunks


def host_leaves(state: Any) -> Tuple[List[np.ndarray], Any]:
    """Flatten ``state`` to host numpy leaves + its treedef. Works on live
    (device-placed, possibly sharded) pytrees: single-controller arrays are
    fully addressable, so ``device_get`` materializes the global value."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(state)
    return [np.asarray(jax.device_get(x)) for x in leaves], treedef


class CkptPlane:
    """One worker's handle on the memory-resident checkpoint plane.

    Everything here is best-effort by design: replication that fails (the
    coordinator is down, mid-restart, out of memory) logs and returns
    None — the blob-store checkpoint the caller just wrote is the durable
    copy; the plane only makes recovery *faster*, never *possible*.
    """

    def __init__(self, client, replicas: int = 1,
                 owner_prefix: str = OWNER_PREFIX,
                 chunk_bytes: int = CHUNK_BYTES,
                 instruments=None, tracer=None):
        if replicas < 1:
            raise ValueError(f"CkptPlane needs replicas >= 1, got {replicas}")
        # Plane traffic goes to the RAW transport, not an OutboxClient
        # facade: buffering multi-MB shard chunks for outage replay would
        # turn the outbox into a second (worse) checkpoint store.
        self.client = getattr(client, "client", client)
        self.replicas = int(replicas)
        self.owner_prefix = owner_prefix
        self.chunk_bytes = int(chunk_bytes)
        if instruments is None:
            from edl_tpu.obs.instruments import CkptPlaneInstruments

            instruments = CkptPlaneInstruments()
        self.obs = instruments
        self.tracer = tracer
        #: last epoch whose placement map this worker published (the key
        #: ``on_epoch`` invalidates when the epoch moves on).
        self._published_epoch: Optional[int] = None
        #: ranks under an advance-notice revocation: excluded from every
        #: replica ring this plane computes until the drain completes.
        self._revoked: set = set()

    # -- revocation override ---------------------------------------------------

    def set_revoked(self, ranks) -> None:
        """Install the revocation override: ``ranks`` are doomed hosts that
        must not HOLD replicas (they may still own shards — that data is
        what ``evacuate`` copies off). Pass an empty iterable to clear."""
        self._revoked = {int(r) for r in (ranks or ())}

    def evacuate(self, state: Any, step: int, world: int) -> Optional[Dict]:
        """Re-push the revoked ranks' shards under the exclusion override,
        landing their ZeRO slices on surviving hosts specifically — the
        drain step of an advance-notice revocation. No-op (None) when no
        revoked rank is in range."""
        doomed = sorted(r for r in self._revoked if 0 <= r < world)
        if not doomed:
            return None
        return self._replicate_ranks(state, step, doomed, world)

    # -- placement lifecycle ---------------------------------------------------

    def on_epoch(self, epoch: int, world: int, rank: int) -> None:
        """Membership epoch adopted: publish the new placement map and
        invalidate the previous epoch's. Idempotent and best-effort."""
        try:
            publish_placement(self.client, epoch, world, self.replicas,
                              prev_epoch=self._published_epoch,
                              exclude=sorted(self._revoked))
            self._published_epoch = int(epoch)
        except Exception:  # edl: noqa[EDL005] placement publish is advisory metadata; losing it degrades to manifest-derived discovery, never to data loss
            log.debug("ckpt-plane placement publish failed", exc_info=True)

    # -- replication -----------------------------------------------------------

    def replicate(self, state: Any, step: int, rank: int,
                  world: int) -> Optional[Dict]:
        """Push this rank's ZeRO slice of ``state`` at ``step`` to the
        plane (the multi-controller path: each process owns one slice).
        Returns {bytes, chunks, seconds} or None on failure."""
        return self._replicate_ranks(state, step, [rank], world)

    def replicate_all(self, state: Any, step: int,
                      world: int) -> Optional[Dict]:
        """Push EVERY rank's slice from one process — the single-controller
        path (``ElasticWorker``'s mesh is fully addressable, so one host
        gather serves all ``world`` shards). The plane still stores them as
        ``world`` independent owners: recovery and the group-death fallback
        behave identically to the per-process layout."""
        return self._replicate_ranks(state, step, list(range(world)), world)

    def _replicate_ranks(self, state: Any, step: int, ranks: List[int],
                         world: int) -> Optional[Dict]:
        t0 = time.perf_counter()
        t0_wall = time.time()  # spans stitch on the wall clock
        total = 0
        chunk_count = 0
        try:
            leaves, _ = host_leaves(state)
            for rank in ranks:
                blob = serialize_shard(leaves, step, rank, world)
                chunks = chunk_blob(blob, self.chunk_bytes)
                group = [owner_key(h, self.owner_prefix)
                         for h in replica_group(rank, world, self.replicas,
                                                exclude=self._revoked)]
                self._put_chunks(owner_key(rank, self.owner_prefix), step,
                                 chunks, len(blob), group)
                total += len(blob)
                chunk_count += len(chunks)
        except Exception:  # edl: noqa[EDL005] replication is the fast path on top of a durable blob save; any transport/serialization failure must degrade, not propagate
            log.warning("ckpt-plane replicate failed at step %s; blob "
                        "checkpoint remains the restore source", step,
                        exc_info=True)
            return None
        seconds = time.perf_counter() - t0
        self.obs.replicated_bytes.inc(float(total))
        self.obs.replications.inc()
        self.obs.replication_lag.set(seconds)
        if self.tracer is not None:
            self.tracer.record("peer_replicate", t0_wall, time.time(),
                               component="worker", step=int(step),
                               bytes=total, chunks=chunk_count)
        return {"bytes": total, "chunks": chunk_count, "seconds": seconds}

    def _put_chunks(self, owner: str, step: int, chunks: List[str],
                    nbytes: int, group: List[str]) -> None:
        """Wire the chunks, batched through one ``batch`` frame per window
        when the transport supports it (one round trip, positional
        replies), else one ``shard_put`` per chunk."""
        call_batch = getattr(self.client, "call_batch", None)
        total = len(chunks)
        if callable(call_batch):
            window = 8  # keep each batch frame's line well under a few MB
            for base in range(0, total, window):
                ops = []
                for i, data in enumerate(chunks[base:base + window]):
                    chunk = base + i
                    ops.append({
                        "op": "shard_put", "owner": owner, "step": int(step),
                        "chunk": chunk, "chunks": total, "nbytes": int(nbytes),
                        "data": data, "group": group,
                        "put_id": f"{owner}.s{step}.c{chunk}",
                    })
                for sub in call_batch(ops):
                    if not sub.get("ok"):
                        raise RuntimeError(f"shard_put rejected: {sub}")
        else:
            for chunk, data in enumerate(chunks):
                reply = self.client.shard_put(
                    owner, int(step), chunk, total, data,
                    nbytes=int(nbytes), group=group,
                    put_id=f"{owner}.s{step}.c{chunk}",
                )
                if not reply.get("ok"):
                    raise RuntimeError(f"shard_put rejected: {reply}")

    # -- recovery (delegates to ckpt_plane.recovery) ---------------------------

    def restore(self, template: Any, mesh=None, spec_tree=None,
                min_step: Optional[int] = None) -> Optional[Tuple[Any, Dict]]:
        """Assemble the full state from the plane; see ``recovery.peer_restore``."""
        from edl_tpu.ckpt_plane.recovery import peer_restore

        return peer_restore(self.client, template, mesh=mesh,
                            spec_tree=spec_tree, min_step=min_step,
                            owner_prefix=self.owner_prefix,
                            instruments=self.obs, tracer=self.tracer)

    # -- admin / test surface --------------------------------------------------

    def drop_owner(self, rank: int, step: int = -1) -> None:
        """Forget one owner's shard (chaos harness: a replica-group death is
        every member's drop)."""
        self.client.shard_drop(owner_key(rank, self.owner_prefix), step)
