"""Job-spec type system for edl_tpu.

TPU-native re-design of the reference's TrainingJob resource types
(`pkg/resource/training_job.go`, `pkg/apis/paddlepaddle/v1/types.go`): the
schedulable accelerator unit is a TPU slice shape (e.g. ``v5e-4``) instead of an
``nvidia.com/gpu`` count, and the pserver role is gone — its state lives in HBM,
sharded by the mesh; its discovery role moved to the coordinator.
"""

from edl_tpu.api.quantity import (
    Quantity,
    ResourceList,
    parse_quantity,
    format_quantity,
)
from edl_tpu.api.types import (
    JobPhase,
    ReplicaSpec,
    ResourceRequirements,
    ScaleRecord,
    ServingSpec,
    TPUSpec,
    TrainerStatus,
    TrainingJob,
    TrainingJobSpec,
    TrainingJobStatus,
)
from edl_tpu.api.validation import ValidationError, normalize, set_defaults, validate

__all__ = [
    "JobPhase",
    "Quantity",
    "ReplicaSpec",
    "ResourceList",
    "ResourceRequirements",
    "ScaleRecord",
    "TPUSpec",
    "TrainerStatus",
    "TrainingJob",
    "ServingSpec",
    "TrainingJobSpec",
    "TrainingJobStatus",
    "ValidationError",
    "format_quantity",
    "normalize",
    "parse_quantity",
    "set_defaults",
    "validate",
]
