"""Defaulting + validation for TrainingJob specs.

Keeps the reference's defaulting/validation semantics
(`pkg/updater/jobparser.go:40-64`, `pkg/jobparser.go:47-71`): fill default
port/image/passes, force ``fault_tolerant`` when the job is elastic, reject
inverted instance ranges — plus TPU-specific checks (power-of-two-ish slice
shapes, mesh-axis product must divide the chip count).
"""

from __future__ import annotations

from edl_tpu.api.types import TrainingJob

DEFAULT_PORT = 7164
DEFAULT_PASSES = 1


class ValidationError(ValueError):
    pass


def set_defaults(job: TrainingJob) -> TrainingJob:
    """Fill reference-style defaults in place and return the job."""
    spec = job.spec
    if spec.port <= 0:
        spec.port = DEFAULT_PORT
    if spec.passes <= 0:
        spec.passes = DEFAULT_PASSES
    if not spec.trainer.image:
        spec.trainer.image = spec.image
    if not spec.coordinator.image:
        spec.coordinator.image = spec.image
    # Elastic implies fault tolerant (ref: pkg/jobparser.go:56-58) — a job whose
    # trainer count changes mid-flight must tolerate member churn.
    if job.elastic():
        spec.fault_tolerant = True
    spec.coordinator.min_instance = spec.coordinator.max_instance = 1
    if not spec.parallelism:
        spec.parallelism = {"data": max(1, spec.tpu.chips_per_trainer)}
    return job


def validate(job: TrainingJob) -> TrainingJob:
    """Raise ValidationError on a malformed spec; return the job otherwise."""
    spec = job.spec
    if not job.name:
        raise ValidationError("job name is required")
    t = spec.trainer
    if t.min_instance < 1:
        raise ValidationError(f"trainer.min_instance must be >= 1, got {t.min_instance}")
    if t.max_instance < t.min_instance:
        raise ValidationError(
            f"trainer.max_instance ({t.max_instance}) < min_instance ({t.min_instance})"
        )
    if spec.tpu.chips_per_trainer < 0:
        raise ValidationError("tpu.chips_per_trainer must be >= 0")
    if spec.port <= 0 or spec.port > 65535:
        raise ValidationError(f"invalid port {spec.port}")
    if spec.passes < 1:
        raise ValidationError(f"passes must be >= 1, got {spec.passes}")
    if job.elastic() and not spec.fault_tolerant:
        raise ValidationError("elastic jobs must be fault_tolerant (run set_defaults first)")
    # Parallelism sizes are per-trainer-slice local factors (the data axis
    # additionally spans trainers), so their product must divide the slice.
    # CPU-only jobs (chips_per_trainer == 0) map axes onto virtual host
    # devices instead, with no divisibility constraint to enforce here.
    axis_product = 1
    for axis, size in spec.parallelism.items():
        if size < 1:
            raise ValidationError(f"parallelism axis {axis!r} must be >= 1, got {size}")
        axis_product *= size
    local_chips = spec.tpu.chips_per_trainer
    if local_chips > 0 and local_chips % axis_product != 0:
        raise ValidationError(
            f"parallelism axes product {axis_product} must divide "
            f"chips_per_trainer {local_chips}"
        )
    if spec.serving is not None:
        s = spec.serving
        if not s.model_dir:
            raise ValidationError("serving.model_dir is required")
        if not s.buckets or any(b <= 0 for b in s.buckets) \
                or any(a >= b for a, b in zip(s.buckets, s.buckets[1:])):
            raise ValidationError(
                f"serving.buckets must be positive and strictly "
                f"ascending, got {s.buckets}"
            )
        if s.slo_p99_seconds <= 0:
            raise ValidationError("serving.slo_p99_seconds must be > 0")
        if s.max_queue_per_replica <= 0:
            raise ValidationError("serving.max_queue_per_replica must be > 0")
    return job


def normalize(job: TrainingJob) -> TrainingJob:
    """set_defaults + validate, the controller's admission path."""
    return validate(set_defaults(job))
