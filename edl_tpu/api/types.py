"""TrainingJob resource types.

TPU-native redesign of the reference's job schema
(`pkg/resource/training_job.go:61-212`, `pkg/apis/paddlepaddle/v1/types.go:36-173`).
Differences from the reference, by design:

- Roles are ``coordinator`` + ``trainer``. The reference's third role, the
  parameter server (`pkg/resource/training_job.go:84-93`), does not exist on
  TPU: parameters live in HBM sharded by the mesh, and the pserver's
  registration/discovery duties moved into the coordinator.
- Accelerators are TPU slices (``TPUSpec``: accelerator type + chips per
  trainer + mesh topology), not ``nvidia.com/gpu`` counts
  (`pkg/resource/training_job.go:194-207`).
- ``parallelism`` describes the logical mesh axes (data/model/sequence/expert)
  the trainer runtime should build — the reference has only implicit data
  parallelism via trainer count.

Phases and predicates keep reference semantics: ``elastic`` iff
min_instance < max_instance (`pkg/resource/training_job.go:189-191`), elastic
implies fault_tolerant (`pkg/updater/jobparser.go:47-71`), phase machine
None→Creating→Running→Scaling→Succeeded/Failed
(`pkg/apis/paddlepaddle/v1/types.go:95-106`).
"""

from __future__ import annotations

import dataclasses
import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from edl_tpu.api.quantity import ResourceList


class JobPhase(str, enum.Enum):
    """Lifecycle phases (ref: pkg/apis/paddlepaddle/v1/types.go:95-106)."""

    NONE = "None"
    CREATING = "Creating"
    RUNNING = "Running"
    SCALING = "Scaling"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"

    def terminal(self) -> bool:
        return self in (JobPhase.SUCCEEDED, JobPhase.FAILED)


class TrainerStatus(str, enum.Enum):
    """Per-replica states (ref: pkg/apis/paddlepaddle/v1/types.go:141-148)."""

    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"


@dataclass
class ResourceRequirements:
    """Per-replica host resources: requests/limits maps in base units."""

    requests: ResourceList = field(default_factory=ResourceList)
    limits: ResourceList = field(default_factory=ResourceList)

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "ResourceRequirements":
        d = d or {}
        return cls(
            requests=ResourceList.make(d.get("requests")),
            limits=ResourceList.make(d.get("limits")),
        )

    def to_dict(self) -> dict:
        return {"requests": dict(self.requests), "limits": dict(self.limits)}


@dataclass
class TPUSpec:
    """The schedulable accelerator unit: a TPU slice shape per trainer.

    Replaces the reference's GPU-count accounting
    (`pkg/resource/training_job.go:194-207`, `pkg/cluster.go:224-232`). The
    autoscaler treats ``chips_per_trainer`` as the indivisible scheduling
    granule — you can't hand a trainer half a slice.
    """

    accelerator_type: str = "v5e"
    chips_per_trainer: int = 4
    #: logical mesh axis sizes within one trainer's slice, e.g. {"data": 4}.
    topology: Dict[str, int] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "TPUSpec":
        d = d or {}
        return cls(
            accelerator_type=d.get("accelerator_type", "v5e"),
            chips_per_trainer=int(d.get("chips_per_trainer", 4)),
            topology=dict(d.get("topology", {})),
        )

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclass
class ReplicaSpec:
    """One role's replica template (ref: pkg/apis/paddlepaddle/v1/types.go:67-90)."""

    entrypoint: str = ""
    workspace: str = ""
    image: str = ""
    min_instance: int = 1
    max_instance: int = 1
    resources: ResourceRequirements = field(default_factory=ResourceRequirements)
    env: Dict[str, str] = field(default_factory=dict)
    #: name of a PersistentVolumeClaim to mount at ``workspace`` instead of
    #: the default pod-lifetime emptyDir. For the coordinator role this makes
    #: the durable state file (queue/done/KV) survive pod RESCHEDULING, not
    #: just container crashes — the full etcd-sidecar durability story.
    state_pvc: str = ""

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "ReplicaSpec":
        d = d or {}
        return cls(
            entrypoint=d.get("entrypoint", ""),
            workspace=d.get("workspace", ""),
            image=d.get("image", ""),
            min_instance=int(d.get("min_instance", d.get("min-instance", 1))),
            max_instance=int(d.get("max_instance", d.get("max-instance", 1))),
            resources=ResourceRequirements.from_dict(d.get("resources")),
            env=dict(d.get("env", {})),
            state_pvc=d.get("state_pvc", d.get("state-pvc", "")),
        )

    def to_dict(self) -> dict:
        return {
            "entrypoint": self.entrypoint,
            "workspace": self.workspace,
            "image": self.image,
            "min_instance": self.min_instance,
            "max_instance": self.max_instance,
            "resources": self.resources.to_dict(),
            "env": dict(self.env),
            "state_pvc": self.state_pvc,
        }


@dataclass
class ServingSpec:
    """Marks a job as a serving-tier job: its replicas run the
    continuous-batching inference frontend (`edl_tpu.serving`) over the
    artifact at ``model_dir`` instead of a train loop, and the autoscaler
    scales them on scraped `edl_serve_*` p99 latency + queue depth
    instead of cluster utilization."""

    model_dir: str = ""
    buckets: List[int] = field(default_factory=lambda: [1, 8, 32])
    #: grow a replica when the tier p99 breaches this
    slo_p99_seconds: float = 0.25
    #: ... or the mean queue backlog per replica exceeds this
    max_queue_per_replica: float = 8.0

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> Optional["ServingSpec"]:
        if d is None:
            return None
        return cls(
            model_dir=d.get("model_dir", d.get("model-dir", "")),
            buckets=[int(b) for b in d.get("buckets", [1, 8, 32])],
            slo_p99_seconds=float(d.get("slo_p99_seconds", 0.25)),
            max_queue_per_replica=float(d.get("max_queue_per_replica", 8.0)),
        )

    def to_dict(self) -> dict:
        return {
            "model_dir": self.model_dir,
            "buckets": list(self.buckets),
            "slo_p99_seconds": self.slo_p99_seconds,
            "max_queue_per_replica": self.max_queue_per_replica,
        }


@dataclass
class TrainingJobSpec:
    """Job spec (ref: pkg/resource/training_job.go:61-106).

    ``parallelism`` names the logical mesh axes the runtime builds with
    ``edl_tpu.parallel``; sizes are per-trainer-slice local factors — the data
    axis additionally spans trainers.
    """

    image: str = ""
    port: int = 7164
    fault_tolerant: bool = False
    passes: int = 1
    tpu: TPUSpec = field(default_factory=TPUSpec)
    trainer: ReplicaSpec = field(default_factory=ReplicaSpec)
    coordinator: ReplicaSpec = field(default_factory=lambda: ReplicaSpec(min_instance=1, max_instance=1))
    parallelism: Dict[str, int] = field(default_factory=dict)
    #: dataset shard descriptors fed to the coordinator's task queue.
    data_shards: List[str] = field(default_factory=list)
    #: steps between async checkpoints (also taken on rescale signals).
    checkpoint_interval: int = 1000
    checkpoint_dir: str = ""
    #: per-job coordinator secret (EDL_COORD_TOKEN): the updater generates
    #: one at admission when empty, and every pod of the job gets it via
    #: make_env — so the 0.0.0.0-bound coordinator rejects other jobs'
    #: (or strangers') pods. Stored in the spec, the in-tree stand-in for
    #: projecting a K8s Secret; the reference's etcd sidecar had no auth
    #: at all (pkg/jobparser.go:167-184).
    auth_token: str = ""
    #: non-None marks a serving-tier job (see :class:`ServingSpec`)
    serving: Optional["ServingSpec"] = None

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "TrainingJobSpec":
        d = d or {}
        return cls(
            image=d.get("image", ""),
            port=int(d.get("port", 7164)),
            fault_tolerant=bool(d.get("fault_tolerant", False)),
            passes=int(d.get("passes", 1)),
            tpu=TPUSpec.from_dict(d.get("tpu")),
            trainer=ReplicaSpec.from_dict(d.get("trainer")),
            coordinator=ReplicaSpec.from_dict(d.get("coordinator")),
            parallelism={k: int(v) for k, v in (d.get("parallelism") or {}).items()},
            data_shards=list(d.get("data_shards", [])),
            checkpoint_interval=int(d.get("checkpoint_interval", 1000)),
            checkpoint_dir=d.get("checkpoint_dir", ""),
            auth_token=d.get("auth_token", ""),
            serving=ServingSpec.from_dict(d.get("serving")),
        )

    def to_dict(self) -> dict:
        out = {
            "image": self.image,
            "port": self.port,
            "fault_tolerant": self.fault_tolerant,
            "passes": self.passes,
            "tpu": self.tpu.to_dict(),
            "trainer": self.trainer.to_dict(),
            "coordinator": self.coordinator.to_dict(),
            "parallelism": dict(self.parallelism),
            "data_shards": list(self.data_shards),
            "checkpoint_interval": self.checkpoint_interval,
            "checkpoint_dir": self.checkpoint_dir,
            "auth_token": self.auth_token,
        }
        if self.serving is not None:
            out["serving"] = self.serving.to_dict()
        return out


@dataclass
class ScaleRecord:
    """One autoscaler decision, kept in status for observability."""

    timestamp: float
    from_replicas: int
    to_replicas: int
    reason: str = ""


@dataclass
class TrainingJobStatus:
    """Job status (ref: pkg/apis/paddlepaddle/v1/types.go:151-162)."""

    phase: JobPhase = JobPhase.NONE
    reason: str = ""
    #: current actuated trainer replica count (the scale target).
    parallelism: int = 0
    replica_statuses: Dict[str, TrainerStatus] = field(default_factory=dict)
    scale_history: List[ScaleRecord] = field(default_factory=list)


@dataclass
class TrainingJob:
    """A named job: metadata + spec + status (ref: training_job.go:109-131)."""

    name: str
    namespace: str = "default"
    spec: TrainingJobSpec = field(default_factory=TrainingJobSpec)
    status: TrainingJobStatus = field(default_factory=TrainingJobStatus)
    labels: Dict[str, str] = field(default_factory=dict)
    #: server-assigned object identity (K8s metadata.uid). Distinguishes two
    #: runs of a same-named job — stamped into pods as EDL_RUN_ID so the
    #: coordinator never resumes a previous run's state file.
    uid: str = ""

    # -- predicates (ref: pkg/resource/training_job.go:189-207) ---------------

    def elastic(self) -> bool:
        """Elastic iff the trainer instance range is a real range."""
        return self.spec.trainer.min_instance < self.spec.trainer.max_instance

    def serving(self) -> bool:
        """True for serving-tier jobs: replicas run the inference frontend
        and scale on SLO signals, not cluster utilization."""
        return self.spec.serving is not None

    def need_tpu(self) -> bool:
        return self.spec.tpu.chips_per_trainer > 0

    # -- resource math for the scheduler --------------------------------------

    def trainer_request(self) -> ResourceList:
        """Per-trainer resource demand, incl. the TPU slice granule."""
        req = self.spec.trainer.resources.requests.copy()
        if self.need_tpu():
            req["tpu"] = float(self.spec.tpu.chips_per_trainer)
        return req

    def trainer_limit(self) -> ResourceList:
        lim = self.spec.trainer.resources.limits.copy()
        if self.need_tpu():
            lim["tpu"] = float(self.spec.tpu.chips_per_trainer)
        return lim

    @classmethod
    def from_dict(cls, d: dict) -> "TrainingJob":
        meta = d.get("metadata", {})
        job = cls(
            name=meta.get("name", d.get("name", "")),
            namespace=meta.get("namespace", d.get("namespace", "default")),
            spec=TrainingJobSpec.from_dict(d.get("spec")),
            labels=dict(meta.get("labels", {})),
            uid=meta.get("uid", ""),
        )
        st = d.get("status")
        if st:
            job.status = TrainingJobStatus(
                phase=JobPhase(st.get("phase", "None")),
                reason=st.get("reason", ""),
                parallelism=int(st.get("parallelism", 0)),
                replica_statuses={
                    k: TrainerStatus(v) for k, v in st.get("replica_statuses", {}).items()
                },
                scale_history=[ScaleRecord(**r) for r in st.get("scale_history", [])],
            )
        return job

    def to_dict(self) -> dict:
        meta = {
            "name": self.name,
            "namespace": self.namespace,
            "labels": dict(self.labels),
        }
        if self.uid:
            meta["uid"] = self.uid
        return {
            "metadata": meta,
            "spec": self.spec.to_dict(),
            "status": {
                "phase": self.status.phase.value,
                "reason": self.status.reason,
                "parallelism": self.status.parallelism,
                "replica_statuses": {
                    k: v.value for k, v in self.status.replica_statuses.items()
                },
                "scale_history": [dataclasses.asdict(r) for r in self.status.scale_history],
            },
        }

    @classmethod
    def from_yaml(cls, text: str) -> "TrainingJob":
        import yaml

        return cls.from_dict(yaml.safe_load(text))
