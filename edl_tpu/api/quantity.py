"""Kubernetes-style resource quantities.

Equivalent of the reference's quantity handling (`pkg/utils.go:23-34`
``AddResourceList`` plus the implicit k8s ``resource.Quantity`` parsing it leans
on): parse "500m" CPUs, "30Gi" memory, integer TPU-chip counts, and accumulate
per-resource totals across pods/jobs.

We normalize every quantity to a float in base units (CPUs in cores, memory in
bytes, chips in chips) so the autoscaler's arithmetic stays simple.
"""

from __future__ import annotations

import re
from typing import Dict, Mapping

Quantity = float

_BINARY_SUFFIX = {
    "Ki": 1024.0,
    "Mi": 1024.0**2,
    "Gi": 1024.0**3,
    "Ti": 1024.0**4,
    "Pi": 1024.0**5,
    "Ei": 1024.0**6,
}
_DECIMAL_SUFFIX = {
    "n": 1e-9,
    "u": 1e-6,
    "m": 1e-3,
    "": 1.0,
    "k": 1e3,
    "K": 1e3,
    "M": 1e6,
    "G": 1e9,
    "T": 1e12,
    "P": 1e15,
    "E": 1e18,
}

_QTY_RE = re.compile(r"^\s*([+-]?[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?)\s*([A-Za-z]*)\s*$")


def parse_quantity(value) -> Quantity:
    """Parse a k8s-style quantity ("500m", "30Gi", 4, "2.5") to base units."""
    if isinstance(value, (int, float)):
        return float(value)
    if not isinstance(value, str):
        raise TypeError(f"cannot parse quantity from {type(value).__name__}")
    m = _QTY_RE.match(value)
    if not m:
        raise ValueError(f"invalid quantity: {value!r}")
    number, suffix = m.groups()
    if suffix in _BINARY_SUFFIX:
        return float(number) * _BINARY_SUFFIX[suffix]
    if suffix in _DECIMAL_SUFFIX:
        return float(number) * _DECIMAL_SUFFIX[suffix]
    raise ValueError(f"unknown quantity suffix {suffix!r} in {value!r}")


def format_quantity(value: Quantity) -> str:
    """Render a base-unit quantity compactly (inverse of parse, best effort)."""
    if value == int(value):
        v = int(value)
        for suffix, mult in reversed(list(_BINARY_SUFFIX.items())):
            if v and v % int(mult) == 0 and v >= int(mult):
                return f"{v // int(mult)}{suffix}"
        return str(v)
    if abs(value) < 1.0 and round(value * 1000) == value * 1000:
        return f"{int(round(value * 1000))}m"
    return repr(value)


class ResourceList(Dict[str, Quantity]):
    """Named resource totals: {"cpu": cores, "memory": bytes, "tpu": chips}.

    Mirrors ``AddResourceList`` (`pkg/utils.go:23-34`): addition accumulates
    per-key; missing keys are zero.
    """

    @classmethod
    def make(cls, spec: Mapping[str, object] | None) -> "ResourceList":
        out = cls()
        for key, val in (spec or {}).items():
            out[key] = parse_quantity(val)
        return out

    def get_q(self, key: str) -> Quantity:
        return self.get(key, 0.0)

    def add(self, other: Mapping[str, Quantity]) -> "ResourceList":
        for key, val in other.items():
            self[key] = self.get(key, 0.0) + val
        return self

    def sub(self, other: Mapping[str, Quantity]) -> "ResourceList":
        for key, val in other.items():
            self[key] = self.get(key, 0.0) - val
        return self

    def scaled(self, factor: float) -> "ResourceList":
        return ResourceList({k: v * factor for k, v in self.items()})

    def fits_within(self, capacity: Mapping[str, Quantity]) -> bool:
        """True if every requested resource is available in ``capacity``."""
        return all(capacity.get(k, 0.0) >= v for k, v in self.items() if v > 0)

    def copy(self) -> "ResourceList":
        return ResourceList(self)
