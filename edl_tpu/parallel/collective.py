"""Explicit data-plane collectives: ZeRO-1 shard placement, gradient
buckets, and the closed-form bytes-on-wire cost model.

The implicit data plane (seed behavior) leaves gradient exchange entirely
to XLA: the loss's ``pmean`` over the batch axis becomes a full-gradient
all-reduce, and the ZeRO-1 moment sharding drags an all-gather of the
updated params behind it. That program moves ``3·P·(N−1)/N`` bytes per
chip per step (all-reduce 2P + all-gather P). The explicit plane this
module supports restructures the step as

    reduce-scatter(grads) → sharded optimizer update → all-gather(params)

which moves ``2·P·(N−1)/N`` — the all-reduce's reduce phase is fused with
the shard the optimizer actually needs, so the gather half of the
all-reduce is never paid. On a hierarchical ``("dcn", "data")`` mesh the
same structure keeps the cross-slice hop at shard size (``P/k`` over DCN
instead of P). `collective_bytes` is the closed form for all of it,
validated leaf-by-leaf in tests and committed per-arm by
``bench_collective.py`` — the honest-accounting convention of
``bench_pipeline.py`` applied to the data plane.

Nothing here opens a channel or calls a collective directly: the
"issuance" primitive inside jit-SPMD is `jax.lax.with_sharding_constraint`
— pinning a gradient to its ZeRO shard layout is what makes the
partitioner lower the cross-batch-axis reduction as reduce-scatter
instead of all-reduce. Buckets group those constraints so the async
collective scheduler has bounded-size transfers to overlap with the
backward pass of the next microbatch (`Trainer` grad-accumulation mode).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from edl_tpu.parallel.sharding import BatchAxis, batch_shardings, present_axes

__all__ = [
    "GradBucket",
    "assign_buckets",
    "collective_bytes",
    "constrain_to_specs",
    "estimate_collective_seconds",
    "ring_bytes",
    "split_microbatches",
    "zero1_step_bytes",
    "zero_shard_dim",
    "zero_shard_spec",
]

#: default per-chip interconnect bandwidths (bytes/sec) for the
#: ``collective_ms`` estimate series. TPU-v4-generation ballpark: ~1e11 B/s
#: of ICI bandwidth per chip, ~2.5e10 B/s per chip across the data-center
#: network. Estimates, not measurements — override via
#: ``estimate_collective_seconds(..., ici_bps=, dcn_bps=)`` (the profiler
#: series exists to expose the bytes-vs-time structure, not to predict a
#: specific fabric).
ICI_BYTES_PER_SEC = 1.0e11
DCN_BYTES_PER_SEC = 2.5e10


# -- ZeRO shard placement ------------------------------------------------------


def zero_shard_dim(shape: Sequence[int], n: int) -> Optional[int]:
    """The dim a ZeRO-1 shard splits: the LARGEST dim divisible by ``n``
    (ties broken toward dim 0). Largest-first keeps the per-chip shards
    contiguous runs of the biggest axis — balanced and DMA-friendly —
    where first-divisible would happily split a size-8 leading dim of a
    (8, 4096) tensor into 1-row slivers. None when nothing divides (the
    leaf stays replicated) or there is nothing to split (n <= 1)."""
    if n <= 1:
        return None
    best: Optional[int] = None
    for dim, size in enumerate(shape):
        if size > 0 and size % n == 0:
            if best is None or size > shape[best]:
                best = dim
    return best


def zero_shard_spec(
    shape: Sequence[int], mesh: Mesh, axis: BatchAxis
) -> Optional[P]:
    """PartitionSpec placing a replicated leaf's ZeRO-1 shard over the
    batch axis (or axis hierarchy): ``zero_shard_dim`` carries the present
    axes, every other dim replicated. None when the mesh has no batch axis
    or no dim divides."""
    axes = present_axes(mesh, axis)
    if not axes:
        return None
    n = 1
    for a in axes:
        n *= mesh.shape[a]
    dim = zero_shard_dim(shape, n)
    if dim is None:
        return None
    spec: List[Any] = [None] * len(shape)
    spec[dim] = axes if len(axes) > 1 else axes[0]
    return P(*spec)


def constrain_to_specs(tree: Any, specs: Any, mesh: Mesh) -> Any:
    """Apply `with_sharding_constraint` per leaf; a ``None`` spec leaves
    that leaf unconstrained (non-ZeRO leaves keep whatever layout the
    partitioner chose). ``specs`` mirrors ``tree`` with Optional[P] leaves."""
    return jax.tree_util.tree_map(
        lambda x, s: x if s is None else jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, s)
        ),
        tree,
        specs,
        is_leaf=lambda x: x is None or isinstance(x, P),
    )


# -- gradient buckets ----------------------------------------------------------


@dataclass(frozen=True)
class GradBucket:
    """A contiguous group of gradient leaves reduced as one unit.

    ``indices`` are flat-leaf positions in ``tree_leaves`` order; ``nbytes``
    is the group's full (unsharded) gradient payload. Buckets bound the
    size of each issued reduction so the first reductions can start before
    the whole backward finishes — the DDP/ZeRO overlap granularity.
    """

    indices: Tuple[int, ...]
    nbytes: int


def assign_buckets(
    leaf_nbytes: Sequence[int], bucket_bytes: int
) -> List[GradBucket]:
    """Greedy contiguous packing of gradient leaves into ~``bucket_bytes``
    buckets, walking leaves in REVERSE traversal order — backward produces
    the LAST parameters' gradients first, so reverse packing lets the
    first-completed bucket be the first reduction issued. A leaf larger
    than ``bucket_bytes`` gets a bucket of its own (never split: the
    reduction unit is a whole leaf). Returned in issue order (reverse of
    tree order); every leaf appears in exactly one bucket."""
    if bucket_bytes <= 0:
        raise ValueError(f"bucket_bytes must be > 0, got {bucket_bytes}")
    buckets: List[GradBucket] = []
    pending: List[int] = []
    pending_bytes = 0
    for idx in reversed(range(len(leaf_nbytes))):
        nb = int(leaf_nbytes[idx])
        if pending and pending_bytes + nb > bucket_bytes:
            buckets.append(GradBucket(tuple(pending), pending_bytes))
            pending, pending_bytes = [], 0
        pending.append(idx)
        pending_bytes += nb
    if pending:
        buckets.append(GradBucket(tuple(pending), pending_bytes))
    return buckets


# -- closed-form bytes on wire -------------------------------------------------


def ring_bytes(nbytes: float, n: int, op: str) -> float:
    """Per-chip bytes-on-wire of one ring collective over ``n`` chips on a
    buffer whose FULL (unsharded) size is ``nbytes``:

    - ``reduce_scatter`` / ``all_gather``: each chip sends (n−1) shards of
      nbytes/n — ``nbytes·(n−1)/n``.
    - ``all_reduce``: reduce-scatter + all-gather back to back —
      ``2·nbytes·(n−1)/n``.

    These are the bandwidth-optimal algorithm counts (ring or equivalently
    bidirectional torus per-link totals); latency terms are out of scope.
    """
    if n <= 1:
        return 0.0
    frac = (n - 1) / n
    if op == "all_reduce":
        return 2.0 * nbytes * frac
    if op in ("reduce_scatter", "all_gather"):
        return nbytes * frac
    raise ValueError(f"unknown collective op {op!r}")


def collective_bytes(
    nbytes: float, tiers: Sequence[Tuple[str, int]], op: str
) -> Dict[str, float]:
    """Per-chip bytes-on-wire of a (possibly hierarchical) collective.

    ``tiers`` lists (name, size) outermost → innermost, matching the mesh
    axis tuple — e.g. ``[("dcn", 2), ("data", 4)]``. A single tier is the
    flat ring (`ring_bytes`). With multiple tiers the standard hierarchical
    lowering is priced, innermost (fastest fabric) first:

    - ``all_reduce``: inner reduce-scatter at full size, outer all-reduce
      on the 1/k shard, inner all-gather — the intra-slice RS / inter-slice
      AR / intra-slice AG structure XLA emits for a psum over
      ``("dcn", "data")``.
    - ``reduce_scatter``: inner RS at full size, then outer RS on the
      shard — each tier only ever moves the data that still needs crossing
      it.
    - ``all_gather``: the exact reverse — outer AG assembles the
      slice-level shard, inner AG replicates it.

    Returns {tier name: bytes, "total": bytes}. Degenerate tiers (size 1)
    contribute 0. The recursion peels the innermost tier, so >2 tiers work,
    though nothing in the codebase builds them today.
    """
    tiers = [(name, int(size)) for name, size in tiers]
    out: Dict[str, float] = {name: 0.0 for name, _ in tiers}
    if op not in ("all_reduce", "reduce_scatter", "all_gather"):
        raise ValueError(f"unknown collective op {op!r}")

    def _recurse(nbytes: float, tiers: Sequence[Tuple[str, int]], op: str):
        if not tiers:
            return
        if len(tiers) == 1:
            name, n = tiers[0]
            out[name] += ring_bytes(nbytes, n, op)
            return
        outer, (inner_name, k) = tiers[:-1], tiers[-1]
        if op == "all_reduce":
            out[inner_name] += ring_bytes(nbytes, k, "reduce_scatter")
            _recurse(nbytes / max(k, 1), outer, "all_reduce")
            out[inner_name] += ring_bytes(nbytes, k, "all_gather")
        elif op == "reduce_scatter":
            out[inner_name] += ring_bytes(nbytes, k, "reduce_scatter")
            _recurse(nbytes / max(k, 1), outer, "reduce_scatter")
        else:  # all_gather: outer assembles shard, inner replicates
            _recurse(nbytes / max(k, 1), outer, "all_gather")
            out[inner_name] += ring_bytes(nbytes, k, "all_gather")

    _recurse(float(nbytes), tiers, op)
    out["total"] = sum(out[name] for name, _ in tiers)
    return out


def zero1_step_bytes(
    sharded_bytes: float,
    replicated_bytes: float,
    tiers: Sequence[Tuple[str, int]],
    grad_sync: str,
) -> Dict[str, float]:
    """Analytic per-chip bytes-on-wire of ONE train step's data-plane
    collectives under ZeRO-1 moment sharding.

    ``sharded_bytes`` — total gradient/param bytes of the leaves that carry
    a ZeRO shard layout (a divisible dim exists); ``replicated_bytes`` —
    leaves that stay replicated (their gradient is all-reduced either way).

    - ``psum`` (implicit): all_reduce(all grads) + all_gather(sharded
      params) — the gather is the price of the moment sharding: each chip
      only computes its shard of the update, the full params must
      reassemble.
    - ``reduce_scatter`` (explicit): reduce_scatter(sharded grads) +
      all_reduce(replicated grads) + all_gather(sharded params). The
      sharded fraction's sync drops from 3 units to 2.

    Returns per-tier bytes plus {"grad_bytes", "param_bytes", "total"}.
    The strict inequality RS < psum (whenever sharded_bytes > 0 and some
    tier has size > 1) is the acceptance invariant BENCH_COLLECTIVE.json
    commits and tests assert.
    """
    if grad_sync not in ("psum", "reduce_scatter"):
        raise ValueError(f"unknown grad_sync {grad_sync!r}")
    per_tier: Dict[str, float] = {name: 0.0 for name, _ in tiers}

    def _add(acct: Dict[str, float]) -> float:
        for name, _ in tiers:
            per_tier[name] += acct[name]
        return acct["total"]

    grad = _add(collective_bytes(replicated_bytes, tiers, "all_reduce"))
    if grad_sync == "psum":
        grad += _add(collective_bytes(sharded_bytes, tiers, "all_reduce"))
    else:
        grad += _add(collective_bytes(sharded_bytes, tiers, "reduce_scatter"))
    param = _add(collective_bytes(sharded_bytes, tiers, "all_gather"))
    return {
        **per_tier,
        "grad_bytes": grad,
        "param_bytes": param,
        "total": grad + param,
    }


def estimate_collective_seconds(
    per_tier_bytes: Dict[str, float],
    ici_bps: float = ICI_BYTES_PER_SEC,
    dcn_bps: float = DCN_BYTES_PER_SEC,
) -> float:
    """Bandwidth-model time estimate for per-tier byte counts: the ``dcn``
    tier moves at DCN speed, every other tier at ICI speed, tiers summed
    (hierarchical phases are sequential). An ESTIMATE for observability
    (the profiler's ``collective_ms`` series), not a measurement."""
    seconds = 0.0
    for name, nbytes in per_tier_bytes.items():
        if name in ("total", "grad_bytes", "param_bytes"):
            continue
        seconds += nbytes / (dcn_bps if name == "dcn" else ici_bps)
    return seconds


# -- microbatch split (gradient accumulation) ----------------------------------


def split_microbatches(
    batch: Dict[str, jax.Array],
    n_micro: int,
    mesh: Mesh,
    axis: BatchAxis,
    specs: Optional[Any] = None,
) -> Dict[str, jax.Array]:
    """Reshape every batch leaf (B, ...) → (n_micro, B/n_micro, ...) for a
    `lax.scan` over microbatches, pushing each leaf's batch sharding from
    dim 0 to dim 1 (the microbatch dim is the scan carrier and must be
    replicated). With ``specs`` (the model's `batch_spec` pytree) each
    leaf's own layout shifts right; without, the default leading-dim batch
    sharding does. Requires every leaf's dim 0 divisible by ``n_micro``.

    Which samples land in which microbatch is a partition choice with no
    effect on the ACCUMULATED gradient — every sample appears exactly once
    and the final gradient is the mean over all of them (reassociated
    floating-point, same as any reduction-order change).
    """
    if n_micro <= 1:
        raise ValueError(f"n_micro must be > 1, got {n_micro}")
    shardings = batch_shardings(mesh, axis, specs)
    per_leaf = not isinstance(shardings, jax.sharding.Sharding)

    def _split(x: jax.Array, sharding) -> jax.Array:
        b = x.shape[0]
        if b % n_micro:
            raise ValueError(
                f"batch dim {b} not divisible by microbatches {n_micro}"
            )
        y = x.reshape((n_micro, b // n_micro) + tuple(x.shape[1:]))
        spec = sharding.spec if hasattr(sharding, "spec") else P()
        return jax.lax.with_sharding_constraint(
            y, NamedSharding(mesh, P(None, *spec))
        )

    if per_leaf:
        return jax.tree_util.tree_map(_split, dict(batch), shardings)
    return jax.tree_util.tree_map(lambda x: _split(x, shardings), dict(batch))
