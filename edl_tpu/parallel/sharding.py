"""Sharding helpers: NamedSharding construction and host->mesh data placement."""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

#: a batch axis is one mesh axis or a hierarchy of them (e.g.
#: ("dcn", "data") for multi-slice data parallelism — see parallel.mesh)
BatchAxis = Union[str, Sequence[str]]


def present_axes(mesh: Mesh, axis: BatchAxis) -> Tuple[str, ...]:
    """The subset of ``axis`` (str or sequence) present on ``mesh``."""
    names = (axis,) if isinstance(axis, str) else tuple(axis)
    return tuple(a for a in names if a in mesh.axis_names)


def axis_size(mesh: Mesh, axis: BatchAxis) -> int:
    """Product of the present axes' sizes (1 when none present)."""
    n = 1
    for a in present_axes(mesh, axis):
        n *= mesh.shape[a]
    return n


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def replicate(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, axis: BatchAxis = "data") -> NamedSharding:
    """Leading-dim batch sharding over the data axis or axis hierarchy
    (absent axes drop out; none present: replicate)."""
    have = present_axes(mesh, axis)
    if have:
        # a single axis stays a bare name: P(("data",)) and P("data") shard
        # identically but compare unequal, and the normalized form is what
        # every other spec in the codebase (and tests) uses
        return NamedSharding(mesh, P(have if len(have) > 1 else have[0]))
    return replicate(mesh)


def batch_shardings(
    mesh: Mesh,
    axis: BatchAxis = "data",
    specs: Optional[Any] = None,
):
    """Sharding(s) for placing a host batch on ``mesh``.

    With ``specs`` (a PartitionSpec pytree from `Model.batch_spec`): a
    NamedSharding pytree matching the batch structure. Without: ONE
    leading-dim batch sharding shared by every leaf. Hoisted out of the
    placement tree_maps so NamedSharding construction happens once per
    batch, not once per leaf — and reused by the AOT warm-compile path
    (`Trainer.warm_compile`) to derive placed-batch avals without placing
    anything.
    """
    if specs is not None:
        return jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), specs,
            is_leaf=lambda x: isinstance(x, P),
        )
    return batch_sharding(mesh, axis)


def shard_batch(
    batch: Any,
    mesh: Mesh,
    axis: BatchAxis = "data",
    specs: Optional[Any] = None,
):
    """Place a host-side batch pytree onto the mesh.

    Default: every array sharded on dim 0 over ``axis``. With ``specs`` (a
    PartitionSpec pytree matching ``batch``, from `Model.batch_spec`), each
    array gets its own layout — e.g. transformer tokens (B, S) over data x seq.

    The per-trainer data path: each trainer produces its local slice of the
    global batch (from its leased data shards); `jax.device_put` with a
    NamedSharding makes the global array. Replaces the reference's
    per-trainer file-shard reader (`example/fit_a_line/fluid/common.py:24-40`,
    `idx % trainers == trainer_id`).

    Multi-process (`jax.distributed` initialized): each process passes its
    LOCAL slice and `jax.make_array_from_process_local_data` assembles the
    global array — no host ever holds the full batch.
    """
    shardings = batch_shardings(mesh, axis, specs)
    per_leaf = not isinstance(shardings, jax.sharding.Sharding)
    if jax.process_count() > 1:
        # Shardings are built once above and leaves convert to numpy in one
        # pass here — mirroring the single-process batched dispatch below
        # instead of rebuilding a NamedSharding and re-converting inside the
        # assembly tree_map for every leaf of every step's batch.
        host_batch = jax.tree_util.tree_map(lambda a: np.asarray(a), batch)
        if per_leaf:
            return jax.tree_util.tree_map(
                lambda a, s: jax.make_array_from_process_local_data(s, a),
                host_batch,
                shardings,
            )
        return jax.tree_util.tree_map(
            lambda a: jax.make_array_from_process_local_data(shardings, a),
            host_batch,
        )

    # Single process: ONE device_put over the whole tree — a single batched
    # dispatch instead of one call per key. Device arrays pass through
    # (device_put reshards them); everything else becomes host numpy so the
    # transfer goes STRAIGHT to the target sharding — jnp.asarray here would
    # bounce through the default device first (an extra hop on the
    # PCIe-bound input path).
    host_batch = jax.tree_util.tree_map(
        lambda a: a if isinstance(a, jax.Array) else np.asarray(a), batch
    )
    return jax.device_put(host_batch, shardings)


def global_batch_size(local_batch: int, mesh: Mesh, axis: BatchAxis = "data") -> int:
    return local_batch * axis_size(mesh, axis)
