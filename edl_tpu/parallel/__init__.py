"""Parallelism layer: device meshes, sharding rules, sharded embeddings.

This is the TPU-native replacement for the reference's entire parameter-server
data plane (`paddle pserver` C++, sparse port pools `pkg/jobparser.go:232-247`,
`DistributeTranspiler` graph rewriting `example/ctr/ctr/train.py:211-212`):

- Dense parameters are replicated or sharded over a `jax.sharding.Mesh`;
  gradient exchange is an ICI all-reduce XLA inserts under `jit` — no
  gradient-server RPC protocol exists.
- The sparse-pserver path (the reference's proto-expert-parallelism for
  1e6-row CTR embedding tables) becomes a row-sharded embedding living in HBM
  across the mesh, with lookups/updates done via `shard_map` + collectives
  (`ShardedEmbedding`).
- "Transpiling" a single-device program into a distributed one is replaced by
  sharding annotations: same train step, any mesh.
"""

from edl_tpu.parallel.collective import (
    assign_buckets,
    collective_bytes,
    ring_bytes,
    zero1_step_bytes,
    zero_shard_spec,
)
from edl_tpu.parallel.mesh import (
    MeshSpec, build_hierarchical_mesh, build_mesh, local_mesh,
)
from edl_tpu.parallel.sharding import (
    batch_sharding,
    named_sharding,
    replicate,
    shard_batch,
)
from edl_tpu.parallel.embedding import ShardedEmbedding
from edl_tpu.parallel.pipeline import pipeline_apply
from edl_tpu.parallel.planner import (
    ModelProfile, Plan, Topology, data_only_plan, plan_layout,
)
from edl_tpu.parallel.ring_attention import dense_attention, ring_attention

__all__ = [
    "MeshSpec",
    "ModelProfile",
    "Plan",
    "ShardedEmbedding",
    "Topology",
    "assign_buckets",
    "batch_sharding",
    "build_hierarchical_mesh",
    "build_mesh",
    "collective_bytes",
    "data_only_plan",
    "dense_attention",
    "local_mesh",
    "named_sharding",
    "pipeline_apply",
    "plan_layout",
    "replicate",
    "ring_attention",
    "ring_bytes",
    "shard_batch",
    "zero1_step_bytes",
    "zero_shard_spec",
]
