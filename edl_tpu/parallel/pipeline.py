"""Pipeline parallelism: GPipe-style microbatch schedule over a ``pipe`` axis.

The reference has no pipeline parallelism (SURVEY §2.3) — its distribution is
data-parallel PS only — but a TPU framework schedules models too big for one
chip's HBM, so stages are first-class here. Design:

- Stage parameters are a pytree whose LEADING dim is the stage index, sharded
  over the ``pipe`` mesh axis: each device holds one stage's weights (for a
  transformer, its contiguous chunk of layers).
- The schedule is the classic (microbatches + stages - 1)-tick loop: at tick
  ``t`` stage ``r`` processes microbatch ``t - r``; activations hop one ICI
  neighbor per tick via `jax.lax.ppermute`. Warmup/drain bubble ticks compute
  on garbage that is masked out of the output and carries zero cotangent, so
  the whole schedule is differentiable through `jax.lax.scan`.
- Stage outputs must have the stage-input shape (the standard homogeneous-
  stage restriction; residual-stream models satisfy it by construction).

`_pipeline_local` is the inside-a-shard_map form (composable with tensor and
sequence parallelism — the transformer calls it with ring attention inside the
stage function); `pipeline_apply` wraps it for standalone use.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def _pipeline_local(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    x: jax.Array,
    *,
    pipe_axis: str,
    n_stages: int,
    microbatches: int,
) -> jax.Array:
    """Run the pipeline schedule on local shards — call inside a shard_map
    whose manual axes include ``pipe_axis``.

    ``stage_params`` is THIS device's stage slice (leading stage dim already
    consumed by the enclosing in_spec). ``x``: (B_local, ...) activations; the
    full batch enters at stage 0 and the result is psum-broadcast to all
    stages so downstream (loss) code stays SPMD-uniform.
    """
    if n_stages == 1:
        return stage_fn(stage_params, x)
    M = microbatches
    B = x.shape[0]
    if B % M:
        raise ValueError(f"local batch {B} must be divisible by microbatches {M}")
    mb = x.reshape((M, B // M) + x.shape[1:])
    idx = jax.lax.axis_index(pipe_axis)
    fwd = [(i, i + 1) for i in range(n_stages - 1)]  # stage r -> r+1, no wrap

    def tick(carry, t):
        state, outs = carry
        # Stage 0 feeds microbatch t (clipped re-feeds during drain are
        # masked garbage); later stages consume the hop received last tick.
        inp = jnp.where(idx == 0, mb[jnp.clip(t, 0, M - 1)], state)
        y = stage_fn(stage_params, inp)
        opos = jnp.clip(t - (n_stages - 1), 0, M - 1)
        write = (idx == n_stages - 1) & (t >= n_stages - 1)
        prev = jax.lax.dynamic_index_in_dim(outs, opos, 0, keepdims=False)
        outs = jax.lax.dynamic_update_index_in_dim(
            outs, jnp.where(write, y, prev), opos, 0
        )
        state = jax.lax.ppermute(y, pipe_axis, fwd)
        return (state, outs), None

    state0 = jnp.zeros_like(mb[0])
    outs0 = jnp.zeros_like(mb)
    (_, outs), _ = jax.lax.scan(
        tick, (state0, outs0), jnp.arange(M + n_stages - 1)
    )
    # Only the last stage wrote real outputs (zeros elsewhere): broadcast.
    outs = jax.lax.psum(jnp.where(idx == n_stages - 1, outs, 0), pipe_axis)
    return outs.reshape((B,) + x.shape[1:])


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    x: jax.Array,
    mesh: Mesh,
    *,
    pipe_axis: str = "pipe",
    batch_axis: str = "data",
    microbatches: Optional[int] = None,
) -> jax.Array:
    """Standalone pipeline over ``mesh``. ``stage_params`` leaves have a
    leading stage dim == pipe axis size; ``x`` (B, ...) is batch-sharded over
    ``batch_axis``. ``microbatches`` defaults to the stage count (bubble
    fraction (n-1)/(M+n-1); raise it to shrink the bubble)."""
    if pipe_axis not in mesh.axis_names or mesh.shape[pipe_axis] == 1:
        # No pipe axis on this mesh (e.g. after an elastic rescale dropped
        # it): run every stage sequentially on each device.
        n_stages = jax.tree_util.tree_leaves(stage_params)[0].shape[0]
        out = x
        for i in range(n_stages):
            one = jax.tree_util.tree_map(lambda a, i=i: a[i], stage_params)
            out = stage_fn(one, out)
        return out
    n = mesh.shape[pipe_axis]
    M = microbatches or n

    param_specs = jax.tree_util.tree_map(lambda _: P(pipe_axis), stage_params)
    x_spec = P(batch_axis if batch_axis in mesh.axis_names else None)

    def kernel(params_local, x_local):
        one = jax.tree_util.tree_map(lambda a: a[0], params_local)
        return _pipeline_local(
            stage_fn, one, x_local, pipe_axis=pipe_axis, n_stages=n,
            microbatches=M,
        )

    return shard_map(
        kernel,
        mesh=mesh,
        in_specs=(param_specs, x_spec),
        out_specs=x_spec,
        check_vma=False,
    )(stage_params, x)
