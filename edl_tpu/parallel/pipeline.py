"""Pipeline parallelism: microbatch schedules over a ``pipe`` mesh axis.

The reference has no pipeline parallelism (SURVEY §2.3) — its distribution is
data-parallel PS only — but a TPU framework schedules models too big for one
chip's HBM, so stages are first-class here. Design:

- Stage parameters are a pytree whose LEADING dim is the stage index, sharded
  over the ``pipe`` mesh axis: each device holds one stage's weights (for a
  transformer, its contiguous chunk of layers).
- Activations hop one ICI neighbor per tick via `jax.lax.ppermute`;
  warmup/drain bubble ticks compute on garbage that is masked out, so the
  schedules stay jit-compilable with static shapes.
- Stage outputs must have the stage-input shape (the standard homogeneous-
  stage restriction; residual-stream models satisfy it by construction).

Three schedules:

- **GPipe** (`_pipeline_local`): the classic (M + n - 1)-tick forward loop,
  differentiated by autodiff — backward replays the reversed schedule. The
  activation stash grows O(M) per stage (every microbatch's stage input is
  saved for the backward scan).
- **1F1B** (`pipeline_train_1f1b`, ``virtual_stages=1``): forward AND
  backward interleave in ONE scan — each tick runs stage ``r``'s forward of
  microbatch ``t - r`` and its backward of microbatch ``t - 2(n-1) + r``,
  with a cotangent hop riding `ppermute` in the reverse direction. Because
  backward consumes activations while forward produces them, the stash is a
  ring buffer of at most ``min(M, 2n - 1)`` microbatch inputs — O(n),
  independent of M. That is the 1F1B memory property, and it is only
  reachable as a combined schedule: autodiff of any forward-only scan must
  first finish all M forwards (activations O(M)) before its reverse pass, so
  the construct computes loss and all gradients in its forward rule
  (`jax.custom_vjp`; the vjp just scales the stashed grads by the upstream
  cotangent).
- **Interleaved 1F1B** (``virtual_stages=v > 1``): each pipe rank owns ``v``
  NONCONTIGUOUS virtual stage chunks — rank ``r`` holds global virtual
  stages ``r + k*n`` for ``k < v`` (`interleaved_layout` gives the matching
  chunk-major storage packing) — and the combined scan advances in
  chunk-ticks of 1/v the per-rank work. Activations traverse all
  ``V = n*v`` virtual stages on a forward ring (wraparound ``n-1 -> 0``
  carries chunk ``k`` to chunk ``k+1``); cotangents ride the reverse ring.
  Microbatches are injected in groups of ``n`` (M must divide by n), giving
  the conflict-free timetable: forward of virtual stage ``s`` for microbatch
  ``m = q*n + j`` at chunk-tick ``q*n*v + s + j``, backward mirrored at
  ``q*n*v + j + 2*(V-1) - s``. Total span is ``M*v + n*v + n - 2``
  chunk-ticks — at v=1 exactly the plain schedule's ``M + 2(n-1)`` — so the
  warmup/drain bubble shrinks by ~v at fixed M (strictly, for n >= 3; at
  n=2 the lockstep span ties plain 1F1B). The stash grows to
  ``v * min(M, 3n)`` microbatch inputs — still O(n*v), independent of M.

Schedule economics on TPU (honest accounting, `bubble_fraction`): XLA's
static schedule executes masked bubble ticks at full cost, so at EQUAL M the
plain combined 1F1B scan (``M + 2(n-1)`` ticks of fwd+bwd) loses wall-clock
to GPipe's effective ``M + n - 1`` — plain 1F1B's win is HBM headroom (O(n)
stash admits a much larger M where GPipe OOMs). Interleaving closes that
gap at the schedule level: bubble ``(nv + n - 2)/v`` full-tick equivalents
vs plain's ``2(n-1)``. The committed sweep (`bench_pipeline.py` ->
`BENCH_PIPELINE.json`, crossover table in `BENCH_NOTES.md`) quantifies all
three on the same mesh: per-step wall time and stash bytes across M and v.
Pick the schedule from those numbers — GPipe while the O(M) stash fits,
1F1B when activation memory binds, interleaved 1F1B (v >= 2, n >= 3) to buy
back most of 1F1B's bubble at a ~v-fold stash premium over plain 1F1B
(still M-independent).

`_pipeline_local` is the inside-a-shard_map form (composable with tensor and
sequence parallelism — the transformer calls it with ring attention inside the
stage function); `pipeline_apply` wraps it for standalone use. Stage
functions may carry a per-stage auxiliary value (MoE load-balance loss)
through any schedule: with ``stage_aux``/``aux_weight`` the stage function
returns ``(y, aux)`` — aux shape (1,), not rank-0: jax 0.4's shard_map
transpose gives residuals a leading-dim sharding that a scalar cannot
carry — the schedules accumulate aux only over real (stage, microbatch)
executions, psum it over the pipe axis, and fold
``aux_weight * mean_over_microbatches`` into the loss — gradients included
(the 1F1B runners seed the aux cotangent with ``aux_weight`` in each
per-tick vjp).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from edl_tpu.parallel.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def bubble_fraction(
    schedule: str,
    n_stages: int,
    microbatches: int,
    virtual_stages: int = 1,
) -> float:
    """Fraction of stage executions that are masked warmup/drain garbage
    (XLA executes them at full cost — this is wasted wall-clock, not just
    idle time). GPipe: (n-1)/(M+n-1) in each of the forward and backward
    scans. 1F1B combined scan: 2(n-1)/(M+2(n-1)) of its fwd+bwd ticks.
    Interleaved 1F1B advances in chunk-ticks of 1/v the per-rank work over
    a span of M*v + n*v + n - 2, of which M*v are useful:
    (n*v + n - 2)/(M*v + n*v + n - 2) — equal to plain 1F1B at v=1, and
    strictly below it for v >= 2 whenever n >= 3 (at n=2 the lockstep
    schedule ties)."""
    n, m, v = n_stages, microbatches, virtual_stages
    if v < 1:
        raise ValueError(f"virtual_stages must be >= 1, got {v}")
    if v != 1 and schedule != "1f1b-interleaved":
        raise ValueError(
            f"virtual_stages={v} only applies to '1f1b-interleaved', "
            f"not {schedule!r}"
        )
    if n <= 1:
        return 0.0
    if schedule == "gpipe":
        return (n - 1) / (m + n - 1)
    if schedule == "1f1b":
        return 2 * (n - 1) / (m + 2 * (n - 1))
    if schedule == "1f1b-interleaved":
        return (n * v + n - 2) / (m * v + n * v + n - 2)
    raise ValueError(f"unknown schedule {schedule!r}")


def stash_slots(
    schedule: str,
    n_stages: int,
    microbatches: int,
    virtual_stages: int = 1,
) -> int:
    """Per-device activation-stash entries, in units of one microbatch
    stage-input (the boundary activation; per-block internals are the remat
    story, orthogonal to the schedule). GPipe's forward scan saves its
    stage input every tick — M + n - 1 entries, O(M). Plain 1F1B holds a
    ring of min(M, 2n-1). Interleaved 1F1B holds v rings of min(M, 3n)
    (chunk k's input lives up to 2(V-1-s)+1 chunk-ticks; microbatches in
    flight per chunk span < 3n indices) — O(n*v), still M-independent."""
    n, m, v = n_stages, microbatches, virtual_stages
    if n <= 1:
        return 0
    if schedule == "gpipe":
        return m + n - 1
    if schedule == "1f1b":
        return min(m, 2 * n - 1)
    if schedule == "1f1b-interleaved":
        return v * min(m, 3 * n)
    raise ValueError(f"unknown schedule {schedule!r}")


def interleaved_layout(
    n_layers: int, n_stages: int, virtual_stages: int
) -> np.ndarray:
    """Layer permutation for chunk-major interleaved storage: entry ``p`` is
    the LOGICAL layer held at stacked-storage row ``p``. Rank ``r``'s
    contiguous shard (rows ``[r*L/n, (r+1)*L/n)`` under a ``P(pipe)``
    leading-dim sharding) then holds its virtual stages ``r + k*n`` back to
    back, chunk-major — rows ``k*Lc + j`` of the shard are logical layer
    ``(r + k*n)*Lc + j`` (``Lc = L/(n*v)``). Apply as ``stacked[perm]`` at
    init; invert with ``np.argsort(perm)`` to map gradients or checkpoints
    back to logical layer order. Identity at v=1."""
    n, v = n_stages, virtual_stages
    if n_layers % (n * v):
        raise ValueError(
            f"n_layers={n_layers} must divide by n_stages*virtual_stages="
            f"{n * v}"
        )
    lc = n_layers // (n * v)
    rows = [
        layer
        for r in range(n)
        for k in range(v)
        for layer in range((r + k * n) * lc, (r + k * n + 1) * lc)
    ]
    return np.asarray(rows, dtype=np.int64)


def _pipeline_local(
    stage_fn: Callable[[Any, jax.Array], Any],
    stage_params: Any,
    x: jax.Array,
    *,
    pipe_axis: str,
    n_stages: int,
    microbatches: int,
    stage_aux: bool = False,
) -> Any:
    """Run the GPipe schedule on local shards — call inside a shard_map
    whose manual axes include ``pipe_axis``.

    ``stage_params`` is THIS device's stage slice (leading stage dim already
    consumed by the enclosing in_spec). ``x``: (B_local, ...) activations; the
    full batch enters at stage 0 and the result is psum-broadcast to all
    stages so downstream (loss) code stays SPMD-uniform.

    With ``stage_aux=True`` the stage function returns ``(y, aux)`` (aux
    shape (1,) — a rank-0 aux in the differentiated scan carry trips jax
    0.4's shard_map scalar-residual transpose bug) and the return value is
    ``(outs, aux)`` where ``aux`` is the pipe-psum'd shape-(1,) per-stage
    value, accumulated only over real (stage, microbatch) executions and
    averaged over microbatches — differentiable, so GPipe's autodiff
    carries the aux gradient for free.
    """
    if n_stages == 1:
        return stage_fn(stage_params, x)
    M = microbatches
    B = x.shape[0]
    if B % M:
        raise ValueError(f"local batch {B} must be divisible by microbatches {M}")
    mb = x.reshape((M, B // M) + x.shape[1:])
    idx = jax.lax.axis_index(pipe_axis)
    fwd = [(i, i + 1) for i in range(n_stages - 1)]  # stage r -> r+1, no wrap

    def tick(carry, t):
        state, outs, aux_acc = carry
        # Stage 0 feeds microbatch t (clipped re-feeds during drain are
        # masked garbage); later stages consume the hop received last tick.
        inp = jnp.where(idx == 0, mb[jnp.clip(t, 0, M - 1)], state)
        out = stage_fn(stage_params, inp)
        y, aux_val = out if stage_aux else (out, None)
        if stage_aux:
            fm = t - idx  # this stage's microbatch this tick
            valid = (fm >= 0) & (fm < M)
            aux_acc = aux_acc + jnp.where(valid, aux_val, 0.0)
        opos = jnp.clip(t - (n_stages - 1), 0, M - 1)
        write = (idx == n_stages - 1) & (t >= n_stages - 1)
        prev = jax.lax.dynamic_index_in_dim(outs, opos, 0, keepdims=False)
        outs = jax.lax.dynamic_update_index_in_dim(
            outs, jnp.where(write, y, prev), opos, 0
        )
        state = jax.lax.ppermute(y, pipe_axis, fwd)
        return (state, outs, aux_acc), None

    state0 = jnp.zeros_like(mb[0])
    outs0 = jnp.zeros_like(mb)
    (_, outs, aux_acc), _ = jax.lax.scan(
        tick, (state0, outs0, jnp.zeros((1,), jnp.float32)),
        jnp.arange(M + n_stages - 1)
    )
    # Only the last stage wrote real outputs (zeros elsewhere): broadcast.
    outs = jax.lax.psum(jnp.where(idx == n_stages - 1, outs, 0), pipe_axis)
    outs = outs.reshape((B,) + x.shape[1:])
    if stage_aux:
        return outs, jax.lax.psum(aux_acc, pipe_axis) / M
    return outs


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    x: jax.Array,
    mesh: Mesh,
    *,
    pipe_axis: str = "pipe",
    batch_axis: str = "data",
    microbatches: Optional[int] = None,
) -> jax.Array:
    """Standalone pipeline over ``mesh``. ``stage_params`` leaves have a
    leading stage dim == pipe axis size; ``x`` (B, ...) is batch-sharded over
    ``batch_axis``. ``microbatches`` defaults to the stage count (bubble
    fraction (n-1)/(M+n-1); raise it to shrink the bubble)."""
    if pipe_axis not in mesh.axis_names or mesh.shape[pipe_axis] == 1:
        # No pipe axis on this mesh (e.g. after an elastic rescale dropped
        # it): run every stage sequentially on each device.
        n_stages = jax.tree_util.tree_leaves(stage_params)[0].shape[0]
        out = x
        for i in range(n_stages):
            one = jax.tree_util.tree_map(lambda a, i=i: a[i], stage_params)
            out = stage_fn(one, out)
        return out
    n = mesh.shape[pipe_axis]
    M = microbatches or n

    param_specs = jax.tree_util.tree_map(lambda _: P(pipe_axis), stage_params)
    x_spec = P(batch_axis if batch_axis in mesh.axis_names else None)

    def kernel(params_local, x_local):
        one = jax.tree_util.tree_map(lambda a: a[0], params_local)
        return _pipeline_local(
            stage_fn, one, x_local, pipe_axis=pipe_axis, n_stages=n,
            microbatches=M,
        )

    return shard_map(
        kernel,
        mesh=mesh,
        in_specs=(param_specs, x_spec),
        out_specs=x_spec,
        check_vma=False,
    )(stage_params, x)


# -- 1F1B: combined forward/backward schedule ----------------------------------


def _tree_where(cond, a, b):
    return jax.tree_util.tree_map(
        lambda x, y: jnp.where(cond, x, y), a, b
    )


def _tree_add(a, b):
    return jax.tree_util.tree_map(jnp.add, a, b)


def _tree_zeros(t):
    return jax.tree_util.tree_map(jnp.zeros_like, t)


def _tree_scale(t, s):
    return jax.tree_util.tree_map(lambda x: (x * s).astype(x.dtype), t)


def _run_1f1b(stage_fn, tail_fn, pipe_axis, n_stages, microbatches,
              aux_weight, stage_params, tail_params, x, aux):
    """The plain combined schedule (see module docstring). Local to a
    shard_map.

    Returns ``(loss, (d_stage, d_tail, dx))`` where loss/d_tail/dx are
    pipe-replicated (psum-assembled) and d_stage is this rank's stage
    gradient. All gradients already carry the 1/M mean weighting. With
    ``aux_weight != 0`` the stage function returns ``(y, aux_scalar)`` and
    ``aux_weight * mean_over_microbatches(sum_over_stages(aux))`` is folded
    into the loss, its gradient seeded through each per-tick vjp.
    """
    n, M = n_stages, microbatches
    aux_mode = bool(aux_weight)
    B = x.shape[0]
    if B % M:
        raise ValueError(f"local batch {B} must be divisible by microbatches {M}")
    mb = x.reshape((M, B // M) + x.shape[1:])
    aux_mb = jax.tree_util.tree_map(
        lambda a: a.reshape((M, B // M) + a.shape[1:]), aux
    )
    r = jax.lax.axis_index(pipe_axis)
    fwd_pairs = [(i, i + 1) for i in range(n - 1)]
    bwd_pairs = [(i + 1, i) for i in range(n - 1)]
    n_slots = min(M, 2 * n - 1)  # max in-flight microbatches per stage

    def stage_vjp(a, g):
        """Recompute-forward vjp of one stage application (remat-style:
        only the stage INPUT is stashed). In aux mode the stage output is
        (y, aux[(1,)]) and the aux cotangent is the static aux weight."""
        _, vjp = jax.vjp(lambda p, a_: stage_fn(p, a_), stage_params, a)
        if aux_mode:
            return vjp((g, jnp.full((1,), aux_weight, jnp.float32)))
        return vjp(g)  # (d_params, d_input)

    def tail_grad(y, av):
        """Per-microbatch loss + seed cotangent at the last stage."""
        loss, vjp = jax.vjp(
            lambda tp, y_: tail_fn(tp, y_, av), tail_params, y
        )
        d_tail, g = vjp(jnp.ones_like(loss))
        return loss, d_tail, g

    def tick(carry, t):
        (fwd_hop, bwd_hop, act_buf, d_stage, d_tail, dx_grid, loss_acc,
         aux_acc) = carry

        # ---- F-phase: stage r runs forward of microbatch t - r ----
        fm = t - r
        valid_f = (fm >= 0) & (fm < M)
        fmc = jnp.clip(fm, 0, M - 1)
        inp = jnp.where(r == 0, mb[fmc], fwd_hop)
        out = stage_fn(stage_params, inp)
        y, aux_val = out if aux_mode else (out, None)
        if aux_mode:
            aux_acc = aux_acc + jnp.where(valid_f, aux_val, 0.0)
        # stash the stage input for this microbatch's backward
        slot_f = fmc % n_slots
        prev = jax.lax.dynamic_index_in_dim(act_buf, slot_f, 0, keepdims=False)
        act_buf = jax.lax.dynamic_update_index_in_dim(
            act_buf, jnp.where(valid_f, inp, prev), slot_f, 0
        )

        # ---- B-phase: stage r runs backward of microbatch t - 2(n-1) + r.
        # At the last stage that is exactly this tick's forward microbatch,
        # so its tail cotangent seeds from the y just computed. The tail
        # vjp only carries information on the last stage's valid ticks —
        # everywhere else both branches' outputs are masked downstream, so
        # a real branch skips the (full LM-head-sized) tail work.
        bm = t - 2 * (n - 1) + r
        valid_b = (bm >= 0) & (bm < M)
        bmc = jnp.clip(bm, 0, M - 1)
        av = jax.tree_util.tree_map(lambda a: a[bmc], aux_mb)
        last_valid = valid_b & (r == n - 1)
        loss_mb, d_tail_mb, g_tail = jax.lax.cond(
            last_valid,
            lambda _: tail_grad(y, av),
            lambda _: (jnp.zeros((), jnp.float32), _tree_zeros(tail_params),
                       jnp.zeros_like(y)),
            None,
        )
        g = jnp.where(r == n - 1, g_tail, bwd_hop).astype(y.dtype)
        a_saved = jax.lax.dynamic_index_in_dim(
            act_buf, bmc % n_slots, 0, keepdims=False
        )
        d_p, d_a = stage_vjp(a_saved, g)
        d_stage = _tree_add(d_stage, _tree_where(valid_b, d_p, _tree_zeros(d_p)))
        d_tail = _tree_add(
            d_tail, _tree_where(last_valid, d_tail_mb, _tree_zeros(d_tail_mb))
        )
        loss_acc = loss_acc + jnp.where(last_valid, loss_mb, 0.0)
        prev_dx = jax.lax.dynamic_index_in_dim(dx_grid, bmc, 0, keepdims=False)
        dx_grid = jax.lax.dynamic_update_index_in_dim(
            dx_grid, jnp.where(valid_b & (r == 0), d_a, prev_dx), bmc, 0
        )

        # ---- hops: activations to r+1, cotangents to r-1 ----
        fwd_hop = jax.lax.ppermute(y, pipe_axis, fwd_pairs)
        bwd_hop = jax.lax.ppermute(d_a, pipe_axis, bwd_pairs)
        return (fwd_hop, bwd_hop, act_buf, d_stage, d_tail, dx_grid,
                loss_acc, aux_acc), None

    carry0 = (
        jnp.zeros_like(mb[0]),                       # fwd activation hop
        jnp.zeros_like(mb[0]),                       # bwd cotangent hop
        jnp.zeros((n_slots,) + mb.shape[1:], mb.dtype),  # input ring buffer
        _tree_zeros(stage_params),
        _tree_zeros(tail_params),
        jnp.zeros_like(mb),                          # dx per microbatch
        jnp.zeros((), jnp.float32),
        jnp.zeros((1,), jnp.float32),                # aux accumulator
    )
    (_, _, _, d_stage, d_tail, dx_grid, loss_acc, aux_acc), _ = jax.lax.scan(
        tick, carry0, jnp.arange(M + 2 * (n - 1))
    )

    inv_m = 1.0 / M
    is_last = (r == n - 1).astype(jnp.float32)
    # loss and tail grads live only on the last stage; dx only on stage 0:
    # psum re-replicates them across the pipe axis (zeros elsewhere). Each
    # rank's aux accumulator covers its own stage, so the psum is the sum
    # over stages.
    total = loss_acc * is_last
    if aux_mode:
        total = total + jnp.asarray(aux_weight, jnp.float32) * aux_acc[0]
    loss = jax.lax.psum(total, pipe_axis) * inv_m
    d_tail = jax.tree_util.tree_map(
        lambda v: jax.lax.psum(
            (v * is_last.astype(v.dtype)).astype(v.dtype), pipe_axis
        ) * jnp.asarray(inv_m, v.dtype),
        d_tail,
    )
    # dx stays NONZERO ONLY ON STAGE 0 — the same per-device cotangent
    # pattern autodiff of the GPipe local program produces (x is consumed
    # through `where(r == 0, ...)` there too). The enclosing shard_map
    # transpose reconciles replicated-input cotangents from that pattern;
    # replicating dx across the pipe axis here would double-count.
    dx = (jnp.where(r == 0, dx_grid, 0) * jnp.asarray(inv_m, dx_grid.dtype))
    dx = dx.astype(x.dtype).reshape((B,) + x.shape[1:])
    d_stage = _tree_scale(d_stage, inv_m)
    return loss, (d_stage, d_tail, dx)


def _run_1f1b_interleaved(stage_fn, tail_fn, pipe_axis, n_stages, microbatches,
                          virtual_stages, aux_weight, stage_params,
                          tail_params, x, aux):
    """Interleaved combined schedule: rank ``r`` owns ``v`` chunks (global
    virtual stage ``r + k*n`` at chunk-major slice ``k`` of the leading
    param dim — `interleaved_layout` packing). The scan advances in
    chunk-ticks: each tick this rank runs ONE chunk's forward and ONE
    chunk's backward, the active chunk/microbatch decoded from the tick
    index by the conflict-free timetable derived in the module docstring.
    Same return convention and pipe-replication contract as `_run_1f1b`;
    ``d_stage`` comes back in the rank's stacked (chunk-major) layout.
    """
    n, M, v = n_stages, microbatches, virtual_stages
    V = n * v
    nv = n * v
    aux_mode = bool(aux_weight)
    if M % n:
        raise ValueError(
            f"interleaved 1F1B needs microbatches divisible by the pipe "
            f"size: M={M}, n={n}"
        )
    B = x.shape[0]
    if B % M:
        raise ValueError(f"local batch {B} must be divisible by microbatches {M}")
    for leaf in jax.tree_util.tree_leaves(stage_params):
        if leaf.shape[0] % v:
            raise ValueError(
                f"stage param leading dim {leaf.shape[0]} must divide by "
                f"virtual_stages={v}"
            )
    mb = x.reshape((M, B // M) + x.shape[1:])
    aux_mb = jax.tree_util.tree_map(
        lambda a: a.reshape((M, B // M) + a.shape[1:]), aux
    )
    r = jax.lax.axis_index(pipe_axis)
    # Full rings: the wraparound edge carries chunk k's boundary (stage
    # k*n - 1 -> k*n) forward and its cotangent backward.
    ring_fwd = [(i, (i + 1) % n) for i in range(n)]
    ring_bwd = [(i, (i - 1) % n) for i in range(n)]
    n_slots = min(M, 3 * n)  # in-flight microbatches per chunk (see stash_slots)

    # Chunk-major view of this rank's params: leading dim v, chunk k =
    # global virtual stage r + k*n.
    chunked = jax.tree_util.tree_map(
        lambda a: a.reshape((v, a.shape[0] // v) + a.shape[1:]), stage_params
    )

    def apply_chunk(k, a_in):
        cp = jax.tree_util.tree_map(
            lambda arr: jax.lax.dynamic_index_in_dim(arr, k, 0, keepdims=False),
            chunked,
        )
        return stage_fn(cp, a_in)

    def chunk_vjp(k, a_in, g):
        """vjp of chunk k's application w.r.t. the FULL chunked params —
        the dynamic-index transpose scatters the chunk gradient into an
        otherwise-zero (v, ...) tree, which accumulates directly."""
        def f(ch, a_):
            cp = jax.tree_util.tree_map(
                lambda arr: jax.lax.dynamic_index_in_dim(
                    arr, k, 0, keepdims=False
                ),
                ch,
            )
            return stage_fn(cp, a_)

        _, vjp = jax.vjp(f, chunked, a_in)
        if aux_mode:
            return vjp((g, jnp.full((1,), aux_weight, jnp.float32)))
        return vjp(g)

    def tail_grad(y, av):
        loss, vjp = jax.vjp(
            lambda tp, y_: tail_fn(tp, y_, av), tail_params, y
        )
        d_tail, g = vjp(jnp.ones_like(loss))
        return loss, d_tail, g

    def tick(carry, t):
        (fwd_hop, bwd_hop, act_buf, d_stage, d_tail, dx_grid, loss_acc,
         aux_acc) = carry

        # ---- F-phase: decode (chunk, microbatch) from u = t - r via the
        # mixed-radix timetable u = q*n*v + k*n + j  (j < n, k < v).
        u = t - r
        rem = jnp.mod(u, nv)
        k_f = rem // n
        j_f = rem % n
        fm = jnp.floor_divide(u, nv) * n + j_f
        valid_f = (u >= 0) & (fm < M)
        fmc = jnp.clip(fm, 0, M - 1)
        # Fresh microbatches enter only at virtual stage 0 = rank 0 chunk 0;
        # every other (rank, chunk) consumes the ring hop, which the
        # timetable guarantees is the previous virtual stage's output.
        inp = jnp.where((r == 0) & (k_f == 0), mb[fmc], fwd_hop)
        out = apply_chunk(k_f, inp)
        y, aux_val = out if aux_mode else (out, None)
        if aux_mode:
            aux_acc = aux_acc + jnp.where(valid_f, aux_val, 0.0)
        slot_f = k_f * n_slots + fmc % n_slots
        prev = jax.lax.dynamic_index_in_dim(act_buf, slot_f, 0, keepdims=False)
        act_buf = jax.lax.dynamic_update_index_in_dim(
            act_buf, jnp.where(valid_f, inp, prev), slot_f, 0
        )

        # ---- B-phase: mirrored timetable t = q*n*v + j + 2(V-1) - s with
        # s = r + k*n; substituting k' = v-1-k gives the mixed-radix form
        # z = t - 2(V-1) + r - n = (q-1)*n*v + (k'+1-1)*n ... decoded below.
        z = t - 2 * (V - 1) + r - n
        remb = jnp.mod(z, nv)
        k_b = v - 1 - remb // n
        j_b = remb % n
        bm = (jnp.floor_divide(z, nv) + 1) * n + j_b
        valid_b = (bm >= 0) & (bm < M)
        bmc = jnp.clip(bm, 0, M - 1)
        # The seed point — virtual stage V-1 — is rank n-1's chunk v-1,
        # whose backward tick coincides with its own forward of the same
        # microbatch, so the tail cotangent seeds from this tick's y.
        seed = (r == n - 1) & (k_b == v - 1)
        last_valid = valid_b & seed
        av = jax.tree_util.tree_map(lambda a: a[bmc], aux_mb)
        loss_mb, d_tail_mb, g_tail = jax.lax.cond(
            last_valid,
            lambda _: tail_grad(y, av),
            lambda _: (jnp.zeros((), jnp.float32), _tree_zeros(tail_params),
                       jnp.zeros_like(y)),
            None,
        )
        g = jnp.where(seed, g_tail, bwd_hop).astype(y.dtype)
        slot_b = k_b * n_slots + bmc % n_slots
        a_saved = jax.lax.dynamic_index_in_dim(
            act_buf, slot_b, 0, keepdims=False
        )
        d_p, d_a = chunk_vjp(k_b, a_saved, g)
        d_stage = _tree_add(d_stage, _tree_where(valid_b, d_p, _tree_zeros(d_p)))
        d_tail = _tree_add(
            d_tail, _tree_where(last_valid, d_tail_mb, _tree_zeros(d_tail_mb))
        )
        loss_acc = loss_acc + jnp.where(last_valid, loss_mb, 0.0)
        prev_dx = jax.lax.dynamic_index_in_dim(dx_grid, bmc, 0, keepdims=False)
        dx_grid = jax.lax.dynamic_update_index_in_dim(
            dx_grid,
            jnp.where(valid_b & (r == 0) & (k_b == 0), d_a, prev_dx),
            bmc, 0,
        )

        fwd_hop = jax.lax.ppermute(y, pipe_axis, ring_fwd)
        bwd_hop = jax.lax.ppermute(d_a, pipe_axis, ring_bwd)
        return (fwd_hop, bwd_hop, act_buf, d_stage, d_tail, dx_grid,
                loss_acc, aux_acc), None

    carry0 = (
        jnp.zeros_like(mb[0]),
        jnp.zeros_like(mb[0]),
        jnp.zeros((v * n_slots,) + mb.shape[1:], mb.dtype),
        _tree_zeros(chunked),
        _tree_zeros(tail_params),
        jnp.zeros_like(mb),
        jnp.zeros((), jnp.float32),
        jnp.zeros((1,), jnp.float32),
    )
    ticks = M * v + n * v + n - 2  # == M + 2(n-1) at v=1
    (_, _, _, d_stage, d_tail, dx_grid, loss_acc, aux_acc), _ = jax.lax.scan(
        tick, carry0, jnp.arange(ticks)
    )

    inv_m = 1.0 / M
    is_last = (r == n - 1).astype(jnp.float32)
    total = loss_acc * is_last
    if aux_mode:
        total = total + jnp.asarray(aux_weight, jnp.float32) * aux_acc[0]
    loss = jax.lax.psum(total, pipe_axis) * inv_m
    d_tail = jax.tree_util.tree_map(
        lambda t_: jax.lax.psum(
            (t_ * is_last.astype(t_.dtype)).astype(t_.dtype), pipe_axis
        ) * jnp.asarray(inv_m, t_.dtype),
        d_tail,
    )
    dx = (jnp.where(r == 0, dx_grid, 0) * jnp.asarray(inv_m, dx_grid.dtype))
    dx = dx.astype(x.dtype).reshape((B,) + x.shape[1:])
    # back to the rank's stacked storage layout
    d_stage = jax.tree_util.tree_map(
        lambda a: a.reshape((a.shape[0] * a.shape[1],) + a.shape[2:]), d_stage
    )
    d_stage = _tree_scale(d_stage, inv_m)
    return loss, (d_stage, d_tail, dx)


def _run_combined(stage_fn, tail_fn, pipe_axis, n_stages, microbatches,
                  virtual_stages, aux_weight, stage_params, tail_params,
                  x, aux):
    if virtual_stages == 1:
        return _run_1f1b(stage_fn, tail_fn, pipe_axis, n_stages, microbatches,
                         aux_weight, stage_params, tail_params, x, aux)
    return _run_1f1b_interleaved(
        stage_fn, tail_fn, pipe_axis, n_stages, microbatches, virtual_stages,
        aux_weight, stage_params, tail_params, x, aux,
    )


@partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3, 4, 5, 6))
def pipeline_train_1f1b(stage_fn, tail_fn, pipe_axis, n_stages, microbatches,
                        virtual_stages, aux_weight,
                        stage_params, tail_params, x, aux):
    """1F1B training pipeline (plain at ``virtual_stages=1``, interleaved
    for ``virtual_stages > 1``): mean over microbatches of
    ``tail_fn(tail_params, stage_chain(x_m), aux_m)`` (plus
    ``aux_weight * sum_over_stages(stage_aux)`` when ``aux_weight != 0``,
    in which case ``stage_fn`` returns ``(y, aux_scalar)``).

    Call inside a shard_map whose manual axes include ``pipe_axis``. For
    the interleaved schedule, stage params must be packed chunk-major
    (`interleaved_layout`) and ``microbatches`` must divide by
    ``n_stages``. ``aux`` is a non-differentiated pytree of per-example
    arrays (targets, masks) microbatched alongside ``x``. The loss it
    returns is differentiable w.r.t. ``stage_params``/``tail_params``/``x``
    — but the gradients were already computed by the combined schedule in
    the forward pass (that is the point: fwd and bwd interleave in one
    scan, bounding the activation stash at O(n_stages * virtual_stages));
    the vjp rule just scales them by the upstream cotangent. Calling this
    without differentiating it wastes the backward work — use the GPipe
    path for inference.
    """
    loss, _ = _run_combined(stage_fn, tail_fn, pipe_axis, n_stages,
                            microbatches, virtual_stages, aux_weight,
                            stage_params, tail_params, x, aux)
    return loss


def _1f1b_fwd(stage_fn, tail_fn, pipe_axis, n_stages, microbatches,
              virtual_stages, aux_weight, stage_params, tail_params, x, aux):
    loss, grads = _run_combined(stage_fn, tail_fn, pipe_axis, n_stages,
                                microbatches, virtual_stages, aux_weight,
                                stage_params, tail_params, x, aux)
    return loss, grads


def _1f1b_bwd(stage_fn, tail_fn, pipe_axis, n_stages, microbatches,
              virtual_stages, aux_weight, res, ct):
    d_stage, d_tail, dx = res
    # The construct's forward ends in a psum over the pipe axis (the loss
    # broadcast); a true vjp would therefore deliver the SUM of all ranks'
    # upstream cotangents to the stashed gradients. The enclosing shard_map
    # splits a replicated output's cotangent 1/n_pipe per rank, so
    # short-circuiting with the raw per-rank ct would shrink every grad by
    # n_pipe. Emulate the psum transpose for the grads the machinery reads
    # per-rank (stage shards; stage-0's dx) — but NOT for d_tail, whose
    # replicated in_spec the machinery itself sums over the pipe axis.
    ct_sum = jax.lax.psum(ct, pipe_axis)
    return (_tree_scale(d_stage, ct_sum), _tree_scale(d_tail, ct),
            (dx * ct_sum).astype(dx.dtype), None)


pipeline_train_1f1b.defvjp(_1f1b_fwd, _1f1b_bwd)
