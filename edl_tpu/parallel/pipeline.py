"""Pipeline parallelism: microbatch schedules over a ``pipe`` mesh axis.

The reference has no pipeline parallelism (SURVEY §2.3) — its distribution is
data-parallel PS only — but a TPU framework schedules models too big for one
chip's HBM, so stages are first-class here. Design:

- Stage parameters are a pytree whose LEADING dim is the stage index, sharded
  over the ``pipe`` mesh axis: each device holds one stage's weights (for a
  transformer, its contiguous chunk of layers).
- Activations hop one ICI neighbor per tick via `jax.lax.ppermute`;
  warmup/drain bubble ticks compute on garbage that is masked out, so the
  schedules stay jit-compilable with static shapes.
- Stage outputs must have the stage-input shape (the standard homogeneous-
  stage restriction; residual-stream models satisfy it by construction).

Two schedules:

- **GPipe** (`_pipeline_local`): the classic (M + n - 1)-tick forward loop,
  differentiated by autodiff — backward replays the reversed schedule. The
  activation stash grows O(M) per stage (every microbatch's stage input is
  saved for the backward scan).
- **1F1B** (`pipeline_train_1f1b`): forward AND backward interleave in ONE
  scan — each tick runs stage ``r``'s forward of microbatch ``t - r`` and
  its backward of microbatch ``t - 2(n-1) + r``, with a cotangent hop riding
  `ppermute` in the reverse direction. Because backward consumes activations
  while forward produces them, the stash is a ring buffer of at most
  ``min(M, 2n - 1)`` microbatch inputs — O(n), independent of M. That is the
  1F1B memory property, and it is only reachable as a combined schedule:
  autodiff of any forward-only scan must first finish all M forwards
  (activations O(M)) before its reverse pass, so the construct computes loss
  and all gradients in its forward rule (`jax.custom_vjp`; the vjp just
  scales the stashed grads by the upstream cotangent).

Schedule economics on TPU (honest accounting, `bubble_fraction`): XLA's
static schedule executes masked bubble ticks at full cost, so the combined
1F1B scan runs ``M + 2(n-1)`` ticks of (fwd+bwd) work vs GPipe's effective
``M + n - 1``; per-step wall time therefore favors GPipe at equal M, and
1F1B's win is HBM headroom — it admits a much larger M (smaller bubble
fraction, better lease-granularity) at fixed activation memory, where GPipe
would OOM. Default stays GPipe; flip `TransformerConfig.pipeline_schedule`
to "1f1b" when activation memory binds.

`_pipeline_local` is the inside-a-shard_map form (composable with tensor and
sequence parallelism — the transformer calls it with ring attention inside the
stage function); `pipeline_apply` wraps it for standalone use.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from edl_tpu.parallel.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def bubble_fraction(schedule: str, n_stages: int, microbatches: int) -> float:
    """Fraction of stage executions that are masked warmup/drain garbage
    (XLA executes them at full cost — this is wasted wall-clock, not just
    idle time). GPipe: (n-1)/(M+n-1) in each of the forward and backward
    scans. 1F1B combined scan: 2(n-1)/(M+2(n-1)) of its fwd+bwd ticks."""
    n, m = n_stages, microbatches
    if n <= 1:
        return 0.0
    if schedule == "gpipe":
        return (n - 1) / (m + n - 1)
    if schedule == "1f1b":
        return 2 * (n - 1) / (m + 2 * (n - 1))
    raise ValueError(f"unknown schedule {schedule!r}")


def _pipeline_local(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    x: jax.Array,
    *,
    pipe_axis: str,
    n_stages: int,
    microbatches: int,
) -> jax.Array:
    """Run the pipeline schedule on local shards — call inside a shard_map
    whose manual axes include ``pipe_axis``.

    ``stage_params`` is THIS device's stage slice (leading stage dim already
    consumed by the enclosing in_spec). ``x``: (B_local, ...) activations; the
    full batch enters at stage 0 and the result is psum-broadcast to all
    stages so downstream (loss) code stays SPMD-uniform.
    """
    if n_stages == 1:
        return stage_fn(stage_params, x)
    M = microbatches
    B = x.shape[0]
    if B % M:
        raise ValueError(f"local batch {B} must be divisible by microbatches {M}")
    mb = x.reshape((M, B // M) + x.shape[1:])
    idx = jax.lax.axis_index(pipe_axis)
    fwd = [(i, i + 1) for i in range(n_stages - 1)]  # stage r -> r+1, no wrap

    def tick(carry, t):
        state, outs = carry
        # Stage 0 feeds microbatch t (clipped re-feeds during drain are
        # masked garbage); later stages consume the hop received last tick.
        inp = jnp.where(idx == 0, mb[jnp.clip(t, 0, M - 1)], state)
        y = stage_fn(stage_params, inp)
        opos = jnp.clip(t - (n_stages - 1), 0, M - 1)
        write = (idx == n_stages - 1) & (t >= n_stages - 1)
        prev = jax.lax.dynamic_index_in_dim(outs, opos, 0, keepdims=False)
        outs = jax.lax.dynamic_update_index_in_dim(
            outs, jnp.where(write, y, prev), opos, 0
        )
        state = jax.lax.ppermute(y, pipe_axis, fwd)
        return (state, outs), None

    state0 = jnp.zeros_like(mb[0])
    outs0 = jnp.zeros_like(mb)
    (_, outs), _ = jax.lax.scan(
        tick, (state0, outs0), jnp.arange(M + n_stages - 1)
    )
    # Only the last stage wrote real outputs (zeros elsewhere): broadcast.
    outs = jax.lax.psum(jnp.where(idx == n_stages - 1, outs, 0), pipe_axis)
    return outs.reshape((B,) + x.shape[1:])


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    x: jax.Array,
    mesh: Mesh,
    *,
    pipe_axis: str = "pipe",
    batch_axis: str = "data",
    microbatches: Optional[int] = None,
) -> jax.Array:
    """Standalone pipeline over ``mesh``. ``stage_params`` leaves have a
    leading stage dim == pipe axis size; ``x`` (B, ...) is batch-sharded over
    ``batch_axis``. ``microbatches`` defaults to the stage count (bubble
    fraction (n-1)/(M+n-1); raise it to shrink the bubble)."""
    if pipe_axis not in mesh.axis_names or mesh.shape[pipe_axis] == 1:
        # No pipe axis on this mesh (e.g. after an elastic rescale dropped
        # it): run every stage sequentially on each device.
        n_stages = jax.tree_util.tree_leaves(stage_params)[0].shape[0]
        out = x
        for i in range(n_stages):
            one = jax.tree_util.tree_map(lambda a, i=i: a[i], stage_params)
            out = stage_fn(one, out)
        return out
    n = mesh.shape[pipe_axis]
    M = microbatches or n

    param_specs = jax.tree_util.tree_map(lambda _: P(pipe_axis), stage_params)
    x_spec = P(batch_axis if batch_axis in mesh.axis_names else None)

    def kernel(params_local, x_local):
        one = jax.tree_util.tree_map(lambda a: a[0], params_local)
        return _pipeline_local(
            stage_fn, one, x_local, pipe_axis=pipe_axis, n_stages=n,
            microbatches=M,
        )

    return shard_map(
        kernel,
        mesh=mesh,
        in_specs=(param_specs, x_spec),
        out_specs=x_spec,
        check_vma=False,
    )(stage_params, x)


# -- 1F1B: combined forward/backward schedule ----------------------------------


def _tree_where(cond, a, b):
    return jax.tree_util.tree_map(
        lambda x, y: jnp.where(cond, x, y), a, b
    )


def _tree_add(a, b):
    return jax.tree_util.tree_map(jnp.add, a, b)


def _tree_zeros(t):
    return jax.tree_util.tree_map(jnp.zeros_like, t)


def _tree_scale(t, s):
    return jax.tree_util.tree_map(lambda x: (x * s).astype(x.dtype), t)


def _run_1f1b(stage_fn, tail_fn, pipe_axis, n_stages, microbatches,
              stage_params, tail_params, x, aux):
    """The combined schedule (see module docstring). Local to a shard_map.

    Returns ``(loss, (d_stage, d_tail, dx))`` where loss/d_tail/dx are
    pipe-replicated (psum-assembled) and d_stage is this rank's stage
    gradient. All gradients already carry the 1/M mean weighting.
    """
    n, M = n_stages, microbatches
    B = x.shape[0]
    if B % M:
        raise ValueError(f"local batch {B} must be divisible by microbatches {M}")
    mb = x.reshape((M, B // M) + x.shape[1:])
    aux_mb = jax.tree_util.tree_map(
        lambda a: a.reshape((M, B // M) + a.shape[1:]), aux
    )
    r = jax.lax.axis_index(pipe_axis)
    fwd_pairs = [(i, i + 1) for i in range(n - 1)]
    bwd_pairs = [(i + 1, i) for i in range(n - 1)]
    n_slots = min(M, 2 * n - 1)  # max in-flight microbatches per stage

    def stage_vjp(a, g):
        """Recompute-forward vjp of one stage application (remat-style:
        only the stage INPUT is stashed)."""
        _, vjp = jax.vjp(lambda p, a_: stage_fn(p, a_), stage_params, a)
        return vjp(g)  # (d_params, d_input)

    def tail_grad(y, av):
        """Per-microbatch loss + seed cotangent at the last stage."""
        loss, vjp = jax.vjp(
            lambda tp, y_: tail_fn(tp, y_, av), tail_params, y
        )
        d_tail, g = vjp(jnp.ones_like(loss))
        return loss, d_tail, g

    def tick(carry, t):
        (fwd_hop, bwd_hop, act_buf, d_stage, d_tail, dx_grid, loss_acc) = carry

        # ---- F-phase: stage r runs forward of microbatch t - r ----
        fm = t - r
        valid_f = (fm >= 0) & (fm < M)
        fmc = jnp.clip(fm, 0, M - 1)
        inp = jnp.where(r == 0, mb[fmc], fwd_hop)
        y = stage_fn(stage_params, inp)
        # stash the stage input for this microbatch's backward
        slot_f = fmc % n_slots
        prev = jax.lax.dynamic_index_in_dim(act_buf, slot_f, 0, keepdims=False)
        act_buf = jax.lax.dynamic_update_index_in_dim(
            act_buf, jnp.where(valid_f, inp, prev), slot_f, 0
        )

        # ---- B-phase: stage r runs backward of microbatch t - 2(n-1) + r.
        # At the last stage that is exactly this tick's forward microbatch,
        # so its tail cotangent seeds from the y just computed.
        bm = t - 2 * (n - 1) + r
        valid_b = (bm >= 0) & (bm < M)
        bmc = jnp.clip(bm, 0, M - 1)
        loss_mb, d_tail_mb, g_tail = tail_grad(
            y, jax.tree_util.tree_map(lambda a: a[bmc], aux_mb)
        )
        g = jnp.where(r == n - 1, g_tail, bwd_hop).astype(y.dtype)
        a_saved = jax.lax.dynamic_index_in_dim(
            act_buf, bmc % n_slots, 0, keepdims=False
        )
        d_p, d_a = stage_vjp(a_saved, g)
        d_stage = _tree_add(d_stage, _tree_where(valid_b, d_p, _tree_zeros(d_p)))
        last_valid = valid_b & (r == n - 1)
        d_tail = _tree_add(
            d_tail, _tree_where(last_valid, d_tail_mb, _tree_zeros(d_tail_mb))
        )
        loss_acc = loss_acc + jnp.where(last_valid, loss_mb, 0.0)
        prev_dx = jax.lax.dynamic_index_in_dim(dx_grid, bmc, 0, keepdims=False)
        dx_grid = jax.lax.dynamic_update_index_in_dim(
            dx_grid, jnp.where(valid_b & (r == 0), d_a, prev_dx), bmc, 0
        )

        # ---- hops: activations to r+1, cotangents to r-1 ----
        fwd_hop = jax.lax.ppermute(y, pipe_axis, fwd_pairs)
        bwd_hop = jax.lax.ppermute(d_a, pipe_axis, bwd_pairs)
        return (fwd_hop, bwd_hop, act_buf, d_stage, d_tail, dx_grid,
                loss_acc), None

    carry0 = (
        jnp.zeros_like(mb[0]),                       # fwd activation hop
        jnp.zeros_like(mb[0]),                       # bwd cotangent hop
        jnp.zeros((n_slots,) + mb.shape[1:], mb.dtype),  # input ring buffer
        _tree_zeros(stage_params),
        _tree_zeros(tail_params),
        jnp.zeros_like(mb),                          # dx per microbatch
        jnp.zeros((), jnp.float32),
    )
    (_, _, _, d_stage, d_tail, dx_grid, loss_acc), _ = jax.lax.scan(
        tick, carry0, jnp.arange(M + 2 * (n - 1))
    )

    inv_m = 1.0 / M
    is_last = (r == n - 1).astype(jnp.float32)
    # loss and tail grads live only on the last stage; dx only on stage 0:
    # psum re-replicates them across the pipe axis (zeros elsewhere).
    loss = jax.lax.psum(loss_acc * is_last, pipe_axis) * inv_m
    d_tail = jax.tree_util.tree_map(
        lambda v: jax.lax.psum(
            (v * is_last.astype(v.dtype)).astype(v.dtype), pipe_axis
        ) * jnp.asarray(inv_m, v.dtype),
        d_tail,
    )
    # dx stays NONZERO ONLY ON STAGE 0 — the same per-device cotangent
    # pattern autodiff of the GPipe local program produces (x is consumed
    # through `where(r == 0, ...)` there too). The enclosing shard_map
    # transpose reconciles replicated-input cotangents from that pattern;
    # replicating dx across the pipe axis here would double-count.
    dx = (jnp.where(r == 0, dx_grid, 0) * jnp.asarray(inv_m, dx_grid.dtype))
    dx = dx.astype(x.dtype).reshape((B,) + x.shape[1:])
    d_stage = _tree_scale(d_stage, inv_m)
    return loss, (d_stage, d_tail, dx)


@partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3, 4))
def pipeline_train_1f1b(stage_fn, tail_fn, pipe_axis, n_stages, microbatches,
                        stage_params, tail_params, x, aux):
    """1F1B training pipeline: mean over microbatches of
    ``tail_fn(tail_params, stage_chain(x_m), aux_m)``.

    Call inside a shard_map whose manual axes include ``pipe_axis``.
    ``aux`` is a non-differentiated pytree of per-example arrays (targets,
    masks) microbatched alongside ``x``. The loss it returns is
    differentiable w.r.t. ``stage_params``/``tail_params``/``x`` — but the
    gradients were already computed by the combined schedule in the forward
    pass (that is the point: fwd and bwd interleave in one scan, bounding
    the activation stash at O(n_stages)); the vjp rule just scales them by
    the upstream cotangent. Calling this without differentiating it wastes
    the backward work — use the GPipe path for inference.
    """
    loss, _ = _run_1f1b(stage_fn, tail_fn, pipe_axis, n_stages, microbatches,
                        stage_params, tail_params, x, aux)
    return loss


def _1f1b_fwd(stage_fn, tail_fn, pipe_axis, n_stages, microbatches,
              stage_params, tail_params, x, aux):
    loss, grads = _run_1f1b(stage_fn, tail_fn, pipe_axis, n_stages,
                            microbatches, stage_params, tail_params, x, aux)
    return loss, grads


def _1f1b_bwd(stage_fn, tail_fn, pipe_axis, n_stages, microbatches, res, ct):
    d_stage, d_tail, dx = res
    # The construct's forward ends in a psum over the pipe axis (the loss
    # broadcast); a true vjp would therefore deliver the SUM of all ranks'
    # upstream cotangents to the stashed gradients. The enclosing shard_map
    # splits a replicated output's cotangent 1/n_pipe per rank, so
    # short-circuiting with the raw per-rank ct would shrink every grad by
    # n_pipe. Emulate the psum transpose for the grads the machinery reads
    # per-rank (stage shards; stage-0's dx) — but NOT for d_tail, whose
    # replicated in_spec the machinery itself sums over the pipe axis.
    ct_sum = jax.lax.psum(ct, pipe_axis)
    return (_tree_scale(d_stage, ct_sum), _tree_scale(d_tail, ct),
            (dx * ct_sum).astype(dx.dtype), None)


pipeline_train_1f1b.defvjp(_1f1b_fwd, _1f1b_bwd)
