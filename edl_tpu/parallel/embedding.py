"""Row-sharded embedding tables: the TPU-native sparse parameter server.

The reference serves large sparse embeddings (CTR's 1e6+1-row table,
`example/ctr/ctr/train.py:60-64`) from dedicated C++ pserver processes over
per-pserver sparse ports (`pkg/jobparser.go:232-247`, `docker/paddle_k8s:7-9`).
Here the table is one jax array row-sharded across the mesh — each device's
HBM holds ``vocab/N`` rows, the moral equivalent of one pserver shard — and a
lookup is a `shard_map` collective instead of an RPC:

- ids sharded on the same axis as the table (pure-DP meshes): all-gather the
  ids, gather local rows with an ownership mask, then ``psum_scatter`` so each
  device keeps exactly its batch slice — the classic embedding all-to-all,
  riding ICI.
- ids sharded on a different axis (dedicated ``expert`` axis): each row-shard
  sees its full local batch; masked local gather + ``psum`` over the row axis.

Both paths are differentiable under jit: the backward of gather/psum_scatter
is scatter-add/all-gather, which XLA lowers to the mirror-image collective —
this is what replaces the reference's sparse gradient push.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
from edl_tpu.parallel.compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _round_up(x: int, mult: int) -> int:
    return ((x + mult - 1) // mult) * mult


# -- dedup'd gather: sparse-gradient aggregation (opt-in) ----------------------
#
# The backward of a plain ``table[ids]`` is a scatter-add with duplicate
# indices. This gather's custom vjp pre-combines duplicate ids (sort +
# sorted segment-sum) so the final scatter sees each row at most once and
# can assert ``unique_indices`` — the moral equivalent of the reference
# pserver's aggregated sparse-row update (`docker/paddle_k8s:7-9`).
#
# Measured on v5e with CTR shapes (8192x26 zipf ids into a 1e6x10 table),
# XLA's native scatter-add beat this path (11.6 ms vs 18.6 ms: the 213k-key
# sort dominates), so the lookup paths below use the plain gather; this
# stays available for workloads with far heavier id duplication (it wins
# when duplicates per step >> unique rows, e.g. tiny vocabularies).


@jax.custom_vjp
def dedup_gather(table: jax.Array, flat_ids: jax.Array) -> jax.Array:
    """``table[flat_ids]`` whose backward aggregates duplicate ids before
    scattering. ``flat_ids``: 1-D non-negative int array."""
    return table[flat_ids]


def _dedup_gather_fwd(table, flat_ids):
    return table[flat_ids], (table, flat_ids)


def _dedup_gather_bwd(res, g):
    table, flat_ids = res
    # Canonicalize: the sentinel logic below needs a signed dtype wide enough
    # for table.shape[0] + n (segment_max's identity for unsigned ints is 0,
    # which would collide with real row 0).
    flat_ids = flat_ids.astype(jnp.int32)
    n = flat_ids.shape[0]
    if n == 0:
        return jnp.zeros_like(table), None
    sorted_ids, perm = jax.lax.sort_key_val(
        flat_ids, jnp.arange(n, dtype=jnp.int32)
    )
    g_sorted = jnp.take(g, perm, axis=0)
    starts = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), sorted_ids[1:] != sorted_ids[:-1]]
    )
    seg = jnp.cumsum(starts.astype(jnp.int32)) - 1
    uniq_grad = jax.ops.segment_sum(
        g_sorted, seg, num_segments=n, indices_are_sorted=True
    )
    uniq_ids = jax.ops.segment_max(
        sorted_ids, seg, num_segments=n, indices_are_sorted=True
    )
    # Empty segments hold segment_max's identity (int32 min); remap each to a
    # distinct out-of-range slot so `unique_indices` stays honest and `drop`
    # discards them.
    sentinel = table.shape[0] + jnp.arange(n, dtype=uniq_ids.dtype)
    uniq_ids = jnp.where(uniq_ids < 0, sentinel, uniq_ids)
    dtable = jnp.zeros_like(table).at[uniq_ids].add(
        uniq_grad.astype(table.dtype), mode="drop", unique_indices=True
    )
    return dtable, None


dedup_gather.defvjp(_dedup_gather_fwd, _dedup_gather_bwd)


@dataclass(frozen=True)
class ShardedEmbedding:
    """Config + functional init/apply for one row-sharded table.

    vocab is padded up so every shard holds the same row count (XLA needs
    static equal shards). ``shard_axis`` is the mesh axis rows live on;
    ``batch_axis`` the axis ids/batches are sharded on (may be the same).
    """

    vocab_size: int
    features: int
    shard_axis: str = "data"
    #: one mesh axis or a hierarchy tuple (e.g. ("dcn", "data")) the
    #: ids/batches are sharded over
    batch_axis: Any = "data"
    dtype: jnp.dtype = jnp.float32

    #: vocab is padded to a multiple of this REGARDLESS of mesh size, so the
    #: table shape is stable across elastic rescale (a checkpoint written on a
    #: 4-shard mesh restores onto 8 shards by resharding, not reshaping).
    #: 256 divides evenly for every power-of-two shard count up to 256.
    PAD_MULTIPLE = 256

    def padded_vocab(self, mesh: Mesh) -> int:
        n = mesh.shape[self.shard_axis] if self.shard_axis in mesh.axis_names else 1
        if self.PAD_MULTIPLE % n == 0:
            return _round_up(self.vocab_size, self.PAD_MULTIPLE)
        # Exotic shard counts (e.g. 3, 12) fall back to the LCM so rows still
        # split evenly — at the cost of rescale-compatible shapes.
        return _round_up(self.vocab_size, n * self.PAD_MULTIPLE)

    def table_spec(self) -> P:
        return P(self.shard_axis, None)

    def init(self, key: jax.Array, mesh: Mesh, scale: float = 0.01) -> jax.Array:
        """Initialize the sharded table directly on the mesh (no host copy of
        the full table — rows materialize shard-local, as pserver shards did)."""
        vocab = self.padded_vocab(mesh)
        sharding = NamedSharding(mesh, self.table_spec())

        @partial(jax.jit, out_shardings=sharding)
        def _init():
            return (
                jax.random.normal(key, (vocab, self.features), dtype=self.dtype)
                * scale
            )

        return _init()

    def apply(self, mesh: Mesh, table: jax.Array, ids: jax.Array) -> jax.Array:
        """Lookup: ids (...,) int32 -> embeddings (..., features).

        Out-of-range ids (e.g. the reference's hashed features modulo vocab)
        must be pre-clipped by the caller; padded rows return real (trainable,
        never-updated) values, matching pserver semantics for unused buckets.
        """
        if self.shard_axis not in mesh.axis_names or mesh.shape[self.shard_axis] == 1:
            return table[ids]

        flat = ids.reshape(-1)
        if self.shard_axis == self.batch_axis:
            out = self._lookup_same_axis(mesh, table, flat)
        else:
            out = self._lookup_cross_axis(mesh, table, flat)
        return out.reshape(ids.shape + (self.features,))

    # -- shard_map kernels -----------------------------------------------------

    def _lookup_same_axis(self, mesh: Mesh, table: jax.Array, flat_ids: jax.Array):
        axis = self.shard_axis
        n = mesh.shape[axis]

        def kernel(table_local: jax.Array, ids_local: jax.Array):
            # (B/n,) -> (B,): everyone needs to answer everyone's queries.
            ids_all = jax.lax.all_gather(ids_local, axis, tiled=True)
            local_rows = table_local.shape[0]
            offset = jax.lax.axis_index(axis) * local_rows
            local_ids = ids_all - offset
            hit = (local_ids >= 0) & (local_ids < local_rows)
            safe = jnp.clip(local_ids, 0, local_rows - 1)
            contrib = jnp.where(hit[:, None], table_local[safe], 0)
            # Return each participant its own batch slice, summed over owners.
            return jax.lax.psum_scatter(contrib, axis, scatter_dimension=0, tiled=True)

        return shard_map(
            kernel,
            mesh=mesh,
            in_specs=(self.table_spec(), P(axis)),
            out_specs=P(axis, None),
        )(table, flat_ids)

    def _lookup_cross_axis(self, mesh: Mesh, table: jax.Array, flat_ids: jax.Array):
        from edl_tpu.parallel.sharding import present_axes

        shard_ax = self.shard_axis
        have = present_axes(mesh, self.batch_axis)
        batch_ax = have or None  # P accepts the axis tuple directly
        batch_spec = P(batch_ax) if have else P()

        def kernel(table_local: jax.Array, ids_local: jax.Array):
            local_rows = table_local.shape[0]
            offset = jax.lax.axis_index(shard_ax) * local_rows
            local_ids = ids_local - offset
            hit = (local_ids >= 0) & (local_ids < local_rows)
            safe = jnp.clip(local_ids, 0, local_rows - 1)
            contrib = jnp.where(hit[:, None], table_local[safe], 0)
            return jax.lax.psum(contrib, shard_ax)

        out_spec = P(batch_ax, None) if have else P(None, None)
        return shard_map(
            kernel,
            mesh=mesh,
            in_specs=(self.table_spec(), batch_spec),
            out_specs=out_spec,
        )(table, flat_ids)
