"""Ring attention: sequence/context parallelism over the ``seq`` mesh axis.

The reference predates long context (its longest "sequence" is a 5-gram
window, `example/fit_a_line/train_ft.py:26`); this framework makes sequence
parallelism first-class. Q/K/V live sharded on the sequence dimension across
the ``seq`` axis; each device computes attention for its local query block
while K/V blocks rotate around the ring via `jax.lax.ppermute`, one hop per
step, overlapping the ICI transfer with the block matmuls. Softmax is the
blockwise online form (flash-attention accumulation): running max ``m``,
numerator ``num`` and denominator ``den`` are updated per visiting block, so
the full (S, S) score matrix never materializes and memory stays
O(S_local^2 / n_shards) per device.

Causality is enforced with *global* positions reconstructed from the ring
topology: the block arriving at step ``i`` originated on device
``(my_index - i) mod n``, so its key positions are known statically per step
and the mask costs one compare, no communication.

The public entrypoint wraps its own `shard_map`; `_ring_attention_local` is
the inside-a-shard_map form reused by models that are already manual over the
mesh (e.g. `edl_tpu.models.transformer`).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from edl_tpu.parallel.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

#: scores below this are "masked"; finite so exp() is exactly 0 without nans.
_NEG_INF = -1e30


def dense_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = True,
    scale: Optional[float] = None,
) -> jax.Array:
    """Reference O(S^2)-memory attention. q/k/v: (B, S, H, D).

    The correctness oracle for the ring kernel and the single-device
    fallback; f32 softmax regardless of input dtype.
    """
    B, S, H, D = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        pos = jnp.arange(S)
        s = jnp.where(pos[None, :] <= pos[:, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
    return out.astype(q.dtype)


def _ring_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    seq_axis: str,
    n_shards: int,
    causal: bool = True,
    scale: Optional[float] = None,
    flash: bool = False,
) -> jax.Array:
    """Ring attention over local shards — call inside a shard_map whose manual
    axes include ``seq_axis``. q/k/v: (B, S_local, H_local, D).

    ``flash``: run every block's attention through the Pallas kernel
    (`edl_tpu.ops.flash_attention`) instead of the einsum engine — the
    unsharded case directly, the ring case via per-hop (out, lse) pairs
    merged associatively (gradients flow through the kernel's lse)."""
    B, S, H, D = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    if n_shards == 1:
        if flash:
            from edl_tpu.ops import flash_attention

            return flash_attention(q, k, v, causal=causal, scale=scale)
        return dense_attention(q, k, v, causal=causal, scale=scale)
    if flash:
        return _ring_flash_local(
            q, k, v, seq_axis=seq_axis, n_shards=n_shards, causal=causal,
            scale=scale,
        )

    my = jax.lax.axis_index(seq_axis)
    q_pos = my * S + jnp.arange(S)  # global positions of local queries
    ring = [(i, (i + 1) % n_shards) for i in range(n_shards)]
    qf = q.astype(jnp.float32)

    def accumulate(acc, k_blk, v_blk, src):
        """Fold one visiting K/V block into the online-softmax accumulator."""
        m, num, den = acc
        k_pos = src * S + jnp.arange(S)
        s = jnp.einsum(
            "bqhd,bkhd->bhqk", qf, k_blk.astype(jnp.float32)
        ) * scale
        if causal:
            mask = k_pos[None, :] <= q_pos[:, None]  # (S_q, S_k)
            s = jnp.where(mask[None, None], s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))  # (B, H, S_q)
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])  # (B, H, S_q, S_k)
        num = num * alpha[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32)
        )
        den = den * alpha + p.sum(axis=-1)
        return m_new, num, den

    def step(carry, i):
        k_blk, v_blk, acc = carry
        # Rotate first: the last step's output IS consumed, so exactly
        # n_shards-1 hops move each block all the way around the ring.
        k_blk = jax.lax.ppermute(k_blk, seq_axis, ring)
        v_blk = jax.lax.ppermute(v_blk, seq_axis, ring)
        acc = accumulate(acc, k_blk, v_blk, src=(my - i) % n_shards)
        return (k_blk, v_blk, acc), None

    m0 = jnp.full((B, H, S), _NEG_INF, jnp.float32)
    num0 = jnp.zeros((B, H, S, D), jnp.float32)
    den0 = jnp.zeros((B, H, S), jnp.float32)
    acc0 = accumulate((m0, num0, den0), k, v, src=my)  # local block, hop 0
    (_, _, (_, num, den)), _ = jax.lax.scan(
        step, (k, v, acc0), jnp.arange(1, n_shards)
    )
    out = num / den[..., None]  # (B, H, S_q, D)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def _ring_flash_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    seq_axis: str,
    n_shards: int,
    causal: bool,
    scale: float,
) -> jax.Array:
    """Ring attention with the Pallas kernel as the per-hop block engine.

    Each visiting K/V block runs through `flash_attention(return_lse=True)`
    with global offsets; hops merge associatively in (out, lse) form:
    ``lse' = logaddexp(lse_a, lse_b)``, ``out' = out_a e^{lse_a - lse'} +
    out_b e^{lse_b - lse'}``. Blocks with no visible keys report the finite
    masked sentinel, whose weight underflows to exactly 0 in the merge, so
    no special-casing. Gradients flow through the kernel's custom VJP for
    both outputs."""
    from edl_tpu.ops import flash_attention

    B, S, H, D = q.shape
    my = jax.lax.axis_index(seq_axis)
    ring = [(i, (i + 1) % n_shards) for i in range(n_shards)]

    def block(k_blk, v_blk, src):
        return flash_attention(
            q, k_blk, v_blk, causal=causal, scale=scale,
            q_offset=my * S, k_offset=src * S, return_lse=True,
        )

    def merge(acc, blk):
        (oa, la), (ob, lb) = acc, blk
        lse = jnp.logaddexp(la, lb)  # (B, H, S)
        wa = jnp.exp(la - lse)[..., None].transpose(0, 2, 1, 3)
        wb = jnp.exp(lb - lse)[..., None].transpose(0, 2, 1, 3)
        return (
            oa.astype(jnp.float32) * wa + ob.astype(jnp.float32) * wb,
            lse,
        )

    def step(carry, i):
        k_blk, v_blk, acc = carry
        k_blk = jax.lax.ppermute(k_blk, seq_axis, ring)
        v_blk = jax.lax.ppermute(v_blk, seq_axis, ring)
        acc = merge(acc, block(k_blk, v_blk, src=(my - i) % n_shards))
        return (k_blk, v_blk, acc), None

    out0, lse0 = block(k, v, src=my)  # local block, hop 0
    acc0 = (out0.astype(jnp.float32), lse0)
    (_, _, (out, _)), _ = jax.lax.scan(
        step, (k, v, acc0), jnp.arange(1, n_shards)
    )
    return out.astype(q.dtype)


def _qkv_spec(mesh: Mesh, batch_axis: str, seq_axis: str, head_axis: str) -> P:
    """(B, S, H, D) spec using only axes the mesh actually has."""
    have = mesh.axis_names
    return P(
        batch_axis if batch_axis in have else None,
        seq_axis if seq_axis in have else None,
        head_axis if head_axis in have else None,
        None,
    )


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    seq_axis: str = "seq",
    batch_axis: str = "data",
    head_axis: str = "model",
    causal: bool = True,
    scale: Optional[float] = None,
    flash: bool = False,
) -> jax.Array:
    """Sequence-parallel attention on a mesh. q/k/v: (B, S, H, D) global.

    Sharding: batch over ``batch_axis``, sequence over ``seq_axis``, heads
    over ``head_axis`` (attention is embarrassingly parallel over batch and
    heads; only the sequence axis communicates). Axes absent from the mesh
    are simply unsharded. With no ``seq_axis`` in the mesh this degrades to
    dense attention under `jit` sharding propagation (``flash=False``) or
    to the Pallas kernel on each device's local batch/head block inside a
    communication-free shard_map (``flash=True``).
    """
    n_sp = mesh.shape[seq_axis] if seq_axis in mesh.axis_names else 1
    if n_sp == 1:
        if not flash:
            return dense_attention(q, k, v, causal=causal, scale=scale)
        # flash prefers the shard_map below even with no sequence sharding
        # (pallas_call has no SPMD partitioning rule, so on global arrays
        # XLA would replicate batch/head-sharded inputs), but shard_map
        # demands divisibility — an indivisible batch/head (e.g. a single
        # eval sequence on a data mesh) takes the global call instead,
        # which is always correct, just potentially replicated.
        B, _, H, _ = q.shape
        n_b = mesh.shape.get(batch_axis, 1)
        n_h = mesh.shape.get(head_axis, 1)
        if B % n_b or H % n_h:
            from edl_tpu.ops import flash_attention

            return flash_attention(q, k, v, causal=causal, scale=scale)
    spec = _qkv_spec(mesh, batch_axis, seq_axis, head_axis)
    kernel = partial(
        _ring_attention_local,
        seq_axis=seq_axis,
        n_shards=n_sp,
        causal=causal,
        scale=scale,
        flash=flash,
    )
    return shard_map(
        kernel,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )(q, k, v)
