"""Device-mesh construction from TrainingJob parallelism specs.

The reference distributes by counting processes (`PADDLE_INIT_NUM_GRADIENT_SERVERS`,
`pkg/jobparser.go:296`) and wiring endpoints; here distribution is a mesh of
TPU chips with named logical axes. The trainer count the autoscaler actuates
multiplies the ``data`` axis: a job scaled from 2 to 4 trainers rebuilds its
mesh with twice the data-parallel degree (checkpoint-restore rescale, see
`edl_tpu.runtime.elastic`).

Axis conventions (scaling-book style):
  data    — batch sharding; gradients all-reduced over it (ICI)
  model   — tensor-parallel sharding of weight matrices
  seq     — sequence/context parallelism for long inputs
  expert  — expert/embedding-row sharding (the pserver-replacement axis)

All axes are optional; absent axes have size 1. The product of axis sizes must
equal the number of participating devices.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_ORDER = ("data", "seq", "expert", "model")


@dataclass(frozen=True)
class MeshSpec:
    """Logical axis sizes for a job's mesh.

    ``axes`` maps axis name -> size; unspecified axes are size 1. Built from
    ``TrainingJobSpec.parallelism`` (per-trainer local factors) times the
    actuated trainer count on the data axis.
    """

    axes: Dict[str, int] = field(default_factory=dict)

    def size(self) -> int:
        return math.prod(self.axes.values()) if self.axes else 1

    def axis(self, name: str) -> int:
        return self.axes.get(name, 1)

    def ordered_axes(self) -> List[str]:
        """Axes in canonical order: data outermost (spans hosts — its
        collectives tolerate DCN), model innermost (highest-bandwidth ICI
        neighbors — tensor-parallel collectives are latency-critical)."""
        named = [a for a in AXIS_ORDER if a in self.axes]
        extra = [a for a in self.axes if a not in AXIS_ORDER]
        return named + sorted(extra)

    @classmethod
    def for_job(cls, parallelism: Dict[str, int], num_trainers: int = 1) -> "MeshSpec":
        axes = {k: int(v) for k, v in parallelism.items() if int(v) > 1}
        if num_trainers > 1:
            axes["data"] = axes.get("data", 1) * num_trainers
        if not axes:
            axes = {"data": 1}
        return cls(axes=axes)


def arrange_devices(devs: Sequence, shape: Sequence[int]) -> np.ndarray:
    """Topology-aware device layout for a mesh of ``shape`` (axes ordered
    outermost -> innermost, i.e. data ... model).

    Two tiers, mirroring the hardware hierarchy:

    - Devices with TPU grid coordinates delegate to
      ``jax.experimental.mesh_utils.create_device_mesh`` — XLA's own
      logical->physical assignment, which keeps inner mesh axes on adjacent
      ICI neighbors (ring/torus contiguity) instead of enumeration order.
    - Otherwise (CPU meshes, virtual devices, simulated multi-host) devices
      sort by ``(process_index, id)`` and fill the shape row-major, so the
      INNERMOST axes (model/tensor-parallel — latency-critical collectives)
      vary within one process and the OUTERMOST axis (data — bandwidth-
      tolerant psums) is what spans processes/DCN. A plain
      ``np.array(devs).reshape`` (the previous behavior) preserves whatever
      order the caller enumerated, which on a multi-host slice can straddle
      the model axis across hosts.
    """
    devs = list(devs)
    want = int(np.prod(shape)) if len(shape) else 1
    if len(devs) != want:
        raise ValueError(f"shape {tuple(shape)} needs {want} devices, have {len(devs)}")
    if len(devs) > 1 and all(getattr(d, "coords", None) is not None for d in devs):
        try:
            from jax.experimental import mesh_utils

            return mesh_utils.create_device_mesh(
                tuple(shape), devices=devs, allow_split_physical_axes=True
            )
        except Exception:  # non-grid accelerator kinds: fall through
            pass
    order = sorted(
        range(len(devs)),
        key=lambda i: (getattr(devs[i], "process_index", 0),
                       getattr(devs[i], "id", i)),
    )
    return np.array([devs[i] for i in order], dtype=object).reshape(shape)


def build_mesh(spec: MeshSpec, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a `jax.sharding.Mesh` for the spec.

    Uses every available device by default and requires the axis product to
    match the device count exactly — a mismatch means the controller's
    actuated trainer count and the runtime's world view disagree, which must
    fail loudly (the reference's equivalent failure was trainers blocking on
    `wait_pods_running` forever, `docker/k8s_tools.py:70-78`).

    Device placement is topology-aware (``arrange_devices``): inner axes map
    to ICI neighbors / same-process devices, the data axis to the slowest
    interconnect tier.
    """
    devs = list(devices) if devices is not None else list(jax.devices())
    want = spec.size()
    if want != len(devs):
        raise ValueError(
            f"mesh spec {spec.axes} needs {want} devices, have {len(devs)}"
        )
    names = spec.ordered_axes() or ["data"]
    shape = [spec.axis(n) for n in names]
    mesh_devices = arrange_devices(devs, shape)
    return Mesh(mesh_devices, axis_names=tuple(names))


def local_mesh(axes: Optional[Dict[str, int]] = None) -> Mesh:
    """Single-host mesh over all local devices; default one flat data axis."""
    devs = jax.devices()
    spec = MeshSpec(axes=dict(axes) if axes else {"data": len(devs)})
    return build_mesh(spec, devs)
