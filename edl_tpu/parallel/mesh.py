"""Device-mesh construction from TrainingJob parallelism specs.

The reference distributes by counting processes (`PADDLE_INIT_NUM_GRADIENT_SERVERS`,
`pkg/jobparser.go:296`) and wiring endpoints; here distribution is a mesh of
TPU chips with named logical axes. The trainer count the autoscaler actuates
multiplies the ``data`` axis: a job scaled from 2 to 4 trainers rebuilds its
mesh with twice the data-parallel degree (checkpoint-restore rescale, see
`edl_tpu.runtime.elastic`).

Axis conventions (scaling-book style):
  data    — batch sharding; gradients all-reduced over it (ICI)
  model   — tensor-parallel sharding of weight matrices
  seq     — sequence/context parallelism for long inputs
  expert  — expert/embedding-row sharding (the pserver-replacement axis)

All axes are optional; absent axes have size 1. The product of axis sizes must
equal the number of participating devices.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

#: "dcn" is outermost by construction: it is the only axis whose collectives
#: may cross the data-center network (once-per-step, bandwidth-tolerant
#: gradient reductions). Every other axis — model/seq tensor collectives,
#: pipeline ppermutes, expert gathers — is latency-critical and stays inside
#: one ICI slice, which `build_hierarchical_mesh` guarantees by construction
#: (inner axes never straddle a slice boundary). "pipe" sits between data
#: and the tensor axes: stage ppermutes fire once per microbatch (more
#: latency-tolerant than per-layer model/seq collectives, less than
#: once-per-step data psums). This tuple is also the declared axis-name
#: universe the EDL003 sharding-consistency check validates PartitionSpecs
#: against (edl_tpu/analysis).
AXIS_ORDER = ("dcn", "data", "pipe", "seq", "expert", "model")


@dataclass(frozen=True)
class MeshSpec:
    """Logical axis sizes for a job's mesh.

    ``axes`` maps axis name -> size; unspecified axes are size 1. Built from
    ``TrainingJobSpec.parallelism`` (per-trainer local factors) times the
    actuated trainer count on the data axis.
    """

    axes: Dict[str, int] = field(default_factory=dict)

    def size(self) -> int:
        return math.prod(self.axes.values()) if self.axes else 1

    def axis(self, name: str) -> int:
        return self.axes.get(name, 1)

    def ordered_axes(self) -> List[str]:
        """Axes in canonical order: data outermost (spans hosts — its
        collectives tolerate DCN), model innermost (highest-bandwidth ICI
        neighbors — tensor-parallel collectives are latency-critical)."""
        named = [a for a in AXIS_ORDER if a in self.axes]
        extra = [a for a in self.axes if a not in AXIS_ORDER]
        return named + sorted(extra)

    @classmethod
    def for_job(cls, parallelism: Dict[str, int], num_trainers: int = 1) -> "MeshSpec":
        axes = {k: int(v) for k, v in parallelism.items() if int(v) > 1}
        if num_trainers > 1:
            axes["data"] = axes.get("data", 1) * num_trainers
        if not axes:
            axes = {"data": 1}
        return cls(axes=axes)


def arrange_devices(devs: Sequence, shape: Sequence[int]) -> np.ndarray:
    """Topology-aware device layout for a mesh of ``shape`` (axes ordered
    outermost -> innermost, i.e. data ... model).

    Two tiers, mirroring the hardware hierarchy:

    - Devices with TPU grid coordinates delegate to
      ``jax.experimental.mesh_utils.create_device_mesh`` — XLA's own
      logical->physical assignment, which keeps inner mesh axes on adjacent
      ICI neighbors (ring/torus contiguity) instead of enumeration order.
    - Otherwise (CPU meshes, virtual devices, simulated multi-host) devices
      sort by ``(process_index, id)`` and fill the shape row-major, so the
      INNERMOST axes (model/tensor-parallel — latency-critical collectives)
      vary within one process and the OUTERMOST axis (data — bandwidth-
      tolerant psums) is what spans processes/DCN. A plain
      ``np.array(devs).reshape`` (the previous behavior) preserves whatever
      order the caller enumerated, which on a multi-host slice can straddle
      the model axis across hosts.
    """
    devs = list(devs)
    want = int(np.prod(shape)) if len(shape) else 1
    if len(devs) != want:
        raise ValueError(f"shape {tuple(shape)} needs {want} devices, have {len(devs)}")
    if len(devs) > 1 and all(getattr(d, "coords", None) is not None for d in devs):
        try:
            from jax.experimental import mesh_utils

            return mesh_utils.create_device_mesh(
                tuple(shape), devices=devs, allow_split_physical_axes=True
            )
        except Exception:  # edl: noqa[EDL005] non-grid accelerator kinds fall back to the row-major layout below; nothing is lost
            pass
    order = sorted(
        range(len(devs)),
        key=lambda i: (getattr(devs[i], "process_index", 0),
                       getattr(devs[i], "id", i)),
    )
    return np.array([devs[i] for i in order], dtype=object).reshape(shape)


def build_mesh(spec: MeshSpec, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a `jax.sharding.Mesh` for the spec.

    Uses every available device by default and requires the axis product to
    match the device count exactly — a mismatch means the controller's
    actuated trainer count and the runtime's world view disagree, which must
    fail loudly (the reference's equivalent failure was trainers blocking on
    `wait_pods_running` forever, `docker/k8s_tools.py:70-78`).

    Device placement is topology-aware (``arrange_devices``): inner axes map
    to ICI neighbors / same-process devices, the data axis to the slowest
    interconnect tier.
    """
    devs = list(devices) if devices is not None else list(jax.devices())
    want = spec.size()
    if want != len(devs):
        raise ValueError(
            f"mesh spec {spec.axes} needs {want} devices, have {len(devs)}"
        )
    names = spec.ordered_axes() or ["data"]
    shape = [spec.axis(n) for n in names]
    mesh_devices = arrange_devices(devs, shape)
    return Mesh(mesh_devices, axis_names=tuple(names))


def build_hierarchical_mesh(
    spec: MeshSpec, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """Two-tier mesh for multi-slice jobs: the ``dcn`` axis spans slices,
    every other axis stays inside one slice's ICI domain.

    The scaling-book multi-pod recipe: data-parallel gradient reductions
    (one bandwidth-tolerant psum per step) ride DCN across slices, while
    tensor/sequence/pipeline collectives — latency-critical, many per
    layer — get ICI neighbors. XLA lowers a psum over ("dcn", "data") to
    the hierarchical reduce (intra-slice reduce-scatter, inter-slice
    all-reduce, intra-slice all-gather) on real hardware.

    Slice identity: real TPU slices expose ``device.slice_index``;
    multi-host simulations group by ``process_index``; a single-process
    virtual mesh (tests, the driver dry run) splits the sorted device list
    evenly — the dcn axis is then topologically fictional but compiles the
    identical program (that is the point of the dry run).
    """
    devs = list(devices) if devices is not None else list(jax.devices())
    n_slices = spec.axis("dcn")
    if n_slices <= 1:
        return build_mesh(spec, devs)
    if spec.size() != len(devs):
        raise ValueError(
            f"mesh spec {spec.axes} needs {spec.size()} devices, have {len(devs)}"
        )

    def slice_id(d):
        s = getattr(d, "slice_index", None)
        return s if s is not None else getattr(d, "process_index", 0)

    groups: Dict[int, list] = {}
    for d in devs:
        groups.setdefault(slice_id(d), []).append(d)
    if len(groups) == n_slices:
        slices = [groups[k] for k in sorted(groups)]
        if len({len(s) for s in slices}) != 1:
            raise ValueError(
                f"uneven slices: {[len(s) for s in slices]} devices per slice"
            )
    elif len(groups) == 1:
        # virtual single-process mesh: split evenly in stable id order
        ordered = sorted(devs, key=lambda d: getattr(d, "id", 0))
        per = len(devs) // n_slices
        slices = [ordered[i * per:(i + 1) * per] for i in range(n_slices)]
    else:
        raise ValueError(
            f"dcn={n_slices} but devices form {len(groups)} slice groups"
        )

    inner = MeshSpec({k: v for k, v in spec.axes.items() if k != "dcn"})
    inner_names = inner.ordered_axes() or ["data"]
    inner_shape = [inner.axis(n) for n in inner_names]
    stacked = np.stack(
        [arrange_devices(s, inner_shape) for s in slices]
    )  # (dcn, *inner)
    return Mesh(stacked, axis_names=("dcn", *inner_names))


def local_mesh(axes: Optional[Dict[str, int]] = None) -> Mesh:
    """Single-host mesh over all local devices; default one flat data axis."""
    devs = jax.devices()
    spec = MeshSpec(axes=dict(axes) if axes else {"data": len(devs)})
    return build_mesh(spec, devs)
